"""Trainer runtime — the train_from_dataset / DeviceWorker successor.

Ref: /root/reference/paddle/fluid/framework/trainer.h:38 (TrainerBase →
MultiTrainer/DistMultiTrainer), device_worker.h:151 (HogwildWorker),
:180 (DownpourWorker — PSLib pull sparse → train → push sparse),
executor.py:1107 train_from_dataset, trainer_desc.py / trainer_factory.py
(proto-configured trainer descriptors).

TPU-first redesign: the reference spawns N DeviceWorker threads each
running the op interpreter over a shared DataFeed channel — on TPU the
device consumes ONE stream (XLA executable, internally parallel), so the
thread pool moves to the *host side*: N ingestion threads fill a bounded
channel (the DataFeed successor; can be the C++ dataio reader), one device
loop dequeues, stages the next batch while the current step runs
(double-buffer reader parity), and runs the jitted step. DownpourWorker
parity comes from optional sparse-table pull/push hooks around each step
(parallel/sparse.HostTable — rows cross PCIe, exactly PSLib's flow).
"""

import contextlib
import dataclasses
import queue
import signal
import threading
import time

import jax

from paddle_tpu.core.enforce import enforce
from paddle_tpu.observability import flight as _flight
from paddle_tpu.observability import metrics as _metrics
from paddle_tpu.observability import trace as _trace
from paddle_tpu.observability.spans import span
from paddle_tpu.testing.chaos import fault_point

# conventional "rescheduleable interruption" exit status (BSD EX_TEMPFAIL);
# ElasticRunner respawns this rc immediately without burning crash budget
PREEMPTED_EXIT_CODE = 75


class Preempted(SystemExit):
    """Raised out of Trainer.train after a preemption signal triggered a
    final checkpoint save at the step boundary. Subclasses SystemExit
    with code PREEMPTED_EXIT_CODE, so a worker script that lets it
    propagate exits cleanly (no traceback) with the status the
    supervisor (parallel/elastic.ElasticRunner, or a cluster scheduler
    shim) recognizes as 'resume me'."""

    def __init__(self, step, signum=None):
        super().__init__(PREEMPTED_EXIT_CODE)
        self.step = step
        self.signum = signum

    def __str__(self):
        return (f"preempted by signal {self.signum} at step {self.step} "
                "(checkpoint saved)")


@dataclasses.dataclass
class TrainerConfig:
    """TrainerDesc equivalent (ref trainer_desc.py) — plain dataclass, no
    proto."""
    num_ingest_threads: int = 2
    channel_capacity: int = 8
    prefetch: bool = True          # stage batch t+1 during step t
    log_every: int = 0             # 0 = silent
    max_steps: int = None          # None = drain the dataset
    # failure detection (ref heart_beat_monitor.h:38): None = auto-on when
    # jax.process_count() > 1 and a heartbeat_dir is available
    heartbeat: bool = None
    heartbeat_transport: str = "file"  # "file" (shared dir) | "kv"
                                       # (jax.distributed KV store — no
                                       # shared FS; DCN-grade)
    heartbeat_dir: str = None      # shared dir for cross-process mtimes
    heartbeat_timeout_s: float = None   # default: dist_heartbeat_timeout_s
    heartbeat_interval_s: float = None  # default: dist_heartbeat_interval_s
    heartbeat_kv_client: object = None  # test injection (FakeKV)
    on_peer_stall: callable = None      # (worker, age_s) -> None
    # checkpoint/resume (ref: the Fluid trainer's save_checkpoint flow,
    # io.py save_persistables + executor.py train loop integration)
    checkpoint_dir: str = None     # None = checkpointing off
    checkpoint_every: int = 0      # steps between saves (0 = off)
    resume: bool = True            # restore latest checkpoint before start
    # preemption awareness (TPU pods get SIGTERM with a grace window when
    # the scheduler reclaims capacity): opt-in handler that requests a
    # final checkpoint at the next step boundary, then raises Preempted
    # (exit code 75) so the supervisor resumes at full fidelity instead
    # of losing up to checkpoint_every steps
    handle_preemption: bool = False
    preemption_signals: tuple = None  # default (SIGTERM, SIGINT)
    # step telemetry (observability/telemetry.py): an opt-in
    # TelemetryConfig; None also honors the global `telemetry` flag
    # (PT_FLAGS_telemetry=1 instruments without code changes). Records
    # (wall time, tokens/s, MFU, trailing-fetch loss, memory peaks) go
    # to the configured RunLog every N steps with no device sync added
    # to the hot path.
    telemetry: object = None
    # runtime anomaly watchdog (observability/watchdog.py): True or a
    # WatchdogConfig; None honors the global `watchdog` flag. Latches
    # slow-step / ingest-stall / steady-state-retrace anomalies into
    # watchdog.anomalies{kind} + the telemetry RunLog; the Trainer step
    # function's jit cache is polled for retraces (jit.retraces{fn=
    # trainer.step}) — all host-side, nothing added to the device path.
    watchdog: object = None
    # auto-parallelism (parallel/autoplan): a MeshPlan — the train loop
    # runs inside the planned mesh context and stages batches dp-sharded
    # over it, so a step_fn jitted against the plan's shardings consumes
    # Trainer batches with no per-call placement code
    mesh_plan: object = None
    # training guardian (static/guardian.py): True or a GuardianConfig;
    # None/False = off. Arms in-trace non-finite containment (skip-apply
    # keeps state bit-identical), the host-side loss-spike detector, and
    # the skip -> re-read -> rollback mitigation ladder. Rollback requires
    # checkpointing plus a seekable dataset (the stream is replayed to the
    # restored cursor).
    guardian: object = None
    # abort the step loop as soon as an ingest reader thread dies instead
    # of quietly training on fewer readers until drain; None honors the
    # trainer_ingest_fail_fast flag (default on)
    ingest_fail_fast: bool = None


class _EndOfData:
    pass


_EOD = _EndOfData()


class Trainer:
    """Run `train_step(state, *batch) -> (loss, state)` over a dataset with
    threaded host ingestion + device staging.

    dataset: anything with .reader() -> callable yielding batches (tuples
    of numpy arrays), or a plain iterable factory.
    sparse_tables: optional list of (table, ids_from_batch) pairs; each
    step pulls the batch's rows, passes them to the step via trailing args
    (rows, inv), and pushes the returned row-grads — DownpourWorker's
    pull/push cycle (device_worker.h:180) with HostTable as the server.
    """

    def __init__(self, train_step, config=None, sparse_tables=None):
        self.step_fn = train_step
        self.cfg = config or TrainerConfig()
        self.sparse_tables = sparse_tables or []
        self.history = []
        self.telemetry = None    # StepTelemetry after train() when enabled
        self.watchdog = None     # Watchdog after train() when enabled
        self.guardian = None     # TrainGuardian after train() when enabled
        self._guarded = None     # guardian-wrapped step_fn (jitted once)
        self._ingest_threads = []

    # -- DataFeed channel (ref data_feed.cc multi-threaded file->channel) --
    def _start_ingest(self, readers, on_error=None):
        chan = queue.Queue(maxsize=self.cfg.channel_capacity)
        counts = {"live": len(readers)}
        lock = threading.Lock()
        errors = []
        stop = threading.Event()

        def put(item):
            while not stop.is_set():
                try:
                    chan.put(item, timeout=0.1)
                    return True
                except queue.Full:
                    continue
            return False

        def work(reader):
            try:
                for item in reader():
                    fault_point("trainer.ingest")
                    if not put(item):
                        return  # trainer stopped early (max_steps)
            except BaseException as e:
                # a dead reader is never silent: counted + surfaced to the
                # watchdog immediately, raised by train() (at once under
                # trainer_ingest_fail_fast, else at drain)
                errors.append(e)
                _metrics.counter(
                    "trainer.ingest_errors",
                    "Ingest reader threads that died, by exception "
                    "type.").inc(reason=type(e).__name__)
                if on_error is not None:
                    on_error(e)
            finally:
                with lock:
                    counts["live"] -= 1
                    if counts["live"] == 0:
                        put(_EOD)

        threads = [threading.Thread(target=work, args=(r,), daemon=True)
                   for r in readers]
        for t in threads:
            t.start()
        self._ingest_threads = threads
        return chan, stop, errors

    def _split_readers(self, dataset):
        """One reader per ingest thread: a dataset with .readers(n) gets
        shard-level parallelism; otherwise a single reader feeds the
        channel."""
        n = self.cfg.num_ingest_threads
        if hasattr(dataset, "readers"):
            return dataset.readers(n)
        if hasattr(dataset, "reader"):
            return [dataset.reader()]
        return [dataset]  # assume callable yielding items

    # -- preemption (SIGTERM grace window -> checkpoint -> clean exit) -----
    def _install_preemption_handler(self):
        """Opt-in signal handlers that REQUEST a stop; the train loop acts
        at the next step boundary (mid-step state is not checkpointable).
        Returns (requested: dict, restore: callable)."""
        requested = {"signum": None}
        if not self.cfg.handle_preemption:
            return requested, lambda: None
        sigs = self.cfg.preemption_signals or (signal.SIGTERM,
                                               signal.SIGINT)
        prev = {}

        def on_signal(signum, frame):
            requested["signum"] = signum

        try:
            for s in sigs:
                prev[s] = signal.signal(s, on_signal)
        except ValueError:
            # not the main thread: signals can't be trapped here — run
            # without graceful preemption rather than refuse to train
            print("[trainer] WARNING: handle_preemption requested off the "
                  "main thread; preemption signals will not be trapped")
            return requested, lambda: None

        def restore():
            for s, h in prev.items():
                signal.signal(s, h)

        return requested, restore

    # -- failure detection (ref heart_beat_monitor.h LostWorkerMonitor) ----
    def _start_heartbeat(self, num_workers=None, worker_id=None):
        """Cross-process liveness: ping a shared-dir mtime file per step and
        monitor peers in the background, flagging silent RUNNING workers.
        Returns (ping, finish) callables (no-ops when disabled)."""
        cfg = self.cfg
        enforce(cfg.heartbeat_transport in ("file", "kv"),
                f"heartbeat_transport must be 'file' or 'kv', got "
                f"{cfg.heartbeat_transport!r}")
        enabled = cfg.heartbeat
        kv_mode = cfg.heartbeat_transport == "kv"
        if enabled is None:
            enabled = (jax.process_count() > 1
                       and (kv_mode or cfg.heartbeat_dir is not None))
        if enabled and not kv_mode:
            enforce(cfg.heartbeat_dir is not None,
                    "TrainerConfig(heartbeat=True) requires heartbeat_dir "
                    "(a shared directory all workers can reach) — or set "
                    "heartbeat_transport='kv' to ride the jax.distributed "
                    "KV store with no shared FS")
        if not enabled:
            return (lambda: None), (lambda ok=True: None)
        from paddle_tpu.core import flags as F
        from paddle_tpu.parallel.heartbeat import (STALLED, FileHeartbeat,
                                                   KVHeartbeat, KVMonitor,
                                                   PeerFailureError)
        nw = num_workers if num_workers is not None else jax.process_count()
        wid = worker_id if worker_id is not None else jax.process_index()
        timeout = (cfg.heartbeat_timeout_s if cfg.heartbeat_timeout_s
                   is not None else F.get_flag("dist_heartbeat_timeout_s"))
        interval = (cfg.heartbeat_interval_s if cfg.heartbeat_interval_s
                    is not None else F.get_flag("dist_heartbeat_interval_s"))
        if kv_mode:
            hb = KVHeartbeat(wid, client=cfg.heartbeat_kv_client)
            kv_mon = KVMonitor(nw, timeout_s=timeout,
                               client=cfg.heartbeat_kv_client)

            def scan_once():
                try:
                    return kv_mon.scan()
                except PeerFailureError as e:
                    # connection-level death: attribution unavailable —
                    # surface as worker -1 (the monitor loop's latch
                    # dedups the callback)
                    print(f"[trainer] coordination-service failure: {e}")
                    return {-1: (STALLED, float("inf"))}
        else:
            hb = FileHeartbeat(cfg.heartbeat_dir, wid)

            def scan_once():
                return FileHeartbeat.scan(cfg.heartbeat_dir, nw, timeout)
        hb.ping()
        last_ping = [time.monotonic()]

        def ping():
            # throttle to the monitor interval: per-step open()+utime() on
            # a shared (possibly network) dir would put metadata writes on
            # the hot loop while scan() only samples every interval anyway
            now = time.monotonic()
            if now - last_ping[0] >= min(interval, timeout / 4):
                hb.ping()
                last_ping[0] = now

        stop = threading.Event()
        stalled = self.stalled_peers = set()

        def monitor():
            while not stop.wait(interval):
                for w, (st, age) in scan_once().items():
                    if w != wid and st == STALLED and w not in stalled:
                        stalled.add(w)
                        if not kv_mode:   # KVMonitor counts its own latch
                            _metrics.counter("heartbeat.missed").inc(
                                worker=w)
                        if cfg.on_peer_stall is not None:
                            cfg.on_peer_stall(w, age)
                        else:
                            desc = ("transport reported peer death"
                                    if age == float("inf") else
                                    f"silent for {age:.1f}s (> {timeout}s)")
                            print(f"[trainer] WARNING: worker {w} {desc}")

        t = threading.Thread(target=monitor, daemon=True,
                             name="trainer-heartbeat")
        t.start()

        def finish(ok=True):
            if ok:
                # only a CLEAN exit writes the done marker — a crashed
                # worker must look STALLED to its peers, not COMPLETED
                hb.complete()
            stop.set()
            t.join(timeout=5)

        return ping, finish

    def _start_telemetry(self):
        """StepTelemetry when TrainerConfig.telemetry is set (or the
        global `telemetry` flag is on); None = zero telemetry work in
        the loop. The instance is kept on self.telemetry so callers can
        read .records after train()."""
        from paddle_tpu.core import flags as F
        tcfg = self.cfg.telemetry
        if tcfg is None and not F.get_flag("telemetry"):
            return None
        from paddle_tpu.observability.telemetry import (StepTelemetry,
                                                        TelemetryConfig)
        tele = StepTelemetry(tcfg if tcfg is not None
                             else TelemetryConfig())
        self.telemetry = tele
        return tele if tele.enabled else None

    def _start_watchdog(self, tele, step_callable=None):
        """Watchdog when TrainerConfig.watchdog (or the global flag) is
        set; anomaly events ride the telemetry RunLog when one exists.
        The jitted step function — the guardian-wrapped one when armed,
        since that is the jit the loop dispatches — is polled for
        steady-state retraces."""
        from paddle_tpu.observability.watchdog import maybe_watchdog
        wd = maybe_watchdog(self.cfg.watchdog,
                            run_log=getattr(tele, "_log", None),
                            action=lambda event: self._on_anomaly(
                                event, getattr(tele, "_log", None)))
        if wd is not None:
            wd.watch_jit("trainer.step",
                         step_callable if step_callable is not None
                         else self.step_fn)
        self.watchdog = wd
        return wd

    def _on_anomaly(self, event, run_log=None):
        """Watchdog mitigation hook: every trainer anomaly becomes a
        self-documenting flight bundle — metrics snapshot, the event
        ring (step-phase spans linked into the active trace context),
        and the telemetry RunLog tail. Recording off (flight_ring=0)
        makes this a no-op; the watchdog's dispatcher already swallows
        handler failures."""
        fl = _flight.recorder()
        if fl is None:
            return
        fl.note_event("anomaly", **{k: v for k, v in event.items()
                                    if k not in ("event", "t")})
        _flight.dump_bundle(
            reason=str(event.get("anomaly", "anomaly")),
            run_logs=(run_log,) if run_log is not None else (),
            config=dict(trainer_config=repr(self.cfg)),
            extra=dict(anomaly=event))

    def train(self, state, dataset, batch_size=None, num_workers=None,
              worker_id=None):
        """Drain the dataset (or max_steps); returns (state, stats).

        With batch_size set, ingestion threads enqueue SAMPLES and the
        device loop collates batch_size of them per step off the merged
        channel (drop_last on the global stream) — per-thread remainders
        are not lost, matching the reference's shared DataFeed channel.
        Without it, readers must yield ready batches."""
        cfg = self.cfg
        from paddle_tpu.core import flags as F
        from paddle_tpu.core import random as _random
        step = 0
        guard = None
        step_call = self.step_fn
        if cfg.guardian:
            from paddle_tpu.static.guardian import (GuardianConfig,
                                                    TrainGuardian)
            enforce(not self.sparse_tables,
                    "TrainerConfig.guardian does not support "
                    "sparse_tables (the sparse step's pull/push cycle "
                    "has host-side state the skip-apply gate cannot "
                    "contain)")
            guard = TrainGuardian(
                cfg.guardian if isinstance(cfg.guardian, GuardianConfig)
                else None)
            self.guardian = guard
            if self._guarded is None:
                # jitted once per Trainer; repeated train() calls (and
                # in-run rollbacks) reuse the compiled guarded step
                self._guarded = guard.wrap_step(self.step_fn)
            step_call = self._guarded
        ckpt_mgr = None
        if cfg.checkpoint_dir and cfg.checkpoint_every:
            from paddle_tpu.io.checkpoint import CheckpointManager
            ckpt_mgr = CheckpointManager(
                cfg.checkpoint_dir, save_interval_steps=cfg.checkpoint_every)
            if cfg.resume:
                restored, at = ckpt_mgr.restore(state)
                if restored is not None:
                    state, step = restored, int(at)
                    # bit-exact resume: the step's meta sidecar carries
                    # the global RNG key, the data cursor, and the
                    # guardian's detector state
                    meta = ckpt_mgr.read_meta(step)
                    if meta:
                        _random.set_state(meta.get("rng"))
                        if guard is not None:
                            guard.load_state(meta.get("guardian"))
                    # datasets that support seek(step) continue mid-stream;
                    # plain generator factories restart from the beginning
                    # (epoch semantics — the reference trainer's
                    # save_checkpoint flow restarts epochs the same way)
                    if hasattr(dataset, "seek"):
                        dataset.seek(step)
                    print(f"[trainer] resumed from step {step}")
        start_step = step
        preempt, restore_signals = self._install_preemption_handler()
        tele = self._start_telemetry()
        if tele is not None and getattr(tele, "_log", None) is not None:
            # clock anchor: lets the fleet-trace merge interleave this
            # run's RunLog with serving-replica logs skew-corrected
            _trace.write_anchor(tele._log, role="trainer")
        wd = self._start_watchdog(tele, step_call)
        if guard is not None:
            guard.attach(run_log=getattr(tele, "_log", None), watchdog=wd)

        def on_ingest_error(e):
            # edge-triggered: every dead reader is its own anomaly
            if wd is not None:
                wd.alert("ingest_error", step, latch=False,
                         error=f"{type(e).__name__}: {e}"[:200])

        chan, stop, errors = self._start_ingest(
            self._split_readers(dataset), on_error=on_ingest_error)
        hb_ping, hb_finish = self._start_heartbeat(num_workers, worker_id)
        fail_fast = (cfg.ingest_fail_fast
                     if cfg.ingest_fail_fast is not None
                     else bool(F.get_flag("trainer_ingest_fail_fast")))

        def ckpt_meta():
            m = {"cursor": int(step), "rng": _random.get_state()}
            if guard is not None:
                m["guardian"] = guard.state_dict()
            return m

        t0 = time.perf_counter()
        loss = None
        stall_ctr = _metrics.counter(
            "trainer.ingest_stall_s",
            "Wall time the device loop spent blocked on the ingest "
            "channel.")
        depth_gauge = _metrics.gauge(
            "trainer.channel_depth",
            "Ingest channel occupancy sampled at each dequeue.")
        stall_acc = {"t": 0.0}   # per-step ingest wait for the watchdog

        def stage(batch):
            # host->device transfer starts now, overlapping the running step
            return tuple(jax.device_put(a) for a in batch)

        plan = cfg.mesh_plan
        plan_mesh = None
        if plan is not None:
            # autoplan MeshPlan: stage batches dp-sharded onto the planned
            # mesh (leading dim over "dp" when divisible; replicated
            # otherwise) and run the loop inside the mesh context so the
            # jitted step resolves the plan's axis names
            plan_mesh = plan.build_mesh()
            from jax.sharding import NamedSharding, PartitionSpec
            plan_dp = plan.axes.get("dp", 1)

            def stage(batch):  # noqa: F811 — plan-aware staging
                def put(a):
                    nd = getattr(a, "ndim", 0)
                    spec = (PartitionSpec("dp")
                            if plan_dp > 1 and nd >= 1
                            and a.shape[0] % plan_dp == 0
                            else PartitionSpec())
                    return jax.device_put(
                        a, NamedSharding(plan_mesh, spec))
                return tuple(put(a) for a in batch)

        def get_item():
            tw0 = time.perf_counter()
            item = chan.get()
            dt = time.perf_counter() - tw0
            stall_ctr.inc(dt)
            stall_acc["t"] += dt
            depth_gauge.set(chan.qsize())
            return item

        def next_batch():
            if batch_size is None:
                item = get_item()
                return None if isinstance(item, _EndOfData) else item
            from paddle_tpu.data.loader import _collate
            buf = []
            while len(buf) < batch_size:
                item = get_item()
                if isinstance(item, _EndOfData):
                    return None  # drop_last on the merged stream
                buf.append(item)
            return _collate(buf)

        def do_rollback():
            # mitigation-ladder escalation: restore the newest checkpoint
            # strictly BEFORE the anomaly episode (its update may already
            # be poisoned) and replay the stream to the same cursor
            nonlocal state, step, chan, stop, errors
            enforce(ckpt_mgr is not None,
                    "guardian rollback requires checkpointing "
                    "(TrainerConfig.checkpoint_dir + checkpoint_every)")
            enforce(hasattr(dataset, "seek"),
                    "guardian rollback requires a seekable dataset "
                    "(dataset.seek(step)) to replay the stream")
            bound = guard.rollback_bound
            guard.begin_rollback(step, bound=bound)  # budget; may re-raise
            # halt the in-flight readers; the replay gets a fresh channel
            stop.set()
            for t in self._ingest_threads:
                t.join(timeout=10)
            cands = [s for s in ckpt_mgr.steps()
                     if bound is None or s <= bound]
            restored = at = None
            while cands:
                target = cands.pop()        # newest safe step first
                try:
                    restored, at = ckpt_mgr.restore(state, step=target)
                    break
                except Exception as e:
                    print(f"[trainer] rollback: step {target} "
                          f"unrestorable ({type(e).__name__}: {e}); "
                          "degrading to the previous step")
            enforce(restored is not None,
                    "guardian rollback found no restorable checkpoint at "
                    "or before the anomaly")
            state, step = restored, int(at)
            meta = ckpt_mgr.read_meta(step)
            if meta:
                # rewind the RNG stream with the state; the guardian's
                # live window/counters are NOT rewound — the replay walks
                # the same healthy trajectory the window already holds,
                # and a persistent divergence must re-trip the detector
                _random.set_state(meta.get("rng"))
            guard.note_rollback_done(step)
            dataset.seek(step)
            chan, stop, errors = self._start_ingest(
                self._split_readers(dataset), on_error=on_ingest_error)
            print(f"[trainer] guardian rollback: restored step {step}, "
                  "stream replayed")

        clean = False
        preempted_sig = None
        mesh_scope = contextlib.ExitStack()
        if plan_mesh is not None:
            mesh_scope.enter_context(plan_mesh)
        # one trace context covers the whole train loop: the step-phase
        # spans (ingest/stage/step) below link into it via the flight
        # ring, so an anomaly bundle shows WHERE in the step the run was
        mesh_scope.enter_context(_trace.activate(_trace.TraceContext(
            f"{_trace.mint_run()}/train", span_id="train")))
        try:
            with span("ingest"):
                nxt = next_batch()
            first = True
            it_t = time.perf_counter()
            while nxt is not None:
                if cfg.max_steps is not None and step >= cfg.max_steps:
                    break
                if fail_fast and errors:
                    break    # a reader died: stop now, raise below
                with span("stage"):
                    staged = stage(nxt)
                # prefetch the following batch while this step runs
                if cfg.prefetch:
                    with span("ingest"):
                        nxt = next_batch()
                if first and tele is not None and not self.sparse_tables:
                    tele.maybe_estimate_flops(self.step_fn, state, *staged)
                first = False

                with span("step"):
                    fault_point("trainer.step")
                    applied = None
                    if self.sparse_tables:
                        state, loss = self._sparse_step(state, staged)
                    elif guard is not None:
                        loss, state, applied = step_call(state, *staged)
                    else:
                        loss, state = self.step_fn(state, *staged)
                step += 1
                now = time.perf_counter()
                if tele is not None:
                    # the loss stays a device array here — telemetry
                    # fetches it one interval later (trailing), never
                    # syncing on the step just dispatched
                    tele.on_step(step, staged, loss, state,
                                 wall_s=now - it_t)
                if wd is not None:
                    wd.tick(step, wall_s=now - it_t,
                            stall_s=stall_acc["t"])
                    stall_acc["t"] = 0.0
                it_t = now
                if guard is not None:
                    # parks this step's device scalars, processes the
                    # previous step's (trailing — no sync on the step
                    # just dispatched), returns its mitigation
                    act = guard.observe_step(step, loss, applied, state)
                    if act == "reread":
                        # drop the suspect batch at the cursor, take the
                        # following one instead
                        with span("ingest"):
                            fresh = next_batch()
                            if cfg.prefetch:
                                nxt = fresh
                            elif fresh is None:
                                nxt = None  # stream ended under the drop
                    elif act == "rollback":
                        do_rollback()
                        with span("ingest"):
                            nxt = next_batch()
                        it_t = time.perf_counter()
                        continue
                hb_ping()
                if preempt["signum"] is not None:
                    # step boundary after a preemption notice: flush a
                    # final checkpoint (interval gate bypassed) and stop —
                    # the supervisor resumes at exactly this step
                    if ckpt_mgr is not None:
                        ckpt_mgr.save(step, state, force=True,
                                      meta=ckpt_meta())
                    preempted_sig = preempt["signum"]
                    _metrics.counter("trainer.preempted").inc()
                    print(f"[trainer] preemption signal {preempted_sig}: "
                          f"checkpointed step {step}, exiting for resume")
                    break
                if ckpt_mgr is not None and (guard is None
                                             or guard.healthy()):
                    # interval saves are skipped while an anomaly episode
                    # is open, so the newest checkpoint is always a good
                    # rollback target; a healthy save resets the
                    # consecutive-rollback budget
                    if ckpt_mgr.save(step, state,  # gates the interval
                                     meta=ckpt_meta()) and guard is not None:
                        guard.note_checkpoint(step)
                if cfg.log_every and step % cfg.log_every == 0:
                    lv = float(loss)
                    self.history.append((step, lv))
                    print(f"[trainer] step {step} loss {lv:.6f}")
                if not cfg.prefetch:
                    with span("ingest"):
                        nxt = next_batch()
            clean = preempted_sig is None
        finally:
            mesh_scope.close()
            stop.set()  # release producers even when step_fn raises
            restore_signals()
            # a preempted worker is NOT complete: no done marker — peers
            # see it pause (and revive), never COMPLETED
            hb_finish(clean)
            if ckpt_mgr is not None:
                ckpt_mgr.close()
            if guard is not None:
                guard.flush_trailing()
            if tele is not None:
                extra = {"steps": step, "preempted":
                         preempted_sig is not None}
                if guard is not None:
                    extra.update(nonfinite_skips=guard.skips,
                                 loss_spikes=guard.spikes,
                                 rollbacks=guard.rollbacks)
                tele.finish(extra)
        if preempted_sig is not None:
            raise Preempted(step, preempted_sig)
        run_steps = step - start_step
        if errors:
            raise RuntimeError(
                f"ingestion thread failed after {run_steps} steps "
                f"(total step {step})") from errors[0]
        wall = time.perf_counter() - t0
        stats = {"steps": step, "run_steps": run_steps, "wall_s": wall,
                 "steps_per_s": run_steps / wall if wall > 0 else 0.0,
                 "final_loss": float(loss) if loss is not None else None}
        return state, stats

    def _sparse_step(self, state, batch):
        """DownpourWorker cycle: pull rows -> step over rows -> push row
        grads (ref downpour_worker.cc TrainFiles)."""
        import numpy as np

        pulls = []
        for table, ids_fn in self.sparse_tables:
            ids = np.asarray(ids_fn(batch))
            rows, uniq = table.pull(ids)
            inv = np.searchsorted(uniq, ids.reshape(-1))
            pulls.append((table, uniq, rows, jax.numpy.asarray(inv)))
        extra = []
        for _, _, rows, inv in pulls:
            extra += [rows, inv]
        loss, state, *row_grads = self.step_fn(state, *batch, *extra)
        enforce(len(row_grads) == len(pulls),
                "sparse train_step must return one row-grad per table")
        for (table, uniq, _, _), g in zip(pulls, row_grads):
            table.push(uniq, g)
        return state, loss


def train_from_dataset(train_step, state, dataset, config=None,
                       sparse_tables=None, batch_size=None):
    """Functional one-call form (ref executor.py:1107)."""
    tr = Trainer(train_step, config, sparse_tables)
    return tr.train(state, dataset, batch_size=batch_size)
