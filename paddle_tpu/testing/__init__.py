"""Testing utilities — deterministic fault injection for robustness tests.

  chaos.py   FaultPlan / ChaosFS / fault_point: seedable fault injection
             over the io/fs registry and framework fault points, so
             recovery behavior (retry, degrade, torn-write protection,
             preemption resume) is exercised by tier-1 tests rather than
             trusted.
"""

from paddle_tpu.testing import chaos
from paddle_tpu.testing.chaos import ChaosFS, DirFS, FaultPlan, fault_point
