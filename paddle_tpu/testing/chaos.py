"""Chaos harness — deterministic fault injection for the I/O + recovery
paths.

Ref: the reference framework had no fault-injection story at all — its
failure handling (HeartBeatMonitor warnings, PSLib sleep-through-restart)
shipped untested. Here every recovery behavior is exercised by tests:

  FaultPlan   seedable schedule of faults, matched by operation name,
              occurrence count, and path regex. Deterministic by
              construction (per-op counters); optional probabilistic
              rules draw from the plan's own seeded RNG.
  ChaosFS     wraps any filesystem implementing the 6-primitive surface
              (io/fs.py MemFS template) and consults the plan before
              each primitive: raise an injected error, add latency, or
              silently truncate a write (torn-write simulation).
  DirFS       LocalFS under a URL scheme, rooted at a directory — a
              fault-injectable "remote" store that SURVIVES process
              restarts (MemFS is per-process), for multi-process drills
              like tools/chaos_drill.py.
  fault_point 1-line hooks compiled into framework paths (checkpoint
              mirror, trainer ingest); no-ops unless a plan is active.

    plan = FaultPlan(seed=7).fail("write", path=r"/3/", times=2)
    fs.register_filesystem("mem", ChaosFS(fs.MemFS(), plan))

    with chaos.active(plan): ...        # enables fault_point() hooks

This module deliberately imports nothing from paddle_tpu at module level
so the framework hot paths (io/fs.py, static/trainer.py) can import
`fault_point` without cycles.
"""

import contextlib
import os
import random
import re
import shutil
import threading
import time


class InjectedFault(OSError):
    """Default injected error. Subclasses OSError so the framework's
    default retryable predicate (core/retry.py) treats it as transient —
    exactly what a flaky object store throws."""


class FaultPlan:
    """A deterministic schedule of faults.

    Rules are matched in insertion order against (op, path) events; each
    op keeps its own 1-based occurrence counter. A rule fires when its
    `op` matches, the op's occurrence index is >= `nth`, its `path`
    regex (if any) searches the path, its `times` budget is not spent,
    and its probability (if any) passes the seeded RNG. Actions: raise
    `exc` (default InjectedFault), sleep `latency_s`, or mark the write
    for truncation after `truncate_at` bytes (torn write — the caller
    sees success).
    """

    def __init__(self, seed=0):
        self.rng = random.Random(seed)
        self._rules = []
        self._counts = {}
        self._lock = threading.Lock()
        self.log = []                  # (op, path, action) tuples fired

    def fail(self, op, path=None, nth=1, times=1, exc=None, p=None,
             latency_s=None, truncate_at=None):
        """Add a rule; returns self for chaining."""
        self._rules.append(dict(
            op=op, path=re.compile(path) if path else None, nth=nth,
            remaining=times, exc=exc, p=p, latency_s=latency_s,
            truncate_at=truncate_at))
        return self

    def reset_counts(self):
        with self._lock:
            self._counts.clear()

    def fired(self, op=None):
        """How many faults fired (optionally for one op) — assertions."""
        return len([e for e in self.log if op is None or e[0] == op])

    def check(self, op, path=""):
        """Record one (op, path) event; raise/sleep per the first matching
        rule. Returns a truncation byte limit for write ops, else None."""
        with self._lock:
            n = self._counts[op] = self._counts.get(op, 0) + 1
            rule = None
            for r in self._rules:
                if r["op"] != op or r["remaining"] <= 0 or n < r["nth"]:
                    continue
                if r["path"] is not None and not r["path"].search(str(path)):
                    continue
                if r["p"] is not None and self.rng.random() >= r["p"]:
                    continue
                r["remaining"] -= 1
                rule = r
                break
        if rule is None:
            return None
        if rule["latency_s"]:
            self.log.append((op, path, f"latency:{rule['latency_s']}"))
            time.sleep(rule["latency_s"])
        if rule["truncate_at"] is not None:
            self.log.append((op, path, f"truncate:{rule['truncate_at']}"))
            return rule["truncate_at"]
        if rule["exc"] is not None or rule["latency_s"] is None:
            exc = rule["exc"] or InjectedFault(
                f"injected fault: {op} #{n} on {path!r}")
            self.log.append((op, path, f"raise:{type(exc).__name__}"))
            raise exc
        return None


class _TruncatingWriter:
    """Persists only the first `limit` bytes but reports full success to
    the writer — what a crash mid-upload leaves behind (torn write)."""

    def __init__(self, inner, limit):
        self._inner = inner
        self._left = limit

    def write(self, data):
        if self._left > 0:
            take = data[:self._left]
            self._inner.write(take)
            self._left -= len(take)
        return len(data)

    def close(self):
        self._inner.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    def __getattr__(self, name):
        return getattr(self._inner, name)


class ChaosFS:
    """Fault-injecting wrapper over any registered filesystem.

    Consulted ops (FaultPlan `op` names): "open" (read), "write"
    (write/append open), "exists", "isdir", "listdir", "makedirs",
    "remove". Register it in place of the real backend:

        fs.register_filesystem("gs", ChaosFS(real_gs, plan))
    """

    def __init__(self, inner, plan):
        self.inner = inner
        self.plan = plan

    def open(self, path, mode="rb"):
        writeish = "w" in mode or "a" in mode
        limit = self.plan.check("write" if writeish else "open", path)
        f = self.inner.open(path, mode)
        if writeish and limit is not None:
            return _TruncatingWriter(f, limit)
        return f

    def exists(self, path):
        self.plan.check("exists", path)
        return self.inner.exists(path)

    def isdir(self, path):
        self.plan.check("isdir", path)
        return self.inner.isdir(path)

    def listdir(self, path):
        self.plan.check("listdir", path)
        return self.inner.listdir(path)

    def makedirs(self, path):
        self.plan.check("makedirs", path)
        return self.inner.makedirs(path)

    def remove(self, path):
        self.plan.check("remove", path)
        return self.inner.remove(path)


class DirFS:
    """A 'remote' store backed by a local directory, addressed through a
    URL scheme ('drill://ck/3/x' -> <root>/ck/3/x). Unlike MemFS the
    contents survive process restarts, so multi-process drills
    (ElasticRunner workers dying and resuming) can share one
    fault-injectable store: register ChaosFS(DirFS(root), plan)."""

    def __init__(self, root):
        self.root = os.path.abspath(root)
        os.makedirs(self.root, exist_ok=True)

    def _p(self, path):
        rest = str(path).partition("://")[2] if "://" in str(path) \
            else str(path)
        return os.path.join(self.root, rest.lstrip("/"))

    def open(self, path, mode="rb"):
        p = self._p(path)
        if "w" in mode or "a" in mode:
            os.makedirs(os.path.dirname(p), exist_ok=True)
        return open(p, mode)

    def exists(self, path):
        return os.path.exists(self._p(path))

    def isdir(self, path):
        return os.path.isdir(self._p(path))

    def listdir(self, path):
        p = self._p(path)
        if not os.path.isdir(p):
            raise FileNotFoundError(path)
        return sorted(os.listdir(p))

    def makedirs(self, path):
        os.makedirs(self._p(path), exist_ok=True)

    def remove(self, path):
        p = self._p(path)
        if os.path.isdir(p):
            shutil.rmtree(p)
        elif os.path.exists(p):
            os.remove(p)


# -- fault points: named hooks on framework paths ------------------------
# Registry of every fault_point() name compiled into the framework, so
# drills and plans can't silently drift from the call sites. Linted by a
# tier-1 test (tests/test_chaos.py) that greps paddle_tpu/ both ways:
# every call site must be registered here, and every registered name must
# still have a call site.
FAULT_POINTS = {
    "checkpoint.fetch": "restore-side remote read of a checkpoint step",
    "checkpoint.mirror": "remote mirror push of a committed checkpoint",
    "checkpoint.verify": "restore-side crc32 integrity check of a "
                         "checkpoint step against its manifest",
    "collective.quant": "quantized dp all-reduce strategy resolution (a "
                        "fault degrades the sync to plain f32 psum)",
    "fleet.canary": "canary routing draw for a fresh fleet request (a "
                     "fault degrades the request to the baseline "
                     "version)",
    "fleet.deploy": "rolling weight hot-swap: the checkpoint "
                    "load/verify before any replica is touched, and "
                    "each per-replica engine rebuild on the new "
                    "version (a fault rolls the touched replica back)",
    "fleet.dispatch": "fleet router handing a request to a replica",
    "fleet.handoff": "prefill->decode disaggregation handoff of a "
                     "prefilled request to a decode replica (a fault "
                     "keeps the request on the prefill replica — "
                     "mixed-mode degrade, never a wedge)",
    "fleet.heartbeat": "fleet router per-replica liveness ping",
    "fleet.respawn": "fleet router respawning a dead replica",
    "fleet.scale": "fleet autoscaler acting on a load signal (spawn "
                   "or graceful drain-then-retire)",
    "flight.dump": "anomaly-triggered flight-recorder bundle dump (a "
                   "fault aborts the dump; the anomaly handler must "
                   "survive — no bundle, engine keeps serving)",
    "quant.kv_write": "quantized paged-KV admission write (a fault "
                      "degrades that admission to private pages — no "
                      "prefix-cache mapping or publish)",
    "serve.prefill": "serving admission prefill (per chunk) device call",
    "serve.prefix_cache": "prefix-cache lookup at admission (a hash "
                          "collision or evict-under-use injection "
                          "degrades the match to private pages)",
    "serve.step": "the jitted continuous-batching decode step",
    "spec.verify": "speculative draft-propose + verify round (a fault "
                   "degrades that round to one plain decode step — "
                   "token-exact either way)",
    "trainer.ingest": "ingest-channel dequeue feeding the train step",
    "trainer.rollback": "guardian rollback restoring the last good "
                        "checkpoint after mitigation-ladder escalation",
    "trainer.step": "the jitted train step dispatch",
}

_ACTIVE = None


def install(plan):
    """Activate `plan` for fault_point() hooks process-wide."""
    global _ACTIVE
    _ACTIVE = plan


def uninstall():
    global _ACTIVE
    _ACTIVE = None


@contextlib.contextmanager
def active(plan):
    """Scoped install: `with chaos.active(plan): ...`."""
    install(plan)
    try:
        yield plan
    finally:
        uninstall()


def fault_point(name):
    """Named hook compiled into framework paths (checkpoint mirror,
    trainer ingest). Free when no plan is active; under an active plan it
    is a FaultPlan event with op="fault_point" and path=name."""
    if _ACTIVE is not None:
        _ACTIVE.check("fault_point", name)
