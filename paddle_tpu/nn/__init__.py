"""Layer/Module API — dygraph parity, functional core.

Ref: /root/reference/python/paddle/fluid/dygraph/ (layers.py Layer,
nn.py modules). See nn/module.py for the programming model.
"""

from paddle_tpu.nn.module import Module, ModuleList, Sequential
from paddle_tpu.nn.layers import (
    FC,
    NCE,
    BatchNorm,
    BilinearTensorProduct,
    Conv2D,
    Conv2DTranspose,
    Conv3D,
    Dropout,
    Embedding,
    GRU,
    GroupNorm,
    GRUUnit,
    LSTM,
    LayerNorm,
    Linear,
    MultiHeadAttention,
    Pool2D,
    PRelu,
    RMSNorm,
    RowConv,
    SequenceConv,
    SpectralNorm,
    SyncBatchNorm,
    TreeConv,
    fused_ffn,
    tied_vocab_head,
)

from paddle_tpu.nn.heads import MultiBoxHead
from paddle_tpu.nn.scan import ScanLayers
from paddle_tpu.nn.moe import MoE, top_k_gating
from paddle_tpu.nn.rnn import (RNN, BeamSearchDecoder, Decoder, GRUCell,
                               LSTMCell, RNNCell, dynamic_decode)

Layer = Module  # reference naming alias (dygraph.Layer)
