"""Layer/Module API — dygraph parity, functional core.

Ref: /root/reference/python/paddle/fluid/dygraph/ (layers.py Layer,
nn.py modules). See nn/module.py for the programming model.
"""

from paddle_tpu.nn.module import Module, ModuleList, Sequential
from paddle_tpu.nn.layers import (
    BatchNorm,
    BilinearTensorProduct,
    Conv2D,
    Conv2DTranspose,
    Dropout,
    Embedding,
    GRU,
    GroupNorm,
    LSTM,
    LayerNorm,
    Linear,
    MultiHeadAttention,
    Pool2D,
    PRelu,
    RMSNorm,
    SpectralNorm,
    SyncBatchNorm,
)

Layer = Module  # reference naming alias (dygraph.Layer)
