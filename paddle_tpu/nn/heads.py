"""Detection heads (ref: layers/detection.py multi_box_head — the SSD
prior + loc/conf conv head over multiple feature maps).

The reference's function creates conv weights implicitly through
param_attr; here it is a Module (explicit params, functional apply), with
identical output contract: concatenated (mbox_locs [B, N, 4],
mbox_confs [B, N, C], prior_boxes [N, 4], variances [N, 4]).
"""

import jax.numpy as jnp

from paddle_tpu import initializer as I
from paddle_tpu.nn.layers import Conv2D
from paddle_tpu.nn.module import Module


def _num_priors(min_sizes, max_sizes, aspect_ratios, flip):
    from paddle_tpu.ops.detection import expand_aspect_ratios
    ars = expand_aspect_ratios(aspect_ratios, flip)
    per_min = 1 + len([a for a in ars if abs(a - 1.0) > 1e-6])
    n = len(min_sizes) * per_min
    if max_sizes:
        n += len(min_sizes)
    return n


class MultiBoxHead(Module):
    """SSD multi-box head (ref layers/detection.py multi_box_head).

    per_map_cfg: list of dicts, one per input feature map, each with
    min_sizes, max_sizes (or None), aspect_ratios; in_channels: list of
    input channel counts. base_size: input image size (h == w == base).
    """

    def __init__(self, in_channels, num_classes, per_map_cfg, base_size,
                 kernel_size=3, flip=True, clip=False,
                 variance=(0.1, 0.1, 0.2, 0.2), steps=None, offset=0.5,
                 min_max_aspect_ratios_order=False):
        super().__init__()
        self.num_classes = num_classes
        self.cfgs = per_map_cfg
        self.base_size = base_size
        self.flip, self.clip = flip, clip
        self.variance = tuple(variance)
        self.steps = steps
        self.offset = offset
        self.mmaro = min_max_aspect_ratios_order
        loc_convs, conf_convs, priors = [], [], []
        for ci, cfg in zip(in_channels, per_map_cfg):
            p = _num_priors(cfg["min_sizes"], cfg.get("max_sizes"),
                            cfg["aspect_ratios"], flip)
            priors.append(p)
            loc_convs.append(Conv2D(
                ci, p * 4, kernel_size, padding=(kernel_size - 1) // 2,
                weight_init=I.xavier()))
            conf_convs.append(Conv2D(
                ci, p * num_classes, kernel_size,
                padding=(kernel_size - 1) // 2, weight_init=I.xavier()))
        self.priors_per_map = priors
        # assign complete lists: Module.__setattr__ registers submodules
        # at assignment time
        self.loc_convs = loc_convs
        self.conf_convs = conf_convs

    def forward(self, inputs, image_shape=None):
        """inputs: list of NCHW feature maps. Returns (locs [B, N, 4],
        confs [B, N, C], boxes [N, 4], variances [N, 4])."""
        from paddle_tpu.ops.detection import prior_box
        ih = iw = self.base_size
        if image_shape is not None:
            ih, iw = image_shape
        locs, confs, boxes, vars_ = [], [], [], []
        for x, cfg, p, lc, cc in zip(inputs, self.cfgs,
                                     self.priors_per_map, self.loc_convs,
                                     self.conf_convs):
            b, _, fh, fw = x.shape
            loc = lc(x).transpose(0, 2, 3, 1).reshape(b, -1, 4)
            conf = cc(x).transpose(0, 2, 3, 1).reshape(
                b, -1, self.num_classes)
            if self.steps:
                # reference format: one scalar per map (or a (w, h) pair,
                # reference order); prior_box wants (step_h, step_w)
                st = self.steps[len(boxes)]
                st = ((st, st) if isinstance(st, (int, float))
                      else (st[1], st[0]))
            else:
                st = (0.0, 0.0)
            pb, pv = prior_box(
                (fh, fw), (ih, iw), cfg["min_sizes"],
                cfg.get("max_sizes"), cfg["aspect_ratios"],
                variance=self.variance, flip=self.flip, clip=self.clip,
                steps=st, offset=self.offset,
                min_max_aspect_ratios_order=self.mmaro)
            assert pb.shape[2] == p, (pb.shape, p)
            locs.append(loc)
            confs.append(conf)
            boxes.append(pb.reshape(-1, 4))
            vars_.append(pv.reshape(-1, 4))
        return (jnp.concatenate(locs, 1), jnp.concatenate(confs, 1),
                jnp.concatenate(boxes, 0), jnp.concatenate(vars_, 0))
