"""Standard layers — counterpart of the reference's dygraph nn modules and
static `fluid.layers` builders.

Ref: /root/reference/python/paddle/fluid/dygraph/nn.py:35-2930 (Conv2D,
Pool2D, FC, BatchNorm, Embedding, GRUUnit, LayerNorm, NCE, PRelu,
BilinearTensorProduct, Conv2DTranspose, SequenceConv, GroupNorm,
SpectralNorm, TreeConv) and python/paddle/fluid/layers/nn.py.
"""

import jax
import jax.numpy as jnp

from paddle_tpu import initializer as I
from paddle_tpu.nn.module import Module
from paddle_tpu.ops import activations as A
from paddle_tpu.ops import nn as F
from paddle_tpu.ops import rnn as R


def _act(name, x):
    if name is None:
        return x
    return getattr(A, name)(x)


def _int8_dot(x, q, scale, rhs_axis=0):
    """x contracted with an int8-resident kernel over x's last axis and
    q's rhs_axis, per-channel scale applied on the output — the one
    mixed-dtype dot all weight-only consumers share (quant.weight_only:
    exact because the scale axis is the non-contracted one)."""
    out = jax.lax.dot_general(
        x, q, (((x.ndim - 1,), (rhs_axis,)), ((), ())),
        preferred_element_type=x.dtype)
    return out * scale.astype(x.dtype)


class Linear(Module):
    """ref: dygraph/nn.py FC / Linear."""

    def __init__(self, in_features, out_features, bias=True, act=None,
                 weight_init=None, bias_init=None, dtype=jnp.float32):
        super().__init__()
        self.act = act
        self.has_bias = bias
        self.param("weight", (in_features, out_features),
                   weight_init or I.xavier(), dtype)
        if bias:
            self.param("bias", (out_features,), bias_init or I.zeros(), dtype)

    def forward(self, x):
        if self.has_p("weight_q"):
            # weight-only int8 serving (quant.weight_only): the kernel
            # stays int8 in HBM and the mixed-dtype dot reads it directly
            # (1/2 the bf16 bytes, 1/4 of f32)
            out = _int8_dot(x, self.p("weight_q"), self.p("weight_scale"))
        else:
            out = x @ self.p("weight")
        if self.has_bias:
            out = out + self.p("bias")
        return _act(self.act, out)


def fused_ffn(fc1, fc2, x, act="gelu"):
    """The transformer feed-forward ``fc2(act(fc1(x)))`` routed through
    the fused Pallas MLP kernel (ops/pallas/mlp.py) when it applies —
    the [rows, intermediate] activation never reaches HBM. Quantized
    layers (weight-only int8) and layers with their own fused activation
    keep the unfused path: the int8 mixed-dtype dot is its own kernel."""
    if (fc1.has_p("weight_q") or fc2.has_p("weight_q")
            or fc1.act is not None or fc2.act is not None):
        return fc2(_act(act, fc1(x)))
    from paddle_tpu.ops.pallas.mlp import fused_mlp
    return fused_mlp(x, fc1.p("weight"),
                     fc1.p("bias") if fc1.has_bias else None,
                     fc2.p("weight"),
                     fc2.p("bias") if fc2.has_bias else None, act=act)


class Conv2D(Module):
    """ref: dygraph/nn.py Conv2D — weight OIHW (NCHW) or HWIO (NHWC).

    TPU-first: with data_format='NHWC' the weight is stored physically in
    HWIO. This matters: on TPU, NHWC activations + HWIO weights run the conv
    ~3x faster than NCHW/OIHW (measured on v5e — XLA's layout assignment does
    not recover the fast path from NCHW-layouted operands). Initializer fan
    statistics are computed on the OIHW view either way.
    """

    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, bias=True, act=None,
                 weight_init=None, dtype=jnp.float32, data_format="NCHW"):
        super().__init__()
        k = (kernel_size, kernel_size) if isinstance(kernel_size, int) \
            else tuple(kernel_size)
        self.stride, self.padding, self.dilation, self.groups = \
            stride, padding, dilation, groups
        self.act = act
        self.has_bias = bias
        self.data_format = data_format
        oihw = (out_channels, in_channels // groups) + k
        w_init = weight_init or I.msra()
        if data_format == "NHWC":
            def hwio_init(key, shape, dtype=jnp.float32, _w=w_init, _s=oihw):
                return jnp.transpose(_w(key, _s, dtype), (2, 3, 1, 0))
            self.param("weight", k + (in_channels // groups, out_channels),
                       hwio_init, dtype)
        else:
            self.param("weight", oihw, w_init, dtype)
        if bias:
            self.param("bias", (out_channels,), I.zeros(), dtype)

    def forward(self, x):
        out = F.conv2d(x, self.p("weight"),
                       self.p("bias") if self.has_bias else None,
                       self.stride, self.padding, self.dilation, self.groups,
                       data_format=self.data_format)
        return _act(self.act, out)


class Conv2DTranspose(Module):
    """ref: dygraph/nn.py Conv2DTranspose — weight [in, out/groups, kh, kw]."""

    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, output_padding=0, dilation=1, groups=1, bias=True,
                 act=None, weight_init=None, dtype=jnp.float32):
        super().__init__()
        k = (kernel_size, kernel_size) if isinstance(kernel_size, int) \
            else tuple(kernel_size)
        self.stride, self.padding, self.dilation, self.groups = \
            stride, padding, dilation, groups
        self.output_padding = output_padding
        self.act = act
        self.has_bias = bias
        self.param("weight", (in_channels, out_channels // groups) + k,
                   weight_init or I.xavier(), dtype)
        if bias:
            self.param("bias", (out_channels,), I.zeros(), dtype)

    def forward(self, x):
        out = F.conv2d_transpose(
            x, self.p("weight"), self.p("bias") if self.has_bias else None,
            self.stride, self.padding, self.output_padding, self.dilation,
            self.groups)
        return _act(self.act, out)


class BatchNorm(Module):
    """ref: dygraph/nn.py BatchNorm + operators/batch_norm_op.cc. Running
    stats live in the 'state' collection, updated functionally."""

    def __init__(self, num_channels, momentum=0.9, epsilon=1e-5, act=None,
                 data_format="NCHW", dtype=jnp.float32):
        super().__init__()
        self.momentum, self.epsilon, self.act = momentum, epsilon, act
        self.data_format = data_format
        self.param("scale", (num_channels,), I.ones(), dtype)
        self.param("bias", (num_channels,), I.zeros(), dtype)
        self.state("mean", (num_channels,), I.zeros(), jnp.float32)
        self.state("variance", (num_channels,), I.ones(), jnp.float32)

    def forward(self, x):
        out, new_mean, new_var = F.batch_norm(
            x, self.p("scale"), self.p("bias"), self.s("mean"),
            self.s("variance"), self.epsilon, self.momentum,
            training=self.training, data_format=self.data_format)
        if self.training:
            self.update_state("mean", new_mean)
            self.update_state("variance", new_var)
        return _act(self.act, out)


class SyncBatchNorm(BatchNorm):
    """Cross-replica BN (ref: operators/sync_batch_norm_op.cu + BuildStrategy
    sync_batch_norm pass). Stats are all-reduced over the data-parallel mesh
    axis when running under shard_map/pjit."""

    def __init__(self, num_channels, momentum=0.9, epsilon=1e-5, act=None,
                 axis_name="dp", dtype=jnp.float32):
        super().__init__(num_channels, momentum, epsilon, act, dtype=dtype)
        self.axis_name = axis_name

    def forward(self, x):
        import jax
        if self.training:
            try:
                red = (0, 2, 3)
                m = jnp.mean(x, axis=red)
                m2 = jnp.mean(jnp.square(x), axis=red)
                m = jax.lax.pmean(m, self.axis_name)
                m2 = jax.lax.pmean(m2, self.axis_name)
                v = m2 - jnp.square(m)
            except NameError:  # not under a mapped axis — local BN
                return super().forward(x)
            inv = jax.lax.rsqrt(v + self.epsilon)
            shape = (1, -1, 1, 1)
            out = (x - m.reshape(shape)) * (inv * self.p("scale")).reshape(shape) \
                + self.p("bias").reshape(shape)
            n = x.size // x.shape[1]
            unbiased = v * n / max(n - 1, 1)
            self.update_state("mean", self.momentum * self.s("mean")
                              + (1 - self.momentum) * m)
            self.update_state("variance", self.momentum * self.s("variance")
                              + (1 - self.momentum) * unbiased)
            return _act(self.act, out)
        return super().forward(x)


class LayerNorm(Module):
    """ref: dygraph/nn.py LayerNorm."""

    def __init__(self, normalized_shape, epsilon=1e-5, scale=True, shift=True,
                 dtype=jnp.float32):
        super().__init__()
        if isinstance(normalized_shape, int):
            normalized_shape = (normalized_shape,)
        self.shape = tuple(normalized_shape)
        self.epsilon = epsilon
        self.has_scale, self.has_shift = scale, shift
        n = 1
        for d in self.shape:
            n *= d
        if scale:
            self.param("scale", (n,), I.ones(), dtype)
        if shift:
            self.param("bias", (n,), I.zeros(), dtype)

    def forward(self, x, residual=None):
        """With `residual`, computes ln(x + residual) in one fused HBM
        pass (Pallas add+LN kernel on TPU) — the transformer hot path."""
        begin = x.ndim - len(self.shape)
        scale = self.p("scale") if self.has_scale else None
        bias = self.p("bias") if self.has_shift else None
        if residual is not None:
            from paddle_tpu.ops.pallas.layer_norm import add_layer_norm_fused
            return add_layer_norm_fused(x, residual, scale, bias,
                                        begin_norm_axis=begin,
                                        epsilon=self.epsilon)
        return F.layer_norm(x, scale, bias, begin_norm_axis=begin,
                            epsilon=self.epsilon)


class RMSNorm(Module):
    def __init__(self, dim, epsilon=1e-6, dtype=jnp.float32):
        super().__init__()
        self.epsilon = epsilon
        self.param("scale", (dim,), I.ones(), dtype)

    def forward(self, x):
        return F.rms_norm(x, self.p("scale"), self.epsilon)


class GroupNorm(Module):
    """ref: dygraph/nn.py GroupNorm."""

    def __init__(self, channels, groups=32, epsilon=1e-5, dtype=jnp.float32):
        super().__init__()
        self.groups, self.epsilon = groups, epsilon
        self.param("scale", (channels,), I.ones(), dtype)
        self.param("bias", (channels,), I.zeros(), dtype)

    def forward(self, x):
        return F.group_norm(x, self.p("scale"), self.p("bias"), self.groups,
                            self.epsilon)


class Embedding(Module):
    """ref: dygraph/nn.py Embedding + operators/lookup_table_op.cc."""

    def __init__(self, num_embeddings, embedding_dim, padding_idx=None,
                 weight_init=None, dtype=jnp.float32):
        super().__init__()
        self.padding_idx = padding_idx
        self.param("weight", (num_embeddings, embedding_dim),
                   weight_init or I.normal(0.0, 0.02), dtype)

    def forward(self, ids):
        if self.has_p("weight_q"):
            # weight-only int8 table (per-ROW scale, axis 0): gather the
            # int8 rows from HBM, dequantize the gathered slice only.
            # The scale carries the original table dtype, so a bf16
            # model's activation path stays bf16. ids are normalized ONCE
            # (lookup_table's trailing-1 squeeze) so the row gather and
            # the scale gather can never disagree on indexing.
            idx = (jnp.squeeze(ids, -1)
                   if ids.ndim > 1 and ids.shape[-1] == 1 else ids)
            rows = F.lookup_table(idx, self.p("weight_q"), self.padding_idx)
            s = self.p("weight_scale")
            return rows.astype(s.dtype) * jnp.take(s, idx, axis=0)[..., None]
        return F.lookup_table(ids, self.p("weight"), self.padding_idx)


def tied_vocab_head(emb, x):
    """Weight-tied vocab projection x @ W.T over an Embedding's table
    (BERT/GPT heads). With a weight-only int8 table (quant.weight_only:
    per-row scale) the dot reads the int8 table directly and the row
    scale lands on the logit axis — exact:
    x @ (q*s[:,None]).T == (x @ q.T) * s[None,:]."""
    if emb.has_p("weight_q"):
        return _int8_dot(x, emb.p("weight_q"), emb.p("weight_scale"),
                         rhs_axis=1)
    return x @ emb.p("weight").T


class Dropout(Module):
    """ref: operators/dropout_op.cc; PRNG key from apply(rngs=...)."""

    def __init__(self, rate=0.5, mode="upscale_in_train"):
        super().__init__()
        self.rate, self.mode = rate, mode

    def forward(self, x):
        if not self.training or self.rate == 0.0:
            return F.dropout(x, None, self.rate, training=False,
                             mode=self.mode)
        return F.dropout(x, self.rng("dropout"), self.rate, training=True,
                         mode=self.mode)


class Pool2D(Module):
    """ref: dygraph/nn.py Pool2D."""

    def __init__(self, pool_size=2, pool_type="max", pool_stride=None,
                 pool_padding=0, global_pooling=False):
        super().__init__()
        self.args = (pool_size, pool_type, pool_stride, pool_padding,
                     global_pooling)

    def forward(self, x):
        ps, pt, st, pd, gp = self.args
        return F.pool2d(x, ps, pt, st, pd, global_pooling=gp)


class PRelu(Module):
    """ref: dygraph/nn.py PRelu."""

    def __init__(self, mode="all", channels=None, dtype=jnp.float32):
        super().__init__()
        shape = (1,) if mode == "all" else (channels,)
        self.mode = mode
        self.param("alpha", shape, I.constant(0.25), dtype)

    def forward(self, x):
        a = self.p("alpha")
        if self.mode == "channel":
            a = a.reshape(1, -1, *([1] * (x.ndim - 2)))
        return jnp.where(x >= 0, x, a * x)


class BilinearTensorProduct(Module):
    """ref: dygraph/nn.py BilinearTensorProduct."""

    def __init__(self, in1_features, in2_features, out_features,
                 dtype=jnp.float32):
        super().__init__()
        self.param("weight", (out_features, in1_features, in2_features),
                   I.xavier(), dtype)
        self.param("bias", (out_features,), I.zeros(), dtype)

    def forward(self, x, y):
        out = jnp.einsum("bi,oij,bj->bo", x, self.p("weight"), y)
        return out + self.p("bias")


class SpectralNorm(Module):
    """Spectral normalization of a weight (ref: operators/spectral_norm_op.cc).
    Power-iteration vectors are mutable state."""

    def __init__(self, weight_shape, dim=0, power_iters=1, eps=1e-12,
                 dtype=jnp.float32):
        super().__init__()
        self.dim, self.power_iters, self.eps = dim, power_iters, eps
        h = weight_shape[dim]
        w = 1
        for i, d in enumerate(weight_shape):
            if i != dim:
                w *= d
        self.h, self.w = h, w
        self.state("u", (h,), I.normal(0, 1), dtype)
        self.state("v", (w,), I.normal(0, 1), dtype)

    def forward(self, weight):
        from paddle_tpu.ops.tail import spectral_norm as _sn_op
        normed, u, v = _sn_op(weight, self.s("u"), self.s("v"),
                              dim=self.dim, power_iters=self.power_iters,
                              eps=self.eps)
        if self.training:
            # cast back to the declared state dtype: the op promotes u/v to
            # the weight dtype, and a drifting state pytree dtype breaks
            # scan carries / donated buffers (same invariant as Adam slots)
            self.update_state("u", u.astype(self.s("u").dtype))
            self.update_state("v", v.astype(self.s("v").dtype))
        return normed


class LSTM(Module):
    """Multi-layer LSTM (ref: operators/cudnn_lstm_op.cu capabilities)."""

    def __init__(self, input_size, hidden_size, num_layers=1,
                 bidirectional=False, dtype=jnp.float32):
        super().__init__()
        self.hidden_size, self.num_layers = hidden_size, num_layers
        self.bidirectional = bidirectional
        ndir = 2 if bidirectional else 1
        for layer in range(num_layers):
            isz = input_size if layer == 0 else hidden_size * ndir
            for d in range(ndir):
                sfx = f"l{layer}d{d}"
                self.param(f"w_ih_{sfx}", (isz, 4 * hidden_size), I.xavier(), dtype)
                self.param(f"w_hh_{sfx}", (hidden_size, 4 * hidden_size),
                           I.xavier(), dtype)
                self.param(f"b_{sfx}", (4 * hidden_size,), I.zeros(), dtype)

    def forward(self, x, lengths=None):
        b = x.shape[0]
        h0 = jnp.zeros((b, self.hidden_size), x.dtype)
        c0 = jnp.zeros((b, self.hidden_size), x.dtype)
        out = x
        last_h, last_c = [], []
        for layer in range(self.num_layers):
            if self.bidirectional:
                sf, sb = f"l{layer}d0", f"l{layer}d1"
                of, (hf, cf) = R.lstm(out, h0, c0, self.p(f"w_ih_{sf}"),
                                      self.p(f"w_hh_{sf}"), self.p(f"b_{sf}"),
                                      lengths=lengths)
                ob, (hb, cb) = R.lstm(out, h0, c0, self.p(f"w_ih_{sb}"),
                                      self.p(f"w_hh_{sb}"), self.p(f"b_{sb}"),
                                      lengths=lengths, reverse=True)
                out = jnp.concatenate([of, ob], -1)
                last_h += [hf, hb]
                last_c += [cf, cb]
            else:
                s = f"l{layer}d0"
                out, (h, c) = R.lstm(out, h0, c0, self.p(f"w_ih_{s}"),
                                     self.p(f"w_hh_{s}"), self.p(f"b_{s}"),
                                     lengths=lengths)
                last_h.append(h)
                last_c.append(c)
        return out, (jnp.stack(last_h), jnp.stack(last_c))


class GRU(Module):
    """ref: dygraph/nn.py GRUUnit generalized to multi-step (+bidirectional
    like the reference's stacked fwd/bwd gru pattern in book models)."""

    def __init__(self, input_size, hidden_size, num_layers=1,
                 bidirectional=False, dtype=jnp.float32):
        super().__init__()
        self.hidden_size, self.num_layers = hidden_size, num_layers
        self.bidirectional = bidirectional
        ndir = 2 if bidirectional else 1
        for layer in range(num_layers):
            isz = input_size if layer == 0 else hidden_size * ndir
            for d in range(ndir):
                sfx = f"l{layer}d{d}"
                self.param(f"w_ih_{sfx}", (isz, 3 * hidden_size), I.xavier(),
                           dtype)
                self.param(f"w_hh_{sfx}", (hidden_size, 3 * hidden_size),
                           I.xavier(), dtype)
                self.param(f"b_ih_{sfx}", (3 * hidden_size,), I.zeros(), dtype)
                self.param(f"b_hh_{sfx}", (3 * hidden_size,), I.zeros(), dtype)

    def forward(self, x, lengths=None):
        b = x.shape[0]
        h0 = jnp.zeros((b, self.hidden_size), x.dtype)
        out = x
        last = []
        for layer in range(self.num_layers):
            if self.bidirectional:
                sf, sb = f"l{layer}d0", f"l{layer}d1"
                of, hf = R.gru(out, h0, self.p(f"w_ih_{sf}"),
                               self.p(f"w_hh_{sf}"), self.p(f"b_ih_{sf}"),
                               self.p(f"b_hh_{sf}"), lengths=lengths)
                ob, hb = R.gru(out, h0, self.p(f"w_ih_{sb}"),
                               self.p(f"w_hh_{sb}"), self.p(f"b_ih_{sb}"),
                               self.p(f"b_hh_{sb}"), lengths=lengths,
                               reverse=True)
                out = jnp.concatenate([of, ob], -1)
                last += [hf, hb]
            else:
                s = f"l{layer}d0"
                out, h = R.gru(out, h0, self.p(f"w_ih_{s}"),
                               self.p(f"w_hh_{s}"), self.p(f"b_ih_{s}"),
                               self.p(f"b_hh_{s}"), lengths=lengths)
                last.append(h)
        return out, jnp.stack(last)


class MultiHeadAttention(Module):
    """Fused MHA layer (ref: ir/multihead_matmul_fuse_pass.h semantics)."""

    def __init__(self, embed_dim, num_heads, dropout=0.0, bias=True,
                 use_flash=False, dtype=jnp.float32):
        super().__init__()
        self.num_heads, self.dropout_rate = num_heads, dropout
        self.use_flash = use_flash
        self.has_bias = bias
        for n in ("q", "k", "v", "o"):
            self.param(f"w{n}", (embed_dim, embed_dim), I.xavier(), dtype)
            if bias:
                self.param(f"b{n}", (embed_dim,), I.zeros(), dtype)

    def _w(self, n):
        """Projection kernel, dequantized if weight-only int8 (the full
        forward runs once per sequence, so a materialized dequant is
        fine; decode_step keeps the int8-resident mixed-dot path)."""
        if self.has_p(f"w{n}_q"):
            q, s = self.p(f"w{n}_q"), self.p(f"w{n}_scale")
            return q.astype(s.dtype) * s[None, :]
        return self.p(f"w{n}")

    def _project(self, x, n):
        """x @ w{n} (+ bias) over the last axis; consumes int8-resident
        kernels via the shared mixed-dtype dot when weight-only
        quantized."""
        if self.has_p(f"w{n}_q"):
            out = _int8_dot(x, self.p(f"w{n}_q"), self.p(f"w{n}_scale"))
        else:
            out = x @ self.p(f"w{n}")
        if self.has_bias:
            out = out + self.p(f"b{n}")
        return out

    def prefill(self, x, cache, start=0):
        """Batched cache fill: project the WHOLE prompt in one pass,
        write its K/V into the cache at [0, T), and return the causal
        self-attention output — one forward instead of T sequential
        decode_steps (the serving prefill/decode split; no reference
        counterpart: Fluid's decoders re-ran the network per step).
        x: [B, T, E] -> (out [B, T, E], new_cache). Long prompts ride
        the Pallas flash kernel when use_flash is set (O(T) memory,
        like forward)."""
        from jax import lax as _lax
        if start != 0:
            # chunked prefill would need attention over the cached prefix
            # plus a shifted causal mask — not implemented; failing loudly
            # beats silently ignoring the prefix
            raise NotImplementedError(
                "MultiHeadAttention.prefill only supports start=0 "
                "(whole-prompt prefill); decode_step handles the rest")
        b, t, e = x.shape
        hd = e // self.num_heads

        def heads(y):
            return y.reshape(b, t, self.num_heads, hd).transpose(0, 2, 1, 3)

        q = heads(self._project(x, "q"))
        k = heads(self._project(x, "k"))
        v = heads(self._project(x, "v"))
        cache = {
            "k": _lax.dynamic_update_slice(
                cache["k"], k.astype(cache["k"].dtype), (0, 0, 0, 0)),
            "v": _lax.dynamic_update_slice(
                cache["v"], v.astype(cache["v"].dtype), (0, 0, 0, 0)),
        }
        if self.use_flash:
            from paddle_tpu.ops.pallas.flash_attention import \
                flash_attention
            ctx = flash_attention(q, k, v, causal=True)
        else:
            from paddle_tpu.ops.attention import \
                scaled_dot_product_attention
            ctx = scaled_dot_product_attention(q, k, v, causal=True)
        out = ctx.transpose(0, 2, 1, 3).reshape(b, t, e)
        return self._project(out, "o"), cache

    def forward(self, x, kv=None, mask=None, causal=False, seq_axis=None):
        from paddle_tpu.ops.attention import multihead_attention
        key = self.rng("dropout") if (self.training and self.dropout_rate > 0) \
            else None
        return multihead_attention(
            x, self._w("q"), self._w("k"), self._w("v"), self._w("o"),
            self.p("bq") if self.has_bias else None,
            self.p("bk") if self.has_bias else None,
            self.p("bv") if self.has_bias else None,
            self.p("bo") if self.has_bias else None,
            num_heads=self.num_heads, mask=mask, causal=causal, kv=kv,
            dropout_rate=self.dropout_rate if self.training else 0.0,
            dropout_key=key, use_flash=self.use_flash, seq_axis=seq_axis)

    def init_cache(self, batch, max_len, dtype=jnp.float32):
        """KV cache for incremental decoding: {k, v} [B, H, Tmax, hd]."""
        e = (self.p("wq_q") if self.has_p("wq_q")
             else self.p("wq")).shape[0]
        hd = e // self.num_heads
        shape = (batch, self.num_heads, max_len, hd)
        return {"k": jnp.zeros(shape, dtype), "v": jnp.zeros(shape, dtype)}

    def decode_step(self, x_t, cache, pos, causal=True):
        """One incremental step: project the new token(s), write K/V into
        the cache at `pos`, attend over positions <= pos. x_t: [B, 1, E];
        pos: scalar int (dynamic ok). Returns (out [B, 1, E], new_cache).

        O(1) projection per step — the full-sequence K/V projections are
        never recomputed (the KV-cache serving pattern; no reference
        counterpart: Fluid decoded via beam_search ops re-running the
        whole decoder per step)."""
        from jax import lax as _lax
        b, one, e = x_t.shape
        hd = e // self.num_heads

        def proj(n):
            return self._project(x_t, n).reshape(
                b, 1, self.num_heads, hd).transpose(
                0, 2, 1, 3)                            # [B, H, 1, hd]

        q = proj("q")
        k_t = proj("k").astype(cache["k"].dtype)
        v_t = proj("v").astype(cache["v"].dtype)
        k = _lax.dynamic_update_slice(cache["k"], k_t, (0, 0, pos, 0))
        v = _lax.dynamic_update_slice(cache["v"], v_t, (0, 0, pos, 0))
        scores = jnp.einsum("bhqd,bhkd->bhqk", q, k) / (hd ** 0.5)
        if causal:
            valid = jnp.arange(k.shape[2]) <= pos      # [Tmax]
            scores = jnp.where(valid[None, None, None, :], scores, -1e9)
        probs = jnp.exp(scores - jax.nn.logsumexp(
            scores, axis=-1, keepdims=True))
        ctx = jnp.einsum("bhqk,bhkd->bhqd", probs, v)
        ctx = ctx.transpose(0, 2, 1, 3).reshape(b, 1, e)
        return self._project(ctx, "o"), {"k": k, "v": v}

    # --- paged KV cache (serving fast path; ops/attention.py layout) ---

    def init_page_pool(self, num_pages, page_size, dtype=jnp.float32,
                       kv_dtype=None):
        """This layer's slice of the paged serving cache:
        {"k","v"} [num_pages, H, page_size, hd] (plus per-row
        {"k_scale","v_scale"} for kv_dtype=int8). Reads the embed dim
        from the declaration (ParamSpec), so it works outside apply() —
        the serving engine allocates pools before any forward runs."""
        from paddle_tpu.ops.attention import init_page_pool
        hd = self._params["wq"].shape[0] // self.num_heads
        return init_page_pool(num_pages, self.num_heads, page_size, hd,
                              dtype, kv_dtype=kv_dtype)

    def paged_decode_step(self, x_t, pool, page_table, att_lengths,
                          write_pages, write_offsets):
        """One incremental step against the paged cache. x_t: [S, 1, E]
        (one pending token per slot); page_table: [S, Pmax] int32;
        att_lengths: [S] valid tokens INCLUDING the one written now;
        write_pages/write_offsets: [S] destination of the new K/V
        (out-of-range page id = drop, for inactive slots).
        Returns (out [S, 1, E], new_pool)."""
        from paddle_tpu.ops.attention import (paged_decode_attention,
                                              paged_write)
        s, one, e = x_t.shape
        hd = e // self.num_heads

        def proj(n):
            return self._project(x_t, n).reshape(s, self.num_heads, hd)

        q = proj("q")
        pool = paged_write(pool, proj("k"), proj("v"), write_pages,
                           write_offsets)
        ctx = paged_decode_attention(q, pool["k"], pool["v"], page_table,
                                     att_lengths,
                                     k_scale=pool.get("k_scale"),
                                     v_scale=pool.get("v_scale"))
        return self._project(ctx.reshape(s, 1, e), "o"), pool

    def paged_prefill(self, x, pool, page_ids, offsets):
        """Batched prompt fill into pages: one causal forward over the
        (padded) prompt, K/V scattered to (page_ids, offsets) per
        position ([B, T] int32; out-of-range page id drops the write —
        how pad positions are discarded). Returns (out [B, T, E],
        new_pool). Causal masking alone keeps pad-at-the-end garbage out
        of every valid position's context."""
        from paddle_tpu.ops.attention import paged_write
        b, t, e = x.shape
        hd = e // self.num_heads

        def heads(y):
            return y.reshape(b, t, self.num_heads, hd).transpose(0, 2, 1, 3)

        q = heads(self._project(x, "q"))
        k = heads(self._project(x, "k"))
        v = heads(self._project(x, "v"))
        pool = paged_write(
            pool,
            k.transpose(0, 2, 1, 3).reshape(b * t, self.num_heads, hd),
            v.transpose(0, 2, 1, 3).reshape(b * t, self.num_heads, hd),
            page_ids.reshape(b * t), offsets.reshape(b * t))
        if self.use_flash:
            from paddle_tpu.ops.pallas.flash_attention import \
                flash_attention
            ctx = flash_attention(q, k, v, causal=True)
        else:
            from paddle_tpu.ops.attention import \
                scaled_dot_product_attention
            ctx = scaled_dot_product_attention(q, k, v, causal=True)
        out = ctx.transpose(0, 2, 1, 3).reshape(b, t, e)
        return self._project(out, "o"), pool

    def paged_prefill_chunk(self, x, pool, page_ids, offsets, page_rows,
                            q_pos, chunked):
        """Chunked-admission twin of paged_prefill: K/V of this chunk is
        written exactly as there, but a continuation chunk (chunked[b] =
        True, absolute start > 0) must attend over EVERY token its slot
        has cached so far — so its context is recomputed by gathering the
        slot's whole page table (page_rows: [B, Pmax]) and masking keys
        by absolute position (q_pos: [B, T]). First chunks keep the
        in-chunk causal path, selected per request by jnp.where, so
        single-chunk admissions stay bit-exact with paged_prefill.
        Prefill is admission-rate work; the dense [T, Pmax*ps] score
        temporary never appears on the decode hot path."""
        from paddle_tpu.ops.attention import NEG_INF, paged_write
        b, t, e = x.shape
        hd = e // self.num_heads

        def heads(y):
            return y.reshape(b, t, self.num_heads, hd).transpose(0, 2, 1, 3)

        q = heads(self._project(x, "q"))
        k = heads(self._project(x, "k"))
        v = heads(self._project(x, "v"))
        pool = paged_write(
            pool,
            k.transpose(0, 2, 1, 3).reshape(b * t, self.num_heads, hd),
            v.transpose(0, 2, 1, 3).reshape(b * t, self.num_heads, hd),
            page_ids.reshape(b * t), offsets.reshape(b * t))
        if self.use_flash:
            from paddle_tpu.ops.pallas.flash_attention import \
                flash_attention
            ctx = flash_attention(q, k, v, causal=True)
        else:
            from paddle_tpu.ops.attention import \
                scaled_dot_product_attention
            ctx = scaled_dot_product_attention(q, k, v, causal=True)
        # full-history path: pool pages were just updated with this
        # chunk, so the gather sees prefix + chunk at absolute positions
        # (int8 pools dequantize the gathered pages through the same
        # per-row scales the decode kernel reads)
        tk = page_rows.shape[1] * pool["k"].shape[2]
        kg, vg = pool["k"][page_rows], pool["v"][page_rows]
        if "k_scale" in pool:
            from paddle_tpu.ops.attention import dequantize_pages
            kg = dequantize_pages(kg, pool["k_scale"][page_rows])
            vg = dequantize_pages(vg, pool["v_scale"][page_rows])
        kf = jnp.moveaxis(kg, 2, 1).reshape(b, self.num_heads, tk, hd)
        vf = jnp.moveaxis(vg, 2, 1).reshape(b, self.num_heads, tk, hd)
        scores = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                            kf.astype(jnp.float32)) / (hd ** 0.5)
        keep = (jnp.arange(tk)[None, None, None, :]
                <= q_pos[:, None, :, None])
        scores = jnp.where(keep, scores, NEG_INF)
        m = jnp.max(scores, axis=-1, keepdims=True)
        p = jnp.where(keep, jnp.exp(scores - m), 0.0)
        l = jnp.sum(p, axis=-1, keepdims=True)
        full = jnp.einsum("bhqk,bhkd->bhqd", p, vf.astype(jnp.float32))
        full = jnp.where(l > 0, full / jnp.maximum(l, 1e-30), 0.0)
        ctx = jnp.where(chunked[:, None, None, None],
                        full.astype(ctx.dtype), ctx)
        out = ctx.transpose(0, 2, 1, 3).reshape(b, t, e)
        return self._project(out, "o"), pool


class FC(Linear):
    """ref: dygraph/nn.py FC — Linear with num_flatten_dims semantics."""

    def __init__(self, in_features, out_features, num_flatten_dims=1, **kw):
        super().__init__(in_features, out_features, **kw)
        self.num_flatten_dims = num_flatten_dims

    def forward(self, x):
        out = F.fc(x, self.p("weight"),
                   self.p("bias") if self.has_bias else None,
                   num_flatten_dims=self.num_flatten_dims)
        return _act(self.act, out)


class Conv3D(Module):
    """ref: dygraph/nn.py Conv3D — weight OIDHW."""

    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, bias=True, act=None,
                 weight_init=None, dtype=jnp.float32):
        super().__init__()
        k = (kernel_size,) * 3 if isinstance(kernel_size, int) \
            else tuple(kernel_size)
        self.stride, self.padding, self.dilation, self.groups = \
            stride, padding, dilation, groups
        self.act = act
        self.has_bias = bias
        self.param("weight", (out_channels, in_channels // groups) + k,
                   weight_init or I.msra(), dtype)
        if bias:
            self.param("bias", (out_channels,), I.zeros(), dtype)

    def forward(self, x):
        out = F.conv3d(x, self.p("weight"),
                       self.p("bias") if self.has_bias else None,
                       self.stride, self.padding, self.dilation, self.groups)
        return _act(self.act, out)


class GRUUnit(Module):
    """ref: dygraph/nn.py GRUUnit — one GRU step over [B, I] + [B, H];
    origin_mode as in gru_unit_op.h (False default, h' = z*n + (1-z)*h)."""

    def __init__(self, input_size, hidden_size, bias=True,
                 origin_mode=False, dtype=jnp.float32):
        super().__init__()
        self.hidden_size = hidden_size
        self.has_bias = bias
        self.origin_mode = origin_mode
        self.param("w_ih", (input_size, 3 * hidden_size), I.xavier(), dtype)
        self.param("w_hh", (hidden_size, 3 * hidden_size), I.xavier(), dtype)
        if bias:
            self.param("b_ih", (3 * hidden_size,), I.zeros(), dtype)
            self.param("b_hh", (3 * hidden_size,), I.zeros(), dtype)

    def forward(self, x, h):
        return R.gru_cell(x, h, self.p("w_ih"), self.p("w_hh"),
                          self.p("b_ih") if self.has_bias else None,
                          self.p("b_hh") if self.has_bias else None,
                          origin_mode=self.origin_mode)


class NCE(Module):
    """ref: dygraph/nn.py NCE — noise-contrastive estimation head."""

    def __init__(self, dim, num_total_classes, num_neg_samples=10,
                 dtype=jnp.float32):
        super().__init__()
        self.num_total_classes = num_total_classes
        self.num_neg_samples = num_neg_samples
        self.param("weight", (num_total_classes, dim), I.xavier(), dtype)
        self.param("bias", (num_total_classes,), I.zeros(), dtype)

    def forward(self, input, label):
        from paddle_tpu.ops import loss as L_
        key = self.rng("nce")
        return L_.nce_loss(key, input, label, self.p("weight"),
                           self.p("bias"), self.num_total_classes,
                           self.num_neg_samples)


class SequenceConv(Module):
    """ref: dygraph/nn.py SequenceConv — context-window conv over a
    RaggedBatch."""

    def __init__(self, in_dim, out_dim, context_length=3, context_start=-1,
                 bias=True, act=None, dtype=jnp.float32):
        super().__init__()
        self.context_length = context_length
        self.context_start = context_start
        self.act = act
        self.has_bias = bias
        self.param("filter", (context_length * in_dim, out_dim),
                   I.xavier(), dtype)
        if bias:
            self.param("bias", (out_dim,), I.zeros(), dtype)

    def forward(self, rb, max_len=None):
        from paddle_tpu.core.ragged import RaggedBatch
        from paddle_tpu.ops import sequence as S
        out = S.sequence_conv(rb, self.p("filter"), self.context_start,
                              self.context_length,
                              self.p("bias") if self.has_bias else None,
                              max_len=max_len)
        if self.act is not None:
            out = RaggedBatch(_act(self.act, out.values), out.row_lengths)
        return out


class RowConv(Module):
    """ref: dygraph/nn.py RowConv — lookahead conv over a RaggedBatch."""

    def __init__(self, dim, future_context=2, dtype=jnp.float32):
        super().__init__()
        self.param("filter", (future_context + 1, dim), I.xavier(), dtype)

    def forward(self, rb, max_len=None):
        from paddle_tpu.ops import sequence as S
        return S.row_conv(rb, self.p("filter"), max_len=max_len)


class TreeConv(Module):
    """ref: dygraph/nn.py TreeConv — TBCNN over (nodes, edges), with the
    reference's optional [num_filters] bias."""

    def __init__(self, feature_size, output_size, num_filters, max_depth=2,
                 act=None, bias=True, dtype=jnp.float32):
        super().__init__()
        self.max_depth = max_depth
        self.act = act
        self.has_bias = bias
        self.param("filter", (feature_size, 3, output_size, num_filters),
                   I.xavier(), dtype)
        if bias:
            self.param("bias", (num_filters,), I.zeros(), dtype)

    def build_coef(self, edge_set, n_nodes):
        """Host-side tree2col using THIS layer's max_depth — use this so
        the coefficient depth can't drift from the layer config."""
        import numpy as np
        from paddle_tpu.ops.graph import tree_patch_coefficients
        return tree_patch_coefficients(np.asarray(edge_set), n_nodes,
                                       self.max_depth)

    def forward(self, nodes_vector, coef):
        """coef from self.build_coef(edge_set) (host-built)."""
        from paddle_tpu.ops.graph import tree_conv
        out = tree_conv(nodes_vector, coef, self.p("filter"))
        if self.has_bias:
            out = out + self.p("bias")
        return _act(self.act, out)
