"""Composable RNN cells + decoding protocol.

Ref: /root/reference/python/paddle/fluid/layers/rnn.py:30-960 — the
RNNCell protocol (`call(inputs, states)`, `get_initial_states`,
`state_shape`), GRUCell:144 / LSTMCell:214, the `rnn()` driver :278, the
Decoder protocol :391 (initialize/step/finalize), BeamSearchDecoder:440
and dynamic_decode:791. That stack lets a user plug ANY custom cell into
beam search; the functional twins (`ops/rnn.py` lstm/gru/beam_search_*)
cover the fused fast path, this module restores the pluggable protocol.

TPU-first: everything static-shape, `dynamic_decode` is one `lax.scan` to
`max_step_num` with a `finished` mask (the reference's while_op +
LoD-array writes become a masked scan); `BeamSearchDecoder.step` reuses
the static `beam_search_step` op inside the scan.
"""

import jax
import jax.numpy as jnp
from jax import lax

from paddle_tpu import initializer as I
from paddle_tpu.nn.module import Module
from paddle_tpu.ops.rnn import beam_search_step, gru_cell, lstm_cell


def _tmap(f, *trees):
    return jax.tree_util.tree_map(f, *trees)


class RNNCell(Module):
    """Cell protocol (ref rnn.py:30 RNNCell): subclass and implement
    `forward(inputs, states) -> (outputs, new_states)` plus `state_shape`
    (a pytree of per-example state shapes, batch dim excluded). Any such
    cell drives `RNN`, `BeamSearchDecoder` and `dynamic_decode`."""

    @property
    def state_shape(self):
        raise NotImplementedError(
            f"{type(self).__name__} must define state_shape")

    def get_initial_states(self, batch_size, dtype=jnp.float32):
        """Zero states shaped [batch, *shape] (ref rnn.py:66). A shape
        leaf is a tuple of ints — e.g. LSTM's ((H,), (H,)) is a pair of
        shape leaves, GRU's (H,) a single one."""
        def is_shape(x):
            return isinstance(x, tuple) and \
                all(isinstance(i, int) for i in x)

        return jax.tree_util.tree_map(
            lambda s: jnp.zeros((batch_size,) + tuple(s), dtype),
            self.state_shape, is_leaf=is_shape)


class GRUCell(RNNCell):
    """ref rnn.py:144 GRUCell (origin_mode False = the gru op's default)."""

    def __init__(self, input_size, hidden_size, dtype=jnp.float32):
        super().__init__()
        self.hidden_size = hidden_size
        self.param("w_ih", (input_size, 3 * hidden_size), I.xavier(), dtype)
        self.param("w_hh", (hidden_size, 3 * hidden_size), I.xavier(), dtype)
        self.param("b_ih", (3 * hidden_size,), I.zeros(), dtype)
        self.param("b_hh", (3 * hidden_size,), I.zeros(), dtype)

    @property
    def state_shape(self):
        return (self.hidden_size,)

    def forward(self, inputs, states):
        h = gru_cell(inputs, states, self.p("w_ih"), self.p("w_hh"),
                     self.p("b_ih"), self.p("b_hh"))
        return h, h


class LSTMCell(RNNCell):
    """ref rnn.py:214 LSTMCell; states = (h, c)."""

    def __init__(self, input_size, hidden_size, forget_bias=0.0,
                 dtype=jnp.float32):
        super().__init__()
        self.hidden_size = hidden_size
        self.forget_bias = forget_bias
        self.param("w_ih", (input_size, 4 * hidden_size), I.xavier(), dtype)
        self.param("w_hh", (hidden_size, 4 * hidden_size), I.xavier(), dtype)
        self.param("b", (4 * hidden_size,), I.zeros(), dtype)

    @property
    def state_shape(self):
        return ((self.hidden_size,), (self.hidden_size,))

    def forward(self, inputs, states):
        h, c = states
        h, c = lstm_cell(inputs, h, c, self.p("w_ih"), self.p("w_hh"),
                         self.p("b"), forget_bias=self.forget_bias)
        return h, (h, c)


class RNN(Module):
    """Drive any RNNCell over a time axis (ref rnn.py:278 `rnn()`).
    x: [B, T, D] -> (outputs [B, T, H...], final_states). `lengths` masks
    padded steps (state freezes past a sequence's end, like the
    reference's sequence_length handling)."""

    def __init__(self, cell, is_reverse=False):
        super().__init__()
        self.cell = cell
        self.is_reverse = is_reverse

    def forward(self, x, initial_states=None, lengths=None):
        b, t = x.shape[0], x.shape[1]
        states = (initial_states if initial_states is not None
                  else self.cell.get_initial_states(b, x.dtype))
        xs = jnp.moveaxis(x, 1, 0)                       # [T, B, D]
        if self.is_reverse:
            xs = xs[::-1]
        steps = jnp.arange(t - 1, -1, -1) if self.is_reverse \
            else jnp.arange(t)

        def step(states, inp):
            x_t, t_i = inp
            out, new_states = self.cell(x_t, states)
            if lengths is not None:
                valid = (t_i < lengths)
                new_states = _tmap(
                    lambda n, o: jnp.where(
                        valid.reshape((-1,) + (1,) * (n.ndim - 1)), n, o),
                    new_states, states)
                out = out * valid.reshape(
                    (-1,) + (1,) * (out.ndim - 1)).astype(out.dtype)
            return new_states, out

        states, outs = lax.scan(step, states, (xs, steps))
        if self.is_reverse:
            outs = outs[::-1]
        return jnp.moveaxis(outs, 0, 1), states


class Decoder:
    """Decoding protocol (ref rnn.py:391): initialize() -> (inputs,
    states, finished); step(time, inputs, states) -> (outputs, states,
    next_inputs, finished). Drive with `dynamic_decode`."""

    def initialize(self, inits):
        raise NotImplementedError

    def step(self, time, inputs, states):
        raise NotImplementedError

    def finalize(self, outputs, final_states):
        """Post-process stacked per-step outputs (identity by default)."""
        return outputs, final_states


class BeamSearchDecoder(Decoder):
    """Beam search over ANY RNNCell (ref rnn.py:440).

    cell: an RNNCell; embedding_fn(token_ids [N]) -> [N, D] step inputs;
    output_fn(cell_out [N, H]) -> [N, V] logits (the projection to vocab).
    The decoder tiles every state/batch tensor to batch*beam rows and
    reuses the static `beam_search_step` op for selection; gather of
    parent beams rides jnp.take along the flat row axis.
    """

    def __init__(self, cell, start_token, end_token, beam_size,
                 embedding_fn, output_fn, vocab_size, cell_variables=None):
        self.cell = cell
        self.cell_variables = cell_variables
        self.start_token = start_token
        self.end_token = end_token
        self.beam_size = beam_size
        self.embedding_fn = embedding_fn
        self.output_fn = output_fn
        self.vocab_size = vocab_size

    def _run_cell(self, x, states):
        """Invoke the cell: through apply() with its own variables when
        given (standalone decoding), else directly (the decoder is being
        driven inside an enclosing Module.apply, e.g. a seq2seq model
        whose child the cell is)."""
        if self.cell_variables is not None:
            return self.cell.apply(self.cell_variables, x, states)
        return self.cell(x, states)

    def tile_beam(self, x):
        """[B, ...] -> [B*K, ...] (ref tile_beam_merge_with_batch:412)."""
        k = self.beam_size
        return jnp.repeat(x, k, axis=0)

    def initialize(self, initial_states):
        """initial_states: per-example cell states [B, ...] (e.g. the
        encoder's final state); they are beam-tiled here."""
        b = jax.tree_util.tree_leaves(initial_states)[0].shape[0]
        k = self.beam_size
        states = _tmap(self.tile_beam, initial_states)
        tokens = jnp.full((b * k,), self.start_token, jnp.int32)
        # only beam 0 live at t=0 so the k copies don't fill the beam
        scores = jnp.tile(jnp.concatenate(
            [jnp.zeros((1,)), jnp.full((k - 1,), -1e9)]), (b,))
        finished = jnp.zeros((b * k,), bool)
        return tokens, (states, scores), finished

    def step(self, time, inputs, states_and_scores, finished):
        cell_states, scores = states_and_scores
        b_k = inputs.shape[0]
        b = b_k // self.beam_size
        k = self.beam_size
        out, new_states = self._run_cell(self.embedding_fn(inputs),
                                         cell_states)
        logp = jax.nn.log_softmax(self.output_fn(out), axis=-1)
        tokens, new_scores, parent = beam_search_step(
            scores.reshape(b, k), logp.reshape(b, k, self.vocab_size), k,
            eos_id=self.end_token, done=finished.reshape(b, k))
        flat_parent = (jnp.arange(b)[:, None] * k + parent).reshape(-1)
        new_states = _tmap(lambda s: jnp.take(s, flat_parent, axis=0),
                           new_states)
        next_tokens = tokens.reshape(-1)
        finished = jnp.take(finished, flat_parent, 0) | \
            (next_tokens == self.end_token)
        outputs = {"token": tokens, "parent": parent}
        return outputs, (new_states, new_scores.reshape(-1)), \
            next_tokens, finished

    def finalize(self, outputs, final_states):
        """Backtrace parent pointers into sequences [B, K, T] + scores
        [B, K] (ref beam_search_decode_op.cc's LoD backtrace, done as a
        reverse scan over the stacked parents)."""
        tokens = outputs["token"]        # [T, B, K]
        parents = outputs["parent"]      # [T, B, K]
        t, b, k = tokens.shape

        def back(beam_idx, inp):
            tok_t, par_t = inp
            tok = jnp.take_along_axis(tok_t, beam_idx, axis=1)
            beam_idx = jnp.take_along_axis(par_t, beam_idx, axis=1)
            return beam_idx, tok

        init = jnp.tile(jnp.arange(k)[None], (b, 1))
        _, seq_rev = lax.scan(back, init, (tokens[::-1], parents[::-1]))
        seqs = jnp.moveaxis(seq_rev[::-1], 0, 2)         # [B, K, T]
        _, scores = final_states
        return seqs, scores.reshape(b, k)


def dynamic_decode(decoder, initial_states, max_step_num,
                   return_length=False):
    """Run a Decoder to `max_step_num` steps (ref rnn.py:791). One
    lax.scan with a finished mask — steps after every beam finishes still
    execute (static shape) but cannot change scores (beam_search_step
    pins finished beams to eos at zero cost).

    Returns decoder.finalize's (outputs, final_state-ish) pair —
    for BeamSearchDecoder: (sequences [B, K, T], scores [B, K])
    (+ lengths [B, K] when return_length)."""
    inputs0, states0, finished0 = decoder.initialize(initial_states)

    def step(carry, time):
        inputs, states, finished = carry
        outputs, states, inputs, finished = decoder.step(
            time, inputs, states, finished)
        return (inputs, states, finished), outputs

    (_, final_states, _), outputs = lax.scan(
        step, (inputs0, states0, finished0), jnp.arange(max_step_num))
    seqs, scores = decoder.finalize(outputs, final_states)
    if return_length:
        eos_mask = seqs == decoder.end_token
        lengths = jnp.where(
            eos_mask.any(-1),
            jnp.argmax(eos_mask, axis=-1) + 1,   # include the eos token
            seqs.shape[-1])
        return seqs, scores, lengths.astype(jnp.int32)
    return seqs, scores
