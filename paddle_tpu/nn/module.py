"""Layer/Module system — the dygraph `Layer` equivalent, functional-core.

Ref: /root/reference/python/paddle/fluid/dygraph/layers.py:32 (`Layer` holds
parameters + sublayers, tracks them by attribute assignment) and dygraph/nn.py
(Conv2D, BatchNorm, Embedding, FC...).

TPU-first redesign: layers are *specs*, parameters are *pytrees*. A Layer
declares parameters (shape + initializer) at construction; `init(key)` builds
the parameter pytree by walking the layer tree; `apply(variables, *args)` is a
pure function of (params, inputs) → outputs, so the whole model jits/pjits and
shards as data. Mutable collections (BN running stats) live in a separate
"state" tree threaded functionally, replacing in-place variable mutation in
the reference's Scope.

variables = {"params": {...}, "state": {...}}
"""

import dataclasses
import typing

import jax
import jax.numpy as jnp

from paddle_tpu import initializer as I
from paddle_tpu.core.enforce import EnforceError


@dataclasses.dataclass
class ParamSpec:
    shape: tuple
    init: typing.Callable
    dtype: typing.Any = jnp.float32


@dataclasses.dataclass
class StateSpec:
    shape: tuple
    init: typing.Callable
    dtype: typing.Any = jnp.float32


class Module:
    """Base layer. Subclasses declare params/state/sublayers in __init__ via
    plain attribute assignment; `forward(params, *args, **kwargs)` computes.

    Context passed through `apply`: training flag and PRNG keys for
    stochastic layers (dropout), mirroring the reference's global
    `with fluid.dygraph.guard()` train/eval state but explicit.
    """

    def __init__(self):
        object.__setattr__(self, "_params", {})   # name -> ParamSpec
        object.__setattr__(self, "_state", {})    # name -> StateSpec
        object.__setattr__(self, "_children", {})  # name -> Module

    # --- declaration ---
    def param(self, name, shape, init=None, dtype=jnp.float32):
        self._params[name] = ParamSpec(tuple(shape), init or I.xavier(), dtype)
        return name

    def state(self, name, shape, init=None, dtype=jnp.float32):
        self._state[name] = StateSpec(tuple(shape), init or I.zeros(), dtype)
        return name

    def __setattr__(self, name, value):
        if isinstance(value, Module):
            self._children[name] = value
        elif isinstance(value, (list, tuple)) and value and all(
                isinstance(v, Module) for v in value):
            self._children[name] = ModuleList(value)
            object.__setattr__(self, name, self._children[name])
            return
        object.__setattr__(self, name, value)

    # --- initialization ---
    def init(self, key, dtype=None):
        """Build {'params': ..., 'state': ...} pytree for this subtree."""
        params, state = {}, {}
        n_own = len(self._params) + len(self._state)
        keys = list(jax.random.split(key, max(n_own + len(self._children), 1)))
        ki = 0
        for name, spec in self._params.items():
            params[name] = spec.init(keys[ki], spec.shape,
                                     dtype or spec.dtype)
            ki += 1
        for name, spec in self._state.items():
            state[name] = spec.init(keys[ki], spec.shape, spec.dtype)
            ki += 1
        for name, child in self._children.items():
            sub = child.init(keys[ki], dtype=dtype)
            ki += 1
            if sub["params"]:
                params[name] = sub["params"]
            if sub["state"]:
                state[name] = sub["state"]
        return {"params": params, "state": state}

    # --- application ---
    def apply(self, variables, *args, training=False, rngs=None,
              calibrating=False, method=None, **kwargs):
        """Run forward purely. Returns output, or (output, new_state) when the
        module carries mutable state and training=True.

        method: alternate entry point — a method name (str) or bound method
        of this module to run instead of forward (e.g. a model's
        greedy_decode); it executes with params bound exactly like forward.

        calibrating=True is the PTQ stat-collection mode: layers behave as in
        eval (Dropout off, BatchNorm uses running stats) but quantizer scale
        states still update; the return is ALWAYS (output, new_state).
        Incompatible with training=True."""
        if training and calibrating:
            raise EnforceError(
                "calibrating=True requires training=False (calibration is an "
                "eval-behavior pass that only updates quantizer statistics)")
        ctx = Context(training=training, rngs=rngs or {},
                      calibrating=calibrating)
        fn = (self.forward if method is None
              else getattr(self, method) if isinstance(method, str) else method)
        with _bind(self, variables, ctx):
            out = fn(*args, **kwargs)
        if calibrating or (ctx.state_updates and training):
            new_state = _merge_state(variables.get("state", {}),
                                     ctx.state_updates)
            return out, new_state
        return out

    def __call__(self, *args, **kwargs):
        """Inside a parent's forward(): run with the bound sub-variables."""
        ctx = _CURRENT.ctx
        if ctx is None:
            raise EnforceError(
                "Module must be called via .apply(variables, ...) or inside a "
                "parent module's forward()")
        return self.forward(*args, **kwargs)

    def forward(self, *args, **kwargs):
        raise NotImplementedError

    # --- bound accessors (valid inside forward) ---
    def p(self, name):
        """Fetch own parameter value."""
        scope = _CURRENT.scopes[id(self)]
        return scope["params"][name]

    def has_p(self, name):
        """True when the bound params dict carries `name` — lets layers
        accept transformed parameter layouts (e.g. weight-only int8
        serving replaces 'weight' with 'weight_q' + 'weight_scale')."""
        scope = _CURRENT.scopes[id(self)]
        return name in scope["params"]

    def s(self, name):
        """Fetch own state value (latest update if already written)."""
        scope = _CURRENT.scopes[id(self)]
        upd = _CURRENT.ctx.state_updates
        path = scope["path"] + (name,)
        if path in upd:
            return upd[path]
        return scope["state"][name]

    def update_state(self, name, value):
        scope = _CURRENT.scopes[id(self)]
        _CURRENT.ctx.state_updates[scope["path"] + (name,)] = value

    @property
    def training(self):
        return _CURRENT.ctx.training

    @property
    def calibrating(self):
        return getattr(_CURRENT.ctx, "calibrating", False)

    def rng(self, name="dropout"):
        ctx = _CURRENT.ctx
        if name not in ctx.rngs:
            raise EnforceError(
                f"Missing PRNG key '{name}': pass rngs={{'{name}': key}} to apply()")
        key, sub = jax.random.split(ctx.rngs[name])
        ctx.rngs[name] = key
        return sub

    # --- introspection ---
    def named_children(self):
        return dict(self._children)

    def param_specs(self):
        out = dict(self._params)
        for cname, child in self._children.items():
            for pname, spec in child.param_specs().items():
                out[f"{cname}.{pname}"] = spec
        return out


class Context:
    def __init__(self, training, rngs, calibrating=False):
        self.training = training
        self.calibrating = calibrating
        self.rngs = dict(rngs)
        self.state_updates = {}  # path tuple -> value


class _Current(object):
    def __init__(self):
        self.ctx = None
        self.scopes = {}


_CURRENT = _Current()


class _bind:
    """Context manager: walk the module tree, binding each module's slice of
    the variables pytree so nested __call__ works without passing dicts."""

    def __init__(self, root, variables, ctx):
        self.root = root
        self.variables = variables
        self.ctx = ctx

    def __enter__(self):
        self.prev_ctx = _CURRENT.ctx
        self.prev_scopes = _CURRENT.scopes
        _CURRENT.ctx = self.ctx
        _CURRENT.scopes = {}
        self._walk(self.root, self.variables.get("params", {}),
                   self.variables.get("state", {}), ())
        return self

    def _walk(self, mod, params, state, path):
        _CURRENT.scopes[id(mod)] = {
            "params": params, "state": state, "path": path}
        for name, child in mod._children.items():
            self._walk(child,
                       params.get(name, {}) if isinstance(params, dict) else {},
                       state.get(name, {}) if isinstance(state, dict) else {},
                       path + (name,))

    def __exit__(self, *exc):
        _CURRENT.ctx = self.prev_ctx
        _CURRENT.scopes = self.prev_scopes
        return False


def _merge_state(state, updates):
    state = jax.tree_util.tree_map(lambda x: x, state)  # shallow-ish copy

    def set_path(d, path, value):
        d = dict(d)
        if len(path) == 1:
            d[path[0]] = value
        else:
            d[path[0]] = set_path(d.get(path[0], {}), path[1:], value)
        return d

    for path, value in updates.items():
        state = set_path(state, path, value)
    return state


class ModuleList(Module):
    """Ordered container (ref: dygraph LayerList)."""

    def __init__(self, modules=()):
        super().__init__()
        self._items = []
        for m in modules:
            self.append(m)

    def append(self, m):
        idx = len(self._items)
        self._items.append(m)
        self._children[str(idx)] = m
        return self

    def __iter__(self):
        return iter(self._items)

    def __len__(self):
        return len(self._items)

    def __getitem__(self, i):
        return self._items[i]

    def forward(self, x, *args, **kwargs):
        for m in self._items:
            x = m(x, *args, **kwargs)
        return x


class Sequential(ModuleList):
    """ref: dygraph Sequential"""
    pass
