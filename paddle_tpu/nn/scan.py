"""Scan-over-layers — stacked layer params + lax.scan + remat.

The unrolled transformer encoders trace and compile every block separately
(12-24x the HLO for identical math) and give XLA no remat boundary, so
activation residency caps the per-chip batch. ScanLayers stores the L
homogeneous blocks as ONE param tree with a leading layer axis and runs
them as a `lax.scan`: one traced block body, compile time O(1) in depth,
and a natural `jax.checkpoint` site per layer (policy from cfg.remat or
the ``remat_policy`` flag: nothing | dots_saveable | full).

Checkpoint format: the stacked tree lives under the single child name
"layer" (params["<attr>"]["layer"]) instead of per-index children
(params["<attr>"]["0"] ...). io/checkpoint.py stack_layer_tree /
unstack_layer_tree convert old<->new.

Dropout inside the scan threads a PRNG key through the carry (splitting
per layer) — a naive closure would bake ONE folded key into the traced
body and reuse it for every layer.
"""

import jax
import jax.numpy as jnp
from jax import lax

from paddle_tpu.core.enforce import enforce
from paddle_tpu.nn.module import Module

REMAT_POLICIES = ("nothing", "dots_saveable", "full")


def resolve_remat(policy):
    """cfg.remat override or the global flag; validated."""
    if policy is None:
        from paddle_tpu.core.flags import get_flag
        policy = get_flag("remat_policy")
    enforce(policy in REMAT_POLICIES,
            f"remat policy {policy!r} not in {REMAT_POLICIES}")
    return policy


def apply_remat(fn, policy):
    policy = resolve_remat(policy)
    if policy == "nothing":
        return fn
    if policy == "full":
        return jax.checkpoint(fn)
    return jax.checkpoint(fn, policy=jax.checkpoint_policies.dots_saveable)


class ScanLayers(Module):
    """A stack of `num_layers` copies of a prototype block, scanned.

    The prototype must be stateless (params only — transformer blocks
    are); per-layer mutable state inside a scan carry would need a
    stacked state tree threaded through apply, which no current block
    needs. Broadcast inputs (masks etc.) pass through **kwargs and are
    closed over by the scan body.
    """

    def __init__(self, layer, num_layers, remat=None, needs_rng=True,
                 rng_name="dropout"):
        super().__init__()
        self.layer = layer                     # child "layer": the prototype
        self.num_layers = num_layers
        self.remat = remat
        self.needs_rng = needs_rng
        self.rng_name = rng_name

    def init(self, key, dtype=None):
        subs = [self.layer.init(k, dtype=dtype)
                for k in jax.random.split(key, self.num_layers)]
        enforce(not jax.tree_util.tree_leaves(subs[0]["state"]),
                "ScanLayers requires a stateless block (found mutable "
                "state in the prototype layer)")
        stacked = jax.tree_util.tree_map(
            lambda *xs: jnp.stack(xs), *[s["params"] for s in subs])
        return {"params": {"layer": stacked}, "state": {}}

    def forward(self, x, **kwargs):
        stacked = self.p("layer")              # leading axis = layer
        training = self.training
        proto = self.layer
        use_rng = training and self.needs_rng

        if use_rng:
            def body(carry, lp):
                h, k = carry
                k, sub = jax.random.split(k)
                y = proto.apply({"params": lp, "state": {}}, h,
                                training=True,
                                rngs={self.rng_name: sub}, **kwargs)
                return (y, k), None
            body = apply_remat(body, self.remat)
            (x, _), _ = lax.scan(body, (x, self.rng(self.rng_name)),
                                 stacked)
        else:
            def body(h, lp):
                return proto.apply({"params": lp, "state": {}}, h,
                                   training=training, **kwargs), None
            body = apply_remat(body, self.remat)
            x, _ = lax.scan(body, x, stacked)
        return x
