"""Mixture-of-Experts layer with expert parallelism over the "ep" axis.

Ref: no MoE exists in the reference (2019-era); its expert-sharding
ancestor is the parameter-server's row-sharded tables
(/root/reference/paddle/fluid/framework/fleet/fleet_wrapper.h:55). This is
the modern successor the brief's scale requirements imply: top-k gating,
capacity-bounded dispatch, experts sharded over a mesh axis with the
token exchange as ONE all_to_all pair per layer (ICI), not RPC.

TPU-first design (static shapes throughout):
  * gating: softmax top-k with load-balancing auxiliary loss (the
    Switch/GShard aux), expressed as dense [T, E] one-hots — no dynamic
    gather/scatter shapes.
  * dispatch: capacity C = ceil(k * T / E * capacity_factor); tokens
    beyond an expert's capacity are DROPPED (their combine weight is
    zero) — the standard static-shape MoE contract.
  * single-device: one einsum pipeline. Expert-parallel: call
    `moe_shard_map`-style under shard_map with experts sharded over
    "ep"; dispatch/combine ride lax.all_to_all.
"""

import jax
import jax.numpy as jnp
from jax import lax

from paddle_tpu import initializer as I
from paddle_tpu.nn.module import Module


def top_k_gating(logits, k, capacity):
    """Static-shape top-k gating. logits: [T, E].

    Returns (dispatch [T, E, C] one-hot, combine [T, E, C] weights,
    aux_loss scalar). Position of a token inside its expert's buffer is
    its rank among the tokens routed there (cumsum order); overflow
    positions >= capacity get zero weight.
    """
    t, e = logits.shape
    probs = jax.nn.softmax(logits, axis=-1)

    dispatch = jnp.zeros((t, e, capacity), probs.dtype)
    combine = jnp.zeros((t, e, capacity), probs.dtype)
    # occupancy carried across the k rounds so second choices pack after
    # first choices (GShard's sequential-greedy assignment)
    occupancy = jnp.zeros((e,), jnp.int32)
    masked = probs
    for _ in range(k):
        choice = jnp.argmax(masked, axis=-1)              # [T]
        onehot = jax.nn.one_hot(choice, e, dtype=probs.dtype)
        # rank of each token within its chosen expert this round
        pos_in_round = (jnp.cumsum(onehot, axis=0) - onehot)  # [T, E]
        pos = (pos_in_round + occupancy[None, :]) * onehot
        pos_idx = jnp.sum(pos, axis=-1).astype(jnp.int32)     # [T]
        keep = pos_idx < capacity
        gate = jnp.sum(probs * onehot, axis=-1) * keep        # [T]
        # pos_oh is all-zero for overflow tokens (the where() routes them
        # to the sliced-off column), so no extra keep factor is needed
        pos_oh = jax.nn.one_hot(jnp.where(keep, pos_idx, capacity),
                                capacity + 1,
                                dtype=probs.dtype)[:, :capacity]
        dispatch = dispatch + onehot[:, :, None] * pos_oh[:, None, :]
        combine = combine + (gate[:, None, None]
                             * onehot[:, :, None] * pos_oh[:, None, :])
        occupancy = occupancy + jnp.sum(onehot, axis=0).astype(jnp.int32)
        masked = masked * (1.0 - onehot)                  # exclude chosen

    # load-balancing aux (Switch Transformer eq. 4): E * sum_e f_e * p_e
    first_choice = jax.nn.one_hot(jnp.argmax(probs, -1), e,
                                  dtype=probs.dtype)
    f = jnp.mean(first_choice, axis=0)
    p = jnp.mean(probs, axis=0)
    aux = e * jnp.sum(f * p)
    return dispatch, combine, aux


class MoE(Module):
    """Top-k routed expert FFN. x: [B, T, D] -> [B, T, D].

    Single-call usage computes all experts locally; under shard_map with
    experts sharded over `ep_axis`, the dispatched token buffers are
    exchanged with one all_to_all pair and each device runs only its own
    experts.
    """

    def __init__(self, dim, hidden, num_experts, k=2, capacity_factor=1.25,
                 ep_axis=None, dtype=jnp.float32):
        super().__init__()
        from paddle_tpu.core.enforce import enforce
        enforce(k <= num_experts, "MoE top-k needs k <= num_experts")
        self.dim, self.hidden = dim, hidden
        self.num_experts, self.k = num_experts, k
        self.capacity_factor = capacity_factor
        self.ep_axis = ep_axis
        self.param("w_gate", (dim, num_experts), I.xavier(), dtype)
        # explicit per-expert Linear fans: the default conv-style fans
        # would treat [E, D, H] as OIHW and init experts ~sqrt(E)x too
        # small
        self.param("w1", (num_experts, dim, hidden),
                   I.xavier(fan_in=dim, fan_out=hidden), dtype)
        self.param("b1", (num_experts, hidden), I.zeros(), dtype)
        self.param("w2", (num_experts, hidden, dim),
                   I.xavier(fan_in=hidden, fan_out=dim), dtype)
        self.param("b2", (num_experts, dim), I.zeros(), dtype)

    def _capacity(self, tokens, num_experts):
        import math
        c = math.ceil(self.k * tokens * self.capacity_factor / num_experts)
        return max(c, 1)

    def forward(self, x):
        return self.forward_with_aux(x)[0]

    def forward_with_aux(self, x):
        """Returns (y, aux_loss) — add `aux_loss * coef` to the training
        objective (apply(..., method="forward_with_aux"))."""
        b, t, d = x.shape
        tokens = b * t
        xf = x.reshape(tokens, d)
        logits = xf @ self.p("w_gate")
        e = self.num_experts
        cap = self._capacity(tokens, e)
        dispatch, combine, aux = top_k_gating(logits, self.k, cap)

        def expert_ffn(buf):
            h = jnp.einsum("ecd,edh->ech", buf, self.p("w1")) \
                + self.p("b1")[:, None, :]
            h = jax.nn.gelu(h)
            return jnp.einsum("ech,ehd->ecd", h, self.p("w2")) \
                + self.p("b2")[:, None, :]

        # [E, C, D] expert input buffers
        buf = jnp.einsum("td,tec->ecd", xf, dispatch)
        if self.ep_axis is not None:
            n = lax.axis_size(self.ep_axis)
            el = e // n                           # experts owned locally
            # exchange: split expert dim across devices, gather the
            # capacity dim — each device ends with [el, n*C, D] (its own
            # experts' tokens from every device)
            buf = buf.reshape(n, el, cap, d)
            buf = lax.all_to_all(buf, self.ep_axis, split_axis=0,
                                 concat_axis=0, tiled=False)
            buf = buf.transpose(1, 0, 2, 3).reshape(el, n * cap, d)
            out = expert_ffn(buf)
            out = out.reshape(el, n, cap, d).transpose(1, 0, 2, 3)
            out = lax.all_to_all(out, self.ep_axis, split_axis=0,
                                 concat_axis=0, tiled=False)
            out = out.reshape(e, cap, d)
        else:
            out = expert_ffn(buf)
        y = jnp.einsum("ecd,tec->td", out, combine)
        return y.reshape(b, t, d), aux
