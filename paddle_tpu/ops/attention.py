"""Attention ops.

Ref: the reference has no attention *op* — transformer attention appears as a
fused IR pass (/root/reference/paddle/fluid/framework/ir/
multihead_matmul_fuse_pass.h) over matmul/softmax subgraphs, plus
layers/nn.py scaled_dot_product_attention. Here attention is a first-class
op with an XLA path and a Pallas flash-attention path for long sequences
(ops/pallas/flash_attention.py).
"""

import functools

import jax
import jax.numpy as jnp

from paddle_tpu.core.registry import register_op


@register_op("scaled_dot_product_attention")
def scaled_dot_product_attention(q, k, v, mask=None, scale=None,
                                 causal=False, dropout_rate=0.0,
                                 dropout_key=None):
    """q,k,v: [B, H, T, D] (or [B, T, D]). mask: broadcastable to
    [B, H, Tq, Tk], True/1 = keep.

    XLA path: materializes the [Tq, Tk] score matrix — fine up to ~4k tokens;
    beyond that use `flash_attention` (Pallas, O(T) memory).
    """
    scale = scale if scale is not None else 1.0 / jnp.sqrt(q.shape[-1])
    scores = jnp.einsum("...qd,...kd->...qk", q, k) * scale
    keep = None
    if causal:
        tq, tk = scores.shape[-2], scores.shape[-1]
        keep = jnp.tril(jnp.ones((tq, tk), bool), k=tk - tq)
    if mask is not None:
        keep = mask.astype(bool) if keep is None else keep & mask.astype(bool)
    if keep is not None:
        scores = jnp.where(keep, scores, -1e9)
    probs = jax.nn.softmax(scores, axis=-1)
    if keep is not None:
        # fully-masked rows are defined as exactly zero output — the same
        # semantics as the flash/chunked paths (a plain softmax would emit
        # the uniform mean-of-v artifact instead)
        any_keep = jnp.any(jnp.broadcast_to(keep, scores.shape), -1,
                           keepdims=True)
        probs = jnp.where(any_keep, probs, 0.0)
    if dropout_rate > 0.0 and dropout_key is not None:
        keep = jax.random.bernoulli(dropout_key, 1.0 - dropout_rate,
                                    probs.shape)
        probs = jnp.where(keep, probs / (1.0 - dropout_rate), 0.0)
    return jnp.einsum("...qk,...kd->...qd", probs, v)


def _as_key_padding_mask(mask, batch, tk):
    """Reduce a broadcastable attention mask to key-padding form [B, Tk]
    when its per-head and per-query dims are 1 (the padded-batch case the
    reference feeds through the fused path's eltwise-add bias input).
    Returns None for masks that genuinely vary per query/head."""
    if mask is None:
        return None
    m = mask
    if m.ndim == 4 and m.shape[1] == 1 and m.shape[2] == 1:
        m = m[:, 0, 0, :]
    elif m.ndim == 3 and m.shape[0] == 1 and m.shape[1] == 1:
        # a 3D mask's leading dim broadcasts against the HEAD axis in the
        # dense path, so only the fully-degenerate [1,1,Tk] is unambiguous
        m = m[:, 0, :]
    elif m.ndim == 2 and m.shape[0] == 1:
        # [1, Tk] broadcasts identically under both interpretations; a
        # [B, Tk] 2D mask would broadcast as [Tq, Tk] per-query in the
        # dense path, so it must NOT be reduced to key-padding form
        pass
    else:
        return None
    if m.shape[-1] != tk:
        return None
    if m.shape[0] == 1 and batch > 1:
        m = jnp.broadcast_to(m, (batch, tk))
    elif m.shape[0] != batch:
        return None
    return m.astype(bool)


@register_op("multihead_attention")
def multihead_attention(x, wq, wk, wv, wo, bq=None, bk=None, bv=None, bo=None,
                        num_heads=8, mask=None, causal=False, kv=None,
                        dropout_rate=0.0, dropout_key=None, use_flash=False,
                        seq_axis=None):
    """Full fused MHA forward (ref: ir/multihead_matmul_fuse_pass.h — the
    reference *fuses* q/k/v matmuls post-hoc; we write it fused from the
    start). x: [B, T, E]; w*: [E, E]."""
    b, t, e = x.shape
    hd = e // num_heads
    kv = kv if kv is not None else x

    def proj(inp, w, bias):
        out = inp @ w
        if bias is not None:
            out = out + bias
        return out.reshape(b, -1, num_heads, hd).transpose(0, 2, 1, 3)

    q = proj(x, wq, bq)
    k = proj(kv, wk, bk)
    v = proj(kv, wv, bv)
    no_dropout = dropout_rate == 0.0 or dropout_key is None
    if seq_axis is not None:
        # sequence sharded over a mesh axis: ring attention (flash-backed
        # on TPU). Per-device positions are contiguous so block-granular
        # causality is exact. Masks/dropout are not supported here.
        from paddle_tpu.core.enforce import enforce
        enforce(mask is None and no_dropout,
                "seq_axis attention supports no mask/attention-dropout")
        from paddle_tpu.parallel.ring_attention import ring_flash_attention
        ctx = ring_flash_attention(q, k, v, seq_axis, causal=causal)
        ctx = ctx.transpose(0, 2, 1, 3).reshape(b, t, e)
        out = ctx @ wo
        return out + bo if bo is not None else out
    # flash path handles key-padding masks ([B,1,1,Tk]-style) natively;
    # only an arbitrary per-query mask or attention dropout falls back to
    # the XLA path
    kv_mask = _as_key_padding_mask(mask, b, k.shape[2])
    if use_flash and (mask is None or kv_mask is not None) and no_dropout:
        from paddle_tpu.ops.pallas.flash_attention import flash_attention
        ctx = flash_attention(q, k, v, causal=causal, kv_mask=kv_mask)
    else:
        ctx = scaled_dot_product_attention(q, k, v, mask=mask, causal=causal,
                                           dropout_rate=dropout_rate,
                                           dropout_key=dropout_key)
    ctx = ctx.transpose(0, 2, 1, 3).reshape(b, t, e)
    out = ctx @ wo
    if bo is not None:
        out = out + bo
    return out
