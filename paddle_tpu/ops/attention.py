"""Attention ops.

Ref: the reference has no attention *op* — transformer attention appears as a
fused IR pass (/root/reference/paddle/fluid/framework/ir/
multihead_matmul_fuse_pass.h) over matmul/softmax subgraphs, plus
layers/nn.py scaled_dot_product_attention. Here attention is a first-class
op with an XLA path and a Pallas flash-attention path for long sequences
(ops/pallas/flash_attention.py).
"""

import functools

import jax
import jax.numpy as jnp

from paddle_tpu.core.registry import register_op


@register_op("scaled_dot_product_attention")
def scaled_dot_product_attention(q, k, v, mask=None, scale=None,
                                 causal=False, dropout_rate=0.0,
                                 dropout_key=None):
    """q,k,v: [B, H, T, D] (or [B, T, D]). mask: broadcastable to
    [B, H, Tq, Tk], True/1 = keep.

    XLA path: materializes the [Tq, Tk] score matrix — fine up to ~4k tokens;
    beyond that use `flash_attention` (Pallas, O(T) memory).
    """
    scale = scale if scale is not None else 1.0 / jnp.sqrt(q.shape[-1])
    scores = jnp.einsum("...qd,...kd->...qk", q, k) * scale
    if causal:
        tq, tk = scores.shape[-2], scores.shape[-1]
        cm = jnp.tril(jnp.ones((tq, tk), bool), k=tk - tq)
        scores = jnp.where(cm, scores, -1e9)
    if mask is not None:
        scores = jnp.where(mask.astype(bool), scores, -1e9)
    probs = jax.nn.softmax(scores, axis=-1)
    if dropout_rate > 0.0 and dropout_key is not None:
        keep = jax.random.bernoulli(dropout_key, 1.0 - dropout_rate,
                                    probs.shape)
        probs = jnp.where(keep, probs / (1.0 - dropout_rate), 0.0)
    return jnp.einsum("...qk,...kd->...qd", probs, v)


@register_op("multihead_attention")
def multihead_attention(x, wq, wk, wv, wo, bq=None, bk=None, bv=None, bo=None,
                        num_heads=8, mask=None, causal=False, kv=None,
                        dropout_rate=0.0, dropout_key=None, use_flash=False):
    """Full fused MHA forward (ref: ir/multihead_matmul_fuse_pass.h — the
    reference *fuses* q/k/v matmuls post-hoc; we write it fused from the
    start). x: [B, T, E]; w*: [E, E]."""
    b, t, e = x.shape
    hd = e // num_heads
    kv = kv if kv is not None else x

    def proj(inp, w, bias):
        out = inp @ w
        if bias is not None:
            out = out + bias
        return out.reshape(b, -1, num_heads, hd).transpose(0, 2, 1, 3)

    q = proj(x, wq, bq)
    k = proj(kv, wk, bk)
    v = proj(kv, wv, bv)
    # flash path supports no arbitrary mask / attention dropout — fall back
    # to the XLA path rather than silently dropping them
    if use_flash and mask is None and (dropout_rate == 0.0
                                       or dropout_key is None):
        from paddle_tpu.ops.pallas.flash_attention import flash_attention
        ctx = flash_attention(q, k, v, causal=causal)
    else:
        ctx = scaled_dot_product_attention(q, k, v, mask=mask, causal=causal,
                                           dropout_rate=dropout_rate,
                                           dropout_key=dropout_key)
    ctx = ctx.transpose(0, 2, 1, 3).reshape(b, t, e)
    out = ctx @ wo
    if bo is not None:
        out = out + bo
    return out
