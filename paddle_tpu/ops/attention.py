"""Attention ops.

Ref: the reference has no attention *op* — transformer attention appears as a
fused IR pass (/root/reference/paddle/fluid/framework/ir/
multihead_matmul_fuse_pass.h) over matmul/softmax subgraphs, plus
layers/nn.py scaled_dot_product_attention. Here attention is a first-class
op with an XLA path and a Pallas flash-attention path for long sequences
(ops/pallas/flash_attention.py).
"""

import functools

import jax
import jax.numpy as jnp

from paddle_tpu.core.registry import register_op

NEG_INF = -1e30


@register_op("scaled_dot_product_attention")
def scaled_dot_product_attention(q, k, v, mask=None, scale=None,
                                 causal=False, dropout_rate=0.0,
                                 dropout_key=None):
    """q,k,v: [B, H, T, D] (or [B, T, D]). mask: broadcastable to
    [B, H, Tq, Tk], True/1 = keep.

    XLA path: materializes the [Tq, Tk] score matrix — fine up to ~4k tokens;
    beyond that use `flash_attention` (Pallas, O(T) memory).
    """
    scale = scale if scale is not None else 1.0 / jnp.sqrt(q.shape[-1])
    scores = jnp.einsum("...qd,...kd->...qk", q, k) * scale
    keep = None
    if causal:
        tq, tk = scores.shape[-2], scores.shape[-1]
        keep = jnp.tril(jnp.ones((tq, tk), bool), k=tk - tq)
    if mask is not None:
        keep = mask.astype(bool) if keep is None else keep & mask.astype(bool)
    if keep is not None:
        scores = jnp.where(keep, scores, -1e9)
    probs = jax.nn.softmax(scores, axis=-1)
    if keep is not None:
        # fully-masked rows are defined as exactly zero output — the same
        # semantics as the flash/chunked paths (a plain softmax would emit
        # the uniform mean-of-v artifact instead)
        any_keep = jnp.any(jnp.broadcast_to(keep, scores.shape), -1,
                           keepdims=True)
        probs = jnp.where(any_keep, probs, 0.0)
    if dropout_rate > 0.0 and dropout_key is not None:
        keep = jax.random.bernoulli(dropout_key, 1.0 - dropout_rate,
                                    probs.shape)
        probs = jnp.where(keep, probs / (1.0 - dropout_rate), 0.0)
    return jnp.einsum("...qk,...kd->...qd", probs, v)


def _as_key_padding_mask(mask, batch, tk):
    """Reduce a broadcastable attention mask to key-padding form [B, Tk]
    when its per-head and per-query dims are 1 (the padded-batch case the
    reference feeds through the fused path's eltwise-add bias input).
    Returns None for masks that genuinely vary per query/head."""
    if mask is None:
        return None
    m = mask
    if m.ndim == 4 and m.shape[1] == 1 and m.shape[2] == 1:
        m = m[:, 0, 0, :]
    elif m.ndim == 3 and m.shape[0] == 1 and m.shape[1] == 1:
        # a 3D mask's leading dim broadcasts against the HEAD axis in the
        # dense path, so only the fully-degenerate [1,1,Tk] is unambiguous
        m = m[:, 0, :]
    elif m.ndim == 2 and m.shape[0] == 1:
        # [1, Tk] broadcasts identically under both interpretations; a
        # [B, Tk] 2D mask would broadcast as [Tq, Tk] per-query in the
        # dense path, so it must NOT be reduced to key-padding form
        pass
    else:
        return None
    if m.shape[-1] != tk:
        return None
    if m.shape[0] == 1 and batch > 1:
        m = jnp.broadcast_to(m, (batch, tk))
    elif m.shape[0] != batch:
        return None
    return m.astype(bool)


# --- paged KV cache (serving fast path) -----------------------------------
#
# The per-request contiguous [B, H, Tmax, hd] decode cache streams the whole
# padded buffer every generated token and welds requests into one fixed
# lockstep batch. The paged layout replaces it with a slot/page-pool scheme:
# one pool of fixed-size pages per layer ([N, H, page_size, hd]) plus a
# per-slot page table ([slots, Pmax] int32) and token counts ([slots]
# int32). Memory scales with tokens actually held, mixed-length requests
# share one batch, and a finished request frees its pages without reshaping
# anything — the jitted serve step's shapes never change across admissions
# (paddle_tpu/serving/ owns the host-side allocator).
#
# Quantized pools (kv_dtype=int8) add {"k_scale","v_scale"} f32
# [num_pages, page_size] beside the int8 value tensors: one symmetric
# absmax scale per (page, token-row), shared across heads and head_dim.
# Row granularity makes the incremental decode write exact (each new token
# sets its own int8 row + one scale scalar; existing rows are untouched),
# and keying scales by page id means prefix-cache sharing, copy-on-write
# and recovery-rebuild all carry scales for free — they only ever move
# whole pages.


def quantized_pool(pool):
    """True iff `pool` is an int8 pool carrying per-row scales."""
    return "k_scale" in pool


def quantize_kv_rows(x):
    """Symmetric per-token-row int8 quantization. x: [T, H, hd] ->
    (q int8 [T, H, hd], scale f32 [T]) with scale = absmax/127. An
    all-zero row stores scale 0 and dequantizes to exactly zero."""
    absmax = jnp.max(jnp.abs(x.astype(jnp.float32)), axis=(1, 2))
    scale = absmax / 127.0
    q = jnp.clip(jnp.round(x.astype(jnp.float32)
                           / jnp.maximum(scale, 1e-30)[:, None, None]),
                 -127.0, 127.0).astype(jnp.int8)
    return q, scale


def dequantize_pages(pages, scales):
    """Dequantize gathered pages. pages: [..., H, ps, hd] int8 with
    leading gather dims; scales: [..., ps] f32 aligned on those dims.
    -> f32 of pages.shape."""
    return pages.astype(jnp.float32) * scales[..., None, :, None]


def init_page_pool(num_pages, num_heads, page_size, head_dim,
                   dtype=jnp.float32, kv_dtype=None):
    """One layer's KV page pool: {"k","v"} [num_pages, H, page_size, hd].
    kv_dtype=int8 adds {"k_scale","v_scale"} f32 [num_pages, page_size]
    (per-row symmetric scales) and stores values as int8."""
    shape = (num_pages, num_heads, page_size, head_dim)
    if kv_dtype is None or jnp.dtype(kv_dtype) == jnp.dtype(dtype):
        return {"k": jnp.zeros(shape, dtype), "v": jnp.zeros(shape, dtype)}
    if jnp.dtype(kv_dtype) != jnp.dtype(jnp.int8):
        raise ValueError(f"unsupported kv_dtype {kv_dtype!r} "
                         "(supported: int8, or None for the pool dtype)")
    sshape = (num_pages, page_size)
    return {"k": jnp.zeros(shape, jnp.int8),
            "v": jnp.zeros(shape, jnp.int8),
            "k_scale": jnp.zeros(sshape, jnp.float32),
            "v_scale": jnp.zeros(sshape, jnp.float32)}


def paged_write(pool, k_t, v_t, page_ids, offsets):
    """Scatter per-token K/V into pool pages. k_t/v_t: [T, H, hd];
    page_ids/offsets: [T] int32. An out-of-range page id DROPS the write
    (mode="drop") — the engine routes inactive slots and pad positions to
    page id == num_pages on purpose. On a quantized pool each row is
    quantized on the way in and its scale written beside it."""
    if quantized_pool(pool):
        k_q, k_s = quantize_kv_rows(k_t)
        v_q, v_s = quantize_kv_rows(v_t)
        return {
            "k": pool["k"].at[page_ids, :, offsets, :].set(
                k_q, mode="drop"),
            "v": pool["v"].at[page_ids, :, offsets, :].set(
                v_q, mode="drop"),
            "k_scale": pool["k_scale"].at[page_ids, offsets].set(
                k_s, mode="drop"),
            "v_scale": pool["v_scale"].at[page_ids, offsets].set(
                v_s, mode="drop"),
        }
    return {
        "k": pool["k"].at[page_ids, :, offsets, :].set(
            k_t.astype(pool["k"].dtype), mode="drop"),
        "v": pool["v"].at[page_ids, :, offsets, :].set(
            v_t.astype(pool["v"].dtype), mode="drop"),
    }


def copy_pages(pool, src_ids, dst_ids):
    """Copy whole pages src->dst within one layer's pool — the serving
    engine's copy-on-write primitive: a slot about to write into a
    prefix-cache-shared page first duplicates it to a private page.
    src_ids/dst_ids: [M] int32. An out-of-range dst DROPS the copy
    (mode="drop"), matching paged_write's inactive-slot convention.
    Generic over the pool's entries, so a quantized pool's per-row
    scales travel with their int8 pages (already-quantized content is
    copied bit-exact — no requantization error on CoW)."""
    return {name: arr.at[dst_ids].set(arr[src_ids], mode="drop")
            for name, arr in pool.items()}


def _paged_attention_xla(q, k_pages, v_pages, page_table, lengths, scale,
                         k_scale=None, v_scale=None):
    """Gather-and-mask reference: pull every table page densely and mask by
    length. Materializes [S, H, Pmax*ps]-scale score temporaries — the
    parity oracle for the Pallas kernel and the CPU fallback, never the
    serving hot path (compile_smoke's serve probe asserts the kernel path
    holds no such temporary, with this path as the positive control).
    k_scale/v_scale [N, ps] dequantize int8 pools on the same gathered
    pages the kernel reads."""
    s_slots, h, hd = q.shape
    page_size = k_pages.shape[2]
    p_max = page_table.shape[1]
    t = p_max * page_size
    kg = k_pages[page_table]                   # [S, Pmax, H, ps, hd]
    vg = v_pages[page_table]
    if k_scale is not None:
        kg = dequantize_pages(kg, k_scale[page_table])
        vg = dequantize_pages(vg, v_scale[page_table])
    k = jnp.moveaxis(kg, 2, 1).reshape(s_slots, h, t, hd)
    v = jnp.moveaxis(vg, 2, 1).reshape(s_slots, h, t, hd)
    scores = jnp.einsum("shd,shtd->sht", q.astype(jnp.float32),
                        k.astype(jnp.float32)) * scale
    valid = (jnp.arange(t)[None, :] < lengths[:, None])[:, None, :]
    scores = jnp.where(valid, scores, NEG_INF)
    m = jnp.max(scores, axis=-1, keepdims=True)
    # mask p, not just scores: a fully-masked slot (length 0) keeps m at
    # the NEG_INF sentinel where exp(s - m) would be 1
    p = jnp.where(valid, jnp.exp(scores - m), 0.0)
    l = jnp.sum(p, axis=-1, keepdims=True)
    out = jnp.einsum("sht,shtd->shd", p, v.astype(jnp.float32))
    out = jnp.where(l > 0, out / jnp.maximum(l, 1e-30), 0.0)
    return out.astype(q.dtype)


@register_op("paged_decode_attention")
def paged_decode_attention(q, k_pages, v_pages, page_table, lengths,
                           scale=None, k_scale=None, v_scale=None):
    """Single-query attention over a paged KV cache (the serving decode
    read). q: [S, H, hd] — one query token per slot; k_pages/v_pages:
    [N, H, page_size, hd]; page_table: [S, Pmax] int32 with IN-RANGE
    entries everywhere (0 for unallocated); lengths: [S] int32 valid
    token counts (0 = inactive slot -> exactly-zero output).
    k_scale/v_scale: [N, page_size] f32 per-row scales when the pool is
    int8 (init_page_pool(kv_dtype=int8)); both paths dequantize the same
    gathered pages, so kernel-vs-fallback parity holds for quantized
    pools too.

    On TPU (or under pallas_interpret): the Pallas kernel gathers only
    live pages through the page table and runs flash-style online softmax
    over page tiles. Elsewhere, or with use_pallas_decode=False: the XLA
    gather-and-mask formulation (same semantics, dense temporaries)."""
    from paddle_tpu.core.flags import get_flag
    from paddle_tpu.ops.pallas.core import kernel_mode
    scale = (float(scale) if scale is not None
             else 1.0 / (q.shape[-1] ** 0.5))
    page_size = k_pages.shape[2]
    interpret = get_flag("pallas_interpret")
    shape_ok = (page_size % 8 == 0
                and (interpret or q.shape[-1] % 64 == 0))
    mode = kernel_mode(
        "decode_attention", enable_flag="use_pallas_decode",
        unsupported=None if shape_ok else (
            f"page_size={page_size} not a multiple of 8 or "
            f"hd={q.shape[-1]} not a multiple of 64 "
            "(supported: page_size%8==0, hd%64==0 on silicon)"))
    if mode is not None:
        from paddle_tpu.ops.pallas.decode_attention import (
            paged_decode_attention_tpu)
        return paged_decode_attention_tpu(
            q, k_pages, v_pages, page_table, lengths, scale,
            k_scale=k_scale, v_scale=v_scale, interpret=interpret)
    return _paged_attention_xla(q, k_pages, v_pages, page_table, lengths,
                                scale, k_scale=k_scale, v_scale=v_scale)


@register_op("multihead_attention")
def multihead_attention(x, wq, wk, wv, wo, bq=None, bk=None, bv=None, bo=None,
                        num_heads=8, mask=None, causal=False, kv=None,
                        dropout_rate=0.0, dropout_key=None, use_flash=False,
                        seq_axis=None):
    """Full fused MHA forward (ref: ir/multihead_matmul_fuse_pass.h — the
    reference *fuses* q/k/v matmuls post-hoc; we write it fused from the
    start). x: [B, T, E]; w*: [E, E]."""
    b, t, e = x.shape
    hd = e // num_heads
    kv = kv if kv is not None else x

    def proj(inp, w, bias):
        out = inp @ w
        if bias is not None:
            out = out + bias
        return out.reshape(b, -1, num_heads, hd).transpose(0, 2, 1, 3)

    q = proj(x, wq, bq)
    k = proj(kv, wk, bk)
    v = proj(kv, wv, bv)
    no_dropout = dropout_rate == 0.0 or dropout_key is None
    if seq_axis is not None:
        # sequence sharded over a mesh axis: ring attention (flash-backed
        # on TPU). Per-device positions are contiguous so block-granular
        # causality is exact. Masks/dropout are not supported here.
        from paddle_tpu.core.enforce import enforce
        enforce(mask is None and no_dropout,
                "seq_axis attention supports no mask/attention-dropout")
        from paddle_tpu.parallel.ring_attention import ring_flash_attention
        ctx = ring_flash_attention(q, k, v, seq_axis, causal=causal)
        ctx = ctx.transpose(0, 2, 1, 3).reshape(b, t, e)
        out = ctx @ wo
        return out + bo if bo is not None else out
    # flash path handles key-padding masks ([B,1,1,Tk]-style) natively;
    # only an arbitrary per-query mask or attention dropout falls back to
    # the XLA path
    kv_mask = _as_key_padding_mask(mask, b, k.shape[2])
    if use_flash and (mask is None or kv_mask is not None) and no_dropout:
        from paddle_tpu.ops.pallas.flash_attention import flash_attention
        ctx = flash_attention(q, k, v, causal=causal, kv_mask=kv_mask)
    else:
        ctx = scaled_dot_product_attention(q, k, v, mask=mask, causal=causal,
                                           dropout_rate=dropout_rate,
                                           dropout_key=dropout_key)
    ctx = ctx.transpose(0, 2, 1, 3).reshape(b, t, e)
    out = ctx @ wo
    if bo is not None:
        out = out + bo
    return out
