"""Tree/graph ops: tree_conv (TBCNN tree-based convolution).

Ref: /root/reference/paddle/fluid/operators/tree_conv_op.{cc,h} +
operators/math/tree2col.{h,cc}. The reference builds, per sample, a patch
for every node (DFS to max_depth) with three continuous-binary-tree weights
per visited node (eta_t/eta_l/eta_r, tree2col.h:34-52), then one GEMM
patch x Filter.

TPU-first split: the tree walk is irregular, data-dependent host work →
``tree_patch_coefficients`` precomputes (numpy) a dense coefficient tensor
coef[b, root, node, 3] from the edge sets once per batch. The device op
``tree_conv`` is then a single einsum over (coef, features, filter) — the
whole batch in one MXU contraction instead of per-sample GEMMs. Gradients
flow through features and filter via autodiff (coef is data, like the
reference where Col2Tree replays the same structure).
"""

import numpy as np

import jax.numpy as jnp

from paddle_tpu.core.enforce import enforce
from paddle_tpu.core.registry import register_op


def _build_adjacency(edges):
    """edges [E, 2] int (1-indexed parent->child, (0,0)-terminated).
    Returns (children dict, node_count). Mirrors tree2col.cc
    construct_tree: rows after the first (0,0) are ignored."""
    tr = {}
    node_count = 0
    for u, v in edges:
        u, v = int(u), int(v)
        if u == 0 or v == 0:
            break
        node_count += 1
        tr.setdefault(u, []).append(v)
    return tr, node_count + 1


def _patch(root, max_depth, tr):
    """DFS patch of (node, index, pclen, depth) — tree2col.cc
    construct_patch, iterative stack walk with a visited set."""
    out = [(root, 1, 1, 0)]
    stack = [(root, 1, 1, 0)]
    visited = {root}
    while stack:
        node, idx, pclen, depth = stack[-1]
        end = True
        kids = tr.get(node, [])
        for i, v in enumerate(kids):
            if v not in visited and depth + 1 < max_depth:
                visited.add(v)
                stack.append((v, i, len(kids), depth + 1))
                out.append((v, i + 1, len(kids), depth + 1))
                end = False
        if end:
            stack.pop()
    return out


def tree_patch_coefficients(edge_sets, n_nodes, max_depth):
    """Host-side tree2col: edge_sets [B, E, 2] (numpy/int) →
    coef [B, n_nodes, n_nodes, 3] float32 with
    coef[b, root-1, node-1] = (eta_l, eta_r, eta_t) of node in root's patch.
    """
    edge_sets = np.asarray(edge_sets)
    B = edge_sets.shape[0]
    coef = np.zeros((B, n_nodes, n_nodes, 3), np.float32)
    fd = float(max_depth)
    for b in range(B):
        tr, node_count = _build_adjacency(edge_sets[b])
        for root in range(1, node_count + 1):
            for node, idx, pclen, depth in _patch(root, max_depth, tr):
                eta_t = (fd - depth) / fd
                if pclen == 1:
                    tmp = 0.5
                else:
                    tmp = (idx - 1.0) / (pclen - 1.0)
                eta_l = (1.0 - eta_t) * tmp
                eta_r = (1.0 - eta_t) * (1.0 - tmp)
                # += : revisits accumulate, matching tree2col.cc's
                # patch_data[...] += eta * feature
                coef[b, root - 1, node - 1, 0] += eta_l
                coef[b, root - 1, node - 1, 1] += eta_r
                coef[b, root - 1, node - 1, 2] += eta_t
    return coef


@register_op("tree_conv")
def tree_conv(nodes_vector, coef, filter):
    """TBCNN convolution (device op).

    nodes_vector: [B, N, F] node embeddings
    coef:         [B, N, N, 3] from tree_patch_coefficients
    filter:       [F, 3, O, M] (feature, eta-slot, output_size, num_filters)
    Returns [B, N, O, M] — out[b, root] = patch(root) @ Filter, zero for
    roots past the sample's node count (their coef rows are all-zero).
    """
    enforce(filter.ndim == 4 and filter.shape[1] == 3,
            "tree_conv filter must be [F, 3, output_size, num_filters]")
    # patch[b, r, f, k] = sum_n coef[b,r,n,k] * feat[b,n,f]
    patch = jnp.einsum("brnk,bnf->brfk", coef, nodes_vector)
    return jnp.einsum("brfk,fkom->brom", patch, filter)


def tree_conv_layer(nodes_vector, edge_set, filter, max_depth):
    """Convenience wrapper matching the reference layer signature
    (layers/nn.py tree_conv): host-builds coefficients, then runs the op.
    edge_set must be concrete (host) data."""
    n = nodes_vector.shape[1]
    coef = jnp.asarray(tree_patch_coefficients(np.asarray(edge_set), n,
                                               max_depth))
    return tree_conv(nodes_vector, coef, filter)
