"""Remaining layers/nn.py surface tail — small ops for full API parity.

Ref: /root/reference/python/paddle/fluid/layers/nn.py and the matching
operators/*.cc. Each op documents its reference and any TPU-first
reinterpretation (static shapes; PRNG keys explicit). Renamed twins of
already-present ops are registered as aliases at the bottom.
"""

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from paddle_tpu.core.enforce import enforce
from paddle_tpu.core.registry import GLOBAL_OP_REGISTRY, register_op


@register_op("label_smooth")
def label_smooth(label, prior_dist=None, epsilon=0.1):
    """ref nn.py label_smooth / operators/label_smooth_op.cc:
    (1-eps)*label + eps*prior (uniform 1/K default)."""
    k = label.shape[-1]
    prior = prior_dist if prior_dist is not None else 1.0 / k
    return (1.0 - epsilon) * label + epsilon * prior


@register_op("multiplex")
def multiplex(inputs, index):
    """ref operators/multiplex_op.cc: out[i] = inputs[index[i]][i] —
    row-wise select among candidate tensors."""
    stacked = jnp.stack(inputs, 0)                  # [N, B, ...]
    idx = index.reshape(-1).astype(jnp.int32)       # [B]
    rows = jnp.arange(stacked.shape[1])
    return stacked[idx, rows]


@register_op("mean_iou")
def mean_iou(prediction, label, num_classes):
    """ref operators/metrics? mean_iou_op.cc: per-class IoU averaged over
    classes present; returns (mean_iou, out_wrong [K], out_correct [K])."""
    pred = prediction.reshape(-1).astype(jnp.int32)
    lab = label.reshape(-1).astype(jnp.int32)
    correct_mask = pred == lab
    out_correct = jnp.zeros((num_classes,), jnp.int32).at[
        jnp.where(correct_mask, lab, num_classes)].add(1, mode="drop")
    # wrong: count each mismatched position under BOTH its pred and label
    wrong_pred = jnp.zeros((num_classes,), jnp.int32).at[
        jnp.where(~correct_mask, pred, num_classes)].add(1, mode="drop")
    wrong_lab = jnp.zeros((num_classes,), jnp.int32).at[
        jnp.where(~correct_mask, lab, num_classes)].add(1, mode="drop")
    out_wrong = wrong_pred + wrong_lab
    # IoU_c = correct_c / (correct_c + wrong_c) — mean_iou_op.h:100
    union = out_correct + out_wrong
    present = union > 0
    iou = jnp.where(present, out_correct / jnp.maximum(union, 1), 0.0)
    miou = jnp.sum(iou) / jnp.maximum(jnp.sum(present), 1)
    return miou, out_wrong, out_correct


@register_op("crop_tensor")
def crop_tensor(x, shape, offsets=None):
    """ref operators/crop_tensor_op.cc: static slice of `shape` at
    `offsets` (zeros default)."""
    offsets = tuple(offsets) if offsets is not None else (0,) * x.ndim
    enforce(len(shape) == x.ndim and len(offsets) == x.ndim,
            "crop_tensor: shape/offsets rank mismatch")
    return lax.slice(x, offsets,
                     tuple(o + s for o, s in zip(offsets, shape)))


@register_op("crop")
def crop(x, shape, offsets=None):
    """ref nn.py crop (older twin of crop_tensor)."""
    return crop_tensor(x, shape, offsets)


@register_op("bilinear_tensor_product")
def bilinear_tensor_product(x, y, weight, bias=None):
    """ref operators/bilinear_tensor_product_op.cc:
    out[b, k] = x[b] @ W[k] @ y[b] + bias[k]; W: [K, Dx, Dy]."""
    out = jnp.einsum("bd,kde,be->bk", x, weight, y)
    if bias is not None:
        out = out + bias
    return out


@register_op("gather_tree")
def gather_tree(ids, parents):
    """ref operators/gather_tree_op.h: beam-search backtrace.
    ids/parents: [T, B, beam]; walks parents from the last step backwards,
    emitting the ancestor id chain per final beam."""
    T = ids.shape[0]

    def step(parent, t):
        # t runs T-2 .. 0; gather ids/parents at the current parent index
        idt = jnp.take_along_axis(ids[t], parent, axis=-1)
        new_parent = jnp.take_along_axis(parents[t], parent, axis=-1)
        return new_parent, idt

    parent0 = parents[T - 1]
    init_out = ids[T - 1]
    _, outs = lax.scan(step, parent0, jnp.arange(T - 2, -1, -1))
    # outs is [T-1, B, beam] for steps T-2..0 — reverse and append the tail
    return jnp.concatenate([outs[::-1], init_out[None]], axis=0)


def _murmur32(x):
    """murmur3 32-bit finalizer — explicit uint32 so bucket ids are
    IDENTICAL regardless of jax_enable_x64 (uint64 would silently
    canonicalize to uint32 under the default config)."""
    x = x.astype(jnp.uint32)
    x = (x ^ (x >> 16)) * np.uint32(0x85EBCA6B)
    x = (x ^ (x >> 13)) * np.uint32(0xC2B2AE35)
    return x ^ (x >> 16)


@register_op("hash")
def hash_bucket(ids, mod_by, num_hash=1):
    """ref operators/hash_op.h: num_hash hashes (seeded 0..n-1) of each id
    row, modulo mod_by. TPU-first: a murmur3-finalizer digest instead of
    XXH64 — same contract (deterministic int hash family), vectorized, and
    config-independent (pure uint32 math)."""
    flat = ids.reshape(ids.shape[0], -1).astype(jnp.uint32)
    outs = []
    for seed in range(num_hash):
        h = jnp.full((flat.shape[0],),
                     np.uint32((seed * 0x9E3779B9 + 1) & 0xFFFFFFFF),
                     jnp.uint32)
        for j in range(flat.shape[1]):  # mix the row like a running digest
            h = _murmur32(h ^ _murmur32(flat[:, j]))
        outs.append((h % np.uint32(mod_by)).astype(jnp.int32))
    return jnp.stack(outs, axis=-1)                 # [rows, num_hash]


@register_op("soft_relu")
def soft_relu(x, threshold=40.0):
    """ref operators/activation_op.h SoftRelu: log(1 + exp(clip(x)))."""
    c = jnp.clip(x, -threshold, threshold)
    return jnp.log1p(jnp.exp(c))


@register_op("sampling_id")
def sampling_id(probs, key):
    """ref operators/sampling_id_op.cc: sample a column index per row from
    the given probabilities (PRNG key explicit — TPU counter RNG)."""
    return jax.random.categorical(key, jnp.log(jnp.maximum(probs, 1e-30)),
                                  axis=-1)


@register_op("pad_constant_like")
def pad_constant_like(ref_larger, x, pad_value=0.0):
    """ref operators/pad_constant_like_op.cc: zero-pad x at the end of each
    dim up to ref's shape."""
    pads = [(0, r - s) for r, s in zip(ref_larger.shape, x.shape)]
    return jnp.pad(x, pads, constant_values=pad_value)


@register_op("uniform_random_batch_size_like")
def uniform_random_batch_size_like(like, key, shape, min=-1.0, max=1.0,
                                   batch_dim=0, dtype=jnp.float32):
    """ref nn.py: random tensor whose batch dim copies `like`'s."""
    shape = list(shape)
    shape[batch_dim] = like.shape[batch_dim]
    return jax.random.uniform(key, tuple(shape), dtype, min, max)


@register_op("gaussian_random_batch_size_like")
def gaussian_random_batch_size_like(like, key, shape, mean=0.0, std=1.0,
                                    batch_dim=0, dtype=jnp.float32):
    shape = list(shape)
    shape[batch_dim] = like.shape[batch_dim]
    return mean + std * jax.random.normal(key, tuple(shape), dtype)


@register_op("ctc_greedy_decoder")
def ctc_greedy_decoder(probs, lengths=None, blank=0):
    """ref nn.py ctc_greedy_decoder: argmax per frame then CTC collapse
    (merge repeats, drop blanks). probs: [B, T, C] (padded batch twin of
    the reference's LoD input). Returns (decoded [B, T], out_lengths)."""
    from paddle_tpu.ops.sequence import ctc_align
    tokens = jnp.argmax(probs, axis=-1).astype(jnp.int32)
    return ctc_align(tokens, lengths, blank=blank, merge_repeated=True)


@register_op("sequence_reshape")
def sequence_reshape(rb, new_dim):
    """ref sequence_ops/sequence_reshape_op.cc: refold each sequence's
    [len, D] values into [len*D/new_dim, new_dim]; lengths scale by
    D/new_dim."""
    from paddle_tpu.core.ragged import RaggedBatch
    d = rb.values.shape[-1]
    lengths_np = np.asarray(rb.row_lengths)
    enforce(bool(((lengths_np * d) % new_dim == 0).all()),
            "sequence_reshape: every sequence's len*D must divide new_dim "
            "(per-row, not just the total — ref sequence_reshape_op.cc)")
    vals = rb.values.reshape(-1, new_dim)
    lengths = (rb.row_lengths * d) // new_dim
    return RaggedBatch(vals, lengths)


@register_op("lod_reset")
def lod_reset(rb, new_lengths):
    """ref operators/lod_reset_op.cc: replace the partition (values
    unchanged)."""
    from paddle_tpu.core.ragged import RaggedBatch
    return RaggedBatch(rb.values, jnp.asarray(new_lengths, jnp.int32))


@register_op("random_crop")
def random_crop(x, key, shape):
    """ref operators/random_crop_op.cc: random spatial crop to `shape`
    (per-batch same offset; key explicit)."""
    offsets = []
    keys = jax.random.split(key, x.ndim)
    for i, (full, want) in enumerate(zip(x.shape, shape)):
        enforce(want <= full, "random_crop: crop larger than input")
        offsets.append(jax.random.randint(keys[i], (), 0, full - want + 1)
                       if full > want else jnp.zeros((), jnp.int32))
    return lax.dynamic_slice(x, offsets, shape)


@register_op("teacher_student_sigmoid_loss")
def teacher_student_sigmoid_loss(logits, label, soft_max_up_bound=15.0,
                                 soft_max_lower_bound=-15.0):
    """ref operators/teacher_student_sigmoid_loss_op.cc: CTR distillation
    loss — label < 0 encodes a teacher score via -label; else hard ctr."""
    x = jnp.clip(logits, soft_max_lower_bound, soft_max_up_bound)
    # log(1+e^x) - z*x with z = hard label or teacher soft score
    z = jnp.where(label < 0, -label, label)
    return jnp.log1p(jnp.exp(x)) - z * x


# --- aliases: renamed twins of present ops (reference-name parity).
# Called from ops/__init__ AFTER every op module has imported, so targets
# registered later in the import order still resolve.
def _alias(name, target):
    if name not in GLOBAL_OP_REGISTRY and target in GLOBAL_OP_REGISTRY:
        GLOBAL_OP_REGISTRY.register(name, GLOBAL_OP_REGISTRY.get(target),
                                    alias_of=target)


def register_reference_aliases():
    for name, target in (
            ("embedding", "lookup_table"),
            ("topk", "top_k"),
            ("image_resize", "interpolate"),
            ("resize_bilinear", "interpolate"),
            ("resize_nearest", "interpolate"),
            ("warpctc", "ctc_loss"),
            ("smooth_l1", "smooth_l1_loss"),
            ("nce", "nce_loss"),
            ("cross_entropy2", "cross_entropy"),
            ("unique", "unique_with_counts"),
            ("cvm", "continuous_value_model"),
            ("deformable_psroi_pooling", "deformable_psroi_pool"),
            ("deformable_roi_pooling", "deformable_psroi_pool"),
            ("dynamic_lstm", "lstm"),
            ("dynamic_gru", "gru"),
            ("gru_unit", "gru_cell"),
            ("lstm_unit", "lstm_cell"),
            ("While", "while_loop"),
            ("Switch", "switch_case"),
            ("IfElse", "cond"),
            ("StaticRNN", "scan"),
            ("DynamicRNN", "scan"),
            ("Print", "print"),
            ("range", "arange"),
            ("basic_gru", "gru"),
            ("basic_lstm", "lstm"),
            ("BasicGRUUnit", "gru_cell"),
            ("BasicLSTMUnit", "lstm_cell")):
        _alias(name, target)


@register_op("continuous_value_model")
def continuous_value_model(x, use_cvm=True):
    """ref operators/cvm_op.h CvmComputeKernel: each row's first two
    features are (show, click). use_cvm=True: y0=log(show+1),
    y1=log(click+1)-y0, rest copied. use_cvm=False: drop the two columns."""
    if not use_cvm:
        return x[:, 2:]
    y0 = jnp.log(x[:, :1] + 1.0)
    y1 = jnp.log(x[:, 1:2] + 1.0) - y0
    return jnp.concatenate([y0, y1, x[:, 2:]], axis=1)


@register_op("adaptive_pool3d")
def adaptive_pool3d(x, output_size, pool_type="avg"):
    """ref operators/pool_op.cc adaptive 3-D path; x [N, C, D, H, W];
    divisible sizes only (static shapes on TPU)."""
    od, oh, ow = ((output_size,) * 3 if isinstance(output_size, int)
                  else tuple(output_size))
    n, c, d, h, w = x.shape
    enforce(d % od == 0 and h % oh == 0 and w % ow == 0,
            "adaptive_pool3d requires divisible sizes on TPU")
    x6 = x.reshape(n, c, od, d // od, oh, h // oh, ow, w // ow)
    red = (3, 5, 7)
    return jnp.max(x6, axis=red) if pool_type == "max" \
        else jnp.mean(x6, axis=red)


@register_op("lod_append")
def lod_append(values, outer_lengths, inner_lengths):
    """ref lod_reset/lod_append family: build a two-level partition over
    `values`, returning a NestedRagged (multi-level LoD, lod_tensor.h:52).
    outer_lengths counts inner groups per outer row; inner_lengths counts
    value rows per inner group (sums must chain)."""
    from paddle_tpu.core.ragged import NestedRagged
    return NestedRagged.from_parts(values, (outer_lengths, inner_lengths))


@register_op("image_resize_short")
def image_resize_short(x, out_short_len, resample="BILINEAR",
                       data_format="NCHW"):
    """ref nn.py image_resize_short: scale so the SHORTER edge equals
    out_short_len, keeping aspect ratio."""
    from paddle_tpu.ops.nn import interpolate
    if data_format == "NCHW":
        h, w = x.shape[2], x.shape[3]
    else:
        h, w = x.shape[1], x.shape[2]
    short = min(h, w)
    # int(x + 0.5), not banker's rounding (ref nn.py image_resize_short)
    out_h = int(h * out_short_len / short + 0.5)
    out_w = int(w * out_short_len / short + 0.5)
    return interpolate(x, size=(out_h, out_w),
                       mode=resample.lower(), data_format=data_format)


@register_op("spectral_norm")
def spectral_norm(weight, u, v, dim=0, power_iters=1, eps=1e-12):
    """Functional spectral normalization (ref operators/spectral_norm_op.cc):
    returns (w / sigma, new_u, new_v). The nn.SpectralNorm layer carries
    u/v as mutable state; this is the op-level twin."""
    h = weight.shape[dim]
    wmat = jnp.moveaxis(weight, dim, 0).reshape(h, -1)
    for _ in range(power_iters):
        v = wmat.T @ u
        v = v / (jnp.linalg.norm(v) + eps)
        u = wmat @ v
        u = u / (jnp.linalg.norm(u) + eps)
    sigma = u @ wmat @ v
    return weight / sigma, u, v


@register_op("dynamic_lstmp")
def dynamic_lstmp(x, h0, c0, w_ih, w_hh, w_proj, b=None, lengths=None,
                  reverse=False, time_major=False, proj_activation="tanh"):
    """LSTM with a recurrent projection layer (ref operators/lstmp_op.cc):
    the hidden state fed back through the recurrence is
    r_t = proj_act(h_t @ w_proj) (smaller than the cell), the classic LSTMP
    of speech models. proj_activation defaults to tanh like the reference
    (lstmp_op.cc SetDefault("tanh")); pass None for identity.

    x [B,T,I]; h0 [B,P]; c0 [B,H]; w_ih [I,4H]; w_hh [P,4H]; w_proj [H,P].
    Returns (projected outputs [B,T,P], (r, c)).
    """
    from paddle_tpu.ops.rnn import _masked_scan, lstm_cell
    if not time_major:
        x = jnp.swapaxes(x, 0, 1)
    if proj_activation is None:
        proj_act = lambda z: z
    else:
        from paddle_tpu.ops import activations
        proj_act = getattr(activations, proj_activation)

    def step(carry, x_t):
        r, c = carry
        h, c = lstm_cell(x_t, r, c, w_ih, w_hh, b)
        r = proj_act(h @ w_proj)
        return (r, c)

    (r, c), outs = _masked_scan(step, x, (h0, c0), lengths, reverse)
    if not time_major:
        outs = jnp.swapaxes(outs, 0, 1)
    return outs, (r, c)


@register_op("filter_by_instag")
def filter_by_instag(x, ins_tags, filter_tags, out_size=None, pad_tag=0):
    """ref operators/filter_by_instag_op.cc: keep rows whose tag set
    intersects filter_tags. Static-shape twin: returns (filtered [K, ...]
    rows compacted to the front with zero padding, keep_mask [B],
    row_map [K] original indices with K = out_size or B; slots past the
    kept count map to B). ins_tags rows are padded with `pad_tag`, which
    never matches (the dense twin of the reference's ragged tag lists)."""
    B = x.shape[0]
    K = out_size if out_size is not None else B
    ftags = jnp.asarray(filter_tags)
    hit = jnp.any((ins_tags[:, :, None] == ftags[None, None, :])
                  & (ins_tags[:, :, None] != pad_tag), axis=(1, 2))
    order = jnp.argsort(~hit, stable=True)            # kept rows first
    slots = jnp.arange(K)
    row_map = jnp.where(slots < B, order[jnp.minimum(slots, B - 1)], B)
    valid = (slots < B) & jnp.take(hit, jnp.minimum(row_map, B - 1))
    row_map = jnp.where(valid, row_map, B)            # B = "no row"
    out = jnp.where(valid.reshape((-1,) + (1,) * (x.ndim - 1)),
                    jnp.take(x, jnp.minimum(row_map, B - 1), axis=0), 0)
    return out, hit, row_map


@register_op("conv_shift")
def conv_shift(x, y):
    """ref operators/conv_shift_op.cc — NTM circular correlation:
    out[i, j] = sum_k x[i, (j + k - (N-1)/2) mod M] * y[i, k];
    x [B, M], y [B, N] (N odd, N <= M) -> [B, M]."""
    B, M = x.shape
    N = y.shape[1]
    half = (N - 1) // 2
    # gather index matrix [M, N]: column j of out reads x at (j+k-half)%M
    j = jnp.arange(M)[:, None]
    k = jnp.arange(N)[None, :]
    idx = (j + k - half) % M
    return jnp.einsum("bmn,bn->bm", x[:, idx], y)


@register_op("squared_l2_distance")
def squared_l2_distance(x, y):
    """ref operators/squared_l2_distance_op.cc — rowwise ||x-y||²; y may
    have batch 1 (broadcast). Returns (distance [N, 1], sub [N, D])."""
    sub = x - y
    return jnp.sum(sub * sub, axis=-1, keepdims=True), sub


@register_op("squared_l2_norm")
def squared_l2_norm(x):
    """ref operators/squared_l2_norm_op.cc — sum of squares (scalar)."""
    return jnp.sum(x * x)


@register_op("l1_norm")
def l1_norm(x):
    """ref operators/l1_norm_op.cc — sum of absolute values (scalar)."""
    return jnp.sum(jnp.abs(x))


@register_op("modified_huber_loss")
def modified_huber_loss(x, y):
    """ref operators/modified_huber_loss_op.h — binary classification loss
    on margin val = (2y-1)*x: val<-1 -> -4*val; val<1 -> (1-val)²; else 0."""
    val = (2.0 * y - 1.0) * x
    return jnp.where(val < -1.0, -4.0 * val,
                     jnp.where(val < 1.0, (1.0 - val) ** 2, 0.0))


@register_op("positive_negative_pair")
def positive_negative_pair(score, label, query_id):
    """ref operators/positive_negative_pair_op.cc — LTR metric: within each
    query, count item pairs ranked concordantly (positive), discordantly
    (negative); ties count 0.5 each. Returns (positive, negative, neutral).

    TPU-first: the reference walks a per-query hash map; here one [N, N]
    comparison matrix masked to same-query pairs (static shape)."""
    s = score.reshape(-1)
    l = label.reshape(-1)
    q = query_id.reshape(-1)
    same_q = q[:, None] == q[None, :]
    upper = jnp.triu(jnp.ones_like(same_q), k=1)       # each pair once
    pair = same_q & (upper > 0) & (l[:, None] != l[None, :])
    prod = (s[:, None] - s[None, :]) * (l[:, None] - l[None, :]).astype(
        s.dtype)
    # reference tie semantics (positive_negative_pair_op.h:94-99): a score
    # tie increments neutral AND falls into the negative branch (the
    # ternary's > 0 test fails at exactly 0)
    pos = jnp.sum(jnp.where(pair, (prod > 0).astype(s.dtype), 0.0))
    neg = jnp.sum(jnp.where(pair, (prod <= 0).astype(s.dtype), 0.0))
    neu = jnp.sum(jnp.where(pair, (s[:, None] == s[None, :]).astype(s.dtype),
                            0.0))
    return pos, neg, neu


@register_op("sample_logits")
def sample_logits(logits, labels, num_samples, key, remove_accidental_hits=True,
                  use_customized_samples=False, customized_samples=None,
                  customized_probabilities=None):
    """ref operators/sample_logits_op.{cc,h} — sampled-softmax helper.

    samples = concat(labels, drawn negatives) [N, T+S] with per-column
    sampler probabilities q; output = gather(logits, samples) - log(q)
    (the same correction for true and sampled columns, as the reference's
    `smp_logits - probs.log()`), with accidental hits (a sampled column
    equal to one of the row's true labels) pushed to -inf. With
    use_customized_samples, customized_samples/probabilities are the full
    [N, T+S] arrays (the reference ShareDataWith's them verbatim).
    Returns (sampled_logits [N, T+S], sampled_labels [N, T]).

    Deviation: negatives are drawn uniformly (q = 1/K) rather than
    log-uniform — Zipf resampling is data-dependent control flow; feed
    customized samples for a log-uniform schedule."""
    n, k = logits.shape
    t = labels.shape[1]
    if use_customized_samples:
        samples = customized_samples                       # [N, T+S]
        probs = customized_probabilities
    else:
        drawn = jax.random.randint(key, (n, num_samples), 0, k)
        samples = jnp.concatenate([labels, drawn], axis=1)  # [N, T+S]
        probs = jnp.full((n, t + num_samples), 1.0 / k, logits.dtype)
    out = jnp.take_along_axis(logits, samples, axis=1)     # [N, T+S]
    if remove_accidental_hits:
        hit = samples[:, None, t:] == labels[:, :, None]   # [N, T, S]
        out = out.at[:, t:].add(jnp.where(hit.any(1), -1e20, 0.0))
    out = out - jnp.log(probs)
    sampled_labels = jnp.broadcast_to(jnp.arange(t)[None, :], (n, t))
    return out, sampled_labels


@register_op("similarity_focus")
def similarity_focus(x, axis, indexes):
    """ref operators/similarity_focus_op.cc — per (batch, index) slice
    T=[B', C'], greedily mark min(B',C') maxima with distinct rows AND
    columns (like a greedy assignment), OR the masks over indexes, and
    broadcast back to x's shape. x: 4-D [N, A, B, C]; axis in {1, 2, 3}.

    TPU-first: the reference's sort-and-scan becomes a lax.fori_loop of
    argmax + row/col suppression (min(B', C') static iterations)."""
    enforce(x.ndim == 4, "similarity_focus expects a 4-D input")
    enforce(axis in (1, 2, 3), "axis must be 1, 2 or 3")
    perm = {1: (0, 1, 2, 3), 2: (0, 2, 1, 3), 3: (0, 3, 1, 2)}[axis]
    xt = jnp.transpose(x, perm)                       # [N, K, R, C]
    n, _, r, c = xt.shape
    iters = min(r, c)

    def one_slice(t):                                  # [R, C] -> mask
        def body(_, carry):
            mask, rowf, colf = carry
            neg = jnp.finfo(t.dtype).min
            masked = jnp.where(rowf[:, None] | colf[None, :], neg, t)
            flat = jnp.argmax(masked)
            i, j = flat // c, flat % c
            mask = mask.at[i, j].set(1.0)
            return mask, rowf.at[i].set(True), colf.at[j].set(True)

        mask0 = jnp.zeros_like(t)
        rowf0 = jnp.zeros((r,), bool)
        colf0 = jnp.zeros((c,), bool)
        mask, _, _ = lax.fori_loop(0, iters, body, (mask0, rowf0, colf0))
        return mask

    sel = xt[:, jnp.asarray(list(indexes))]            # [N, I, R, C]
    masks = jax.vmap(jax.vmap(one_slice))(sel)         # [N, I, R, C]
    merged = masks.max(axis=1, keepdims=True)          # OR over indexes
    out = jnp.broadcast_to(merged, xt.shape)
    inv = np.argsort(perm)
    return jnp.transpose(out, tuple(inv))


@register_op("is_empty")
def is_empty(x):
    """ref operators/is_empty_op.cc — static on TPU: shapes are compile-time."""
    return jnp.asarray(x.size == 0)


@register_op("minus")
def minus(x, y):
    """ref operators/minus_op.cc — out = x - y."""
    return x - y





@register_op("scatter_nd")
def scatter_nd(index, updates, shape):
    """ref operators/scatter_nd_add_op.cc family / layers/nn.py scatter_nd:
    zeros(shape) with `updates` added at `index` (duplicates accumulate).
    index: [..., K] int; updates: index.shape[:-1] + shape[K:]."""
    out = jnp.zeros(shape, updates.dtype)
    k = index.shape[-1]
    idx = tuple(jnp.moveaxis(index, -1, 0))
    return out.at[idx].add(updates)


@register_op("autoincreased_step_counter")
def autoincreased_step_counter(counter=None):
    """ref layers/nn.py autoincreased_step_counter — the reference mutates
    a persistable counter var in the scope; the functional redesign takes
    the counter as carried state and returns it incremented (keep it in
    the optimizer/train state pytree)."""
    if counter is None:
        counter = jnp.zeros((), jnp.int64 if jax.config.jax_enable_x64
                            else jnp.int32)
    return counter + 1


@register_op("resize_trilinear")
def resize_trilinear(x, size=None, scale_factor=None, align_corners=False):
    """ref operators/interpolate_op.cc trilinear path — NCDHW volumetric
    resize by separable linear interpolation along D, H, W."""
    n, c, d, h, w = x.shape
    if size is None:
        s = (scale_factor,) * 3 if isinstance(
            scale_factor, (int, float)) else tuple(scale_factor)
        size = (int(d * s[0]), int(h * s[1]), int(w * s[2]))
    od, oh, ow = size

    def axis_coords(out_n, in_n):
        if align_corners and out_n > 1:
            return jnp.arange(out_n) * ((in_n - 1) / (out_n - 1))
        return jnp.maximum((jnp.arange(out_n) + 0.5) * (in_n / out_n) - 0.5,
                           0.0)

    def lin(x, coords, axis):
        i0 = jnp.floor(coords).astype(jnp.int32)
        i1 = jnp.minimum(i0 + 1, x.shape[axis] - 1)
        wgt = (coords - i0).astype(x.dtype)
        a = jnp.take(x, i0, axis=axis)
        b = jnp.take(x, i1, axis=axis)
        shape = [1] * x.ndim
        shape[axis] = -1
        return a + (b - a) * wgt.reshape(shape)

    x = lin(x, axis_coords(od, d), 2)
    x = lin(x, axis_coords(oh, h), 3)
    x = lin(x, axis_coords(ow, w), 4)
    return x


@register_op("merge_selected_rows")
def merge_selected_rows(ids, rows):
    """ref operators/merge_selected_rows_op.cc — merge duplicate rows of a
    SelectedRows. Functional twin over the (ids, rows) pair encoding:
    returns (unique_ids [k], merged_rows [k, D], valid [k]) with k =
    ids.size (static worst case)."""
    from paddle_tpu.parallel.sparse import segment_rowsum, unique_ids
    uniq, inv, valid = unique_ids(ids)
    merged = segment_rowsum(rows, inv, uniq.shape[0])
    return uniq, merged, valid


@register_op("get_tensor_from_selected_rows")
def get_tensor_from_selected_rows(ids, rows, height):
    """ref operators/get_tensor_from_selected_rows_op.cc — densify a
    SelectedRows into a [height, D] tensor (duplicates accumulate)."""
    out = jnp.zeros((height, rows.shape[-1]), rows.dtype)
    return out.at[ids.reshape(-1)].add(
        rows.reshape(-1, rows.shape[-1]))


@register_op("py_func")
def py_func(func, *args, out_shape_dtype):
    """ref operators/py_func_op.cc / layers/nn.py py_func — run arbitrary
    host Python inside a compiled program. TPU-era mechanism:
    jax.pure_callback (host round-trip at the op's position; func must be
    pure per its contract, same as the reference's func semantics).
    out_shape_dtype: a jax.ShapeDtypeStruct (or pytree of them)."""
    return jax.pure_callback(func, out_shape_dtype, *args)


@register_op("assign")
def assign(x, output=None):
    """ref operators/assign_op.cc — identity copy (functional: output arg
    is the reference's in-place target, ignored here)."""
    return jnp.asarray(x)


@register_op("sums")
def sums(inputs):
    """ref operators/sum_op.cc over a list — elementwise sum of tensors."""
    out = inputs[0]
    for t in inputs[1:]:
        out = out + t
    return out


@register_op("has_inf")
def has_inf(x):
    """ref operators/isfinite_op.cc has_inf."""
    return jnp.any(jnp.isinf(x))


@register_op("has_nan")
def has_nan(x):
    """ref operators/isfinite_op.cc has_nan."""
    return jnp.any(jnp.isnan(x))


@register_op("tensor_array_to_tensor")
def tensor_array_to_tensor(array, axis=1, use_stack=False):
    """ref operators/tensor_array_to_tensor_op.cc — our TensorArray is
    already a stacked [N, ...] tensor: stack moves the array dim to
    `axis`; concat merges entries along `axis`."""
    if use_stack:
        return jnp.moveaxis(array, 0, axis)
    parts = [array[i] for i in range(array.shape[0])]
    return jnp.concatenate(parts, axis=axis)


@register_op("ones")
def ones(shape, dtype=jnp.float32):
    """ref layers/tensor.py ones — fill_constant(shape, 1) like the
    reference."""
    from paddle_tpu.ops.tensor_ops import fill_constant
    return fill_constant(shape, dtype, 1.0)


@register_op("zeros")
def zeros(shape, dtype=jnp.float32):
    """ref layers/tensor.py zeros — fill_constant(shape, 0)."""
    from paddle_tpu.ops.tensor_ops import fill_constant
    return fill_constant(shape, dtype, 0.0)


@register_op("create_tensor")
def create_tensor(dtype=jnp.float32, shape=()):
    """ref layers/tensor.py create_tensor — a zero tensor (variables are
    just arrays here; mutation is functional)."""
    return jnp.zeros(shape, dtype)


@register_op("create_global_var")
def create_global_var(shape, value, dtype=jnp.float32):
    """ref layers/tensor.py create_global_var — a filled array to carry in
    the train-state pytree (persistable scope vars are state here)."""
    return jnp.full(tuple(shape), value, dtype)


@register_op("create_parameter")
def create_parameter(shape, dtype=jnp.float32, initializer=None, key=None):
    """ref layers/tensor.py create_parameter — initializer-backed array.
    Random initializers REQUIRE a PRNG key (explicit TPU RNG — a silent
    constant key would hand every parameter identical values)."""
    if initializer is None:
        return jnp.zeros(tuple(shape), dtype)
    enforce(key is not None,
            "create_parameter with an initializer needs a PRNG key "
            "(jax.random.key(...)) — parameters must not share a "
            "constant default key")
    return initializer(key, tuple(shape), dtype)
