"""Instance-mask target ops (Mask R-CNN training).

Ref: /root/reference/paddle/fluid/operators/detection/
generate_mask_labels_op.cc + mask_util.cc (Poly2Mask — COCO-style polygon
rasterization; Polys2MaskWrtBox — rasterize a gt's polygon parts into an
M x M grid over a box).

TPU-first split: polygons are ragged HOST data, so rasterization is a
numpy op (like the reference's CPU-only kernel); the produced dense
[R, M, M] targets feed the jitted mask head. The rasterizer uses even-odd
crossing counts at pixel centers (sub-pixel boundary handling differs from
COCO's 5x-upsampled RLE by at most the boundary pixels).
"""

import numpy as np

from paddle_tpu.core.enforce import enforce
from paddle_tpu.core.registry import register_op


@register_op("poly2mask")
def poly2mask(poly_xy, h, w):
    """Rasterize one polygon (flat [x0, y0, x1, y1, ...]) into a uint8
    [h, w] mask — even-odd rule at pixel centers (ref mask_util.cc
    Poly2Mask capability)."""
    pts = np.asarray(poly_xy, np.float64).reshape(-1, 2)
    enforce(len(pts) >= 3, "polygon needs >= 3 points")
    ys = np.arange(h) + 0.5
    xs = np.arange(w) + 0.5
    x0 = pts[:, 0]
    y0 = pts[:, 1]
    x1 = np.roll(x0, -1)
    y1 = np.roll(y0, -1)
    mask = np.zeros((h, w), np.uint8)
    for row, yc in enumerate(ys):
        # edges crossing this scanline
        cross = (y0 <= yc) != (y1 <= yc)
        if not cross.any():
            continue
        xi = x0[cross] + (yc - y0[cross]) * (x1[cross] - x0[cross]) \
            / (y1[cross] - y0[cross])
        inside = (xi[None, :] <= xs[:, None]).sum(axis=1) % 2 == 1
        mask[row] = inside
    return mask


@register_op("polys_to_mask_wrt_box")
def polys_to_mask_wrt_box(polygons, box, resolution):
    """Rasterize a gt's polygon parts into an M x M grid over `box`
    (ref mask_util.cc Polys2MaskWrtBox: scale each part into the box frame,
    union the parts)."""
    x0, y0, x1, y1 = [float(v) for v in box]
    w = max(x1 - x0, 1.0)
    h = max(y1 - y0, 1.0)
    out = np.zeros((resolution, resolution), np.uint8)
    for part in polygons:
        p = np.asarray(part, np.float64).reshape(-1, 2).copy()
        p[:, 0] = (p[:, 0] - x0) * resolution / w
        p[:, 1] = (p[:, 1] - y0) * resolution / h
        out |= poly2mask(p.reshape(-1), resolution, resolution)
    return out


@register_op("generate_mask_labels")
def generate_mask_labels(rois, labels, gt_boxes, gt_polys, resolution=14):
    """Mask targets for sampled fg rois (ref generate_mask_labels_op.cc).

    rois [R, 4]; labels [R] (output of generate_proposal_labels: class id
    for fg, 0 bg, -1 ignore); gt_boxes [G, 4]; gt_polys: list of G
    polygon-part lists. Returns float32 [R, resolution, resolution] with
    mask targets for fg rois and -1 (ignore) elsewhere — the dense static
    twin of the reference's gathered mask_rois/mask_int32.
    """
    rois = np.asarray(rois, np.float64)
    labels = np.asarray(labels).astype(int)
    gtb = np.asarray(gt_boxes, np.float64)
    R = rois.shape[0]
    out = np.full((R, resolution, resolution), -1.0, np.float32)
    # match rois to gts with the SAME +1 IoU convention as the label
    # sampler (iou_similarity box_normalized=False), so the mask comes
    # from the gt whose class the roi was labeled with
    from paddle_tpu.ops.detection import iou_similarity
    iou = np.asarray(iou_similarity(rois.astype(np.float32),
                                    gtb.astype(np.float32),
                                    box_normalized=False))  # [R, G]
    for r in range(R):
        if labels[r] <= 0:
            continue
        g = int(np.argmax(iou[r]))
        if iou[r, g] <= 0:
            continue  # label/gt mismatch from the caller: keep -1 ignore
        out[r] = polys_to_mask_wrt_box(gt_polys[g], rois[r],
                                       resolution).astype(np.float32)
    return out
