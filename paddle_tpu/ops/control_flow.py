"""Control-flow ops — structured, compiler-friendly.

Ref: /root/reference/paddle/fluid/operators/controlflow/ (while_op.cc,
conditional_block_op.cc) and operators/recurrent_op.cc — the reference runs
sub-blocks through a nested Executor with step-scopes.

TPU-first: control flow must stay inside the compiled program, so these are
thin wrappers over `lax.while_loop` / `lax.cond` / `lax.scan` / `lax.switch`
operating on pytree carries (the step-scope equivalent). No Python-level
interpretation at run time.
"""

import jax
from jax import lax

from paddle_tpu.core.registry import register_op


@register_op("while_loop")
def while_loop(cond, body, loop_vars):
    """ref: operators/controlflow/while_op.cc"""
    return lax.while_loop(cond, body, loop_vars)


@register_op("cond")
def cond(pred, true_fn, false_fn, *operands):
    """ref: operators/controlflow/conditional_block_op.cc"""
    return lax.cond(pred, true_fn, false_fn, *operands)


@register_op("case")
def case(pred_fn_pairs, default=None):
    """ref: layers/control_flow.py case() — first true predicate wins."""
    preds = [p for p, _ in pred_fn_pairs]
    fns = [f for _, f in pred_fn_pairs]
    if default is not None:
        fns = fns + [default]

    def step(i, carry):
        return carry

    # build nested conds from the back
    def make(i):
        if i == len(preds):
            if default is None:
                return fns[-1]
            return default
        return lambda: lax.cond(preds[i], fns[i], make(i + 1))

    return make(0)()


@register_op("switch_case")
def switch_case(branch_index, branch_fns, *operands):
    """ref: layers/control_flow.py switch_case()"""
    return lax.switch(branch_index, branch_fns, *operands)


@register_op("scan")
def scan(f, init, xs, length=None, reverse=False, unroll=1):
    """The static-RNN primitive (ref: operators/recurrent_op.cc — the
    reference's RecurrentOp runs a sub-block per step with step-scopes; scan
    compiles the whole loop into one XLA While with stacked outputs)."""
    return lax.scan(f, init, xs, length=length, reverse=reverse, unroll=unroll)


@register_op("fori_loop")
def fori_loop(lower, upper, body, init):
    return lax.fori_loop(lower, upper, body, init)


@register_op("stop_gradient")
def stop_gradient(x):
    return lax.stop_gradient(x)


# ---- TensorArray successors (ref: layers/control_flow.py array_write/
# array_read/array_length over LoDTensorArray) — functional redesign: the
# array is a pre-sized stacked jnp array carried through the loop (static
# shapes; lax.scan/while carry it), index writes are at[].set.
@register_op("create_array")
def create_array(size, element_shape, dtype=None):
    """Fixed-capacity TensorArray: zeros([size, *element_shape])."""
    import jax.numpy as jnp
    return jnp.zeros((size,) + tuple(element_shape),
                     dtype if dtype is not None else jnp.float32)


@register_op("array_write")
def array_write(array, i, x):
    """ref layers/control_flow.py array_write — arr[i] = x (functional)."""
    return array.at[i].set(x)


@register_op("array_read")
def array_read(array, i):
    """ref layers/control_flow.py array_read."""
    return array[i]


@register_op("array_length")
def array_length(array):
    """ref layers/control_flow.py array_length — static capacity."""
    import jax.numpy as jnp
    return jnp.asarray(array.shape[0], jnp.int32)


@register_op("print")
def print_op(x, message="", summarize=8):
    """ref operators/print_op.cc / layers/control_flow.py Print — print a
    tensor from inside a compiled program (jax.debug.print host hop);
    returns x unchanged so it drops into dataflow like the reference op.
    summarize: print only the first N elements (<=0 prints all)."""
    import jax
    import jax.numpy as jnp
    shown = jnp.ravel(x)[:summarize] if summarize and summarize > 0 else x
    # message passed as a value, not a format string — braces are safe
    jax.debug.print("{m}{x}", m=message, x=shown)
    return x
