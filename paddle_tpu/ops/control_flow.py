"""Control-flow ops — structured, compiler-friendly.

Ref: /root/reference/paddle/fluid/operators/controlflow/ (while_op.cc,
conditional_block_op.cc) and operators/recurrent_op.cc — the reference runs
sub-blocks through a nested Executor with step-scopes.

TPU-first: control flow must stay inside the compiled program, so these are
thin wrappers over `lax.while_loop` / `lax.cond` / `lax.scan` / `lax.switch`
operating on pytree carries (the step-scope equivalent). No Python-level
interpretation at run time.
"""

import jax
from jax import lax

from paddle_tpu.core.registry import register_op


@register_op("while_loop")
def while_loop(cond, body, loop_vars):
    """ref: operators/controlflow/while_op.cc"""
    return lax.while_loop(cond, body, loop_vars)


@register_op("cond")
def cond(pred, true_fn, false_fn, *operands):
    """ref: operators/controlflow/conditional_block_op.cc"""
    return lax.cond(pred, true_fn, false_fn, *operands)


@register_op("case")
def case(pred_fn_pairs, default=None):
    """ref: layers/control_flow.py case() — first true predicate wins."""
    preds = [p for p, _ in pred_fn_pairs]
    fns = [f for _, f in pred_fn_pairs]
    if default is not None:
        fns = fns + [default]

    def step(i, carry):
        return carry

    # build nested conds from the back
    def make(i):
        if i == len(preds):
            if default is None:
                return fns[-1]
            return default
        return lambda: lax.cond(preds[i], fns[i], make(i + 1))

    return make(0)()


@register_op("switch_case")
def switch_case(branch_index, branch_fns, *operands):
    """ref: layers/control_flow.py switch_case()"""
    return lax.switch(branch_index, branch_fns, *operands)


@register_op("scan")
def scan(f, init, xs, length=None, reverse=False, unroll=1):
    """The static-RNN primitive (ref: operators/recurrent_op.cc — the
    reference's RecurrentOp runs a sub-block per step with step-scopes; scan
    compiles the whole loop into one XLA While with stacked outputs)."""
    return lax.scan(f, init, xs, length=length, reverse=reverse, unroll=unroll)


@register_op("fori_loop")
def fori_loop(lower, upper, body, init):
    return lax.fori_loop(lower, upper, body, init)


@register_op("stop_gradient")
def stop_gradient(x):
    return lax.stop_gradient(x)
