"""Sequence ops over ragged batches.

Ref: /root/reference/paddle/fluid/operators/sequence_ops/ (24 ops:
sequence_pool, sequence_softmax, sequence_expand, sequence_pad/unpad,
sequence_concat, sequence_reverse, sequence_mask, sequence_slice,
sequence_first/last_step …) — all keyed off LoDTensor offsets.

TPU-first: sequences are `RaggedBatch` (flat values + row_lengths); pooling
uses `jax.ops.segment_*` (static-size, XLA-scatter based), and the
dense/padded conversions live on RaggedBatch itself. `sequence_mask` is the
bridge to MXU-friendly padded compute.
"""

import jax
import jax.numpy as jnp

from paddle_tpu.core.ragged import RaggedBatch
from paddle_tpu.core.registry import register_op


@register_op("sequence_mask")
def sequence_mask(lengths, maxlen=None, dtype=jnp.float32):
    """ref: operators/sequence_ops/sequence_mask_op.cc"""
    maxlen = int(maxlen) if maxlen is not None else int(jnp.max(lengths))
    return (jnp.arange(maxlen)[None, :] < lengths[:, None]).astype(dtype)


@register_op("sequence_pool")
def sequence_pool(rb: RaggedBatch, pool_type="sum"):
    """ref: sequence_pool_op.cc — per-sequence {sum,mean,max,min,sqrt,first,last}."""
    seg = rb.segment_ids()
    n = rb.nrows
    v = rb.values
    if pool_type == "sum":
        return jax.ops.segment_sum(v, seg, n)
    if pool_type == "mean":
        s = jax.ops.segment_sum(v, seg, n)
        cnt = jnp.maximum(rb.row_lengths, 1).astype(v.dtype)
        return s / cnt.reshape((-1,) + (1,) * (v.ndim - 1))
    if pool_type == "sqrt":
        s = jax.ops.segment_sum(v, seg, n)
        cnt = jnp.maximum(rb.row_lengths, 1).astype(v.dtype)
        return s / jnp.sqrt(cnt).reshape((-1,) + (1,) * (v.ndim - 1))
    if pool_type == "max":
        return jax.ops.segment_max(v, seg, n)
    if pool_type == "min":
        return jax.ops.segment_min(v, seg, n)
    offs = rb.offsets()
    if pool_type == "first":
        return v[offs[:-1]]
    if pool_type == "last":
        return v[jnp.maximum(offs[1:] - 1, 0)]
    raise ValueError(f"unknown pool_type {pool_type}")


@register_op("sequence_softmax")
def sequence_softmax(rb: RaggedBatch):
    """ref: sequence_softmax_op.cc — softmax within each sequence (1-D values)."""
    seg = rb.segment_ids()
    n = rb.nrows
    m = jax.ops.segment_max(rb.values, seg, n)
    e = jnp.exp(rb.values - m[seg])
    z = jax.ops.segment_sum(e, seg, n)
    return RaggedBatch(e / z[seg], rb.row_lengths)


@register_op("sequence_expand")
def sequence_expand(x, rb_y: RaggedBatch):
    """ref: sequence_expand_op.cc — repeat row i of x y.row_lengths[i] times."""
    reps = rb_y.row_lengths
    idx = jnp.repeat(jnp.arange(x.shape[0]), reps,
                     total_repeat_length=int(rb_y.values.shape[0]))
    return RaggedBatch(x[idx], reps)


@register_op("sequence_reverse")
def sequence_reverse(rb: RaggedBatch):
    """ref: sequence_reverse_op.cc — reverse each sequence in place."""
    offs = rb.offsets()
    seg = rb.segment_ids()
    pos = jnp.arange(rb.values.shape[0])
    local = pos - offs[seg]
    rev_idx = offs[seg] + (rb.row_lengths[seg] - 1 - local)
    return RaggedBatch(rb.values[rev_idx], rb.row_lengths)


@register_op("sequence_pad")
def sequence_pad(rb: RaggedBatch, pad_value=0.0, maxlen=None):
    """ref: sequence_pad_op.cc — returns (padded, lengths)."""
    dense, _ = rb.to_padded(maxlen, pad_value)
    return dense, rb.row_lengths


@register_op("sequence_unpad")
def sequence_unpad(x, lengths):
    """ref: sequence_unpad_op.cc"""
    return RaggedBatch.from_padded(x, lengths)


@register_op("sequence_concat")
def sequence_concat(rbs):
    """ref: sequence_concat_op.cc — concat sequence-wise (row i = concat of
    row i across inputs)."""
    n = rbs[0].nrows
    parts = []
    for i in range(n):
        for rb in rbs:
            offs = rb.offsets()
            parts.append(rb.values[int(offs[i]):int(offs[i + 1])])
    values = jnp.concatenate(parts, 0)
    lengths = rbs[0].row_lengths
    for rb in rbs[1:]:
        lengths = lengths + rb.row_lengths
    return RaggedBatch(values, lengths)


@register_op("sequence_first_step")
def sequence_first_step(rb: RaggedBatch):
    return sequence_pool(rb, "first")


@register_op("sequence_last_step")
def sequence_last_step(rb: RaggedBatch):
    return sequence_pool(rb, "last")


@register_op("sequence_slice")
def sequence_slice(rb: RaggedBatch, offset, length):
    """ref: sequence_slice_op.cc — take [offset, offset+length) of each seq."""
    offs = rb.offsets()[:-1]
    starts = offs + offset
    max_l = int(jnp.max(length)) if hasattr(length, "shape") else int(length)
    idx = starts[:, None] + jnp.arange(max_l)[None, :]
    idx = jnp.clip(idx, 0, rb.values.shape[0] - 1)
    vals = rb.values[idx.reshape(-1)]
    lengths = jnp.broadcast_to(jnp.asarray(length), (rb.nrows,)).astype(jnp.int32)
    valid = (jnp.arange(max_l)[None, :] < lengths[:, None]).reshape(-1)
    order = jnp.argsort(~valid, stable=True)
    return RaggedBatch(vals[order], lengths)


@register_op("sequence_enumerate")
def sequence_enumerate(x, win_size, pad_value=0):
    """ref: sequence_enumerate_op.cc — sliding windows over 1-D ids."""
    n = x.shape[0]
    idx = jnp.arange(n)[:, None] + jnp.arange(win_size)[None, :]
    valid = idx < n
    idx = jnp.clip(idx, 0, n - 1)
    out = jnp.where(valid, x[idx], pad_value)
    return out
