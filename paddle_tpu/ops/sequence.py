"""Sequence ops over ragged batches.

Ref: /root/reference/paddle/fluid/operators/sequence_ops/ (24 ops:
sequence_pool, sequence_softmax, sequence_expand, sequence_pad/unpad,
sequence_concat, sequence_reverse, sequence_mask, sequence_slice,
sequence_first/last_step …) — all keyed off LoDTensor offsets.

TPU-first: sequences are `RaggedBatch` (flat values + row_lengths); pooling
uses `jax.ops.segment_*` (static-size, XLA-scatter based), and the
dense/padded conversions live on RaggedBatch itself. `sequence_mask` is the
bridge to MXU-friendly padded compute.
"""

import jax
import jax.numpy as jnp

from paddle_tpu.core.ragged import RaggedBatch
from paddle_tpu.core.registry import register_op


@register_op("sequence_mask")
def sequence_mask(lengths, maxlen=None, dtype=jnp.float32):
    """ref: operators/sequence_ops/sequence_mask_op.cc"""
    maxlen = int(maxlen) if maxlen is not None else int(jnp.max(lengths))
    return (jnp.arange(maxlen)[None, :] < lengths[:, None]).astype(dtype)


@register_op("sequence_pool")
def sequence_pool(rb: RaggedBatch, pool_type="sum"):
    """ref: sequence_pool_op.cc — per-sequence {sum,mean,max,min,sqrt,first,last}."""
    seg = rb.segment_ids()
    n = rb.nrows
    v = rb.values
    if pool_type == "sum":
        return jax.ops.segment_sum(v, seg, n)
    if pool_type == "mean":
        s = jax.ops.segment_sum(v, seg, n)
        cnt = jnp.maximum(rb.row_lengths, 1).astype(v.dtype)
        return s / cnt.reshape((-1,) + (1,) * (v.ndim - 1))
    if pool_type == "sqrt":
        s = jax.ops.segment_sum(v, seg, n)
        cnt = jnp.maximum(rb.row_lengths, 1).astype(v.dtype)
        return s / jnp.sqrt(cnt).reshape((-1,) + (1,) * (v.ndim - 1))
    if pool_type == "max":
        return jax.ops.segment_max(v, seg, n)
    if pool_type == "min":
        return jax.ops.segment_min(v, seg, n)
    offs = rb.offsets()
    if pool_type == "first":
        return v[offs[:-1]]
    if pool_type == "last":
        return v[jnp.maximum(offs[1:] - 1, 0)]
    raise ValueError(f"unknown pool_type {pool_type}")


@register_op("sequence_softmax")
def sequence_softmax(rb: RaggedBatch):
    """ref: sequence_softmax_op.cc — softmax within each sequence (1-D values)."""
    seg = rb.segment_ids()
    n = rb.nrows
    m = jax.ops.segment_max(rb.values, seg, n)
    e = jnp.exp(rb.values - m[seg])
    z = jax.ops.segment_sum(e, seg, n)
    return RaggedBatch(e / z[seg], rb.row_lengths)


@register_op("sequence_expand")
def sequence_expand(x, rb_y: RaggedBatch):
    """ref: sequence_expand_op.cc — repeat row i of x y.row_lengths[i] times."""
    reps = rb_y.row_lengths
    idx = jnp.repeat(jnp.arange(x.shape[0]), reps,
                     total_repeat_length=int(rb_y.values.shape[0]))
    return RaggedBatch(x[idx], reps)


@register_op("sequence_reverse")
def sequence_reverse(rb: RaggedBatch):
    """ref: sequence_reverse_op.cc — reverse each sequence in place."""
    offs = rb.offsets()
    seg = rb.segment_ids()
    pos = jnp.arange(rb.values.shape[0])
    local = pos - offs[seg]
    rev_idx = offs[seg] + (rb.row_lengths[seg] - 1 - local)
    return RaggedBatch(rb.values[rev_idx], rb.row_lengths)


@register_op("sequence_pad")
def sequence_pad(rb: RaggedBatch, pad_value=0.0, maxlen=None):
    """ref: sequence_pad_op.cc — returns (padded, lengths)."""
    dense, _ = rb.to_padded(maxlen, pad_value)
    return dense, rb.row_lengths


@register_op("sequence_unpad")
def sequence_unpad(x, lengths):
    """ref: sequence_unpad_op.cc"""
    return RaggedBatch.from_padded(x, lengths)


@register_op("sequence_concat")
def sequence_concat(rbs):
    """ref: sequence_concat_op.cc — concat sequence-wise (row i = concat of
    row i across inputs)."""
    n = rbs[0].nrows
    parts = []
    for i in range(n):
        for rb in rbs:
            offs = rb.offsets()
            parts.append(rb.values[int(offs[i]):int(offs[i + 1])])
    values = jnp.concatenate(parts, 0)
    lengths = rbs[0].row_lengths
    for rb in rbs[1:]:
        lengths = lengths + rb.row_lengths
    return RaggedBatch(values, lengths)


@register_op("sequence_first_step")
def sequence_first_step(rb: RaggedBatch):
    return sequence_pool(rb, "first")


@register_op("sequence_last_step")
def sequence_last_step(rb: RaggedBatch):
    return sequence_pool(rb, "last")


@register_op("sequence_slice")
def sequence_slice(rb: RaggedBatch, offset, length):
    """ref: sequence_slice_op.cc — take [offset, offset+length) of each seq."""
    offs = rb.offsets()[:-1]
    starts = offs + offset
    max_l = int(jnp.max(length)) if hasattr(length, "shape") else int(length)
    idx = starts[:, None] + jnp.arange(max_l)[None, :]
    idx = jnp.clip(idx, 0, rb.values.shape[0] - 1)
    vals = rb.values[idx.reshape(-1)]
    lengths = jnp.broadcast_to(jnp.asarray(length), (rb.nrows,)).astype(jnp.int32)
    valid = (jnp.arange(max_l)[None, :] < lengths[:, None]).reshape(-1)
    order = jnp.argsort(~valid, stable=True)
    return RaggedBatch(vals[order], lengths)


@register_op("sequence_enumerate")
def sequence_enumerate(x, win_size, pad_value=0):
    """ref: sequence_enumerate_op.cc — sliding windows over 1-D ids."""
    n = x.shape[0]
    idx = jnp.arange(n)[:, None] + jnp.arange(win_size)[None, :]
    valid = idx < n
    idx = jnp.clip(idx, 0, n - 1)
    out = jnp.where(valid, x[idx], pad_value)
    return out


@register_op("sequence_erase")
def sequence_erase(rb: RaggedBatch, tokens):
    """ref: sequence_ops/sequence_erase_op.cc — drop every occurrence of the
    given token ids from each sequence.

    Host-side (eager only): the output's total length is data-dependent, and
    RaggedBatch requires sum(row_lengths) == values.shape[0], so this is a
    concrete (numpy) computation — like the reference's CPU-only kernel.
    Under jit, mask tokens out with sequence ops instead of erasing.
    """
    import numpy as np
    from paddle_tpu.core.enforce import enforce
    enforce(not isinstance(rb.values, jax.core.Tracer),
            "sequence_erase is host-side only (data-dependent output size); "
            "do not call it under jit")
    v = np.asarray(rb.values)
    seg = np.asarray(rb.segment_ids())
    keep = ~np.isin(v, np.asarray(list(tokens)))
    new_lengths = np.bincount(seg[keep], minlength=rb.nrows).astype(np.int32)
    return RaggedBatch(jnp.asarray(v[keep]), jnp.asarray(new_lengths))


@register_op("sequence_expand_as")
def sequence_expand_as(x, rb_y: RaggedBatch):
    """ref: sequence_ops/sequence_expand_as_op.cc — row i of x repeated
    rb_y.row_lengths[i] times (same mechanics as sequence_expand here)."""
    return sequence_expand(x, rb_y)


@register_op("sequence_scatter")
def sequence_scatter(x, rb_ids: RaggedBatch, rb_updates: RaggedBatch):
    """ref: sequence_ops/sequence_scatter_op.cc — for row i:
    out[i, ids_i[k]] += updates_i[k]."""
    rows = rb_ids.segment_ids()
    return x.at[rows, rb_ids.values].add(rb_updates.values)


def _repack(dense, rb):
    """Inverse of rb.to_padded for a same-layout result: gather the valid
    [B, T, ...] entries back to rb's flat layout. Fully static (the flat
    total is rb.values.shape[0]) — works under jit, unlike from_padded."""
    seg = rb.segment_ids()                                   # [total]
    offs = rb.offsets()[:-1]
    pos = jnp.arange(rb.values.shape[0], dtype=jnp.int32) - offs[seg]
    return RaggedBatch(dense[seg, pos], rb.row_lengths)


def _padded_max_len(rb, max_len):
    """Concrete longest-row length when available (eager), else None (the
    caller's to_padded falls back to the flat total — correct but wasteful)."""
    if max_len is not None:
        return int(max_len)
    if isinstance(rb.row_lengths, jax.core.Tracer):
        return None
    return int(jnp.max(rb.row_lengths))


@register_op("sequence_conv")
def sequence_conv(rb: RaggedBatch, filter_w, context_start=-1,
                  context_length=3, bias=None, max_len=None):
    """ref: sequence_ops/sequence_conv_op.cc — context-window projection.

    For each position t: concat(x[t+context_start], ...,
    x[t+context_start+context_length-1]) @ filter_w, zero-padded at sequence
    boundaries. filter_w: [context_length * D, out_dim].
    """
    dense, _ = rb.to_padded(_padded_max_len(rb, max_len))    # [B, T, D]
    B, T, D = dense.shape
    lengths = rb.row_lengths
    mask = (jnp.arange(T)[None, :] < lengths[:, None])
    dense = jnp.where(mask[..., None], dense, 0.0)
    cols = []
    for k in range(context_length):
        off = context_start + k
        shifted = jnp.roll(dense, -off, axis=1)
        pos = jnp.arange(T) + off
        valid = (pos >= 0)[None, :] & (pos[None, :] < lengths[:, None])
        cols.append(jnp.where(valid[..., None], shifted, 0.0))
    ctx = jnp.concatenate(cols, axis=-1)                     # [B,T,ctx*D]
    out = ctx @ filter_w
    if bias is not None:
        out = out + bias
    return _repack(out, rb)


@register_op("row_conv")
def row_conv(rb: RaggedBatch, filter_w, max_len=None):
    """ref: operators/row_conv_op.cc — lookahead convolution
    (DeepSpeech2-style): out[t] = sum_k filter_w[k] * x[t + k], per channel,
    future context only, zero past the sequence end."""
    dense, _ = rb.to_padded(_padded_max_len(rb, max_len))    # [B,T,D]
    B, T, D = dense.shape
    lengths = rb.row_lengths
    K = filter_w.shape[0]
    out = jnp.zeros_like(dense)
    for k in range(K):
        shifted = jnp.roll(dense, -k, axis=1)
        valid = (jnp.arange(T) + k)[None, :] < lengths[:, None]
        out = out + jnp.where(valid[..., None], shifted, 0.0) * filter_w[k]
    mask = jnp.arange(T)[None, :] < lengths[:, None]
    out = jnp.where(mask[..., None], out, 0.0)
    return _repack(out, rb)


@register_op("im2sequence")
def im2sequence(x, kernels, strides=(1, 1), paddings=(0, 0, 0, 0)):
    """ref: operators/im2sequence_op.cc — slide a kernel over NCHW images,
    each patch flattened to one timestep: [N, C, H, W] ->
    [N, out_h * out_w, C * kh * kw]."""
    N, C, H, W = x.shape
    kh, kw = kernels
    sh, sw = strides
    pt, pl, pb, pr = paddings
    x = jnp.pad(x, ((0, 0), (0, 0), (pt, pb), (pl, pr)))
    Hp, Wp = H + pt + pb, W + pl + pr
    out_h = (Hp - kh) // sh + 1
    out_w = (Wp - kw) // sw + 1
    i0 = jnp.arange(out_h) * sh
    j0 = jnp.arange(out_w) * sw
    ii = i0[:, None] + jnp.arange(kh)[None, :]               # [oh, kh]
    jj = j0[:, None] + jnp.arange(kw)[None, :]               # [ow, kw]
    patches = x[:, :, ii[:, None, :, None], jj[None, :, None, :]]
    # -> [N, C, oh, ow, kh, kw]
    patches = jnp.transpose(patches, (0, 2, 3, 1, 4, 5))
    return patches.reshape(N, out_h * out_w, C * kh * kw)


@register_op("add_position_encoding")
def add_position_encoding(x, alpha=1.0, beta=1.0):
    """ref: operators/add_position_encoding_op.cc — out = alpha * x +
    beta * sinusoid(position) over [B, T, D]; divisor 10000^(k/(half-1))
    per add_position_encoding_op.h."""
    B, T, D = x.shape
    pos = jnp.arange(T, dtype=x.dtype)[:, None]
    half = D // 2
    if half <= 1:
        div = jnp.full((max(half, 1),), 10000.0, x.dtype)
    else:
        div = jnp.power(10000.0,
                        jnp.arange(half, dtype=x.dtype) / (half - 1))
    enc = jnp.concatenate(
        [jnp.sin(pos / div), jnp.cos(pos / div)], axis=-1)
    if enc.shape[-1] < D:
        enc = jnp.pad(enc, ((0, 0), (0, D - enc.shape[-1])))
    return alpha * x + beta * enc[None]


@register_op("ctc_align")
def ctc_align(tokens, lengths=None, blank=0, merge_repeated=True,
              padding_value=0):
    """CTC decode alignment: drop blanks and (optionally) collapse repeats.

    Ref: operators/ctc_align_op.h — for each position in order, keep
    token iff token != blank and not (merge_repeated and token == previous
    raw token); prev tracks the RAW stream (so a blank between repeats
    un-merges them).

    tokens [B, T] int; lengths [B] (None = all T valid).
    Returns (aligned [B, T] padded with padding_value, out_lengths [B]) —
    the static-shape twin of the reference's LoD-shrinking output.
    """
    B, T = tokens.shape
    if lengths is None:
        lengths = jnp.full((B,), T, jnp.int32)
    prev = jnp.concatenate(
        [jnp.full((B, 1), -1, tokens.dtype), tokens[:, :-1]], axis=1)
    in_range = jnp.arange(T)[None, :] < lengths[:, None]
    keep = (tokens != blank) & in_range
    if merge_repeated:
        keep &= tokens != prev
    pos = jnp.cumsum(keep.astype(jnp.int32), axis=1) - 1     # target slot
    out = jnp.full((B, T), padding_value, tokens.dtype)
    rows = jnp.broadcast_to(jnp.arange(B)[:, None], (B, T))
    cols = jnp.where(keep, pos, T)                           # T -> dropped
    out = out.at[rows, cols].set(tokens, mode="drop")
    return out, jnp.sum(keep, axis=1).astype(jnp.int32)
