"""Fused layer norm — Pallas TPU kernel + XLA fallback.

The counterpart of the reference's hand-written CUDA layer_norm
(/root/reference/paddle/fluid/operators/layer_norm_op.cu — block-reduce
mean/var then normalize in one pass) and the fused
fused_fc_elementwise_layernorm op family. One HBM read + one write per
element: mean/var/normalize/affine all happen on a VMEM-resident row tile;
the kernel also emits mean/rstd so the backward needs no second stats pass.

Layout: x [R, C] (rows = everything before begin_norm_axis, flattened).
Grid: (ceil(R / BR),); each program normalizes a [BR, C] tile (the padded
tail tile computes garbage rows whose writes fall off the array). fp32
statistics regardless of input dtype; backward consumes the saved stats.

This is the single implementation behind the registered "layer_norm" op
(ops/nn.py routes here), so module path, captured programs, and direct
callers all share it.
"""

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from paddle_tpu.ops.pallas.core import (INTERPRET, kernel_call, kernel_mode,
                                        pick_block_rows, tile_spec)


def _ln_fwd_kernel(x_ref, g_ref, b_ref, o_ref, m_ref, r_ref, *, epsilon):
    x = x_ref[:].astype(jnp.float32)                       # [BR, C]
    m = jnp.mean(x, axis=1, keepdims=True)
    xc = x - m
    v = jnp.mean(xc * xc, axis=1, keepdims=True)
    r = jax.lax.rsqrt(v + epsilon)
    y = xc * r
    y = y * g_ref[:].astype(jnp.float32)[None, :]
    y = y + b_ref[:].astype(jnp.float32)[None, :]
    o_ref[:] = y.astype(o_ref.dtype)
    m_ref[:] = m
    r_ref[:] = r


def _tuned_block_rows(kernel, x2d, runner):
    """Row-tile size, autotuned when the flag is on (the default comes
    from the shared VMEM heuristic). ``runner(block_rows=...)`` executes
    the live kernel for the sweep."""
    R, C = x2d.shape
    br = pick_block_rows(R, C, x2d.dtype.itemsize)
    from paddle_tpu.core.flags import get_flag
    if not get_flag("autotune"):
        return br
    from paddle_tpu.ops.pallas import autotune
    sig = autotune.signature(r=R, c=C, dt=x2d.dtype.name)
    cands = [{"block_rows": b} for b in (32, 64, 128, 256) if b <= R]
    blocks = autotune.tuned_blocks(
        kernel, sig, defaults={"block_rows": br}, candidates=cands,
        runner=runner, flops=9.0 * R * C, args=(x2d,))
    return blocks["block_rows"]


def _stats_pallas(x2d, gamma, beta, epsilon, interpret=False,
                  block_rows=None):
    R, C = x2d.shape
    if block_rows is None:
        block_rows = _tuned_block_rows(
            "layer_norm", x2d,
            lambda block_rows: _stats_pallas(x2d, gamma, beta, epsilon,
                                             interpret, block_rows))
    br = block_rows
    kern = functools.partial(_ln_fwd_kernel, epsilon=epsilon)
    grid = (pl.cdiv(R, br),)
    return kernel_call(
        kern,
        name="layer_norm",
        grid=grid,
        in_specs=[
            tile_spec((br, C), (0, None)),
            tile_spec((C,), (None,)),
            tile_spec((C,), (None,)),
        ],
        out_specs=[
            tile_spec((br, C), (0, None)),
            tile_spec((br, 1), (0, None)),
            tile_spec((br, 1), (0, None)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((R, C), x2d.dtype),
            jax.ShapeDtypeStruct((R, 1), jnp.float32),
            jax.ShapeDtypeStruct((R, 1), jnp.float32),
        ],
        interpret=interpret,
    )(x2d, gamma, beta)


def _stats_xla(x2d, gamma, beta, epsilon):
    x = x2d.astype(jnp.float32)
    m = jnp.mean(x, axis=1, keepdims=True)
    xc = x - m
    v = jnp.mean(xc * xc, axis=1, keepdims=True)
    r = jax.lax.rsqrt(v + epsilon)
    y = xc * r
    y = y * gamma.astype(jnp.float32)[None, :] + \
        beta.astype(jnp.float32)[None, :]
    return y.astype(x2d.dtype), m, r


def _stats(x2d, gamma, beta, epsilon):
    # escape hatch (ADVICE r1): PT_FLAGS_use_pallas_layer_norm=0 forces the
    # XLA twin if the Pallas kernel misbehaves on some shape/hardware;
    # pallas_interpret engages the kernel off-TPU via the interpreter.
    # LN refuses silently — every shape is supported, so the only refusal
    # is "not on TPU", which is not an anomaly worth a log line.
    mode = kernel_mode("layer_norm", enable_flag="use_pallas_layer_norm")
    if mode is not None:
        return _stats_pallas(x2d, gamma, beta, epsilon,
                             interpret=mode == INTERPRET)
    return _stats_xla(x2d, gamma, beta, epsilon)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def _layer_norm_rows(x2d, gamma, beta, epsilon):
    return _stats(x2d, gamma, beta, epsilon)[0]


def _ln_fwd(x2d, gamma, beta, epsilon):
    out, m, r = _stats(x2d, gamma, beta, epsilon)
    return out, (x2d, gamma, beta, m, r)


def _ln_bwd(epsilon, res, dy):
    x2d, gamma, beta, m, r = res
    x = x2d.astype(jnp.float32)
    dy = dy.astype(jnp.float32)
    xhat = (x - m) * r
    dgamma = jnp.sum(dy * xhat, axis=0).astype(gamma.dtype)
    dbeta = jnp.sum(dy, axis=0).astype(beta.dtype)
    wdy = dy * gamma.astype(jnp.float32)[None, :]
    c1 = jnp.mean(wdy, axis=1, keepdims=True)
    c2 = jnp.mean(wdy * xhat, axis=1, keepdims=True)
    dx = (wdy - c1 - xhat * c2) * r
    return dx.astype(x2d.dtype), dgamma, dbeta


_layer_norm_rows.defvjp(_ln_fwd, _ln_bwd)


def layer_norm_fused(x, scale=None, bias=None, begin_norm_axis=1,
                     epsilon=1e-5):
    """Layer norm over dims [begin_norm_axis:]; scale/bias flat over those
    dims (the reference layer_norm_op.cc contract)."""
    lead = x.shape[:begin_norm_axis]
    tail = x.shape[begin_norm_axis:]
    R = 1
    for d in lead:
        R *= d
    C = 1
    for d in tail:
        C *= d
    gamma = (scale.reshape(C) if scale is not None
             else jnp.ones((C,), x.dtype))
    beta = (bias.reshape(C) if bias is not None
            else jnp.zeros((C,), x.dtype))
    out = _layer_norm_rows(x.reshape(R, C), gamma, beta, epsilon)
    return out.reshape(x.shape)


# ---- fused residual-add + layer norm ------------------------------------
# The transformer hot pattern ln(x + h): Pallas kernels are opaque to XLA
# fusion, so the residual add could not fuse into the LN kernel from
# outside — fold it in instead. Saves a full HBM round-trip of the
# activations per call (ref: the reference's fused_fc_elementwise_layernorm
# family, operators/fused/).

def _ln_add_fwd_kernel(x_ref, h_ref, g_ref, b_ref, o_ref, m_ref, r_ref, *,
                       epsilon):
    s = x_ref[:].astype(jnp.float32) + h_ref[:].astype(jnp.float32)
    m = jnp.mean(s, axis=1, keepdims=True)
    sc = s - m
    v = jnp.mean(sc * sc, axis=1, keepdims=True)
    r = jax.lax.rsqrt(v + epsilon)
    y = sc * r
    y = y * g_ref[:].astype(jnp.float32)[None, :]
    y = y + b_ref[:].astype(jnp.float32)[None, :]
    o_ref[:] = y.astype(o_ref.dtype)
    m_ref[:] = m
    r_ref[:] = r


def _stats_add_pallas(x2d, h2d, gamma, beta, epsilon, interpret=False,
                      block_rows=None):
    R, C = x2d.shape
    if block_rows is None:
        block_rows = _tuned_block_rows(
            "add_layer_norm", x2d,
            lambda block_rows: _stats_add_pallas(x2d, h2d, gamma, beta,
                                                 epsilon, interpret,
                                                 block_rows))
    br = block_rows
    kern = functools.partial(_ln_add_fwd_kernel, epsilon=epsilon)
    return kernel_call(
        kern,
        name="add_layer_norm",
        grid=(pl.cdiv(R, br),),
        in_specs=[
            tile_spec((br, C), (0, None)),
            tile_spec((br, C), (0, None)),
            tile_spec((C,), (None,)),
            tile_spec((C,), (None,)),
        ],
        out_specs=[
            tile_spec((br, C), (0, None)),
            tile_spec((br, 1), (0, None)),
            tile_spec((br, 1), (0, None)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((R, C), x2d.dtype),
            jax.ShapeDtypeStruct((R, 1), jnp.float32),
            jax.ShapeDtypeStruct((R, 1), jnp.float32),
        ],
        interpret=interpret,
    )(x2d, h2d, gamma, beta)


def _stats_add(x2d, h2d, gamma, beta, epsilon):
    mode = kernel_mode("layer_norm", enable_flag="use_pallas_layer_norm")
    if mode is not None:
        return _stats_add_pallas(x2d, h2d, gamma, beta, epsilon,
                                 interpret=mode == INTERPRET)
    return _stats_xla((x2d.astype(jnp.float32)
                       + h2d.astype(jnp.float32)).astype(x2d.dtype),
                      gamma, beta, epsilon)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4,))
def _add_layer_norm_rows(x2d, h2d, gamma, beta, epsilon):
    return _stats_add(x2d, h2d, gamma, beta, epsilon)[0]


def _aln_fwd(x2d, h2d, gamma, beta, epsilon):
    out, m, r = _stats_add(x2d, h2d, gamma, beta, epsilon)
    return out, (x2d, h2d, gamma, beta, m, r)


def _aln_bwd(epsilon, res, dy):
    x2d, h2d, gamma, beta, m, r = res
    s = x2d.astype(jnp.float32) + h2d.astype(jnp.float32)
    dy = dy.astype(jnp.float32)
    shat = (s - m) * r
    dgamma = jnp.sum(dy * shat, axis=0).astype(gamma.dtype)
    dbeta = jnp.sum(dy, axis=0).astype(beta.dtype)
    wdy = dy * gamma.astype(jnp.float32)[None, :]
    c1 = jnp.mean(wdy, axis=1, keepdims=True)
    c2 = jnp.mean(wdy * shat, axis=1, keepdims=True)
    ds = (wdy - c1 - shat * c2) * r
    ds_x = ds.astype(x2d.dtype)
    return ds_x, ds.astype(h2d.dtype), dgamma, dbeta


_add_layer_norm_rows.defvjp(_aln_fwd, _aln_bwd)


def add_layer_norm_fused(x, h, scale=None, bias=None, begin_norm_axis=1,
                         epsilon=1e-5):
    """Fused ln(x + h) (residual + layer norm in one HBM pass)."""
    lead = x.shape[:begin_norm_axis]
    C = 1
    for d in x.shape[begin_norm_axis:]:
        C *= d
    R = 1
    for d in lead:
        R *= d
    gamma = (scale.reshape(C) if scale is not None
             else jnp.ones((C,), x.dtype))
    beta = (bias.reshape(C) if bias is not None
            else jnp.zeros((C,), x.dtype))
    out = _add_layer_norm_rows(x.reshape(R, C), h.reshape(R, C), gamma,
                               beta, epsilon)
    return out.reshape(x.shape)
