"""Fused transformer feed-forward (MLP / GLU) — the first kernel built
on the shared primitive core (ops/pallas/core.py).

The unfused composition ``fc2(act(fc1(x)))`` writes the [rows,
intermediate] activation — 4x the hidden width on GPT/BERT — to HBM and
immediately reads it back. This kernel tiles the intermediate axis
through VMEM instead: grid (rows/BN, I/BI) with the intermediate axis
innermost, a [BN, H_out] f32 accumulator resident in scratch across
intermediate tiles, so no [rows, I] array ever exists. With gate weights
(``wg``/``bg``) the block computes the GLU family
``(act(x@w1+b1) * (x@wg+bg)) @ w2 + b2`` in the same sweep.

Everything but the ~50 lines of math here comes from the core layer:
tile routing (tile_spec), tile-size choice (pick_block_rows +
the autotuner), tail masking (tail_valid_cols / tail_zero), dispatch and
fallback telemetry (kernel_mode / kernel_call). The padded row tail
computes garbage rows whose writes fall off the array (the layer_norm
discipline); the padded intermediate tail is masked on BOTH operands of
the second matmul — the activation tile by validity select, the w2 tile
by tail_zero — because 0 * NaN = NaN (Pallas pads out-of-bounds block
regions with undefined values).

Forward only: the backward recomputes through the unfused XLA
composition (jax.vjp over `_mlp_unfused`) — flash-attention-style
recompute-not-store, so training never materializes the activation in
the forward pass either. Numerics: the kernel accumulates in f32
regardless of input dtype; the unfused composition stays in the input
dtype (it IS the pre-existing model math, and the parity reference).
"""

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from paddle_tpu.ops.pallas.core import (INTERPRET, kernel_call, kernel_mode,
                                        legal_block, pick_block_rows,
                                        tail_valid_cols, tail_zero,
                                        tile_spec)

_ACTS = {
    # exact erf gelu — must match ops/activations.py A.gelu for parity
    "gelu": lambda x: jax.nn.gelu(x, approximate=False),
    "relu": lambda x: jnp.maximum(x, 0.0),
    "silu": jax.nn.silu,
    "identity": lambda x: x,
}


def _mlp_kernel(x_ref, w1_ref, b1_ref, *rest, act, total_i, block_i,
                has_gate):
    if has_gate:
        wg_ref, bg_ref, w2_ref, b2_ref, o_ref, acc_scr = rest
    else:
        w2_ref, b2_ref, o_ref, acc_scr = rest
    j = pl.program_id(1)
    nj = pl.num_programs(1)

    @pl.when(j == 0)
    def _init():
        acc_scr[:] = jnp.zeros_like(acc_scr)

    x = x_ref[:].astype(jnp.float32)                       # [BN, H]
    h = jax.lax.dot_general(
        x, w1_ref[:].astype(jnp.float32), (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)                # [BN, BI]
    h = h + b1_ref[:].astype(jnp.float32)[None, :]
    a = _ACTS[act](h)
    if has_gate:
        g = jax.lax.dot_general(
            x, wg_ref[:].astype(jnp.float32), (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        a = a * (g + bg_ref[:].astype(jnp.float32)[None, :])
    w2 = w2_ref[:].astype(jnp.float32)                     # [BI, Hout]
    if total_i % block_i:
        # padded intermediate tail: clean BOTH matmul operands (select
        # discards the garbage; 0 * NaN would not)
        a = jnp.where(tail_valid_cols(j, block_i, total_i, a.shape), a, 0.0)
        w2 = tail_zero(w2, j, block_i, total_i)
    acc_scr[:] += jax.lax.dot_general(
        a, w2, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)                # [BN, Hout]

    @pl.when(j == nj - 1)
    def _finalize():
        o_ref[:] = (acc_scr[:]
                    + b2_ref[:].astype(jnp.float32)[None, :]).astype(
                        o_ref.dtype)


def _mlp_pallas(x2, w1, b1, w2, b2, wg, bg, act, interpret=False,
                blocks=None):
    from paddle_tpu.ops.pallas.core import pltpu
    R, H = x2.shape
    I, Hout = w1.shape[1], w2.shape[1]
    if blocks is None:
        blocks = _tuned_mlp_blocks(x2, w1, b1, w2, b2, wg, bg, act,
                                   interpret)
    bn, bi = blocks
    has_gate = wg is not None
    kern = functools.partial(_mlp_kernel, act=act, total_i=I, block_i=bi,
                             has_gate=has_gate)
    in_specs = [
        tile_spec((bn, H), (0, None)),
        tile_spec((H, bi), (None, 1)),
        tile_spec((bi,), (1,)),
    ]
    operands = [x2, w1, b1]
    if has_gate:
        in_specs += [tile_spec((H, bi), (None, 1)), tile_spec((bi,), (1,))]
        operands += [wg, bg]
    in_specs += [tile_spec((bi, Hout), (1, None)), tile_spec((Hout,),
                                                             (None,))]
    operands += [w2, b2]
    return kernel_call(
        kern,
        name="mlp",
        grid=(pl.cdiv(R, bn), pl.cdiv(I, bi)),
        in_specs=in_specs,
        out_specs=tile_spec((bn, Hout), (0, None)),
        out_shape=jax.ShapeDtypeStruct((R, Hout), x2.dtype),
        scratch_shapes=[pltpu.VMEM((bn, Hout), jnp.float32)],
        interpret=interpret,
    )(*operands)


def _default_mlp_blocks(x2, w1, w2, interpret):
    R, H = x2.shape
    I, Hout = w1.shape[1], w2.shape[1]
    # per row the kernel holds the x row, one activation row and the
    # accumulator row — budget the row tile for those three
    bn = pick_block_rows(R, H + Hout + 512, 4, copies=1)
    if not interpret and bn % 8:
        bn = max((bn // 8) * 8, min(R, 8))
    bi = legal_block(min(I, 512), I, interpret)
    return bn, bi


def _tuned_mlp_blocks(x2, w1, b1, w2, b2, wg, bg, act, interpret):
    bn, bi = _default_mlp_blocks(x2, w1, w2, interpret)
    from paddle_tpu.core.flags import get_flag
    if not get_flag("autotune"):
        return bn, bi
    from paddle_tpu.ops.pallas import autotune
    R, H = x2.shape
    I, Hout = w1.shape[1], w2.shape[1]
    sig = autotune.signature(r=R, h=H, i=I, ho=Hout,
                             g=int(wg is not None), dt=x2.dtype.name)
    cands = [{"bn": cn, "bi": ci}
             for cn in (32, 64, 128, 256) if cn <= max(R, 8)
             for ci in (128, 256, 512) if ci <= I]
    blocks = autotune.tuned_blocks(
        "mlp", sig, defaults={"bn": bn, "bi": bi}, candidates=cands,
        runner=lambda bn, bi: _mlp_pallas(x2, w1, b1, w2, b2, wg, bg, act,
                                          interpret, blocks=(bn, bi)),
        flops=2.0 * R * I * (H + Hout) * (1 + (wg is not None)),
        args=(x2, w1, w2))
    return blocks["bn"], blocks["bi"]


def _mlp_unfused(x2, w1, b1, w2, b2, wg, bg, act):
    """The plain composition — exactly the pre-existing model math
    (Linear matmul + bias in the input dtype, then the activation), kept
    as the fallback, the parity reference, and the backward recompute."""
    h = x2 @ w1 + b1
    a = _ACTS[act](h)
    if wg is not None:
        a = a * (x2 @ wg + bg)
    return a @ w2 + b2


@functools.partial(jax.custom_vjp, nondiff_argnums=(7, 8, 9))
def _mlp_core(x2, w1, b1, w2, b2, wg, bg, act, has_gate, interpret):
    return _mlp_pallas(x2, w1, b1, w2, b2, wg if has_gate else None,
                       bg if has_gate else None, act, interpret)


def _mlp_core_fwd(x2, w1, b1, w2, b2, wg, bg, act, has_gate, interpret):
    out = _mlp_core(x2, w1, b1, w2, b2, wg, bg, act, has_gate, interpret)
    return out, (x2, w1, b1, w2, b2, wg, bg)


def _mlp_core_bwd(act, has_gate, interpret, res, g):
    x2, w1, b1, w2, b2, wg, bg = res
    if has_gate:
        _, vjp = jax.vjp(lambda *a: _mlp_unfused(*a, act=act),
                         x2, w1, b1, w2, b2, wg, bg)
        return vjp(g)
    _, vjp = jax.vjp(
        lambda x2_, w1_, b1_, w2_, b2_: _mlp_unfused(
            x2_, w1_, b1_, w2_, b2_, None, None, act=act),
        x2, w1, b1, w2, b2)
    dx2, dw1, db1, dw2, db2 = vjp(g)
    return dx2, dw1, db1, dw2, db2, jnp.zeros_like(wg), jnp.zeros_like(bg)


_mlp_core.defvjp(_mlp_core_fwd, _mlp_core_bwd)


def fused_mlp(x, w1, b1, w2, b2, wg=None, bg=None, act="gelu"):
    """Fused feed-forward ``act(x@w1+b1) @ w2 + b2`` (GLU with
    ``wg``/``bg``: the activation branch is gated by ``x@wg+bg``).

    x [..., H]; w1 [H, I]; w2 [I, Hout]; biases may be None (zeros).
    On TPU / under pallas_interpret (``use_pallas_mlp`` flag on): the
    Pallas kernel — the [rows, I] activation never reaches HBM.
    Elsewhere: the plain XLA composition, bit-identical to the
    pre-existing unfused model math."""
    if act not in _ACTS:
        raise ValueError(f"fused_mlp: unknown act {act!r} "
                         f"(have {sorted(_ACTS)})")
    H, I = w1.shape
    Hout = w2.shape[1]
    b1 = b1 if b1 is not None else jnp.zeros((I,), x.dtype)
    b2 = b2 if b2 is not None else jnp.zeros((Hout,), x.dtype)
    has_gate = wg is not None
    if has_gate and bg is None:
        bg = jnp.zeros((I,), x.dtype)
    # MLP refuses silently, like layer_norm: every shape is supported,
    # so the only refusal is "not on TPU" — not an anomaly worth logging
    mode = kernel_mode("mlp", enable_flag="use_pallas_mlp")
    if mode is None:
        # unfused fallback on the ORIGINAL leading shape — flattening to
        # [rows, H] hands XLA different fusion boundaries than the
        # pre-existing model math (and a collapsed row count that can
        # collide with the HLO-contract probe dims)
        return _mlp_unfused(x, w1, b1, w2, b2, wg, bg, act)
    lead = x.shape[:-1]
    R = 1
    for d in lead:
        R *= d
    x2 = x.reshape(R, H)
    # dummy gate operands keep the custom_vjp signature static
    wg_ = wg if has_gate else jnp.zeros((1, 1), x.dtype)
    bg_ = bg if has_gate else jnp.zeros((1,), x.dtype)
    out = _mlp_core(x2, w1, b1, w2, b2, wg_, bg_, act, has_gate,
                    mode == INTERPRET)
    return out.reshape(*lead, Hout)
