"""Tile-shape autotuner for the Pallas kernels.

Every tile size in ops/pallas/ used to be a hard-coded guess (flag
defaults, VMEM-budget heuristics). With the ``autotune`` flag on, the
first *eager* contact with a (kernel, shape-signature, chip) triple
sweeps a small candidate set of block shapes through the live kernel,
times each, and caches the winner in a JSON file (``autotune_cache``
flag); every later contact — eager or traced — is a cache hit that
reuses the measured winner without re-sweeping. Off (the default),
kernels keep today's static defaults and this module costs one flag
check.

Inside a jit trace there is nothing to time, so a cache miss under
tracing quietly returns the static defaults — sweeps happen eagerly
(first un-jitted call, ``tools/autotune.py``, or ``bench.py
--autotune``).

The cache doubles as the cost model's measurement feed: entries record
the candidate's achieved time and, when the caller supplies it, the
kernel's flop count — :func:`measured_rate` turns those into an
achieved-flops/s figure per chip that ``autoplan/costmodel.py`` uses in
place of its analytic ``peak * MFU_ASSUMED`` constant (and
``calibration_report()`` labels which source priced the plan).

Telemetry: ``autotune.sweeps{kernel}`` counts sweeps; ``autotune.cache
{event=hit|miss|corrupt}`` counts lookups and unreadable cache files.
Corrupt caches are tolerated — logged, counted, and rebuilt from
scratch, never raised into a training step.
"""

import json
import logging
import os

from paddle_tpu.observability import metrics as _metrics

logger = logging.getLogger("paddle_tpu.autotune")

_CACHE = None       # process-wide cache, keyed to the flag's path
_TIMER = None       # injectable timer (tests: set_timer(fake))

_TPU_KINDS = ("v6e", "v5p", "v5e", "v4")


def signature(**dims):
    """Stable shape-signature string: ``signature(b=2, tq=128)`` ->
    ``"b2,tq128"``. Keys sort, so call sites need not agree on order."""
    return ",".join(f"{k}{v}" for k, v in sorted(dims.items()))


def chip_key(devices=None):
    """The chip family the current backend runs on — same normalization
    as autoplan/topology.detect(), so cache entries and topology presets
    agree on what a "chip" is."""
    try:
        import jax
        d = (list(devices) if devices is not None else jax.devices())[0]
        kind = (str(getattr(d, "device_kind", "")) or d.platform
                or "cpu").lower()
    except Exception:
        return "cpu"
    for k in _TPU_KINDS:
        if k in kind:
            return k
    return "tpu" if "tpu" in kind else "cpu"


def cache_key(kernel, sig):
    return f"{kernel}|{sig}|{chip_key()}"


class AutotuneCache:
    """JSON-backed winner cache. File format (``version`` 1)::

        {"version": 1,
         "entries": {"<kernel>|<sig>|<chip>": {
             "blocks": {"block_q": 256, ...},   # the winning tile sizes
             "time_s": 1.3e-4,                  # its measured best-of time
             "flops": 2.1e9,                    # optional, for rate feeds
             "kernel": "...", "sig": "...", "chip": "...",
             "swept": [{"blocks": {...}, "time_s": ...}, ...]}}}

    Unreadable or wrong-shaped files count ``autotune.cache{event=
    corrupt}`` and are rebuilt — a stale cache must never take down a
    run."""

    def __init__(self, path):
        self.path = path
        self.entries = {}
        self._loaded = False

    def load(self):
        if self._loaded:
            return self
        self._loaded = True
        try:
            with open(self.path) as f:
                data = json.load(f)
            entries = data.get("entries") if isinstance(data, dict) else None
            if not isinstance(entries, dict):
                raise ValueError("missing 'entries' table")
            self.entries = entries
        except FileNotFoundError:
            pass
        except Exception as e:
            _metrics.counter("autotune.cache").inc(event="corrupt")
            logger.warning("autotune cache %s unreadable (%s); starting "
                           "fresh", self.path, e)
        return self

    def get(self, key):
        return self.load().entries.get(key)

    def put(self, key, record):
        self.load().entries[key] = record
        try:
            d = os.path.dirname(self.path)
            if d:
                os.makedirs(d, exist_ok=True)
            tmp = self.path + ".tmp"
            with open(tmp, "w") as f:
                json.dump({"version": 1, "entries": self.entries}, f,
                          indent=1, sort_keys=True)
            os.replace(tmp, self.path)
        except OSError as e:  # read-only fs: keep the in-memory winner
            logger.warning("autotune cache %s not writable (%s)",
                           self.path, e)

    def clear(self):
        self.entries = {}
        self._loaded = True
        try:
            os.remove(self.path)
        except OSError:
            pass


def cache(path=None):
    """The process cache for ``path`` (default: the ``autotune_cache``
    flag). Re-resolved per call so tests repointing the flag get a fresh
    cache."""
    global _CACHE
    if path is None:
        from paddle_tpu.core.flags import get_flag
        path = get_flag("autotune_cache")
    if _CACHE is None or _CACHE.path != path:
        _CACHE = AutotuneCache(path)
    return _CACHE


def set_timer(timer):
    """Override the candidate timer (tests inject a deterministic fake:
    ``timer(thunk) -> seconds``). None restores wall-clock timing."""
    global _TIMER
    _TIMER = timer


def default_timer(thunk, reps=3):
    """Best-of-``reps`` wall time of ``thunk``, compile excluded (one
    warmup call) and dispatch settled (block_until_ready)."""
    import time

    import jax
    jax.block_until_ready(thunk())          # warmup: compile + first run
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        jax.block_until_ready(thunk())
        best = min(best, time.perf_counter() - t0)
    return best


def _is_traced(args):
    import jax
    return any(isinstance(a, jax.core.Tracer) for a in args)


def tuned_blocks(kernel, sig, defaults, candidates=None, runner=None,
                 flops=None, args=()):
    """Resolve tile sizes for one (kernel, shape-signature, chip) triple.

    The one call a kernel dispatcher makes: with the ``autotune`` flag
    off this is ``dict(defaults)``; on, a cached winner is a hit (no
    sweep — counter-verified by tests); a miss with concrete ``args``
    and a ``runner`` sweeps now; a miss under tracing (or with no
    runner) falls back to the static defaults.

    ``runner(**blocks)`` must execute the kernel with those tile sizes;
    ``candidates`` is a list of partial block dicts (or a thunk
    returning one — deferred so the flag-off path never builds it);
    ``flops`` (optional) records the kernel's flop count so the cost
    model can derive an achieved-flops/s rate from the winner.
    """
    from paddle_tpu.core.flags import get_flag
    if not get_flag("autotune"):
        return dict(defaults)
    rec = cache().get(cache_key(kernel, sig))
    if rec is not None and isinstance(rec.get("blocks"), dict):
        _metrics.counter("autotune.cache").inc(event="hit")
        out = dict(defaults)
        out.update({k: v for k, v in rec["blocks"].items() if k in defaults})
        return out
    _metrics.counter("autotune.cache").inc(event="miss")
    if runner is None or _is_traced(args):
        return dict(defaults)
    return sweep(kernel, sig, defaults, candidates, runner,
                 flops=flops)["blocks"]


def sweep(kernel, sig, defaults, candidates, runner, flops=None):
    """Time every candidate through ``runner`` and cache the winner.
    Returns the full cache record (winner + the ranked ``swept`` list —
    what ``tools/autotune.py`` prints). The defaults are always swept
    too, so the winner can only match or beat them; a candidate that
    raises (illegal tile) is skipped, and if every candidate fails the
    defaults win with no measured time."""
    timer = _TIMER or default_timer
    cands = candidates() if callable(candidates) else list(candidates or [])
    seen, uniq = set(), []
    for c in [dict(defaults)] + [dict(defaults, **c) for c in cands]:
        key = tuple(sorted(c.items()))
        if key not in seen:
            seen.add(key)
            uniq.append(c)
    _metrics.counter("autotune.sweeps").inc(kernel=kernel)
    results = []
    for c in uniq:
        try:
            t = float(timer(lambda c=c: runner(**c)))
        except Exception as e:
            logger.debug("autotune %s: candidate %s failed (%s)",
                         kernel, c, e)
            continue
        results.append({"blocks": c, "time_s": t})
    results.sort(key=lambda r: r["time_s"])
    if results:
        best, time_s = results[0]["blocks"], results[0]["time_s"]
    else:
        best, time_s = dict(defaults), None
    record = {"blocks": best, "time_s": time_s, "kernel": kernel,
              "sig": sig, "chip": chip_key(), "swept": results}
    if flops:
        record["flops"] = float(flops)
    cache().put(cache_key(kernel, sig), record)
    return record


# ----------------------------------------------------- cost-model feed

def measured_rates(path=None):
    """{chip: [achieved flops/s, ...]} over cache entries that carry both
    a measured time and a flop count."""
    out = {}
    for rec in cache(path).load().entries.values():
        t, f = rec.get("time_s"), rec.get("flops")
        if t and f and t > 0:
            out.setdefault(rec.get("chip", "cpu"), []).append(f / t)
    return out


def measured_rate(chip, path=None):
    """(harmonic-mean achieved flops/s, entry count) for ``chip``, or
    None with no measurements. Harmonic mean: rates combine over the
    time the kernels actually spend."""
    rates = measured_rates(path).get(chip)
    if not rates:
        return None
    return len(rates) / sum(1.0 / r for r in rates), len(rates)
