"""Fused vocab cross-entropy — Pallas TPU kernels (forward stats + backward).

The LM-head loss is the last untiled HBM sink on the flagship train steps:
``softmax_with_cross_entropy(x @ W.T, y)`` materializes [batch, seq, vocab]
f32 logits (~1.6 GB per GPT step at 16 x 512 x 50k) only to reduce them to
one scalar per row. The forward kernel computes the three per-row
reductions the loss needs — running max/sum-exp (online logsumexp,
flash-attention style), the logit at the label, and the plain logit sum
(label smoothing) — while tiling the vocab axis through VMEM, so no logits
tile ever round-trips HBM.

Layout: hidden [N, H] (rows = batch*seq flattened), weight [V, H] (the
tied-embedding layout), bias [V]. Grid (rows/bn, vocab/bv); the vocab axis
is innermost so the per-row accumulators stay resident in the revisited
output block across vocab tiles. fp32 statistics regardless of input dtype;
the padded tail vocab tile is masked by the static V.

Backward (flash-attention-2 discipline, mirroring _fa_bwd_dq/_fa_bwd_dkv in
flash_attention.py): TWO kernels, each recomputing the per-tile
probabilities from the saved per-row logsumexp instead of storing them —
  * dh: grid (rows, vocab), vocab innermost; the [bn, H] output block is
    revisited across vocab tiles and accumulates gch @ W_tile.
  * dw/db: grid (vocab, rows), rows innermost; the [bv, H] / [1, bv]
    output blocks accumulate gch^T @ h over row tiles.
The smoothed-CE dlogits is closed-form from the recomputed softmax:
(p - sn - (sp - sn) * onehot) * g. The chunked-XLA recompute in
ops/fused.py stays behind ``use_pallas_xent_bwd=False`` as the escape
hatch.

Vocab-sharded (GSPMD) note: both kernels tolerate out-of-range labels —
a row whose label lives on another vocab shard simply never matches any
local column, so `picked` stays 0 and the one-hot term of gch is 0 on
non-owning shards. ops/fused.py uses exactly this to run the kernels
per-shard inside shard_map (labels pre-offset by the shard's base).
"""

import functools
import logging

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from paddle_tpu.ops.pallas.core import (INTERPRET, NEG_INF, kernel_call,
                                        kernel_mode, logsumexp_update,
                                        pick_rv_blocks, tile_spec)


def _xent_fwd_kernel(h_ref, w_ref, b_ref, lbl_ref, m_ref, s_ref, p_ref,
                     sl_ref, *, total_vocab, block_v):
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _init():
        m_ref[:] = jnp.full(m_ref.shape, NEG_INF, m_ref.dtype)
        s_ref[:] = jnp.zeros(s_ref.shape, s_ref.dtype)
        p_ref[:] = jnp.zeros(p_ref.shape, p_ref.dtype)
        sl_ref[:] = jnp.zeros(sl_ref.shape, sl_ref.dtype)

    h = h_ref[:].astype(jnp.float32)                       # [BN, H]
    w = w_ref[:].astype(jnp.float32)                       # [BV, H]
    logits = jax.lax.dot_general(
        h, w, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)                # [BN, BV]
    logits = logits + b_ref[:].astype(jnp.float32)[None, :]
    col = j * block_v + jax.lax.broadcasted_iota(jnp.int32, logits.shape, 1)
    valid = col < total_vocab                   # mask the padded tail tile
    masked = jnp.where(valid, logits, NEG_INF)
    logsumexp_update(masked, m_ref, s_ref)
    # the label's column. Out-of-range labels — another vocab shard's rows
    # in the GSPMD case — must pick 0: a label in [V, padded_V) would
    # otherwise match a PADDED column and pick up its undefined logit, so
    # the hit is intersected with the validity mask.
    hit = (col == lbl_ref[:]) & valid                      # [BN, BV]
    p_ref[:] += jnp.sum(jnp.where(hit, logits, 0.0), axis=1, keepdims=True)
    sl_ref[:] += jnp.sum(jnp.where(valid, logits, 0.0), axis=1,
                         keepdims=True)


def _tuned_blocks(kernel, hidden, v, runner):
    """(bn, bv) from the shared VMEM heuristic, or — with the ``autotune``
    flag on — the cached/swept winner for this (shape, chip)."""
    n, h = hidden.shape
    bn, bv = pick_rv_blocks(n, v, h, hidden.dtype.itemsize)
    from paddle_tpu.core.flags import get_flag
    if not get_flag("autotune"):
        return bn, bv
    from paddle_tpu.ops.pallas import autotune
    sig = autotune.signature(n=n, v=v, h=h, dt=hidden.dtype.name)
    cands = [{"bn": cn, "bv": cv}
             for cn in (64, 128, 256, 512) if cn <= max(n, 8)
             for cv in (256, 512, 1024) if cv <= max(v, 128)]
    blocks = autotune.tuned_blocks(
        kernel, sig, defaults={"bn": bn, "bv": bv}, candidates=cands,
        runner=runner, flops=2.0 * n * v * h, args=(hidden,))
    return blocks["bn"], blocks["bv"]


def xent_stats_pallas(hidden, weight, bias, labels, interpret=False,
                      return_parts=False, blocks=None):
    """Per-row loss stats. Default: (logz, picked, sum_logits), each [N]
    f32. return_parts=True: the raw online pair (m, s, picked, sum_logits)
    — the vocab-sharded caller combines (m, s) across shards with
    pmax/psum before taking logz = m + log(s).

    hidden [N, H]; weight [V, H]; bias [V]; labels [N] int32.
    """
    N, H = hidden.shape
    V = weight.shape[0]
    if blocks is None:
        bn, bv = _tuned_blocks(
            "xent_stats", hidden, V,
            lambda bn, bv: xent_stats_pallas(hidden, weight, bias, labels,
                                             interpret, blocks=(bn, bv)))
    else:
        bn, bv = blocks
    kern = functools.partial(_xent_fwd_kernel, total_vocab=V, block_v=bv)
    row_out = tile_spec((bn, 1), (0, None))
    m, s, picked, sl = kernel_call(
        kern,
        name="xent_stats",
        grid=(pl.cdiv(N, bn), pl.cdiv(V, bv)),
        in_specs=[
            tile_spec((bn, H), (0, None)),
            tile_spec((bv, H), (1, None)),
            tile_spec((bv,), (1,)),
            tile_spec((bn, 1), (0, None)),
        ],
        out_specs=[row_out] * 4,
        out_shape=[jax.ShapeDtypeStruct((N, 1), jnp.float32)] * 4,
        interpret=interpret,
    )(hidden, weight, bias, labels[:, None].astype(jnp.int32))
    if return_parts:
        return m[:, 0], s[:, 0], picked[:, 0], sl[:, 0]
    logz = m[:, 0] + jnp.log(s[:, 0])
    return logz, picked[:, 0], sl[:, 0]


def xent_stats(hidden, weight, bias, labels, return_parts=False, context=""):
    """Kernel when it applies (TPU, or interpreter when pallas_interpret is
    set), else None — the caller falls back to the chunked XLA stats."""
    mode = kernel_mode(
        "xent_stats", enable_flag="use_pallas_xent", log_unavailable=True,
        unavailable_reason="no TPU and pallas_interpret off" + context,
        level=logging.WARNING if context else logging.DEBUG)
    if mode is None:
        return None
    return xent_stats_pallas(hidden, weight, bias, labels,
                             interpret=mode == INTERPRET,
                             return_parts=return_parts)


# ---- backward ------------------------------------------------------------


def _bwd_gch(h, w_ref, b_ref, lbl_ref, logz_ref, g_ref, j, block_v,
             total_vocab, sn, sp, extra_valid=None):
    """Recompute this tile's smoothed-CE dlogits [BN, BV] from the saved
    per-row logsumexp: gch = (softmax - sn - (sp - sn) * onehot) * g.
    Padded tail entries (vocab tail here, plus the caller's row tail) come
    out as garbage from the undefined out-of-bounds block regions and are
    replaced by exact zeros via where() — a select, so NaNs are discarded,
    not propagated."""
    logits = jax.lax.dot_general(
        h, w_ref[:].astype(jnp.float32), (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)                # [BN, BV]
    logits = logits + b_ref[:].astype(jnp.float32)[None, :]
    col = j * block_v + jax.lax.broadcasted_iota(jnp.int32, logits.shape, 1)
    valid = col < total_vocab
    if extra_valid is not None:
        valid = valid & extra_valid
    p = jnp.exp(logits - logz_ref[:])                      # [BN, BV]
    hit = (col == lbl_ref[:]).astype(jnp.float32)
    gch = (p - sn - (sp - sn) * hit) * g_ref[:]
    return jnp.where(valid, gch, 0.0)


def _xent_bwd_dh_kernel(h_ref, w_ref, b_ref, lbl_ref, logz_ref, g_ref,
                        dh_ref, *, total_vocab, block_v, sn, sp):
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _init():
        dh_ref[:] = jnp.zeros(dh_ref.shape, dh_ref.dtype)

    h = h_ref[:].astype(jnp.float32)                       # [BN, H]
    # zero the padded tail rows of the weight tile: gch's zeroed tail
    # columns would otherwise meet undefined rows in the matmul (0 * NaN)
    w = w_ref[:].astype(jnp.float32)                       # [BV, H]
    wrow = j * block_v + jax.lax.broadcasted_iota(
        jnp.int32, (w.shape[0], 1), 0)
    w = jnp.where(wrow < total_vocab, w, 0.0)
    gch = _bwd_gch(h, w_ref, b_ref, lbl_ref, logz_ref, g_ref, j, block_v,
                   total_vocab, sn, sp)
    dh_ref[:] += jax.lax.dot_general(
        gch, w, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)


def _xent_bwd_dwb_kernel(h_ref, w_ref, b_ref, lbl_ref, logz_ref, g_ref,
                         dw_ref, db_ref, *, total_vocab, total_rows,
                         block_n, block_v, sn, sp):
    vj = pl.program_id(0)
    ni = pl.program_id(1)

    @pl.when(ni == 0)
    def _init():
        dw_ref[:] = jnp.zeros(dw_ref.shape, dw_ref.dtype)
        db_ref[:] = jnp.zeros(db_ref.shape, db_ref.dtype)

    # zero the padded tail rows of the hidden tile before BOTH matmuls:
    # gch's zeroed tail rows would otherwise meet undefined h rows (0*NaN)
    h = h_ref[:].astype(jnp.float32)                       # [BN, H]
    hrow = ni * block_n + jax.lax.broadcasted_iota(
        jnp.int32, (h.shape[0], 1), 0)
    h = jnp.where(hrow < total_rows, h, 0.0)
    row_valid = (ni * block_n + jax.lax.broadcasted_iota(
        jnp.int32, (h.shape[0], block_v), 0)) < total_rows
    gch = _bwd_gch(h, w_ref, b_ref, lbl_ref, logz_ref, g_ref, vj, block_v,
                   total_vocab, sn, sp, extra_valid=row_valid)
    dw_ref[:] += jax.lax.dot_general(
        gch, h, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)                # [BV, H]
    db_ref[:] += jnp.sum(gch, axis=0, keepdims=True)       # [1, BV]


def xent_bwd_pallas(hidden, weight, bias, labels, logz, g, sn, sp,
                    interpret=False):
    """(dh [N, H], dw [V, H], db [V]) in f32, for per-row cotangent g.

    hidden [N, H]; weight [V, H] (the vh tied-embedding layout); bias [V];
    labels [N] int (out-of-range rows never hit — vocab-sharded callers
    pre-offset); logz [N] f32 saved by the forward; g [N] f32.
    """
    N, H = hidden.shape
    V = weight.shape[0]
    bn, bv = pick_rv_blocks(N, V, H, hidden.dtype.itemsize)
    lbl2 = labels[:, None].astype(jnp.int32)
    logz2 = logz[:, None].astype(jnp.float32)
    g2 = g[:, None].astype(jnp.float32)
    row_specs = [tile_spec((bn, 1), (0, None))] * 3
    dh = kernel_call(
        functools.partial(_xent_bwd_dh_kernel, total_vocab=V, block_v=bv,
                          sn=sn, sp=sp),
        name="xent_bwd_dh",
        grid=(pl.cdiv(N, bn), pl.cdiv(V, bv)),
        in_specs=[
            tile_spec((bn, H), (0, None)),
            tile_spec((bv, H), (1, None)),
            tile_spec((bv,), (1,)),
            *row_specs,
        ],
        out_specs=tile_spec((bn, H), (0, None)),
        out_shape=jax.ShapeDtypeStruct((N, H), jnp.float32),
        interpret=interpret,
    )(hidden, weight, bias, lbl2, logz2, g2)
    # transposed grid — vocab outer, rows inner — so the [BV, H] dw block
    # (and [1, BV] db block) stays resident across the row sweep
    tr_row_specs = [tile_spec((bn, 1), (1, None))] * 3
    dw, db = kernel_call(
        functools.partial(_xent_bwd_dwb_kernel, total_vocab=V, total_rows=N,
                          block_n=bn, block_v=bv, sn=sn, sp=sp),
        name="xent_bwd_dwb",
        grid=(pl.cdiv(V, bv), pl.cdiv(N, bn)),
        in_specs=[
            tile_spec((bn, H), (1, None)),
            tile_spec((bv, H), (0, None)),
            tile_spec((bv,), (0,)),
            *tr_row_specs,
        ],
        out_specs=[
            tile_spec((bv, H), (0, None)),
            tile_spec((1, bv), (None, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((V, H), jnp.float32),
            jax.ShapeDtypeStruct((1, V), jnp.float32),
        ],
        interpret=interpret,
    )(hidden, weight, bias, lbl2, logz2, g2)
    return dh, dw, db[0]


def xent_bwd(hidden, weight, bias, labels, logz, g, sn, sp, context=""):
    """Backward kernels when they apply (TPU, or interpreter when
    pallas_interpret is set), else None — the caller falls back to the
    chunked XLA recompute."""
    mode = kernel_mode(
        "xent_bwd", enable_flag="use_pallas_xent_bwd", log_unavailable=True,
        unavailable_reason="no TPU and pallas_interpret off" + context,
        level=logging.WARNING if context else logging.DEBUG)
    if mode is None:
        return None
    return xent_bwd_pallas(hidden, weight, bias, labels, logz, g,
                           sn, sp, interpret=mode == INTERPRET)
