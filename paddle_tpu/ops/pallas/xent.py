"""Fused vocab cross-entropy forward stats — Pallas TPU kernel.

The LM-head loss is the last untiled HBM sink on the flagship train steps:
``softmax_with_cross_entropy(x @ W.T, y)`` materializes [batch, seq, vocab]
f32 logits (~1.6 GB per GPT step at 16 x 512 x 50k) only to reduce them to
one scalar per row. This kernel computes the three per-row reductions the
loss needs — running max/sum-exp (online logsumexp, flash-attention style),
the logit at the label, and the plain logit sum (label smoothing) — while
tiling the vocab axis through VMEM, so no logits tile ever round-trips HBM.

Layout: hidden [N, H] (rows = batch*seq flattened), weight [V, H] (the
tied-embedding layout), bias [V]. Grid (rows/bn, vocab/bv); the vocab axis
is innermost so the per-row accumulators stay resident in the revisited
output block across vocab tiles. fp32 statistics regardless of input dtype;
the padded tail vocab tile is masked by the static V.

The backward never needs a kernel: the custom VJP in ops/fused.py
recomputes per-chunk logits from the same inputs (one extra MXU pass, zero
extra HBM residency) — the recompute-over-store discipline of the flash
kernels.
"""

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from paddle_tpu.ops.pallas import on_tpu

_NEG_INF = -1e30


def _xent_fwd_kernel(h_ref, w_ref, b_ref, lbl_ref, m_ref, s_ref, p_ref,
                     sl_ref, *, total_vocab, block_v):
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _init():
        m_ref[:] = jnp.full(m_ref.shape, _NEG_INF, m_ref.dtype)
        s_ref[:] = jnp.zeros(s_ref.shape, s_ref.dtype)
        p_ref[:] = jnp.zeros(p_ref.shape, p_ref.dtype)
        sl_ref[:] = jnp.zeros(sl_ref.shape, sl_ref.dtype)

    h = h_ref[:].astype(jnp.float32)                       # [BN, H]
    w = w_ref[:].astype(jnp.float32)                       # [BV, H]
    logits = jax.lax.dot_general(
        h, w, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)                # [BN, BV]
    logits = logits + b_ref[:].astype(jnp.float32)[None, :]
    col = j * block_v + jax.lax.broadcasted_iota(jnp.int32, logits.shape, 1)
    valid = col < total_vocab                   # mask the padded tail tile
    masked = jnp.where(valid, logits, _NEG_INF)

    m_old = m_ref[:]                                       # [BN, 1]
    m_new = jnp.maximum(m_old, jnp.max(masked, axis=1, keepdims=True))
    s_ref[:] = (s_ref[:] * jnp.exp(m_old - m_new)
                + jnp.sum(jnp.exp(masked - m_new), axis=1, keepdims=True))
    m_ref[:] = m_new
    # the label's column (labels < V, so a hit is always a valid column)
    hit = col == lbl_ref[:]                                # [BN, BV]
    p_ref[:] += jnp.sum(jnp.where(hit, logits, 0.0), axis=1, keepdims=True)
    sl_ref[:] += jnp.sum(jnp.where(valid, logits, 0.0), axis=1,
                         keepdims=True)


def _pick_blocks(n, v, h, dtype_bytes, vmem_budget=2 ** 22):
    """Row/vocab tile sizes: h-tile + w-tile + f32 logits tile within ~4MB."""
    bv = max(min(v, 1024), 128)
    per_row = h * dtype_bytes + bv * 4          # hidden row + logits row
    bn = max(min(vmem_budget // max(per_row, 1), n, 512), 8)
    return bn, bv


def xent_stats_pallas(hidden, weight, bias, labels, interpret=False):
    """Per-row loss stats: (logz, picked, sum_logits), each [N] f32.

    hidden [N, H]; weight [V, H]; bias [V]; labels [N] int32 (< V).
    """
    N, H = hidden.shape
    V = weight.shape[0]
    bn, bv = _pick_blocks(N, V, H, hidden.dtype.itemsize)
    kern = functools.partial(_xent_fwd_kernel, total_vocab=V, block_v=bv)
    m, s, picked, sl = pl.pallas_call(
        kern,
        grid=(pl.cdiv(N, bn), pl.cdiv(V, bv)),
        in_specs=[
            pl.BlockSpec((bn, H), lambda i, j: (i, 0)),
            pl.BlockSpec((bv, H), lambda i, j: (j, 0)),
            pl.BlockSpec((bv,), lambda i, j: (j,)),
            pl.BlockSpec((bn, 1), lambda i, j: (i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((bn, 1), lambda i, j: (i, 0)),
            pl.BlockSpec((bn, 1), lambda i, j: (i, 0)),
            pl.BlockSpec((bn, 1), lambda i, j: (i, 0)),
            pl.BlockSpec((bn, 1), lambda i, j: (i, 0)),
        ],
        out_shape=[jax.ShapeDtypeStruct((N, 1), jnp.float32)] * 4,
        interpret=interpret,
    )(hidden, weight, bias, labels[:, None].astype(jnp.int32))
    logz = m[:, 0] + jnp.log(s[:, 0])
    return logz, picked[:, 0], sl[:, 0]


def xent_stats(hidden, weight, bias, labels):
    """Kernel when it applies (TPU, or interpreter when pallas_interpret is
    set), else None — the caller falls back to the chunked XLA stats."""
    from paddle_tpu.core.flags import get_flag
    if not get_flag("use_pallas_xent"):
        return None
    if on_tpu():
        return xent_stats_pallas(hidden, weight, bias, labels)
    if get_flag("pallas_interpret"):
        return xent_stats_pallas(hidden, weight, bias, labels,
                                 interpret=True)
    return None
