"""Hand-written Pallas TPU kernels.

The counterpart of the reference's hand-written CUDA kernels
(/root/reference/paddle/fluid/operators/*.cu, operators/math/*.cu,
operators/jit/ x86 codegen): where XLA's automatic fusion isn't enough, we
drop to Pallas for explicit VMEM tiling and MXU scheduling.

Kernels gate on TPU availability and fall back to pure-XLA reference
implementations elsewhere (CPU tests run the fallback).
"""

import jax


def on_tpu():
    """True when device 0 is a TPU — including tunneled PJRT plugins whose
    platform string is not literally "tpu" (e.g. axon) but whose
    device_kind is a TPU generation."""
    try:
        d = jax.devices()[0]
        if d.platform == "tpu":
            return True
        return "tpu" in str(getattr(d, "device_kind", "")).lower()
    except Exception:
        return False
