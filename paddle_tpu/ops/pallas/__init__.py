"""Hand-written Pallas TPU kernels.

The counterpart of the reference's hand-written CUDA kernels
(/root/reference/paddle/fluid/operators/*.cu, operators/math/*.cu,
operators/jit/ x86 codegen): where XLA's automatic fusion isn't enough, we
drop to Pallas for explicit VMEM tiling and MXU scheduling.

Kernels gate on TPU availability and fall back to pure-XLA reference
implementations elsewhere (CPU tests run the fallback).
"""

import logging

import jax

from paddle_tpu.observability import metrics as _metrics

logger = logging.getLogger("paddle_tpu.pallas")
_fallback_logged = set()


def log_fallback(kernel, reason, level=logging.WARNING):
    """One-time notice when a Pallas fast path is refused, so a user
    benchmarking the "fused" configuration knows they are measuring the
    chunked XLA fallback. Callers include the *requested* configuration
    (shapes, layout, sharding) vs. what the kernel supports in `reason` —
    a silent drop under GSPMD is otherwise invisible.

    Every refusal (not just the first) also increments the
    `pallas.fallback{kernel=...}` counter, so a run's final telemetry
    snapshot names which kernels ran their XLA fallback — the log line
    is one-time, the counter is the record."""
    _metrics.counter("pallas.fallback").inc(kernel=kernel)
    key = (kernel, reason)
    if key not in _fallback_logged:
        _fallback_logged.add(key)
        logger.log(level, "%s: Pallas path refused (%s); "
                          "using chunked XLA fallback", kernel, reason)


def describe_sharding(**arrays):
    """Compact "name=shape@spec" string for fallback log lines. Concrete
    arrays report their NamedSharding spec; tracers (inside jit, where
    shardings are GSPMD-deferred) report '?'."""
    parts = []
    for name, a in arrays.items():
        try:
            spec = a.sharding.spec
        except Exception:
            spec = "?"
        parts.append(f"{name}={tuple(getattr(a, 'shape', ()))}@{spec}")
    return ", ".join(parts)


def on_tpu():
    """True when device 0 is a TPU — including tunneled PJRT plugins whose
    platform string is not literally "tpu" (e.g. axon) but whose
    device_kind is a TPU generation."""
    try:
        d = jax.devices()[0]
        if d.platform == "tpu":
            return True
        return "tpu" in str(getattr(d, "device_kind", "")).lower()
    except Exception:
        return False
