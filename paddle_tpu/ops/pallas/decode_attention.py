"""Paged decode attention — Pallas TPU kernel for the serving fast path.

Single-query attention over a paged KV cache: each decode slot reads ONLY
its live pages (gathered through the page table by the BlockSpec index
map — the scalar-prefetch idiom, so the DMA engine fetches exactly the
pages a slot owns) and masks by the slot's true token count. Flash-style
online softmax carries (m, l, acc) in VMEM scratch across page tiles, so
no [slots, Tmax] score row ever exists — the XLA escape hatch in
ops/attention.py gathers densely and does materialize one, which is what
tools/compile_smoke.py's serve probe greps for (with the fallback as the
positive control).

Layout: q [S, H, hd] (one query token per slot), k_pages/v_pages
[N, H, page_size, hd] (the pool the whole engine shares), page_table
[S, Pmax] int32, lengths [S] int32 (tokens valid in the cache INCLUDING
the one written this step). Grid (S, H/block_h, Pmax) with the page axis
innermost (sequential on TPU) carrying the softmax state; the head axis
is the autotuned tile knob (``block_h``, default all heads). fp32
statistics and accumulation regardless of the pool dtype (bf16 pools
re-read through f32 math — same contract as flash_attention).

Int8 pools ride the same (m, l, acc) pipeline: the per-row scales
([N, page_size] beside the pool) come in as two extra gathered blocks
and ``core.dequant_rows`` folds them into the loaded K/V tiles before
the score matmul — dequant is a tile-level extension of the existing
pipeline, not a separate kernel (the TPP argument). The quantized
variant registers under its own autotune shape-sig (``kv=int8``), so
sweeps and measured rates feed the cost model per dtype.

Every page_table entry must be an IN-RANGE page index (0 for unallocated
slots/pages is fine — the kernel skips blocks past `length`, but the
BlockSpec still issues the gather DMA for them). A slot with length 0
(inactive) skips every block and emits exactly zero output, matching the
fully-masked-row semantics of the flash/chunked paths.
"""

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from paddle_tpu.ops.pallas.core import (NEG_INF, dequant_rows, kernel_call,
                                        pltpu, softmax_finalize,
                                        softmax_init, softmax_update)


def _decode_kernel(ptab_ref, lens_ref, q_ref, k_ref, v_ref, *refs,
                   scale, page_size, quantized):
    if quantized:
        ks_ref, vs_ref, o_ref, m_scr, l_scr, acc_scr = refs
    else:
        ks_ref = vs_ref = None
        o_ref, m_scr, l_scr, acc_scr = refs
    s = pl.program_id(0)
    j = pl.program_id(2)
    nj = pl.num_programs(2)

    @pl.when(j == 0)
    def _init():
        softmax_init(m_scr, l_scr, acc_scr)

    length = lens_ref[s]

    @pl.when(j * page_size < length)
    def _step():
        q = q_ref[0].astype(jnp.float32)               # [BH, hd]
        if quantized:
            k = dequant_rows(k_ref[0], ks_ref[0])      # [BH, ps, hd]
            v = dequant_rows(v_ref[0], vs_ref[0])
        else:
            k = k_ref[0].astype(jnp.float32)           # [BH, ps, hd]
            v = v_ref[0].astype(jnp.float32)
        sc = jax.lax.dot_general(
            q, k, (((1,), (2,)), ((0,), (0,))),
            preferred_element_type=jnp.float32) * scale  # [BH, ps]
        pos = j * page_size + jax.lax.broadcasted_iota(
            jnp.int32, (1, page_size), 1)
        valid = pos < length                 # [1, ps] broadcasts over heads
        p, alpha = softmax_update(sc, m_scr, l_scr,
                                  jnp.broadcast_to(valid, sc.shape))
        acc_scr[:] = acc_scr[:] * alpha + jax.lax.dot_general(
            p, v, (((1,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32)         # [BH, hd]

    @pl.when(j == nj - 1)
    def _finalize():
        o_ref[0] = softmax_finalize(l_scr[:], acc_scr[:], o_ref.dtype)


def _tuned_block_h(q, k_pages, page_table, runner):
    """Head-tile size for the decode grid, autotuned per (shape, pool
    dtype, chip). The shape-sig carries ``kv=<dtype>`` so the int8 kernel
    is its own cache row — its sweeps/measured rates feed the cost model
    separately from the f32 kernel's."""
    s_slots, h, hd = q.shape
    from paddle_tpu.core.flags import get_flag
    if not get_flag("autotune"):
        return h
    from paddle_tpu.ops.pallas import autotune
    page_size = k_pages.shape[2]
    p_max = page_table.shape[1]
    sig = autotune.signature(s=s_slots, h=h, hd=hd, ps=page_size,
                             pmax=p_max, kv=k_pages.dtype.name)
    cands = [{"block_h": b} for b in (1, 2, 4, 8, 16)
             if b < h and h % b == 0]
    blocks = autotune.tuned_blocks(
        "decode_attention", sig, defaults={"block_h": h}, candidates=cands,
        runner=runner, flops=4.0 * s_slots * h * p_max * page_size * hd,
        args=(q, k_pages, page_table))
    return blocks["block_h"]


def paged_decode_attention_tpu(q, k_pages, v_pages, page_table, lengths,
                               scale, k_scale=None, v_scale=None,
                               interpret=None, block_h=None):
    """q [S, H, hd]; k_pages/v_pages [N, H, ps, hd]; page_table [S, Pmax]
    int32 (in-range everywhere); lengths [S] int32; k_scale/v_scale
    [N, ps] f32 per-row scales for int8 pools (None = unquantized pool).
    -> [S, H, hd]."""
    if interpret is None:
        from paddle_tpu.core.flags import get_flag
        interpret = get_flag("pallas_interpret")
    quantized = k_scale is not None
    if block_h is None:
        block_h = _tuned_block_h(
            q, k_pages, page_table,
            lambda block_h: paged_decode_attention_tpu(
                q, k_pages, v_pages, page_table, lengths, scale,
                k_scale=k_scale, v_scale=v_scale, interpret=interpret,
                block_h=block_h))
    s_slots, h, hd = q.shape
    page_size = k_pages.shape[2]
    p_max = page_table.shape[1]
    bh = block_h if h % block_h == 0 else h
    kernel = functools.partial(_decode_kernel, scale=scale,
                               page_size=page_size, quantized=quantized)
    in_specs = [
        pl.BlockSpec((1, bh, hd), lambda s, b, j, pt, ln: (s, b, 0)),
        pl.BlockSpec((1, bh, page_size, hd),
                     lambda s, b, j, pt, ln: (pt[s, j], b, 0, 0)),
        pl.BlockSpec((1, bh, page_size, hd),
                     lambda s, b, j, pt, ln: (pt[s, j], b, 0, 0)),
    ]
    operands = [q, k_pages, v_pages]
    if quantized:
        in_specs += [
            pl.BlockSpec((1, page_size),
                         lambda s, b, j, pt, ln: (pt[s, j], 0)),
            pl.BlockSpec((1, page_size),
                         lambda s, b, j, pt, ln: (pt[s, j], 0)),
        ]
        operands += [k_scale, v_scale]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(s_slots, h // bh, p_max),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, bh, hd),
                               lambda s, b, j, pt, ln: (s, b, 0)),
        scratch_shapes=[
            pltpu.VMEM((bh, 1), jnp.float32),
            pltpu.VMEM((bh, 1), jnp.float32),
            pltpu.VMEM((bh, hd), jnp.float32),
        ],
    )
    return kernel_call(
        kernel,
        name="decode_attention",
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((s_slots, h, hd), q.dtype),
        interpret=interpret,
    )(page_table.astype(jnp.int32), lengths.astype(jnp.int32),
      *operands)
