"""Paged decode attention — Pallas TPU kernel for the serving fast path.

Single-query attention over a paged KV cache: each decode slot reads ONLY
its live pages (gathered through the page table by the BlockSpec index
map — the scalar-prefetch idiom, so the DMA engine fetches exactly the
pages a slot owns) and masks by the slot's true token count. Flash-style
online softmax carries (m, l, acc) in VMEM scratch across page tiles, so
no [slots, Tmax] score row ever exists — the XLA escape hatch in
ops/attention.py gathers densely and does materialize one, which is what
tools/compile_smoke.py's serve probe greps for (with the fallback as the
positive control).

Layout: q [S, H, hd] (one query token per slot), k_pages/v_pages
[N, H, page_size, hd] (the pool the whole engine shares), page_table
[S, Pmax] int32, lengths [S] int32 (tokens valid in the cache INCLUDING
the one written this step). Grid (S, Pmax) with the page axis innermost
(sequential on TPU) carrying the softmax state. fp32 statistics and
accumulation regardless of the pool dtype (bf16 pools re-read through
f32 math — same contract as flash_attention).

Every page_table entry must be an IN-RANGE page index (0 for unallocated
slots/pages is fine — the kernel skips blocks past `length`, but the
BlockSpec still issues the gather DMA for them). A slot with length 0
(inactive) skips every block and emits exactly zero output, matching the
fully-masked-row semantics of the flash/chunked paths.
"""

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from paddle_tpu.ops.pallas.core import (NEG_INF, kernel_call, pltpu,
                                        softmax_finalize, softmax_init,
                                        softmax_update)


def _decode_kernel(ptab_ref, lens_ref, q_ref, k_ref, v_ref, o_ref,
                   m_scr, l_scr, acc_scr, *, scale, page_size):
    s = pl.program_id(0)
    j = pl.program_id(1)
    nj = pl.num_programs(1)

    @pl.when(j == 0)
    def _init():
        softmax_init(m_scr, l_scr, acc_scr)

    length = lens_ref[s]

    @pl.when(j * page_size < length)
    def _step():
        q = q_ref[0].astype(jnp.float32)               # [H, hd]
        k = k_ref[0].astype(jnp.float32)               # [H, ps, hd]
        v = v_ref[0].astype(jnp.float32)
        sc = jax.lax.dot_general(
            q, k, (((1,), (2,)), ((0,), (0,))),
            preferred_element_type=jnp.float32) * scale  # [H, ps]
        pos = j * page_size + jax.lax.broadcasted_iota(
            jnp.int32, (1, page_size), 1)
        valid = pos < length                 # [1, ps] broadcasts over heads
        p, alpha = softmax_update(sc, m_scr, l_scr,
                                  jnp.broadcast_to(valid, sc.shape))
        acc_scr[:] = acc_scr[:] * alpha + jax.lax.dot_general(
            p, v, (((1,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32)         # [H, hd]

    @pl.when(j == nj - 1)
    def _finalize():
        o_ref[0] = softmax_finalize(l_scr[:], acc_scr[:], o_ref.dtype)


def paged_decode_attention_tpu(q, k_pages, v_pages, page_table, lengths,
                               scale, interpret=None):
    """q [S, H, hd]; k_pages/v_pages [N, H, ps, hd]; page_table [S, Pmax]
    int32 (in-range everywhere); lengths [S] int32. -> [S, H, hd]."""
    if interpret is None:
        from paddle_tpu.core.flags import get_flag
        interpret = get_flag("pallas_interpret")
    s_slots, h, hd = q.shape
    page_size = k_pages.shape[2]
    p_max = page_table.shape[1]
    kernel = functools.partial(_decode_kernel, scale=scale,
                               page_size=page_size)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(s_slots, p_max),
        in_specs=[
            pl.BlockSpec((1, h, hd), lambda s, j, pt, ln: (s, 0, 0)),
            pl.BlockSpec((1, h, page_size, hd),
                         lambda s, j, pt, ln: (pt[s, j], 0, 0, 0)),
            pl.BlockSpec((1, h, page_size, hd),
                         lambda s, j, pt, ln: (pt[s, j], 0, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, h, hd), lambda s, j, pt, ln: (s, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((h, 1), jnp.float32),
            pltpu.VMEM((h, 1), jnp.float32),
            pltpu.VMEM((h, hd), jnp.float32),
        ],
    )
    return kernel_call(
        kernel,
        name="decode_attention",
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((s_slots, h, hd), q.dtype),
        interpret=interpret,
    )(page_table.astype(jnp.int32), lengths.astype(jnp.int32),
      q, k_pages, v_pages)
