"""Flash attention — Pallas TPU kernel + XLA fallback.

The counterpart of the reference's fused attention path
(/root/reference/paddle/fluid/framework/ir/multihead_matmul_fuse_pass.h,
operators/fused/), rebuilt as a memory-efficient online-softmax kernel:
O(T) memory instead of materializing the [Tq, Tk] score matrix, VMEM-tiled
so the MXU stays fed from on-chip memory.

Layout: q,k,v [B, H, T, D]. Grid (B*H, Tq/BQ, Tk/BK); the kv axis is the
innermost (sequential on TPU), carrying the online-softmax state (running
max m, running sum l, unnormalized accumulator acc) in VMEM scratch across
kv steps. fp32 accumulation regardless of input dtype.

Backward: Pallas dq / dkv kernels (flash-attention-2 style — forward saves
the per-row logsumexp, backward recomputes probabilities block-wise from
q,k and lse, never materializing the full score matrix). A recompute-based
fallback (jax.checkpoint over the chunked XLA formulation) remains behind
`flash_pallas_bwd=False` as the escape hatch.
"""

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:
    from jax.experimental.pallas import tpu as pltpu
except ImportError:  # pragma: no cover
    pltpu = None

from paddle_tpu.ops.pallas import on_tpu

NEG_INF = -1e30


def _fa_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, m_scr, l_scr, acc_scr,
               *, scale, causal, block_q, block_k, causal_offset=0):
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ki == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    def _step():
        q = q_ref[0].astype(jnp.float32)            # [BQ, D]
        k = k_ref[0].astype(jnp.float32)            # [BK, D]
        v = v_ref[0].astype(jnp.float32)            # [BK, D]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale   # [BQ, BK]
        if causal:
            # bottom-right aligned (matches scaled_dot_product_attention's
            # tril(k=tk-tq)): query i may attend keys <= i + (tk - tq)
            q_pos = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0) + causal_offset
            k_pos = ki * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        m_prev = m_scr[:]                            # [BQ, 1]
        m_cur = jnp.max(s, axis=1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(s - m_new)                       # [BQ, BK]
        alpha = jnp.exp(m_prev - m_new)              # [BQ, 1]
        l_scr[:] = l_scr[:] * alpha + jnp.sum(p, axis=1, keepdims=True)
        acc_scr[:] = acc_scr[:] * alpha + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_scr[:] = m_new

    if causal:
        # skip fully-masked kv blocks above the diagonal
        @pl.when(ki * block_k <= qi * block_q + block_q - 1 + causal_offset)
        def _():
            _step()
    else:
        _step()

    @pl.when(ki == nk - 1)
    def _finalize():
        l = jnp.maximum(l_scr[:], 1e-30)
        o_ref[0] = (acc_scr[:] / l).astype(o_ref.dtype)
        lse_ref[0] = m_scr[:] + jnp.log(l)


def _flash_attention_fwd_tpu(q, k, v, scale, causal, block_q, block_k,
                             interpret=None, return_lse=False):
    if interpret is None:
        from paddle_tpu.core.flags import get_flag
        interpret = get_flag("pallas_interpret")
    b, h, tq, d = q.shape
    tk = k.shape[2]
    bh = b * h
    q3 = q.reshape(bh, tq, d)
    k3 = k.reshape(bh, tk, d)
    v3 = v.reshape(bh, tk, d)
    block_q = min(block_q, tq)
    block_k = min(block_k, tk)
    grid = (bh, pl.cdiv(tq, block_q), pl.cdiv(tk, block_k))
    kernel = functools.partial(_fa_kernel, scale=scale, causal=causal,
                               block_q=block_q, block_k=block_k,
                               causal_offset=tk - tq)
    out, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda bhi, qi, ki: (bhi, qi, 0)),
            pl.BlockSpec((1, block_k, d), lambda bhi, qi, ki: (bhi, ki, 0)),
            pl.BlockSpec((1, block_k, d), lambda bhi, qi, ki: (bhi, ki, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda bhi, qi, ki: (bhi, qi, 0)),
            pl.BlockSpec((1, block_q, 1), lambda bhi, qi, ki: (bhi, qi, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, tq, d), q.dtype),
            jax.ShapeDtypeStruct((bh, tq, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, d), jnp.float32),
        ],
        interpret=interpret,
    )(q3, k3, v3)
    out = out.reshape(b, h, tq, d)
    if return_lse:
        return out, lse.reshape(b, h, tq, 1)
    return out


def _fa_bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, dlt_ref, dq_ref,
                      dq_scr, *, scale, causal, block_q, block_k,
                      causal_offset=0):
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ki == 0)
    def _init():
        dq_scr[:] = jnp.zeros_like(dq_scr)

    def _step():
        q = q_ref[0].astype(jnp.float32)             # [BQ, D]
        k = k_ref[0].astype(jnp.float32)             # [BK, D]
        v = v_ref[0].astype(jnp.float32)             # [BK, D]
        do = do_ref[0].astype(jnp.float32)           # [BQ, D]
        lse = lse_ref[0]                             # [BQ, 1]
        delta = dlt_ref[0]                           # [BQ, 1]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        if causal:
            # mask p (not s) so fully-masked rows — whose saved lse is the
            # NEG_INF sentinel — can't overflow exp()
            q_pos = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0) + causal_offset
            k_pos = ki * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            p = jnp.where(q_pos >= k_pos, jnp.exp(s - lse), 0.0)
        else:
            p = jnp.exp(s - lse)                     # [BQ, BK]
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)      # [BQ, BK]
        ds = p * (dp - delta) * scale
        dq_scr[:] += jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    if causal:
        @pl.when(ki * block_k <= qi * block_q + block_q - 1 + causal_offset)
        def _():
            _step()
    else:
        _step()

    @pl.when(ki == nk - 1)
    def _finalize():
        dq_ref[0] = dq_scr[:].astype(dq_ref.dtype)


def _fa_bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, dlt_ref,
                       dk_ref, dv_ref, dk_scr, dv_scr, *, scale, causal,
                       block_q, block_k, causal_offset=0):
    ki = pl.program_id(1)
    qi = pl.program_id(2)
    nq = pl.num_programs(2)

    @pl.when(qi == 0)
    def _init():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    def _step():
        q = q_ref[0].astype(jnp.float32)             # [BQ, D]
        k = k_ref[0].astype(jnp.float32)             # [BK, D]
        v = v_ref[0].astype(jnp.float32)             # [BK, D]
        do = do_ref[0].astype(jnp.float32)           # [BQ, D]
        lse = lse_ref[0]                             # [BQ, 1]
        delta = dlt_ref[0]                           # [BQ, 1]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        if causal:
            q_pos = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0) + causal_offset
            k_pos = ki * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            p = jnp.where(q_pos >= k_pos, jnp.exp(s - lse), 0.0)
        else:
            p = jnp.exp(s - lse)                     # [BQ, BK]
        dv_scr[:] += jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)      # [BK, D]
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)      # [BQ, BK]
        ds = p * (dp - delta) * scale
        dk_scr[:] += jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)      # [BK, D]

    if causal:
        @pl.when(qi * block_q + block_q - 1 + causal_offset >= ki * block_k)
        def _():
            _step()
    else:
        _step()

    @pl.when(qi == nq - 1)
    def _finalize():
        dk_ref[0] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[:].astype(dv_ref.dtype)


def _flash_attention_bwd_tpu(q, k, v, out, lse, do, scale, causal,
                             block_q, block_k, interpret=None):
    if interpret is None:
        from paddle_tpu.core.flags import get_flag
        interpret = get_flag("pallas_interpret")
    b, h, tq, d = q.shape
    tk = k.shape[2]
    bh = b * h
    # delta_i = rowsum(dO_i * O_i) — cheap elementwise, XLA fuses it
    delta = jnp.sum(do.astype(jnp.float32) * out.astype(jnp.float32),
                    axis=-1, keepdims=True)          # [B, H, Tq, 1]
    q3 = q.reshape(bh, tq, d)
    k3 = k.reshape(bh, tk, d)
    v3 = v.reshape(bh, tk, d)
    do3 = do.reshape(bh, tq, d)
    lse3 = lse.reshape(bh, tq, 1)
    dlt3 = delta.reshape(bh, tq, 1)
    block_q = min(block_q, tq)
    block_k = min(block_k, tk)
    nq = pl.cdiv(tq, block_q)
    nk = pl.cdiv(tk, block_k)
    offset = tk - tq
    q_specs = [
        pl.BlockSpec((1, block_q, d), lambda bhi, qi, ki: (bhi, qi, 0)),
        pl.BlockSpec((1, block_k, d), lambda bhi, qi, ki: (bhi, ki, 0)),
        pl.BlockSpec((1, block_k, d), lambda bhi, qi, ki: (bhi, ki, 0)),
        pl.BlockSpec((1, block_q, d), lambda bhi, qi, ki: (bhi, qi, 0)),
        pl.BlockSpec((1, block_q, 1), lambda bhi, qi, ki: (bhi, qi, 0)),
        pl.BlockSpec((1, block_q, 1), lambda bhi, qi, ki: (bhi, qi, 0)),
    ]
    dq = pl.pallas_call(
        functools.partial(_fa_bwd_dq_kernel, scale=scale, causal=causal,
                          block_q=block_q, block_k=block_k,
                          causal_offset=offset),
        grid=(bh, nq, nk),
        in_specs=q_specs,
        out_specs=pl.BlockSpec((1, block_q, d),
                               lambda bhi, qi, ki: (bhi, qi, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, tq, d), q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        interpret=interpret,
    )(q3, k3, v3, do3, lse3, dlt3)
    kv_specs = [
        pl.BlockSpec((1, block_q, d), lambda bhi, ki, qi: (bhi, qi, 0)),
        pl.BlockSpec((1, block_k, d), lambda bhi, ki, qi: (bhi, ki, 0)),
        pl.BlockSpec((1, block_k, d), lambda bhi, ki, qi: (bhi, ki, 0)),
        pl.BlockSpec((1, block_q, d), lambda bhi, ki, qi: (bhi, qi, 0)),
        pl.BlockSpec((1, block_q, 1), lambda bhi, ki, qi: (bhi, qi, 0)),
        pl.BlockSpec((1, block_q, 1), lambda bhi, ki, qi: (bhi, qi, 0)),
    ]
    dk, dv = pl.pallas_call(
        functools.partial(_fa_bwd_dkv_kernel, scale=scale, causal=causal,
                          block_q=block_q, block_k=block_k,
                          causal_offset=offset),
        grid=(bh, nk, nq),
        in_specs=kv_specs,
        out_specs=[
            pl.BlockSpec((1, block_k, d), lambda bhi, ki, qi: (bhi, ki, 0)),
            pl.BlockSpec((1, block_k, d), lambda bhi, ki, qi: (bhi, ki, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, tk, d), k.dtype),
            jax.ShapeDtypeStruct((bh, tk, d), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, d), jnp.float32),
            pltpu.VMEM((block_k, d), jnp.float32),
        ],
        interpret=interpret,
    )(q3, k3, v3, do3, lse3, dlt3)
    return (dq.reshape(b, h, tq, d), dk.reshape(b, h, tk, d),
            dv.reshape(b, h, tk, d))


def chunked_attention(q, k, v, scale=None, causal=False, chunk_size=512):
    """Flash-style attention in pure XLA: lax.scan over KV chunks with online
    softmax. O(T) memory, differentiable, runs anywhere. Used as the CPU/
    fallback path and as the recompute backward for the Pallas forward."""
    scale = scale if scale is not None else 1.0 / jnp.sqrt(q.shape[-1])
    b, h, tq, d = q.shape
    tk = k.shape[2]
    chunk = min(chunk_size, tk)
    nchunks = (tk + chunk - 1) // chunk
    pad = nchunks * chunk - tk
    if pad:
        k = jnp.pad(k, ((0, 0), (0, 0), (0, pad), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, 0), (0, pad), (0, 0)))
    kc = k.reshape(b, h, nchunks, chunk, d).transpose(2, 0, 1, 3, 4)
    vc = v.reshape(b, h, nchunks, chunk, d).transpose(2, 0, 1, 3, 4)
    qf = q.astype(jnp.float32)
    # bottom-right aligned causal (matches scaled_dot_product_attention)
    q_pos = jnp.arange(tq) + (tk - tq)

    def step(carry, inp):
        m, l, acc = carry
        kb, vb, ci = inp
        s = jnp.einsum("bhqd,bhkd->bhqk", qf, kb.astype(jnp.float32)) * scale
        k_pos = ci * chunk + jnp.arange(chunk)
        valid = k_pos < tk
        if causal:
            valid = valid[None, :] & (q_pos[:, None] >= k_pos[None, :])
            s = jnp.where(valid[None, None], s, NEG_INF)
        else:
            s = jnp.where(valid[None, None, None, :], s, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, -1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m - m_new)
        l = l * alpha + jnp.sum(p, -1, keepdims=True)
        acc = acc * alpha + jnp.einsum("bhqk,bhkd->bhqd", p,
                                       vb.astype(jnp.float32))
        return (m_new, l, acc), None

    m0 = jnp.full((b, h, tq, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, h, tq, 1), jnp.float32)
    acc0 = jnp.zeros((b, h, tq, d), jnp.float32)
    (m, l, acc), _ = jax.lax.scan(
        jax.checkpoint(step), (m0, l0, acc0),
        (kc, vc, jnp.arange(nchunks)))
    return (acc / jnp.maximum(l, 1e-30)).astype(q.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash_core(q, k, v, scale, causal, block_q, block_k):
    return _flash_attention_fwd_tpu(q, k, v, scale, causal, block_q, block_k)


def _flash_core_fwd(q, k, v, scale, causal, block_q, block_k):
    out, lse = _flash_attention_fwd_tpu(q, k, v, scale, causal, block_q,
                                        block_k, return_lse=True)
    return out, (q, k, v, out, lse)


def _flash_core_bwd(scale, causal, block_q, block_k, res, g):
    q, k, v, out, lse = res
    from paddle_tpu.core.flags import get_flag
    if get_flag("flash_pallas_bwd"):
        return _flash_attention_bwd_tpu(q, k, v, out, lse, g, scale, causal,
                                        block_q, block_k)
    _, vjp = jax.vjp(lambda q_, k_, v_: chunked_attention(
        q_, k_, v_, scale=scale, causal=causal, chunk_size=block_k), q, k, v)
    return vjp(g)


_flash_core.defvjp(_flash_core_fwd, _flash_core_bwd)


def flash_attention(q, k, v, scale=None, causal=False, block_q=512,
                    block_k=512):
    """Memory-efficient attention. q,k,v: [B, H, T, D].

    On TPU: Pallas online-softmax forward + recompute backward. Head dims
    that are multiples of 64 are supported (Mosaic pads the 64-lane case;
    BERT-base's D=64 still wins because the [BQ,BK] matmuls dominate).
    Elsewhere: chunked XLA formulation (same math).
    """
    from paddle_tpu.core.flags import get_flag
    scale = float(scale) if scale is not None else 1.0 / (q.shape[-1] ** 0.5)
    if (on_tpu() or get_flag("pallas_interpret")) and pltpu is not None \
            and q.shape[-1] % 64 == 0 \
            and q.shape[2] % 8 == 0 and k.shape[2] % 8 == 0:
        return _flash_core(q, k, v, scale, causal, block_q, block_k)
    return chunked_attention(q, k, v, scale=scale, causal=causal,
                             chunk_size=block_k)
