"""Flash attention — Pallas TPU kernel + XLA fallback.

The counterpart of the reference's fused attention path
(/root/reference/paddle/fluid/framework/ir/multihead_matmul_fuse_pass.h,
operators/fused/), rebuilt as a memory-efficient online-softmax kernel:
O(T) memory instead of materializing the [Tq, Tk] score matrix, VMEM-tiled
so the MXU stays fed from on-chip memory.

Layout: q,k,v [B, H, T, D]. Grid (B*H, Tq/BQ, Tk/BK); the kv axis is the
innermost (sequential on TPU), carrying the online-softmax state (running
max m, running sum l, unnormalized accumulator acc) in VMEM scratch across
kv steps. fp32 accumulation regardless of input dtype. The tiling,
masking, and (m, l, acc) combiner all come from ops/pallas/core.py — this
module contributes only the attention math.

Masking: `kv_mask` [B, Tk] (True = attend) covers the padded-batch case —
the mask the reference's fused multihead path handles via the eltwise-add
bias input (multihead_matmul_fuse_pass). Tail blocks (T not divisible by
the block size) are masked by absolute position inside the kernels, and
probabilities (not just scores) are masked so a fully-masked row yields
exactly zero output and zero gradients in both the Pallas and chunked
paths.

Backward: Pallas dq / dkv kernels by default (flash-attention-2 style —
the forward saves the per-row logsumexp, the backward recomputes
probabilities block-wise from q,k and lse, never materializing the full
score matrix). A recompute-based fallback (jax.checkpoint over the chunked
XLA formulation) remains behind the `flash_pallas_bwd=False` flag as the
escape hatch.

lse/delta are carried as [B*H, 1, Tq] with block (1, 1, block_q) so the
lane dimension is block_q (a [block_q, 1] layout would pad the single lane
to 128 and waste VMEM/bandwidth). The singleton middle dim matters on real
silicon: Mosaic requires the last two dims of every block to be divisible
by (8, 128) or equal to the array dims — a 2-D [B*H, Tq] array with block
(1, block_q) fails that check (the leading 1 is neither a multiple of 8
nor equal to B*H), which interpret mode does not enforce. Same story for
the [B, Tk] kv mask, carried as [B, 1, Tk].
"""

import functools
import logging

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from paddle_tpu.ops.pallas import describe_sharding, log_fallback
from paddle_tpu.ops.pallas.core import (NEG_INF, block_valid, kernel_call,
                                        kernel_mode, legal_block,
                                        softmax_finalize, softmax_init,
                                        softmax_update, tail_zero,
                                        tail_zero_row, tile_spec)

logger = logging.getLogger("paddle_tpu.flash")


def _log_fallback(reason):
    """One-time notice when the Pallas fast path is refused — so a user
    benchmarking "flash" knows they are measuring the chunked fallback."""
    log_fallback("flash_attention", reason)


def _fa_kernel(q_ref, k_ref, v_ref, *rest, scale, causal, block_q, block_k,
               causal_offset, tq, tk, has_mask):
    if has_mask:
        mask_ref, o_ref, lse_ref, m_scr, l_scr, acc_scr = rest
    else:
        o_ref, lse_ref, m_scr, l_scr, acc_scr = rest
        mask_ref = None
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ki == 0)
    def _init():
        softmax_init(m_scr, l_scr, acc_scr)

    def _step():
        q = tail_zero(q_ref[0].astype(jnp.float32), qi, block_q, tq)
        k = tail_zero(k_ref[0].astype(jnp.float32), ki, block_k, tk)
        v = tail_zero(v_ref[0].astype(jnp.float32), ki, block_k, tk)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale   # [BQ, BK]
        valid = block_valid(qi, ki, block_q=block_q, block_k=block_k,
                            tq=tq, tk=tk, causal=causal,
                            causal_offset=causal_offset,
                            mask_row=mask_ref[0] if has_mask else None)
        p, alpha = softmax_update(s, m_scr, l_scr, valid)
        acc_scr[:] = acc_scr[:] * alpha + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    if causal:
        # skip kv blocks entirely above the diagonal — sound with or
        # without a kv mask (a skipped block contributes p == 0 exactly)
        @pl.when(ki * block_k <= qi * block_q + block_q - 1 + causal_offset)
        def _():
            _step()
    else:
        _step()

    @pl.when(ki == nk - 1)
    def _finalize():
        l = l_scr[:]
        o_ref[0] = softmax_finalize(l, acc_scr[:], o_ref.dtype)
        lse_ref[0] = jnp.transpose(
            m_scr[:] + jnp.log(jnp.maximum(l, 1e-30)), (1, 0))


def _flash_attention_fwd_tpu(q, k, v, scale, causal, block_q, block_k,
                             kv_mask=None, interpret=None, return_lse=False):
    if interpret is None:
        from paddle_tpu.core.flags import get_flag
        interpret = get_flag("pallas_interpret")
    from paddle_tpu.ops.pallas.core import pltpu
    b, h, tq, d = q.shape
    tk = k.shape[2]
    bh = b * h
    q3 = q.reshape(bh, tq, d)
    k3 = k.reshape(bh, tk, d)
    v3 = v.reshape(bh, tk, d)
    block_q = legal_block(block_q, tq, interpret)
    block_k = legal_block(block_k, tk, interpret)
    grid = (bh, pl.cdiv(tq, block_q), pl.cdiv(tk, block_k))
    has_mask = kv_mask is not None
    kernel = functools.partial(_fa_kernel, scale=scale, causal=causal,
                               block_q=block_q, block_k=block_k,
                               causal_offset=tk - tq, tq=tq, tk=tk,
                               has_mask=has_mask)
    in_specs = [
        tile_spec((1, block_q, d), (0, 1, None)),
        tile_spec((1, block_k, d), (0, 2, None)),
        tile_spec((1, block_k, d), (0, 2, None)),
    ]
    operands = [q3, k3, v3]
    if has_mask:
        in_specs.append(pl.BlockSpec(
            (1, 1, block_k), lambda bhi, qi, ki: (bhi // h, 0, ki)))
        operands.append(kv_mask.astype(jnp.int32).reshape(b, 1, tk))
    out, lse = kernel_call(
        kernel,
        name="flash_attention",
        grid=grid,
        in_specs=in_specs,
        out_specs=[
            tile_spec((1, block_q, d), (0, 1, None)),
            tile_spec((1, 1, block_q), (0, None, 1)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, tq, d), q.dtype),
            jax.ShapeDtypeStruct((bh, 1, tq), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, d), jnp.float32),
        ],
        interpret=interpret,
    )(*operands)
    out = out.reshape(b, h, tq, d)
    if return_lse:
        return out, lse.reshape(b, h, tq)
    return out


def _bwd_p(s, lse_row, valid):
    """exp(s - lse) with masking. lse arrives as (1, BQ) — lane-major —
    and is transposed to a column for the row-broadcast. Masked entries are
    exact zeros; for fully-masked rows lse is the ~-1e30 sentinel and the
    where() discards the overflowed exp."""
    lse_col = jnp.transpose(lse_row, (1, 0))         # [BQ, 1]
    p = jnp.exp(s - lse_col)
    if valid is not None:
        p = jnp.where(valid, p, 0.0)
    return p


def _fa_bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, dlt_ref, *rest,
                      scale, causal, block_q, block_k, causal_offset, tq, tk,
                      has_mask):
    if has_mask:
        mask_ref, dq_ref, dq_scr = rest
    else:
        dq_ref, dq_scr = rest
        mask_ref = None
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ki == 0)
    def _init():
        dq_scr[:] = jnp.zeros_like(dq_scr)

    def _step():
        q = tail_zero(q_ref[0].astype(jnp.float32), qi, block_q, tq)
        k = tail_zero(k_ref[0].astype(jnp.float32), ki, block_k, tk)
        v = tail_zero(v_ref[0].astype(jnp.float32), ki, block_k, tk)
        do = tail_zero(do_ref[0].astype(jnp.float32), qi, block_q, tq)
        lse = tail_zero_row(lse_ref[0], qi, block_q, tq)
        dlt = tail_zero_row(dlt_ref[0], qi, block_q, tq)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        valid = block_valid(qi, ki, block_q=block_q, block_k=block_k,
                            tq=tq, tk=tk, causal=causal,
                            causal_offset=causal_offset,
                            mask_row=mask_ref[0] if has_mask else None)
        p = _bwd_p(s, lse, valid)                    # [BQ, BK]
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)      # [BQ, BK]
        delta_col = jnp.transpose(dlt, (1, 0))
        ds = p * (dp - delta_col) * scale
        dq_scr[:] += jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    if causal:
        @pl.when(ki * block_k <= qi * block_q + block_q - 1 + causal_offset)
        def _():
            _step()
    else:
        _step()

    @pl.when(ki == nk - 1)
    def _finalize():
        dq_ref[0] = dq_scr[:].astype(dq_ref.dtype)


def _fa_bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, dlt_ref, *rest,
                       scale, causal, block_q, block_k, causal_offset, tq, tk,
                       has_mask):
    if has_mask:
        mask_ref, dk_ref, dv_ref, dk_scr, dv_scr = rest
    else:
        dk_ref, dv_ref, dk_scr, dv_scr = rest
        mask_ref = None
    ki = pl.program_id(1)
    qi = pl.program_id(2)
    nq = pl.num_programs(2)

    @pl.when(qi == 0)
    def _init():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    def _step():
        q = tail_zero(q_ref[0].astype(jnp.float32), qi, block_q, tq)
        k = tail_zero(k_ref[0].astype(jnp.float32), ki, block_k, tk)
        v = tail_zero(v_ref[0].astype(jnp.float32), ki, block_k, tk)
        do = tail_zero(do_ref[0].astype(jnp.float32), qi, block_q, tq)
        lse = tail_zero_row(lse_ref[0], qi, block_q, tq)
        dlt = tail_zero_row(dlt_ref[0], qi, block_q, tq)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        valid = block_valid(qi, ki, block_q=block_q, block_k=block_k,
                            tq=tq, tk=tk, causal=causal,
                            causal_offset=causal_offset,
                            mask_row=mask_ref[0] if has_mask else None)
        p = _bwd_p(s, lse, valid)                    # [BQ, BK]
        dv_scr[:] += jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)      # [BK, D]
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)      # [BQ, BK]
        delta_col = jnp.transpose(dlt, (1, 0))
        ds = p * (dp - delta_col) * scale
        dk_scr[:] += jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)      # [BK, D]

    if causal:
        @pl.when(qi * block_q + block_q - 1 + causal_offset >= ki * block_k)
        def _():
            _step()
    else:
        _step()

    @pl.when(qi == nq - 1)
    def _finalize():
        dk_ref[0] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[:].astype(dv_ref.dtype)


def _flash_attention_bwd_tpu(q, k, v, out, lse, do, scale, causal,
                             block_q, block_k, kv_mask=None, interpret=None):
    if interpret is None:
        from paddle_tpu.core.flags import get_flag
        interpret = get_flag("pallas_interpret")
    from paddle_tpu.ops.pallas.core import pltpu
    b, h, tq, d = q.shape
    tk = k.shape[2]
    bh = b * h
    # delta_i = rowsum(dO_i * O_i) — cheap elementwise, XLA fuses it
    delta = jnp.sum(do.astype(jnp.float32) * out.astype(jnp.float32),
                    axis=-1)                         # [B, H, Tq]
    q3 = q.reshape(bh, tq, d)
    k3 = k.reshape(bh, tk, d)
    v3 = v.reshape(bh, tk, d)
    do3 = do.reshape(bh, tq, d)
    lse2 = lse.reshape(bh, 1, tq)
    dlt2 = delta.reshape(bh, 1, tq)
    block_q = legal_block(block_q, tq, interpret)
    block_k = legal_block(block_k, tk, interpret)
    nq = pl.cdiv(tq, block_q)
    nk = pl.cdiv(tk, block_k)
    offset = tk - tq
    has_mask = kv_mask is not None
    mask_i32 = (kv_mask.astype(jnp.int32).reshape(b, 1, tk)
                if has_mask else None)
    common = dict(scale=scale, causal=causal, block_q=block_q,
                  block_k=block_k, causal_offset=offset, tq=tq, tk=tk,
                  has_mask=has_mask)
    # dq grid (bh, nq, nk): grid axis 1 picks q blocks, axis 2 kv blocks
    q_specs = [
        tile_spec((1, block_q, d), (0, 1, None)),
        tile_spec((1, block_k, d), (0, 2, None)),
        tile_spec((1, block_k, d), (0, 2, None)),
        tile_spec((1, block_q, d), (0, 1, None)),
        tile_spec((1, 1, block_q), (0, None, 1)),
        tile_spec((1, 1, block_q), (0, None, 1)),
    ]
    q_ops = [q3, k3, v3, do3, lse2, dlt2]
    if has_mask:
        q_specs.append(pl.BlockSpec(
            (1, 1, block_k), lambda bhi, qi, ki: (bhi // h, 0, ki)))
        q_ops.append(mask_i32)
    dq = kernel_call(
        functools.partial(_fa_bwd_dq_kernel, **common),
        name="flash_attention_bwd_dq",
        grid=(bh, nq, nk),
        in_specs=q_specs,
        out_specs=tile_spec((1, block_q, d), (0, 1, None)),
        out_shape=jax.ShapeDtypeStruct((bh, tq, d), q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        interpret=interpret,
    )(*q_ops)
    # dkv grid (bh, nk, nq): grid axis 1 picks kv blocks, axis 2 q blocks
    kv_specs = [
        tile_spec((1, block_q, d), (0, 2, None)),
        tile_spec((1, block_k, d), (0, 1, None)),
        tile_spec((1, block_k, d), (0, 1, None)),
        tile_spec((1, block_q, d), (0, 2, None)),
        tile_spec((1, 1, block_q), (0, None, 2)),
        tile_spec((1, 1, block_q), (0, None, 2)),
    ]
    kv_ops = [q3, k3, v3, do3, lse2, dlt2]
    if has_mask:
        kv_specs.append(pl.BlockSpec(
            (1, 1, block_k), lambda bhi, ki, qi: (bhi // h, 0, ki)))
        kv_ops.append(mask_i32)
    dk, dv = kernel_call(
        functools.partial(_fa_bwd_dkv_kernel, **common),
        name="flash_attention_bwd_dkv",
        grid=(bh, nk, nq),
        in_specs=kv_specs,
        out_specs=[
            tile_spec((1, block_k, d), (0, 1, None)),
            tile_spec((1, block_k, d), (0, 1, None)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, tk, d), k.dtype),
            jax.ShapeDtypeStruct((bh, tk, d), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, d), jnp.float32),
            pltpu.VMEM((block_k, d), jnp.float32),
        ],
        interpret=interpret,
    )(*kv_ops)
    return (dq.reshape(b, h, tq, d), dk.reshape(b, h, tk, d),
            dv.reshape(b, h, tk, d))


def chunked_attention(q, k, v, scale=None, causal=False, kv_mask=None,
                      chunk_size=512):
    """Flash-style attention in pure XLA: lax.scan over KV chunks with online
    softmax. O(T) memory, differentiable, runs anywhere. Used as the CPU/
    fallback path and as the recompute backward for the Pallas forward.
    Same semantics as the Pallas path: kv_mask [B, Tk] (True = attend);
    fully-masked rows yield exactly zero output."""
    scale = scale if scale is not None else 1.0 / jnp.sqrt(q.shape[-1])
    # accumulate in f32, except when fed f64 inputs (the precision-probe
    # ground-truth path under jax_enable_x64) — then keep full f64 so the
    # baseline really is higher-precision than the kernel under test
    acc_dtype = jnp.float64 if q.dtype == jnp.float64 else jnp.float32
    scale = jnp.asarray(scale, acc_dtype)
    b, h, tq, d = q.shape
    tk = k.shape[2]
    chunk = min(chunk_size, tk)
    nchunks = (tk + chunk - 1) // chunk
    pad = nchunks * chunk - tk
    if pad:
        k = jnp.pad(k, ((0, 0), (0, 0), (0, pad), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, 0), (0, pad), (0, 0)))
    kc = k.reshape(b, h, nchunks, chunk, d).transpose(2, 0, 1, 3, 4)
    vc = v.reshape(b, h, nchunks, chunk, d).transpose(2, 0, 1, 3, 4)
    if kv_mask is not None:
        mc = jnp.pad(kv_mask.astype(bool), ((0, 0), (0, pad)),
                     constant_values=False)
        mc = mc.reshape(b, nchunks, chunk).transpose(1, 0, 2)  # [N, B, C]
    qf = q.astype(acc_dtype)
    # bottom-right aligned causal (matches scaled_dot_product_attention)
    q_pos = jnp.arange(tq) + (tk - tq)

    def step(carry, inp):
        m, l, acc = carry
        if kv_mask is not None:
            kb, vb, ci, mb = inp
        else:
            kb, vb, ci = inp
        s = jnp.einsum("bhqd,bhkd->bhqk", qf, kb.astype(acc_dtype)) * scale
        k_pos = ci * chunk + jnp.arange(chunk)
        valid = jnp.broadcast_to((k_pos < tk)[None, None, None, :], s.shape)
        if causal:
            valid = valid & (q_pos[:, None] >= k_pos[None, :])[None, None]
        if kv_mask is not None:
            valid = valid & mb[:, None, None, :]
        s = jnp.where(valid, s, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, -1, keepdims=True))
        # mask p, not just s: in a fully-masked row m_new stays NEG_INF and
        # exp(s - m_new) = 1 — identical semantics to the Pallas kernel
        p = jnp.where(valid, jnp.exp(s - m_new), 0.0)
        alpha = jnp.exp(m - m_new)
        l = l * alpha + jnp.sum(p, -1, keepdims=True)
        acc = acc * alpha + jnp.einsum("bhqk,bhkd->bhqd", p,
                                       vb.astype(acc_dtype))
        return (m_new, l, acc), None

    m0 = jnp.full((b, h, tq, 1), NEG_INF, acc_dtype)
    l0 = jnp.zeros((b, h, tq, 1), acc_dtype)
    acc0 = jnp.zeros((b, h, tq, d), acc_dtype)
    xs = (kc, vc, jnp.arange(nchunks))
    if kv_mask is not None:
        xs = xs + (mc,)
    (m, l, acc), _ = jax.lax.scan(jax.checkpoint(step), (m0, l0, acc0), xs)
    out = jnp.where(l > 0, acc / jnp.maximum(l, 1e-30), 0.0)
    return out.astype(q.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7, 8))
def _flash_core(q, k, v, mask, scale, causal, block_q, block_k, has_mask):
    return _flash_attention_fwd_tpu(q, k, v, scale, causal, block_q, block_k,
                                    kv_mask=mask if has_mask else None)


def _flash_core_fwd(q, k, v, mask, scale, causal, block_q, block_k, has_mask):
    out, lse = _flash_attention_fwd_tpu(
        q, k, v, scale, causal, block_q, block_k,
        kv_mask=mask if has_mask else None, return_lse=True)
    return out, (q, k, v, mask, out, lse)


def _flash_core_bwd(scale, causal, block_q, block_k, has_mask, res, g):
    q, k, v, mask, out, lse = res
    kv_mask = mask if has_mask else None
    from paddle_tpu.core.flags import get_flag
    if get_flag("flash_pallas_bwd"):
        dq, dk, dv = _flash_attention_bwd_tpu(
            q, k, v, out, lse, g, scale, causal, block_q, block_k,
            kv_mask=kv_mask)
    else:
        _, vjp = jax.vjp(lambda q_, k_, v_: chunked_attention(
            q_, k_, v_, scale=scale, causal=causal, kv_mask=kv_mask,
            chunk_size=block_k), q, k, v)
        dq, dk, dv = vjp(g)
    return dq, dk, dv, jnp.zeros_like(mask)


_flash_core.defvjp(_flash_core_fwd, _flash_core_bwd)


def _tuned_flash_blocks(q, k, v, scale, causal, kv_mask, block_q, block_k,
                        interpret):
    """Autotune hook: with the `autotune` flag on, resolve (block_q,
    block_k) through the tile cache — sweeping the forward eagerly on
    first contact with this (shape, chip), reusing the cached winner
    (or the static flag defaults, under tracing) afterwards."""
    from paddle_tpu.ops.pallas import autotune
    b, h, tq, d = q.shape
    tk = k.shape[2]
    sig = autotune.signature(b=b, h=h, tq=tq, tk=tk, d=d, c=int(causal),
                             m=int(kv_mask is not None), dt=q.dtype.name)

    def candidates():
        qs = sorted({legal_block(x, tq, interpret)
                     for x in (64, 128, 256, 512)})
        ks = sorted({legal_block(x, tk, interpret)
                     for x in (64, 128, 256, 512)})
        return [{"block_q": bq, "block_k": bk} for bq in qs for bk in ks]

    def runner(block_q, block_k):
        return _flash_attention_fwd_tpu(q, k, v, scale, causal, block_q,
                                        block_k, kv_mask=kv_mask,
                                        interpret=interpret)

    blocks = autotune.tuned_blocks(
        "flash_attention", sig,
        defaults={"block_q": block_q, "block_k": block_k},
        candidates=candidates, runner=runner,
        flops=4.0 * b * h * tq * tk * d,
        args=(q, k, v) + (() if kv_mask is None else (kv_mask,)))
    return blocks["block_q"], blocks["block_k"]


def flash_attention(q, k, v, scale=None, causal=False, kv_mask=None,
                    block_q=None, block_k=None):
    """Memory-efficient attention. q,k,v: [B, H, T, D]; kv_mask: [B, Tk]
    bool/0-1, True = attend (the key-padding mask of a padded batch).

    On TPU: Pallas online-softmax forward + Pallas dq/dkv backward
    (flash-attention-2 recomputation from the saved logsumexp; set the
    `flash_pallas_bwd=False` flag to fall back to a jax.checkpoint
    recompute over the chunked XLA formulation). Head dims that are
    multiples of 64 are supported (Mosaic pads the 64-lane case;
    BERT-base's D=64 still wins because the [BQ,BK] matmuls dominate).
    Elsewhere: chunked XLA formulation (same math, same semantics).
    """
    from paddle_tpu.core.flags import get_flag
    # default block sizes come from flags so a flash_tune.py sweep result
    # applies fleet-wide via PT_FLAGS_flash_block_{q,k} (no code change)
    block_q = block_q if block_q is not None else get_flag("flash_block_q")
    block_k = block_k if block_k is not None else get_flag("flash_block_k")
    scale = float(scale) if scale is not None else 1.0 / (q.shape[-1] ** 0.5)
    shape_ok = (q.shape[-1] % 64 == 0 and q.shape[2] % 8 == 0
                and k.shape[2] % 8 == 0)
    # include the requested shardings: under GSPMD/shard_map the
    # PER-SHARD T is what must divide by 8, so a globally-legal shape
    # can still land here once the sequence axis is partitioned — the
    # log must show what was asked for vs what the kernel supports
    mode = kernel_mode(
        "flash_attention",
        unsupported=None if shape_ok else (
            f"D={q.shape[-1]} not a multiple of 64 or "
            f"T={q.shape[2]}/{k.shape[2]} not a multiple of 8; "
            f"requested {describe_sharding(q=q, k=k)} "
            "(supported: per-shard D%64==0 and T%8==0)"))
    if mode is not None:
        if get_flag("autotune"):
            block_q, block_k = _tuned_flash_blocks(
                q, k, v, scale, causal, kv_mask, block_q, block_k,
                interpret=(mode == "interpret"))
        if kv_mask is None:
            # dummy float operand keeps the custom_vjp signature static;
            # has_mask=False drops it before the pallas_call
            mask = jnp.zeros((1, 1), jnp.float32)
            return _flash_core(q, k, v, mask, scale, causal, block_q,
                               block_k, False)
        return _flash_core(q, k, v, kv_mask.astype(jnp.float32), scale,
                           causal, block_q, block_k, True)
    return chunked_attention(q, k, v, scale=scale, causal=causal,
                             kv_mask=kv_mask, chunk_size=block_k)
