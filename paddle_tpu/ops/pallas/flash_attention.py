"""Flash attention — Pallas TPU kernel + XLA fallback.

The counterpart of the reference's fused attention path
(/root/reference/paddle/fluid/framework/ir/multihead_matmul_fuse_pass.h,
operators/fused/), rebuilt as a memory-efficient online-softmax kernel:
O(T) memory instead of materializing the [Tq, Tk] score matrix, VMEM-tiled
so the MXU stays fed from on-chip memory.

Layout: q,k,v [B, H, T, D]. Grid (B*H, Tq/BQ, Tk/BK); the kv axis is the
innermost (sequential on TPU), carrying the online-softmax state (running
max m, running sum l, unnormalized accumulator acc) in VMEM scratch across
kv steps. fp32 accumulation regardless of input dtype.

Masking: `kv_mask` [B, Tk] (True = attend) covers the padded-batch case —
the mask the reference's fused multihead path handles via the eltwise-add
bias input (multihead_matmul_fuse_pass). Tail blocks (T not divisible by
the block size) are masked by absolute position inside the kernels, and
probabilities (not just scores) are masked so a fully-masked row yields
exactly zero output and zero gradients in both the Pallas and chunked
paths.

Backward: Pallas dq / dkv kernels by default (flash-attention-2 style —
the forward saves the per-row logsumexp, the backward recomputes
probabilities block-wise from q,k and lse, never materializing the full
score matrix). A recompute-based fallback (jax.checkpoint over the chunked
XLA formulation) remains behind the `flash_pallas_bwd=False` flag as the
escape hatch.

lse/delta are carried as [B*H, 1, Tq] with block (1, 1, block_q) so the
lane dimension is block_q (a [block_q, 1] layout would pad the single lane
to 128 and waste VMEM/bandwidth). The singleton middle dim matters on real
silicon: Mosaic requires the last two dims of every block to be divisible
by (8, 128) or equal to the array dims — a 2-D [B*H, Tq] array with block
(1, block_q) fails that check (the leading 1 is neither a multiple of 8
nor equal to B*H), which interpret mode does not enforce. Same story for
the [B, Tk] kv mask, carried as [B, 1, Tk].
"""

import functools
import logging

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:
    from jax.experimental.pallas import tpu as pltpu
except ImportError:  # pragma: no cover
    pltpu = None

from paddle_tpu.ops.pallas import describe_sharding, log_fallback, on_tpu

NEG_INF = -1e30

logger = logging.getLogger("paddle_tpu.flash")


def _log_fallback(reason):
    """One-time notice when the Pallas fast path is refused — so a user
    benchmarking "flash" knows they are measuring the chunked fallback."""
    log_fallback("flash_attention", reason)


def _block_valid(qi, ki, *, block_q, block_k, tq, tk, causal, causal_offset,
                 mask_row):
    """[BQ, BK] validity for this tile: tail rows/cols past the true
    sequence end, the causal triangle, and the kv padding mask. Returns
    None when every position is valid (no masking work needed)."""
    valid = None
    q_pos = qi * block_q + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0)
    k_pos = ki * block_k + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 1)

    def _and(a, b):
        return b if a is None else a & b

    if tq % block_q:
        valid = _and(valid, q_pos < tq)
    if tk % block_k:
        valid = _and(valid, k_pos < tk)
    if causal:
        valid = _and(valid, q_pos + causal_offset >= k_pos)
    if mask_row is not None:
        valid = _and(valid, mask_row > 0)      # (1, BK) broadcasts over rows
    return valid


def _tail_zero(x, idx, block, t):
    """Zero the rows of a loaded [block, D] tile that lie past the true
    sequence end t. Pallas pads out-of-bounds block regions with undefined
    values (NaN in interpret mode) and 0 * NaN = NaN, so masking the
    probabilities alone is not enough — the operands themselves must be
    clean before they enter a matmul. Static no-op when block divides t."""
    if t % block == 0:
        return x
    rows = idx * block + jax.lax.broadcasted_iota(jnp.int32, (block, 1), 0)
    return jnp.where(rows < t, x, 0.0)


def _tail_zero_row(x, idx, block, t):
    """Same for a (1, block) lane-major tile (lse/delta)."""
    if t % block == 0:
        return x
    cols = idx * block + jax.lax.broadcasted_iota(jnp.int32, (1, block), 1)
    return jnp.where(cols < t, x, 0.0)


def _fa_kernel(q_ref, k_ref, v_ref, *rest, scale, causal, block_q, block_k,
               causal_offset, tq, tk, has_mask):
    if has_mask:
        mask_ref, o_ref, lse_ref, m_scr, l_scr, acc_scr = rest
    else:
        o_ref, lse_ref, m_scr, l_scr, acc_scr = rest
        mask_ref = None
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ki == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    def _step():
        q = _tail_zero(q_ref[0].astype(jnp.float32), qi, block_q, tq)
        k = _tail_zero(k_ref[0].astype(jnp.float32), ki, block_k, tk)
        v = _tail_zero(v_ref[0].astype(jnp.float32), ki, block_k, tk)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale   # [BQ, BK]
        valid = _block_valid(qi, ki, block_q=block_q, block_k=block_k,
                             tq=tq, tk=tk, causal=causal,
                             causal_offset=causal_offset,
                             mask_row=mask_ref[0] if has_mask else None)
        if valid is not None:
            s = jnp.where(valid, s, NEG_INF)
        m_prev = m_scr[:]                            # [BQ, 1]
        m_cur = jnp.max(s, axis=1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(s - m_new)                       # [BQ, BK]
        if valid is not None:
            # mask p, not just s: in a fully-masked row m_new stays at the
            # NEG_INF sentinel and exp(s - m_new) = exp(0) = 1 — without
            # this, masked positions would contribute weight 1 each
            p = jnp.where(valid, p, 0.0)
        alpha = jnp.exp(m_prev - m_new)              # [BQ, 1]
        l_scr[:] = l_scr[:] * alpha + jnp.sum(p, axis=1, keepdims=True)
        acc_scr[:] = acc_scr[:] * alpha + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_scr[:] = m_new

    if causal:
        # skip kv blocks entirely above the diagonal — sound with or
        # without a kv mask (a skipped block contributes p == 0 exactly)
        @pl.when(ki * block_k <= qi * block_q + block_q - 1 + causal_offset)
        def _():
            _step()
    else:
        _step()

    @pl.when(ki == nk - 1)
    def _finalize():
        l = l_scr[:]
        l_safe = jnp.maximum(l, 1e-30)
        # fully-masked rows (l == 0): define the output as exactly zero in
        # every path (chunked_attention matches)
        o_ref[0] = jnp.where(l > 0, acc_scr[:] / l_safe, 0.0).astype(
            o_ref.dtype)
        lse_ref[0] = jnp.transpose(m_scr[:] + jnp.log(l_safe), (1, 0))


def _legal_block(block, t, interpret=False):
    """Largest Mosaic-tileable block ≤ the request. lse/delta/mask ride
    with the block size in the lane dimension, which Mosaic accepts only
    when it is a multiple of 128 or covers the whole sequence — a perf
    knob, never semantics, so silently legalize rather than fall back.
    Interpret mode does NOT legalize: the interpreter has no tiling rule,
    and the CPU suite's small-block cases (block 8/16/32 at T ≤ 128) are
    what exercise the multi-block online-softmax, tail-masking, and
    causal block-skip paths."""
    b = min(block, t)
    if interpret or b == t or b % 128 == 0:
        return b
    return (b // 128) * 128 if b >= 128 else min(t, 128)


def _flash_attention_fwd_tpu(q, k, v, scale, causal, block_q, block_k,
                             kv_mask=None, interpret=None, return_lse=False):
    if interpret is None:
        from paddle_tpu.core.flags import get_flag
        interpret = get_flag("pallas_interpret")
    b, h, tq, d = q.shape
    tk = k.shape[2]
    bh = b * h
    q3 = q.reshape(bh, tq, d)
    k3 = k.reshape(bh, tk, d)
    v3 = v.reshape(bh, tk, d)
    block_q = _legal_block(block_q, tq, interpret)
    block_k = _legal_block(block_k, tk, interpret)
    grid = (bh, pl.cdiv(tq, block_q), pl.cdiv(tk, block_k))
    has_mask = kv_mask is not None
    kernel = functools.partial(_fa_kernel, scale=scale, causal=causal,
                               block_q=block_q, block_k=block_k,
                               causal_offset=tk - tq, tq=tq, tk=tk,
                               has_mask=has_mask)
    in_specs = [
        pl.BlockSpec((1, block_q, d), lambda bhi, qi, ki: (bhi, qi, 0)),
        pl.BlockSpec((1, block_k, d), lambda bhi, qi, ki: (bhi, ki, 0)),
        pl.BlockSpec((1, block_k, d), lambda bhi, qi, ki: (bhi, ki, 0)),
    ]
    operands = [q3, k3, v3]
    if has_mask:
        in_specs.append(pl.BlockSpec(
            (1, 1, block_k), lambda bhi, qi, ki: (bhi // h, 0, ki)))
        operands.append(kv_mask.astype(jnp.int32).reshape(b, 1, tk))
    out, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda bhi, qi, ki: (bhi, qi, 0)),
            pl.BlockSpec((1, 1, block_q), lambda bhi, qi, ki: (bhi, 0, qi)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, tq, d), q.dtype),
            jax.ShapeDtypeStruct((bh, 1, tq), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, d), jnp.float32),
        ],
        interpret=interpret,
    )(*operands)
    out = out.reshape(b, h, tq, d)
    if return_lse:
        return out, lse.reshape(b, h, tq)
    return out


def _bwd_p(s, lse_row, valid):
    """exp(s - lse) with masking. lse arrives as (1, BQ) — lane-major —
    and is transposed to a column for the row-broadcast. Masked entries are
    exact zeros; for fully-masked rows lse is the ~-1e30 sentinel and the
    where() discards the overflowed exp."""
    lse_col = jnp.transpose(lse_row, (1, 0))         # [BQ, 1]
    p = jnp.exp(s - lse_col)
    if valid is not None:
        p = jnp.where(valid, p, 0.0)
    return p


def _fa_bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, dlt_ref, *rest,
                      scale, causal, block_q, block_k, causal_offset, tq, tk,
                      has_mask):
    if has_mask:
        mask_ref, dq_ref, dq_scr = rest
    else:
        dq_ref, dq_scr = rest
        mask_ref = None
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ki == 0)
    def _init():
        dq_scr[:] = jnp.zeros_like(dq_scr)

    def _step():
        q = _tail_zero(q_ref[0].astype(jnp.float32), qi, block_q, tq)
        k = _tail_zero(k_ref[0].astype(jnp.float32), ki, block_k, tk)
        v = _tail_zero(v_ref[0].astype(jnp.float32), ki, block_k, tk)
        do = _tail_zero(do_ref[0].astype(jnp.float32), qi, block_q, tq)
        lse = _tail_zero_row(lse_ref[0], qi, block_q, tq)
        dlt = _tail_zero_row(dlt_ref[0], qi, block_q, tq)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        valid = _block_valid(qi, ki, block_q=block_q, block_k=block_k,
                             tq=tq, tk=tk, causal=causal,
                             causal_offset=causal_offset,
                             mask_row=mask_ref[0] if has_mask else None)
        p = _bwd_p(s, lse, valid)                    # [BQ, BK]
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)      # [BQ, BK]
        delta_col = jnp.transpose(dlt, (1, 0))
        ds = p * (dp - delta_col) * scale
        dq_scr[:] += jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    if causal:
        @pl.when(ki * block_k <= qi * block_q + block_q - 1 + causal_offset)
        def _():
            _step()
    else:
        _step()

    @pl.when(ki == nk - 1)
    def _finalize():
        dq_ref[0] = dq_scr[:].astype(dq_ref.dtype)


def _fa_bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, dlt_ref, *rest,
                       scale, causal, block_q, block_k, causal_offset, tq, tk,
                       has_mask):
    if has_mask:
        mask_ref, dk_ref, dv_ref, dk_scr, dv_scr = rest
    else:
        dk_ref, dv_ref, dk_scr, dv_scr = rest
        mask_ref = None
    ki = pl.program_id(1)
    qi = pl.program_id(2)
    nq = pl.num_programs(2)

    @pl.when(qi == 0)
    def _init():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    def _step():
        q = _tail_zero(q_ref[0].astype(jnp.float32), qi, block_q, tq)
        k = _tail_zero(k_ref[0].astype(jnp.float32), ki, block_k, tk)
        v = _tail_zero(v_ref[0].astype(jnp.float32), ki, block_k, tk)
        do = _tail_zero(do_ref[0].astype(jnp.float32), qi, block_q, tq)
        lse = _tail_zero_row(lse_ref[0], qi, block_q, tq)
        dlt = _tail_zero_row(dlt_ref[0], qi, block_q, tq)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        valid = _block_valid(qi, ki, block_q=block_q, block_k=block_k,
                             tq=tq, tk=tk, causal=causal,
                             causal_offset=causal_offset,
                             mask_row=mask_ref[0] if has_mask else None)
        p = _bwd_p(s, lse, valid)                    # [BQ, BK]
        dv_scr[:] += jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)      # [BK, D]
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)      # [BQ, BK]
        delta_col = jnp.transpose(dlt, (1, 0))
        ds = p * (dp - delta_col) * scale
        dk_scr[:] += jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)      # [BK, D]

    if causal:
        @pl.when(qi * block_q + block_q - 1 + causal_offset >= ki * block_k)
        def _():
            _step()
    else:
        _step()

    @pl.when(qi == nq - 1)
    def _finalize():
        dk_ref[0] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[:].astype(dv_ref.dtype)


def _flash_attention_bwd_tpu(q, k, v, out, lse, do, scale, causal,
                             block_q, block_k, kv_mask=None, interpret=None):
    if interpret is None:
        from paddle_tpu.core.flags import get_flag
        interpret = get_flag("pallas_interpret")
    b, h, tq, d = q.shape
    tk = k.shape[2]
    bh = b * h
    # delta_i = rowsum(dO_i * O_i) — cheap elementwise, XLA fuses it
    delta = jnp.sum(do.astype(jnp.float32) * out.astype(jnp.float32),
                    axis=-1)                         # [B, H, Tq]
    q3 = q.reshape(bh, tq, d)
    k3 = k.reshape(bh, tk, d)
    v3 = v.reshape(bh, tk, d)
    do3 = do.reshape(bh, tq, d)
    lse2 = lse.reshape(bh, 1, tq)
    dlt2 = delta.reshape(bh, 1, tq)
    block_q = _legal_block(block_q, tq, interpret)
    block_k = _legal_block(block_k, tk, interpret)
    nq = pl.cdiv(tq, block_q)
    nk = pl.cdiv(tk, block_k)
    offset = tk - tq
    has_mask = kv_mask is not None
    mask_i32 = (kv_mask.astype(jnp.int32).reshape(b, 1, tk)
                if has_mask else None)
    common = dict(scale=scale, causal=causal, block_q=block_q,
                  block_k=block_k, causal_offset=offset, tq=tq, tk=tk,
                  has_mask=has_mask)
    q_specs = [
        pl.BlockSpec((1, block_q, d), lambda bhi, qi, ki: (bhi, qi, 0)),
        pl.BlockSpec((1, block_k, d), lambda bhi, qi, ki: (bhi, ki, 0)),
        pl.BlockSpec((1, block_k, d), lambda bhi, qi, ki: (bhi, ki, 0)),
        pl.BlockSpec((1, block_q, d), lambda bhi, qi, ki: (bhi, qi, 0)),
        pl.BlockSpec((1, 1, block_q), lambda bhi, qi, ki: (bhi, 0, qi)),
        pl.BlockSpec((1, 1, block_q), lambda bhi, qi, ki: (bhi, 0, qi)),
    ]
    q_ops = [q3, k3, v3, do3, lse2, dlt2]
    if has_mask:
        q_specs.append(pl.BlockSpec(
            (1, 1, block_k), lambda bhi, qi, ki: (bhi // h, 0, ki)))
        q_ops.append(mask_i32)
    dq = pl.pallas_call(
        functools.partial(_fa_bwd_dq_kernel, **common),
        grid=(bh, nq, nk),
        in_specs=q_specs,
        out_specs=pl.BlockSpec((1, block_q, d),
                               lambda bhi, qi, ki: (bhi, qi, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, tq, d), q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        interpret=interpret,
    )(*q_ops)
    kv_specs = [
        pl.BlockSpec((1, block_q, d), lambda bhi, ki, qi: (bhi, qi, 0)),
        pl.BlockSpec((1, block_k, d), lambda bhi, ki, qi: (bhi, ki, 0)),
        pl.BlockSpec((1, block_k, d), lambda bhi, ki, qi: (bhi, ki, 0)),
        pl.BlockSpec((1, block_q, d), lambda bhi, ki, qi: (bhi, qi, 0)),
        pl.BlockSpec((1, 1, block_q), lambda bhi, ki, qi: (bhi, 0, qi)),
        pl.BlockSpec((1, 1, block_q), lambda bhi, ki, qi: (bhi, 0, qi)),
    ]
    kv_ops = [q3, k3, v3, do3, lse2, dlt2]
    if has_mask:
        kv_specs.append(pl.BlockSpec(
            (1, 1, block_k), lambda bhi, ki, qi: (bhi // h, 0, ki)))
        kv_ops.append(mask_i32)
    dk, dv = pl.pallas_call(
        functools.partial(_fa_bwd_dkv_kernel, **common),
        grid=(bh, nk, nq),
        in_specs=kv_specs,
        out_specs=[
            pl.BlockSpec((1, block_k, d), lambda bhi, ki, qi: (bhi, ki, 0)),
            pl.BlockSpec((1, block_k, d), lambda bhi, ki, qi: (bhi, ki, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, tk, d), k.dtype),
            jax.ShapeDtypeStruct((bh, tk, d), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, d), jnp.float32),
            pltpu.VMEM((block_k, d), jnp.float32),
        ],
        interpret=interpret,
    )(*kv_ops)
    return (dq.reshape(b, h, tq, d), dk.reshape(b, h, tk, d),
            dv.reshape(b, h, tk, d))


def chunked_attention(q, k, v, scale=None, causal=False, kv_mask=None,
                      chunk_size=512):
    """Flash-style attention in pure XLA: lax.scan over KV chunks with online
    softmax. O(T) memory, differentiable, runs anywhere. Used as the CPU/
    fallback path and as the recompute backward for the Pallas forward.
    Same semantics as the Pallas path: kv_mask [B, Tk] (True = attend);
    fully-masked rows yield exactly zero output."""
    scale = scale if scale is not None else 1.0 / jnp.sqrt(q.shape[-1])
    # accumulate in f32, except when fed f64 inputs (the precision-probe
    # ground-truth path under jax_enable_x64) — then keep full f64 so the
    # baseline really is higher-precision than the kernel under test
    acc_dtype = jnp.float64 if q.dtype == jnp.float64 else jnp.float32
    scale = jnp.asarray(scale, acc_dtype)
    b, h, tq, d = q.shape
    tk = k.shape[2]
    chunk = min(chunk_size, tk)
    nchunks = (tk + chunk - 1) // chunk
    pad = nchunks * chunk - tk
    if pad:
        k = jnp.pad(k, ((0, 0), (0, 0), (0, pad), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, 0), (0, pad), (0, 0)))
    kc = k.reshape(b, h, nchunks, chunk, d).transpose(2, 0, 1, 3, 4)
    vc = v.reshape(b, h, nchunks, chunk, d).transpose(2, 0, 1, 3, 4)
    if kv_mask is not None:
        mc = jnp.pad(kv_mask.astype(bool), ((0, 0), (0, pad)),
                     constant_values=False)
        mc = mc.reshape(b, nchunks, chunk).transpose(1, 0, 2)  # [N, B, C]
    qf = q.astype(acc_dtype)
    # bottom-right aligned causal (matches scaled_dot_product_attention)
    q_pos = jnp.arange(tq) + (tk - tq)

    def step(carry, inp):
        m, l, acc = carry
        if kv_mask is not None:
            kb, vb, ci, mb = inp
        else:
            kb, vb, ci = inp
        s = jnp.einsum("bhqd,bhkd->bhqk", qf, kb.astype(acc_dtype)) * scale
        k_pos = ci * chunk + jnp.arange(chunk)
        valid = jnp.broadcast_to((k_pos < tk)[None, None, None, :], s.shape)
        if causal:
            valid = valid & (q_pos[:, None] >= k_pos[None, :])[None, None]
        if kv_mask is not None:
            valid = valid & mb[:, None, None, :]
        s = jnp.where(valid, s, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, -1, keepdims=True))
        # mask p, not just s: in a fully-masked row m_new stays NEG_INF and
        # exp(s - m_new) = 1 — identical semantics to the Pallas kernel
        p = jnp.where(valid, jnp.exp(s - m_new), 0.0)
        alpha = jnp.exp(m - m_new)
        l = l * alpha + jnp.sum(p, -1, keepdims=True)
        acc = acc * alpha + jnp.einsum("bhqk,bhkd->bhqd", p,
                                       vb.astype(acc_dtype))
        return (m_new, l, acc), None

    m0 = jnp.full((b, h, tq, 1), NEG_INF, acc_dtype)
    l0 = jnp.zeros((b, h, tq, 1), acc_dtype)
    acc0 = jnp.zeros((b, h, tq, d), acc_dtype)
    xs = (kc, vc, jnp.arange(nchunks))
    if kv_mask is not None:
        xs = xs + (mc,)
    (m, l, acc), _ = jax.lax.scan(jax.checkpoint(step), (m0, l0, acc0), xs)
    out = jnp.where(l > 0, acc / jnp.maximum(l, 1e-30), 0.0)
    return out.astype(q.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7, 8))
def _flash_core(q, k, v, mask, scale, causal, block_q, block_k, has_mask):
    return _flash_attention_fwd_tpu(q, k, v, scale, causal, block_q, block_k,
                                    kv_mask=mask if has_mask else None)


def _flash_core_fwd(q, k, v, mask, scale, causal, block_q, block_k, has_mask):
    out, lse = _flash_attention_fwd_tpu(
        q, k, v, scale, causal, block_q, block_k,
        kv_mask=mask if has_mask else None, return_lse=True)
    return out, (q, k, v, mask, out, lse)


def _flash_core_bwd(scale, causal, block_q, block_k, has_mask, res, g):
    q, k, v, mask, out, lse = res
    kv_mask = mask if has_mask else None
    from paddle_tpu.core.flags import get_flag
    if get_flag("flash_pallas_bwd"):
        dq, dk, dv = _flash_attention_bwd_tpu(
            q, k, v, out, lse, g, scale, causal, block_q, block_k,
            kv_mask=kv_mask)
    else:
        _, vjp = jax.vjp(lambda q_, k_, v_: chunked_attention(
            q_, k_, v_, scale=scale, causal=causal, kv_mask=kv_mask,
            chunk_size=block_k), q, k, v)
        dq, dk, dv = vjp(g)
    return dq, dk, dv, jnp.zeros_like(mask)


_flash_core.defvjp(_flash_core_fwd, _flash_core_bwd)


def flash_attention(q, k, v, scale=None, causal=False, kv_mask=None,
                    block_q=None, block_k=None):
    """Memory-efficient attention. q,k,v: [B, H, T, D]; kv_mask: [B, Tk]
    bool/0-1, True = attend (the key-padding mask of a padded batch).

    On TPU: Pallas online-softmax forward + Pallas dq/dkv backward
    (flash-attention-2 recomputation from the saved logsumexp; set the
    `flash_pallas_bwd=False` flag to fall back to a jax.checkpoint
    recompute over the chunked XLA formulation). Head dims that are
    multiples of 64 are supported (Mosaic pads the 64-lane case;
    BERT-base's D=64 still wins because the [BQ,BK] matmuls dominate).
    Elsewhere: chunked XLA formulation (same math, same semantics).
    """
    from paddle_tpu.core.flags import get_flag
    # default block sizes come from flags so a flash_tune.py sweep result
    # applies fleet-wide via PT_FLAGS_flash_block_{q,k} (no code change)
    block_q = block_q if block_q is not None else get_flag("flash_block_q")
    block_k = block_k if block_k is not None else get_flag("flash_block_k")
    scale = float(scale) if scale is not None else 1.0 / (q.shape[-1] ** 0.5)
    if (on_tpu() or get_flag("pallas_interpret")) and pltpu is not None:
        if q.shape[-1] % 64 == 0 and q.shape[2] % 8 == 0 \
                and k.shape[2] % 8 == 0:
            if kv_mask is None:
                # dummy float operand keeps the custom_vjp signature static;
                # has_mask=False drops it before the pallas_call
                mask = jnp.zeros((1, 1), jnp.float32)
                return _flash_core(q, k, v, mask, scale, causal, block_q,
                                   block_k, False)
            return _flash_core(q, k, v, kv_mask.astype(jnp.float32), scale,
                               causal, block_q, block_k, True)
        # include the requested shardings: under GSPMD/shard_map the
        # PER-SHARD T is what must divide by 8, so a globally-legal shape
        # can still land here once the sequence axis is partitioned — the
        # log must show what was asked for vs what the kernel supports
        _log_fallback(f"D={q.shape[-1]} not a multiple of 64 or "
                      f"T={q.shape[2]}/{k.shape[2]} not a multiple of 8; "
                      f"requested {describe_sharding(q=q, k=k)} "
                      "(supported: per-shard D%64==0 and T%8==0)")
    return chunked_attention(q, k, v, scale=scale, causal=causal,
                             kv_mask=kv_mask, chunk_size=block_k)
