"""Shared tiled-primitive layer for the Pallas TPU kernels.

Every kernel family in ops/pallas/ used to carry private copies of the
same four concerns: (1) deciding whether the Pallas path applies at all
(enable flag, TPU vs interpreter, fallback telemetry), (2) building
BlockSpecs/grids from tile sizes, (3) the online-softmax (m, l, acc)
combiner, and (4) masking — causal triangles, ragged sequence tails, and
the padded tail tiles Pallas fills with undefined values. In the spirit
of Tensor Processing Primitives (arxiv 2104.05755), this module is the
one place those live; a new kernel is ~50 lines of math on top of it
(see ops/pallas/mlp.py, the first kernel born on the layer, and the
README "Pallas primitive core & autotuning" section).

The contract enforced by graft-lint's ``raw-pallas-call`` rule: this
module holds the ONLY ``pl.pallas_call`` site in the tree. Kernels call
:func:`kernel_call`; dispatchers resolve their execution mode through
:func:`kernel_mode`, which owns the enable-flag check, on-TPU/interpret
detection, `log_fallback`, and the ``pallas.fallback{kernel}`` counter.
"""

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:
    from jax.experimental.pallas import tpu as pltpu
except ImportError:  # pragma: no cover
    pltpu = None

from paddle_tpu.ops.pallas import log_fallback, on_tpu

NEG_INF = -1e30

#: execution modes returned by :func:`kernel_mode`
TPU, INTERPRET = "tpu", "interpret"


# --------------------------------------------------------------- dispatch

def kernel_mode(kernel, *, enable_flag=None, unsupported=None,
                log_unavailable=False, unavailable_reason="",
                level=None):
    """Resolve how a kernel should run: ``"tpu"``, ``"interpret"``, or
    None (the caller takes its XLA fallback).

    Owns the whole refusal protocol the five kernel families used to
    duplicate:

      * ``enable_flag`` False -> None, silently (the flag is the
        documented escape hatch; flipping it off is a request, not a
        refusal worth a warning).
      * off-TPU without ``pallas_interpret``, or no pltpu backend ->
        None. Silent by default (plain CPU runs are not an anomaly);
        ``log_unavailable=True`` emits ``unavailable_reason`` the way
        the xent kernels always have.
      * ``unsupported`` (a reason string naming requested vs supported
        configuration, or None when the shapes qualify) -> None with a
        `log_fallback` — a silent drop under GSPMD is invisible, so
        this one always logs and counts ``pallas.fallback{kernel}``.
    """
    import logging
    from paddle_tpu.core.flags import get_flag
    if level is None:
        level = logging.WARNING
    if enable_flag is not None and not get_flag(enable_flag):
        return None
    interpret = get_flag("pallas_interpret")
    if (not (on_tpu() or interpret)) or pltpu is None:
        if log_unavailable and unavailable_reason:
            log_fallback(kernel, unavailable_reason, level)
        return None
    if unsupported:
        log_fallback(kernel, unsupported, level)
        return None
    return TPU if on_tpu() else INTERPRET


def kernel_call(kernel_fn, *, name, grid=None, grid_spec=None,
                in_specs=None, out_specs=None, out_shape=None,
                scratch_shapes=None, interpret=False):
    """The one ``pl.pallas_call`` site in the tree (graft-lint's
    ``raw-pallas-call`` rule rejects any other). Accepts either a plain
    ``grid`` + in/out specs or a prebuilt ``grid_spec`` (e.g. the
    scalar-prefetch spec of the paged decode kernel, which carries its
    own scratch shapes). ``name`` identifies the kernel to the autotuner
    and in debugging; it is not forwarded to Pallas."""
    del name
    kwargs = {}
    if grid_spec is not None:
        kwargs["grid_spec"] = grid_spec
    else:
        kwargs["grid"] = grid
        kwargs["in_specs"] = in_specs
        kwargs["out_specs"] = out_specs
    if scratch_shapes is not None:
        kwargs["scratch_shapes"] = scratch_shapes
    return pl.pallas_call(kernel_fn, out_shape=out_shape,
                          interpret=interpret, **kwargs)


# --------------------------------------------------- BlockSpec/grid builders

def tile_spec(block_shape, dims):
    """BlockSpec whose index map routes grid axes to block dims:
    ``dims[k]`` is the grid-axis index feeding block dim ``k``, or None
    for a dim pinned at 0. ``tile_spec((1, bq, d), (0, 1, None))`` is
    the flash q tile — grid axis 0 picks the batch*head slab, axis 1 the
    query block, and the head dim is whole."""
    dims = tuple(dims)

    def imap(*gids):
        return tuple(0 if d is None else gids[d] for d in dims)

    return pl.BlockSpec(block_shape, imap)


def legal_block(block, t, interpret=False):
    """Largest Mosaic-tileable block ≤ the request. Lane-major operands
    (lse/delta/masks) ride with the block size in the lane dimension,
    which Mosaic accepts only when it is a multiple of 128 or covers the
    whole sequence — a perf knob, never semantics, so silently legalize
    rather than fall back. Interpret mode does NOT legalize: the
    interpreter has no tiling rule, and the CPU suite's small-block
    cases (block 8/16/32 at T ≤ 128) are what exercise the multi-block
    online-softmax, tail-masking, and causal block-skip paths."""
    b = min(block, t)
    if interpret or b == t or b % 128 == 0:
        return b
    return (b // 128) * 128 if b >= 128 else min(t, 128)


def pick_block_rows(rows, cols, dtype_bytes, vmem_budget=2 ** 21, copies=2,
                    cap=256, floor=1):
    """Rows per tile for a rows-major kernel: keep ``copies`` copies of a
    [rows, cols] tile within the VMEM budget. Need not divide rows — the
    grid rounds up and the tail tile is padded (callers mask it)."""
    per_row = max(cols * dtype_bytes * copies, 1)
    return max(min(vmem_budget // per_row, rows, cap), floor)


def pick_rv_blocks(n, v, h, dtype_bytes, vmem_budget=2 ** 22):
    """(row tile, vocab tile) for the rows x vocab kernels: h-tile +
    w-tile + f32 logits tile within ~4MB."""
    bv = max(min(v, 1024), 128)
    per_row = h * dtype_bytes + bv * 4          # hidden row + logits row
    bn = max(min(vmem_budget // max(per_row, 1), n, 512), 8)
    return bn, bv


# ------------------------------------------------------- masking builders

def block_valid(qi, ki, *, block_q, block_k, tq, tk, causal, causal_offset,
                mask_row):
    """[BQ, BK] validity for one attention tile: tail rows/cols past the
    true sequence end, the causal triangle, and the kv padding mask.
    Returns None when every position is valid (no masking work)."""
    valid = None
    q_pos = qi * block_q + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0)
    k_pos = ki * block_k + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 1)

    def _and(a, b):
        return b if a is None else a & b

    if tq % block_q:
        valid = _and(valid, q_pos < tq)
    if tk % block_k:
        valid = _and(valid, k_pos < tk)
    if causal:
        valid = _and(valid, q_pos + causal_offset >= k_pos)
    if mask_row is not None:
        valid = _and(valid, mask_row > 0)      # (1, BK) broadcasts over rows
    return valid


def tail_zero(x, idx, block, t):
    """Zero the rows of a loaded [block, D] tile that lie past the true
    sequence end t. Pallas pads out-of-bounds block regions with
    undefined values (NaN in interpret mode) and 0 * NaN = NaN, so
    masking the probabilities alone is not enough — the operands
    themselves must be clean before they enter a matmul. Static no-op
    when block divides t."""
    if t % block == 0:
        return x
    rows = idx * block + jax.lax.broadcasted_iota(jnp.int32, (block, 1), 0)
    return jnp.where(rows < t, x, 0.0)


def tail_zero_row(x, idx, block, t):
    """Same for a (1, block) lane-major tile (lse/delta)."""
    if t % block == 0:
        return x
    cols = idx * block + jax.lax.broadcasted_iota(jnp.int32, (1, block), 1)
    return jnp.where(cols < t, x, 0.0)


def tail_valid_cols(idx, block, total, shape, axis=1):
    """[shape] bool marking columns ``idx*block + i < total`` along
    ``axis`` — the padded-tail mask of a tiled reduction axis (vocab
    tiles, intermediate tiles)."""
    pos = idx * block + jax.lax.broadcasted_iota(jnp.int32, shape, axis)
    return pos < total


# ------------------------------------------------- quantized-tile primitive

def dequant_rows(x, row_scales):
    """Dequantize a loaded [H, R, D] int8 value tile against its per-row
    symmetric scales ([R], one scale per token row shared across heads
    and head_dim — the paged-KV layout of ops/attention.py). Lives here
    rather than in the decode kernel because it is the tiled-primitive
    counterpart of quantize_kv_rows: any future int8 kernel (prefill
    chunk, flash over quantized caches) reuses the same contract."""
    return x.astype(jnp.float32) * row_scales[None, :, None]


# ------------------------------------------- online-softmax (m, l) combiner

def softmax_init(m_scr, l_scr, *acc_scrs):
    """Reset the online-softmax carry at the first sequential step:
    m <- -inf sentinel, l <- 0, each accumulator <- 0."""
    m_scr[:] = jnp.full_like(m_scr, NEG_INF)
    l_scr[:] = jnp.zeros_like(l_scr)
    for acc in acc_scrs:
        acc[:] = jnp.zeros_like(acc)


def softmax_update(s, m_scr, l_scr, valid=None):
    """One online-softmax step over a [R, C] score tile: rescale the
    running (m, l) carry and return ``(p, alpha)`` — the tile's masked
    probabilities and the accumulator rescale factor — so the caller
    applies ``acc <- acc * alpha + p @ v`` with whatever contraction its
    value layout needs (flash: [BQ,BK]x[BK,D]; decode: head-batched).

    Masks p, not just s: in a fully-masked row m stays at the NEG_INF
    sentinel and exp(s - m) = exp(0) = 1 — without the p mask, masked
    positions would each contribute weight 1."""
    if valid is not None:
        s = jnp.where(valid, s, NEG_INF)
    m_prev = m_scr[:]                            # [R, 1]
    m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
    p = jnp.exp(s - m_new)                       # [R, C]
    if valid is not None:
        p = jnp.where(valid, p, 0.0)
    alpha = jnp.exp(m_prev - m_new)              # [R, 1]
    l_scr[:] = l_scr[:] * alpha + jnp.sum(p, axis=1, keepdims=True)
    m_scr[:] = m_new
    return p, alpha


def softmax_finalize(l, acc, out_dtype):
    """Normalize the accumulator by the softmax denominator. Fully-masked
    rows (l == 0) are defined as exactly zero output in every path — the
    chunked/XLA fallbacks match."""
    return jnp.where(l > 0, acc / jnp.maximum(l, 1e-30), 0.0).astype(
        out_dtype)


def logsumexp_update(masked, m_ref, s_ref):
    """Online logsumexp over a [R, C] tile of NEG_INF-masked logits:
    fold the tile into the running (max, sum-exp) pair held in the
    revisited output refs (the xent-stats discipline — same carry as
    softmax_update without a value accumulator)."""
    m_old = m_ref[:]                                       # [R, 1]
    m_new = jnp.maximum(m_old, jnp.max(masked, axis=1, keepdims=True))
    s_ref[:] = (s_ref[:] * jnp.exp(m_old - m_new)
                + jnp.sum(jnp.exp(masked - m_new), axis=1, keepdims=True))
    m_ref[:] = m_new
