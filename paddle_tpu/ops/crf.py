"""Linear-chain CRF ops + edit distance.

Ref: /root/reference/paddle/fluid/operators/linear_chain_crf_op.cc (forward
algorithm log-likelihood), crf_decoding_op.cc (Viterbi decode),
edit_distance_op.cc (Levenshtein). These back the reference's
label_semantic_roles book model (tests/book/test_label_semantic_roles.py).

TPU-first: sequences are padded dense [B, T, K] + lengths (MXU-friendly static
shapes); the time recurrences are `lax.scan`s. The reference's transition
parameter layout is kept for parity: Transition is [K + 2, K] where row 0 =
start weights, row 1 = stop weights, rows 2: = w[i, j] (score of tag i -> j).
"""

import jax
import jax.numpy as jnp
from jax import lax

from paddle_tpu.core.registry import register_op


def _split_transition(transition):
    start, stop, trans = transition[0], transition[1], transition[2:]
    return start, stop, trans


@register_op("linear_chain_crf")
def linear_chain_crf(emission, transition, label, lengths):
    """Negative log-likelihood of `label` paths under a linear-chain CRF.

    emission: [B, T, K] float unnormalized tag scores.
    transition: [K+2, K] (row0 start, row1 stop, rows2: tag->tag).
    label: [B, T] int gold tags.
    lengths: [B] int valid lengths (>= 1).
    Returns [B] negative log-likelihood (the reference's LogLikelihood output
    is used directly as the cost; linear_chain_crf_op.cc computes
    -(path_score - logZ)).
    """
    start, stop, trans = _split_transition(transition)
    B, T, K = emission.shape
    t_idx = jnp.arange(T)
    mask = (t_idx[None, :] < lengths[:, None]).astype(emission.dtype)  # [B,T]

    # ---- path score -----------------------------------------------------
    lab = jnp.clip(label, 0, K - 1)
    em_score = jnp.sum(
        jnp.take_along_axis(emission, lab[..., None], axis=-1)[..., 0] * mask,
        axis=1)
    pair_scores = trans[lab[:, :-1], lab[:, 1:]]                       # [B,T-1]
    pair_mask = mask[:, 1:]
    tr_score = jnp.sum(pair_scores * pair_mask, axis=1)
    first_tag = lab[:, 0]
    last_pos = jnp.maximum(lengths - 1, 0)
    last_tag = jnp.take_along_axis(lab, last_pos[:, None], axis=1)[:, 0]
    score = em_score + tr_score + start[first_tag] + stop[last_tag]

    # ---- partition function (forward algorithm) -------------------------
    alpha0 = start[None, :] + emission[:, 0, :]                        # [B,K]

    def step(alpha, inp):
        em_t, m_t = inp                                                # [B,K],[B]
        nxt = jax.nn.logsumexp(alpha[:, :, None] + trans[None], axis=1) + em_t
        alpha = jnp.where(m_t[:, None] > 0, nxt, alpha)
        return alpha, None

    xs = (jnp.moveaxis(emission[:, 1:, :], 1, 0), jnp.moveaxis(mask[:, 1:], 1, 0))
    alphaT, _ = lax.scan(step, alpha0, xs)
    log_z = jax.nn.logsumexp(alphaT + stop[None, :], axis=1)
    return log_z - score


@register_op("crf_decoding")
def crf_decoding(emission, transition, lengths, label=None):
    """Viterbi decode. Returns [B, T] best tag path (0 beyond length).

    With `label` given, returns instead a [B, T] 0/1 array marking positions
    where the decoded path matches the gold label (the reference's
    crf_decoding_op.cc behavior when Label is fed).
    """
    start, stop, trans = _split_transition(transition)
    B, T, K = emission.shape
    t_idx = jnp.arange(T)
    mask = t_idx[None, :] < lengths[:, None]

    alpha0 = start[None, :] + emission[:, 0, :]

    def fwd(alpha, inp):
        em_t, m_t = inp
        cand = alpha[:, :, None] + trans[None]                         # [B,K,K]
        best_prev = jnp.argmax(cand, axis=1)                           # [B,K]
        nxt = jnp.max(cand, axis=1) + em_t
        alpha_new = jnp.where(m_t[:, None], nxt, alpha)
        # beyond the end, point back at the same tag so backtrace is stable
        best_prev = jnp.where(m_t[:, None], best_prev,
                              jnp.arange(K)[None, :])
        return alpha_new, best_prev

    xs = (jnp.moveaxis(emission[:, 1:, :], 1, 0),
          jnp.moveaxis(mask[:, 1:], 1, 0))
    alphaT, backptrs = lax.scan(fwd, alpha0, xs)                       # [T-1,B,K]
    last_tag = jnp.argmax(alphaT + stop[None, :], axis=1)              # [B]

    def back(tag, bp):
        prev = jnp.take_along_axis(bp, tag[:, None], axis=1)[:, 0]
        return prev, tag

    # reverse scan: step i consumes backptrs[i] with carry = tag[i+1] and
    # emits that carry as ys[i]; the final carry is the tag at position 0.
    first_tag, path_tail = lax.scan(back, last_tag, backptrs, reverse=True)
    path = jnp.concatenate([first_tag[None], path_tail], axis=0)       # [T,B]
    path = jnp.moveaxis(path, 0, 1)                                    # [B,T]
    path = jnp.where(mask, path, 0)
    if label is not None:
        return jnp.where(mask, (path == label).astype(jnp.int32), 0)
    return path


@register_op("edit_distance")
def edit_distance(hyp, hyp_lengths, ref, ref_lengths, normalized=False):
    """Batched Levenshtein distance (ref: edit_distance_op.cc).

    hyp: [B, T1] int, ref: [B, T2] int, with per-row valid lengths.
    Returns ([B] distances float32, [B] ref sequence lengths int64-ish) to
    mirror the reference's (Out, SequenceNum) pair — here just the distance
    (and optionally normalized by ref length).
    """
    B, T1 = hyp.shape
    T2 = ref.shape[1]
    big = jnp.float32(T1 + T2 + 1)
    jr = jnp.arange(T2 + 1, dtype=jnp.float32)

    row0 = jnp.broadcast_to(jr, (B, T2 + 1))

    def step(row, i):
        h_i = hyp[:, i]                                                # [B]
        sub_cost = (ref != h_i[:, None]).astype(jnp.float32)           # [B,T2]
        # c[j] = min(row[j] + 1 (delete), row[j-1] + sub) for j=1..T2
        c = jnp.minimum(row[:, 1:] + 1.0, row[:, :-1] + sub_cost)
        c = jnp.concatenate([row[:, :1] + 1.0, c], axis=1)             # [B,T2+1]
        # resolve insert chain new[j] = min_k<=j (c[k] + (j-k)) via cummin
        new = jnp.minimum(
            c, lax.cummin(c - jr[None, :], axis=1) + jr[None, :])
        # rows past hyp_len are frozen, so the final row is the answer row
        row = jnp.where((i < hyp_lengths)[:, None], new, row)
        return row, None

    row, _ = lax.scan(step, row0, jnp.arange(T1))
    res = jnp.take_along_axis(row, ref_lengths[:, None], axis=1)[:, 0]
    if normalized:
        res = res / jnp.maximum(ref_lengths.astype(jnp.float32), 1.0)
    return res
