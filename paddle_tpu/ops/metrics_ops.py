"""Metric ops.

Ref: /root/reference/paddle/fluid/operators/metrics/ — accuracy_op.cc,
auc_op.cc, precision_recall_op.cc.
"""

import jax.numpy as jnp

from paddle_tpu.core.registry import register_op


@register_op("accuracy")
def accuracy(input, label, k=1):
    """ref: operators/metrics/accuracy_op.cc — top-k accuracy from logits or
    probabilities [B, C] against labels [B] or [B,1]."""
    if label.ndim > 1:
        label = jnp.squeeze(label, -1)
    topk = jnp.argsort(-input, axis=-1)[:, :k]
    correct = jnp.any(topk == label[:, None], axis=-1)
    return jnp.mean(correct.astype(jnp.float32))


@register_op("auc")
def auc(predict, label, num_thresholds=4096):
    """Streaming-free AUC over a batch (ref: operators/metrics/auc_op.cc uses
    stat buckets; here one-shot bucketed trapezoid)."""
    if label.ndim > 1:
        label = jnp.squeeze(label, -1)
    pos_score = predict[:, 1] if predict.ndim > 1 else predict
    bucket = jnp.clip((pos_score * num_thresholds).astype(jnp.int32),
                      0, num_thresholds - 1)
    lbl = label.astype(jnp.float32)
    pos_hist = jnp.zeros(num_thresholds).at[bucket].add(lbl)
    neg_hist = jnp.zeros(num_thresholds).at[bucket].add(1.0 - lbl)
    # integrate from the highest threshold down
    pos_c = jnp.cumsum(pos_hist[::-1])
    neg_c = jnp.cumsum(neg_hist[::-1])
    tot_pos = pos_c[-1]
    tot_neg = neg_c[-1]
    pos_prev = jnp.concatenate([jnp.zeros(1), pos_c[:-1]])
    neg_prev = jnp.concatenate([jnp.zeros(1), neg_c[:-1]])
    area = jnp.sum((neg_c - neg_prev) * (pos_c + pos_prev) / 2.0)
    return area / jnp.maximum(tot_pos * tot_neg, 1e-12)


@register_op("precision_recall")
def precision_recall(pred_label, label, num_classes):
    """ref: operators/metrics/precision_recall_op.cc — returns per-class
    (precision, recall, f1) macro arrays."""
    if label.ndim > 1:
        label = jnp.squeeze(label, -1)
    if pred_label.ndim > 1:
        pred_label = jnp.squeeze(pred_label, -1)
    tp = jnp.zeros(num_classes)
    fp = jnp.zeros(num_classes)
    fn = jnp.zeros(num_classes)
    correct = pred_label == label
    tp = tp.at[pred_label].add(correct.astype(jnp.float32))
    fp = fp.at[pred_label].add((~correct).astype(jnp.float32))
    fn = fn.at[label].add((~correct).astype(jnp.float32))
    precision = tp / jnp.maximum(tp + fp, 1e-12)
    recall = tp / jnp.maximum(tp + fn, 1e-12)
    f1 = 2 * precision * recall / jnp.maximum(precision + recall, 1e-12)
    return precision, recall, f1


def _extract_chunks(tags, chunk_scheme, num_chunk_types):
    """Parse a tag sequence into {(start, end, type)} chunks.

    Tag encoding matches the reference (operators/metrics/chunk_eval_op.cc /
    .h ChunkEvalOp): with S tags per chunk type (IOB/IOE: 2, IOBES: 4,
    plain: 1), tag = chunk_type * S + tag_index; any tag >= num_chunk_types*S
    is Outside.
    """
    schemes = {"IOB": ("B", "I"), "IOE": ("I", "E"),
               "IOBES": ("B", "I", "E", "S"), "plain": ("U",)}
    names = schemes[chunk_scheme]
    S = len(names)
    chunks = set()
    start = None
    ctype = None

    def close(end):
        nonlocal start, ctype
        if start is not None:
            chunks.add((start, end, ctype))
        start, ctype = None, None

    for i, t in enumerate(tags):
        t = int(t)
        if t < 0 or t >= num_chunk_types * S:
            close(i - 1)
            continue
        ty, ti = divmod(t, S)
        tag = names[ti]
        if chunk_scheme == "plain":
            # a maximal run of same-type tokens is one chunk (chunk_eval_op.h
            # ChunkEnd is false for consecutive same-type plain tags)
            if ctype != ty:
                close(i - 1)
                start, ctype = i, ty
        elif chunk_scheme == "IOB":
            if tag == "B" or ctype != ty:
                close(i - 1)
                start, ctype = i, ty
        elif chunk_scheme == "IOE":
            if ctype != ty:
                close(i - 1)
                start, ctype = i, ty
            if tag == "E":
                close(i)
        elif chunk_scheme == "IOBES":
            if tag == "S":
                close(i - 1)
                chunks.add((i, i, ty))
            elif tag == "B" or ctype != ty:
                close(i - 1)
                start, ctype = i, ty
            if tag == "E" and start is not None:
                close(i)
    close(len(tags) - 1)
    return chunks


@register_op("chunk_eval")
def chunk_eval(inference, label, lengths, chunk_scheme="IOB",
               num_chunk_types=1, excluded_chunk_types=()):
    """ref: operators/metrics/chunk_eval_op.cc — chunk-level P/R/F1 counts.

    Host-side (the reference kernel is CPU-only too). inference/label:
    [B, T] int arrays; lengths: [B]. Returns (precision, recall, f1,
    num_infer_chunks, num_label_chunks, num_correct_chunks).
    """
    import numpy as np
    inference = np.asarray(inference)
    label = np.asarray(label)
    lengths = np.asarray(lengths)
    excl = set(excluded_chunk_types)
    n_inf = n_lab = n_cor = 0
    for b in range(inference.shape[0]):
        L = int(lengths[b])
        inf_c = {c for c in _extract_chunks(
            inference[b, :L], chunk_scheme, num_chunk_types)
            if c[2] not in excl}
        lab_c = {c for c in _extract_chunks(
            label[b, :L], chunk_scheme, num_chunk_types)
            if c[2] not in excl}
        n_inf += len(inf_c)
        n_lab += len(lab_c)
        n_cor += len(inf_c & lab_c)
    precision = n_cor / max(n_inf, 1e-12)
    recall = n_cor / max(n_lab, 1e-12)
    f1 = 2 * precision * recall / max(precision + recall, 1e-12)
    return precision, recall, f1, n_inf, n_lab, n_cor
