"""Metric ops.

Ref: /root/reference/paddle/fluid/operators/metrics/ — accuracy_op.cc,
auc_op.cc, precision_recall_op.cc.
"""

import jax.numpy as jnp

from paddle_tpu.core.registry import register_op


@register_op("accuracy")
def accuracy(input, label, k=1):
    """ref: operators/metrics/accuracy_op.cc — top-k accuracy from logits or
    probabilities [B, C] against labels [B] or [B,1]."""
    if label.ndim > 1:
        label = jnp.squeeze(label, -1)
    topk = jnp.argsort(-input, axis=-1)[:, :k]
    correct = jnp.any(topk == label[:, None], axis=-1)
    return jnp.mean(correct.astype(jnp.float32))


@register_op("auc")
def auc(predict, label, num_thresholds=4096):
    """Streaming-free AUC over a batch (ref: operators/metrics/auc_op.cc uses
    stat buckets; here one-shot bucketed trapezoid)."""
    if label.ndim > 1:
        label = jnp.squeeze(label, -1)
    pos_score = predict[:, 1] if predict.ndim > 1 else predict
    bucket = jnp.clip((pos_score * num_thresholds).astype(jnp.int32),
                      0, num_thresholds - 1)
    lbl = label.astype(jnp.float32)
    pos_hist = jnp.zeros(num_thresholds).at[bucket].add(lbl)
    neg_hist = jnp.zeros(num_thresholds).at[bucket].add(1.0 - lbl)
    # integrate from the highest threshold down
    pos_c = jnp.cumsum(pos_hist[::-1])
    neg_c = jnp.cumsum(neg_hist[::-1])
    tot_pos = pos_c[-1]
    tot_neg = neg_c[-1]
    pos_prev = jnp.concatenate([jnp.zeros(1), pos_c[:-1]])
    neg_prev = jnp.concatenate([jnp.zeros(1), neg_c[:-1]])
    area = jnp.sum((neg_c - neg_prev) * (pos_c + pos_prev) / 2.0)
    return area / jnp.maximum(tot_pos * tot_neg, 1e-12)


@register_op("precision_recall")
def precision_recall(pred_label, label, num_classes):
    """ref: operators/metrics/precision_recall_op.cc — returns per-class
    (precision, recall, f1) macro arrays."""
    if label.ndim > 1:
        label = jnp.squeeze(label, -1)
    if pred_label.ndim > 1:
        pred_label = jnp.squeeze(pred_label, -1)
    tp = jnp.zeros(num_classes)
    fp = jnp.zeros(num_classes)
    fn = jnp.zeros(num_classes)
    correct = pred_label == label
    tp = tp.at[pred_label].add(correct.astype(jnp.float32))
    fp = fp.at[pred_label].add((~correct).astype(jnp.float32))
    fn = fn.at[label].add((~correct).astype(jnp.float32))
    precision = tp / jnp.maximum(tp + fp, 1e-12)
    recall = tp / jnp.maximum(tp + fn, 1e-12)
    f1 = 2 * precision * recall / jnp.maximum(precision + recall, 1e-12)
    return precision, recall, f1
