"""Activation ops.

Ref: /root/reference/paddle/fluid/operators/activation_op.cc — the reference
registers ~30 activation kernels with hand-written CUDA grads. Here each is a
jnp expression; XLA fuses them into adjacent matmuls/convs (replacing the
reference's fused_ops/fused_elemwise_activation and ir fusion passes), and
jax.grad derives the backward.
"""

import jax
import jax.numpy as jnp

from paddle_tpu.core.registry import register_op


@register_op("relu")
def relu(x):
    return jnp.maximum(x, 0)


@register_op("relu6")
def relu6(x, threshold=6.0):
    return jnp.clip(x, 0, threshold)


@register_op("leaky_relu")
def leaky_relu(x, alpha=0.02):
    return jnp.where(x >= 0, x, alpha * x)


@register_op("prelu")
def prelu(x, alpha):
    return jnp.where(x >= 0, x, alpha * x)


@register_op("elu")
def elu(x, alpha=1.0):
    safe = jnp.where(x > 0, 0.0, x)
    return jnp.where(x > 0, x, alpha * (jnp.exp(safe) - 1.0))


@register_op("selu")
def selu(x, scale=1.0507009873554805, alpha=1.6732632423543772):
    return scale * elu(x, alpha)


@register_op("gelu")
def gelu(x, approximate=False):
    return jax.nn.gelu(x, approximate=approximate)


@register_op("sigmoid")
def sigmoid(x):
    return jax.nn.sigmoid(x)


@register_op("logsigmoid")
def logsigmoid(x):
    return jax.nn.log_sigmoid(x)


@register_op("hard_sigmoid")
def hard_sigmoid(x, slope=0.2, offset=0.5):
    return jnp.clip(slope * x + offset, 0.0, 1.0)


@register_op("tanh")
def tanh(x):
    return jnp.tanh(x)


@register_op("tanh_shrink")
def tanh_shrink(x):
    return x - jnp.tanh(x)


@register_op("hard_shrink")
def hard_shrink(x, threshold=0.5):
    return jnp.where(jnp.abs(x) > threshold, x, 0.0)


@register_op("softshrink")
def softshrink(x, lambda_=0.5):
    return jnp.sign(x) * jnp.maximum(jnp.abs(x) - lambda_, 0.0)


@register_op("softplus")
def softplus(x):
    return jax.nn.softplus(x)


@register_op("softsign")
def softsign(x):
    return x / (1.0 + jnp.abs(x))


@register_op("swish")
def swish(x, beta=1.0):
    return x * jax.nn.sigmoid(beta * x)


@register_op("silu")
def silu(x):
    return jax.nn.silu(x)


@register_op("hard_swish")
def hard_swish(x, threshold=6.0, scale=6.0, offset=3.0):
    return x * jnp.clip(x + offset, 0.0, threshold) / scale


@register_op("mish")
def mish(x):
    return x * jnp.tanh(jax.nn.softplus(x))


@register_op("brelu")
def brelu(x, t_min=0.0, t_max=24.0):
    return jnp.clip(x, t_min, t_max)


@register_op("thresholded_relu")
def thresholded_relu(x, threshold=1.0):
    return jnp.where(x > threshold, x, 0.0)


@register_op("stanh")
def stanh(x, scale_a=0.67, scale_b=1.7159):
    return scale_b * jnp.tanh(scale_a * x)


@register_op("softmax")
def softmax(x, axis=-1):
    """ref: operators/softmax_op.cc (+softmax_cudnn); XLA fuses the
    max-subtract/exp/normalize chain on the VPU."""
    return jax.nn.softmax(x, axis=axis)


@register_op("log_softmax")
def log_softmax(x, axis=-1):
    return jax.nn.log_softmax(x, axis=axis)


@register_op("maxout")
def maxout(x, groups, axis=1):
    """ref: operators/maxout_op.cc"""
    c = x.shape[axis]
    new_shape = list(x.shape)
    new_shape[axis] = c // groups
    new_shape.insert(axis + 1, groups)
    return jnp.max(x.reshape(new_shape), axis=axis + 1)
