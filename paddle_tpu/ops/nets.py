"""Composite network helpers (ref: python/paddle/fluid/nets.py).

The reference's nets.py builds small op compositions over fluid.layers:
simple_img_conv_pool :28, img_conv_group :138, sequence_conv_pool :251,
glu :319, scaled_dot_product_attention :360 (the last lives in
ops/attention.py here). Functional versions over the ops library; the
conv/pool ones take explicit weights (functional core) and also exist as
Module compositions in models/.
"""

import jax.numpy as jnp

from paddle_tpu.core.registry import register_op
from paddle_tpu.ops import nn as F
from paddle_tpu.ops import sequence as S


@register_op("glu")
def glu(x, axis=-1):
    """Gated linear unit (ref nets.py:319): split in half along `axis`,
    a * sigmoid(b)."""
    import jax
    a, b = jnp.split(x, 2, axis=axis)
    return a * jax.nn.sigmoid(b)


def _ksize(w, data_format):
    # NCHW weights are OIHW, NHWC weights are HWIO (ops/nn.py conv2d)
    return (w.shape[2], w.shape[3]) if data_format == "NCHW" \
        else (w.shape[0], w.shape[1])


@register_op("simple_img_conv_pool")
def simple_img_conv_pool(x, conv_w, conv_b=None, pool_size=2, pool_stride=2,
                         pool_type="max", act=None, data_format="NCHW"):
    """conv2d -> act -> pool2d (ref nets.py:28)."""
    kh, kw = _ksize(conv_w, data_format)
    out = F.conv2d(x, conv_w, conv_b,
                   padding=((kh - 1) // 2, (kw - 1) // 2),
                   data_format=data_format)
    if act is not None:
        from paddle_tpu.ops import activations
        out = getattr(activations, act)(out)
    return F.pool2d(out, pool_size, pool_type, pool_stride,
                    data_format=data_format)


@register_op("img_conv_group")
def img_conv_group(x, conv_weights, conv_biases=None, act="relu",
                   pool_size=2, pool_stride=2, pool_type="max",
                   data_format="NCHW"):
    """N stacked conv+act then one pool (ref nets.py:138, the VGG block)."""
    from paddle_tpu.ops import activations
    act_fn = getattr(activations, act)
    biases = conv_biases or [None] * len(conv_weights)
    for w, b in zip(conv_weights, biases):
        kh, kw = _ksize(w, data_format)
        x = act_fn(F.conv2d(x, w, b, padding=((kh - 1) // 2, (kw - 1) // 2),
                            data_format=data_format))
    return F.pool2d(x, pool_size, pool_type, pool_stride,
                    data_format=data_format)


@register_op("sequence_conv_pool")
def sequence_conv_pool(rb, filter_w, act="tanh", pool_type="max"):
    """sequence_conv -> act -> sequence_pool (ref nets.py:251; the text-CNN
    block over ragged sequences)."""
    from paddle_tpu.core.ragged import RaggedBatch
    from paddle_tpu.ops import activations
    out = S.sequence_conv(rb, filter_w)
    vals = getattr(activations, act)(out.values
                                     if isinstance(out, RaggedBatch) else out)
    return S.sequence_pool(RaggedBatch(vals, rb.row_lengths), pool_type)
