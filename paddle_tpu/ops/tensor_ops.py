"""Tensor manipulation ops.

Ref: /root/reference/paddle/fluid/operators/ — concat_op.cc, split_op.cc,
stack_op.cc, squeeze_op.cc, transpose_op.cc, slice_op.cc, gather_op.cc,
scatter_op.cc, expand_op.cc, top_k_op.cc/.cu, argsort_op.cc, one_hot_op.cc,
fill_constant_op.cc, uniform_random_op.cc, gaussian_random_op.cc, range_op.cc,
where_op, shard_index_op.cc, unique_op.cc …

All static-shape, XLA-friendly. Ops whose reference semantics are dynamic
(masked_select, unique) return padded results + validity counts, keeping
jit-compatibility on TPU.
"""

import jax
import jax.numpy as jnp
from jax import lax

from paddle_tpu.core.dtype import convert_dtype
from paddle_tpu.core.registry import register_op


@register_op("cast")
def cast(x, dtype):
    return x.astype(convert_dtype(dtype))


@register_op("concat")
def concat(xs, axis=0):
    return jnp.concatenate(xs, axis=axis)


@register_op("split")
def split(x, num_or_sections, axis=0):
    if isinstance(num_or_sections, int):
        return jnp.split(x, num_or_sections, axis=axis)
    idx = list(jnp.cumsum(jnp.array(num_or_sections))[:-1])
    return jnp.split(x, [int(i) for i in idx], axis=axis)


@register_op("stack")
def stack(xs, axis=0):
    return jnp.stack(xs, axis=axis)


@register_op("unstack")
def unstack(x, axis=0):
    return [jnp.squeeze(s, axis) for s in jnp.split(x, x.shape[axis], axis)]


@register_op("squeeze")
def squeeze(x, axes=None):
    return jnp.squeeze(x, axis=tuple(axes) if axes else None)


@register_op("unsqueeze")
def unsqueeze(x, axes):
    if isinstance(axes, int):
        axes = [axes]
    for a in sorted(axes):
        x = jnp.expand_dims(x, a)
    return x


@register_op("reshape")
def reshape(x, shape):
    return jnp.reshape(x, shape)


@register_op("flatten")
def flatten(x, axis=1):
    """ref: operators/flatten_op.cc — flatten to 2-D at `axis`."""
    lead = 1
    for d in x.shape[:axis]:
        lead *= d
    return x.reshape(lead, -1)


@register_op("transpose")
def transpose(x, perm):
    return jnp.transpose(x, perm)


@register_op("slice")
def slice(x, axes, starts, ends):
    """ref: operators/slice_op.cc"""
    idx = [jnp.s_[:]] * x.ndim
    for a, s, e in zip(axes, starts, ends):
        idx[a] = jnp.s_[s:e]
    return x[tuple(idx)]


@register_op("strided_slice")
def strided_slice(x, axes, starts, ends, strides):
    idx = [jnp.s_[:]] * x.ndim
    for a, s, e, st in zip(axes, starts, ends, strides):
        idx[a] = jnp.s_[s:e:st]
    return x[tuple(idx)]


@register_op("gather")
def gather(x, index, axis=0):
    """ref: operators/gather_op.cc"""
    return jnp.take(x, index, axis=axis)


@register_op("gather_nd")
def gather_nd(x, index):
    """ref: operators/gather_nd_op.cc — index [..., k] selects x[idx] over
    leading k dims."""
    k = index.shape[-1]
    flat_index = tuple(jnp.moveaxis(index, -1, 0))
    return x[flat_index]


@register_op("scatter")
def scatter(x, index, updates, overwrite=True):
    """ref: operators/scatter_op.cc — rows of x at `index` set/add to updates."""
    if overwrite:
        return x.at[index].set(updates)
    return x.at[index].add(updates)


@register_op("scatter_nd_add")
def scatter_nd_add(x, index, updates):
    flat_index = tuple(jnp.moveaxis(index, -1, 0))
    return x.at[flat_index].add(updates)


@register_op("expand")
def expand(x, expand_times):
    """ref: operators/expand_op.cc — tile semantics."""
    return jnp.tile(x, expand_times)


@register_op("expand_as")
def expand_as(x, target):
    reps = [t // s for t, s in zip(target.shape, x.shape)]
    return jnp.tile(x, reps)


@register_op("broadcast_to")
def broadcast_to(x, shape):
    return jnp.broadcast_to(x, shape)


@register_op("tile")
def tile(x, reps):
    return jnp.tile(x, reps)


@register_op("reverse")
def reverse(x, axis):
    if isinstance(axis, int):
        axis = [axis]
    for a in axis:
        x = jnp.flip(x, a)
    return x


@register_op("roll")
def roll(x, shifts, axis=None):
    return jnp.roll(x, shifts, axis)


@register_op("top_k")
def top_k(x, k):
    """ref: operators/top_k_op.cc/.cu — returns (values, indices)."""
    return lax.top_k(x, k)


@register_op("argsort")
def argsort(x, axis=-1, descending=False):
    """ref: operators/argsort_op.cc — returns (sorted, indices)."""
    idx = jnp.argsort(-x if descending else x, axis=axis)
    sorted_x = jnp.take_along_axis(x, idx, axis=axis)
    return sorted_x, idx


@register_op("sort")
def sort(x, axis=-1, descending=False):
    s = jnp.sort(x, axis=axis)
    return jnp.flip(s, axis) if descending else s


@register_op("argmax")
def argmax(x, axis=-1):
    return jnp.argmax(x, axis=axis)


@register_op("argmin")
def argmin(x, axis=-1):
    return jnp.argmin(x, axis=axis)


@register_op("one_hot")
def one_hot(x, depth, dtype=jnp.float32):
    """ref: operators/one_hot_op.cc"""
    x = jnp.squeeze(x, -1) if x.ndim > 1 and x.shape[-1] == 1 else x
    return jax.nn.one_hot(x, depth, dtype=convert_dtype(dtype))


@register_op("where")
def where(condition, x=None, y=None):
    if x is None and y is None:
        # dynamic nonzero is not jit-able; return mask-based indices padded
        return jnp.nonzero(condition)
    return jnp.where(condition, x, y)


@register_op("masked_select")
def masked_select(x, mask, size=None):
    """Static-shape masked select: returns (values[size], count). Padded with
    zeros — TPU redesign of the reference's dynamic-shape masked select."""
    flat_x = x.reshape(-1)
    flat_m = mask.reshape(-1)
    size = size if size is not None else flat_x.shape[0]
    order = jnp.argsort(~flat_m, stable=True)
    vals = jnp.where(flat_m[order], flat_x[order], 0)[:size]
    return vals, jnp.sum(flat_m.astype(jnp.int32))


@register_op("unique_with_counts")
def unique_with_counts(x, size=None):
    """Static-shape unique (ref: operators/unique_op.cc): returns
    (unique[size], counts[size], num_unique). Padded beyond num_unique."""
    size = size if size is not None else x.shape[0]
    u, cnt = jnp.unique_counts(x, size=size, fill_value=0)
    num = jnp.sum(cnt > 0)
    return u, cnt, num


@register_op("shard_index")
def shard_index(x, index_num, nshards, shard_id, ignore_value=-1):
    """ref: operators/shard_index_op.cc — remap global ids to per-shard local
    ids (used by sharded embedding / model-parallel fc)."""
    shard_size = (index_num + nshards - 1) // nshards
    in_shard = (x // shard_size) == shard_id
    return jnp.where(in_shard, x % shard_size, ignore_value)


@register_op("index_select")
def index_select(x, index, axis=0):
    return jnp.take(x, index, axis=axis)


@register_op("index_sample")
def index_sample(x, index):
    """ref: operators/index_sample — per-row gather."""
    return jnp.take_along_axis(x, index, axis=1)


# --- creation ops ---
@register_op("fill_constant")
def fill_constant(shape, dtype, value):
    return jnp.full(shape, value, dtype=convert_dtype(dtype))


@register_op("fill_constant_batch_size_like")
def fill_constant_batch_size_like(input, shape, dtype, value):
    shape = (input.shape[0],) + tuple(shape[1:])
    return jnp.full(shape, value, dtype=convert_dtype(dtype))


@register_op("zeros_like")
def zeros_like(x):
    return jnp.zeros_like(x)


@register_op("ones_like")
def ones_like(x):
    return jnp.ones_like(x)


@register_op("arange")
def arange(start, end=None, step=1, dtype=jnp.int64):
    return jnp.arange(start, end, step, dtype=convert_dtype(dtype))


@register_op("linspace")
def linspace(start, stop, num, dtype=jnp.float32):
    return jnp.linspace(start, stop, num, dtype=convert_dtype(dtype))


@register_op("eye")
def eye(num_rows, num_columns=None, dtype=jnp.float32):
    return jnp.eye(num_rows, num_columns, dtype=convert_dtype(dtype))


@register_op("diag")
def diag(x):
    return jnp.diag(x)


@register_op("uniform_random")
def uniform_random(key, shape, dtype=jnp.float32, min=-1.0, max=1.0):
    """ref: operators/uniform_random_op.cc — explicit PRNG key (TPU-native:
    counter-based PRNG, reproducible under jit/pjit)."""
    return jax.random.uniform(key, shape, convert_dtype(dtype), min, max)


@register_op("gaussian_random")
def gaussian_random(key, shape, dtype=jnp.float32, mean=0.0, std=1.0):
    return mean + std * jax.random.normal(key, shape, convert_dtype(dtype))


@register_op("randint")
def randint(key, low, high, shape, dtype=jnp.int32):
    return jax.random.randint(key, shape, low, high, convert_dtype(dtype))


@register_op("randperm")
def randperm(key, n, dtype=jnp.int32):
    return jax.random.permutation(key, n).astype(convert_dtype(dtype))


@register_op("multinomial")
def multinomial(key, probs, num_samples, replacement=True):
    logits = jnp.log(jnp.maximum(probs, 1e-30))
    if replacement:
        return jax.random.categorical(
            key, logits, shape=probs.shape[:-1] + (num_samples,))
    # without replacement: Gumbel-top-k trick
    g = jax.random.gumbel(key, logits.shape)
    _, idx = lax.top_k(logits + g, num_samples)
    return idx


@register_op("shape")
def shape(x):
    return jnp.array(x.shape, dtype=jnp.int32)


@register_op("size")
def size(x):
    return jnp.array(x.size, dtype=jnp.int64)


# --- comparison / logical (ref: operators/controlflow/compare_op.cc, logical_op.cc)
@register_op("equal")
def equal(x, y):
    return jnp.equal(x, y)


@register_op("not_equal")
def not_equal(x, y):
    return jnp.not_equal(x, y)


@register_op("less_than")
def less_than(x, y):
    return jnp.less(x, y)


@register_op("less_equal")
def less_equal(x, y):
    return jnp.less_equal(x, y)


@register_op("greater_than")
def greater_than(x, y):
    return jnp.greater(x, y)


@register_op("greater_equal")
def greater_equal(x, y):
    return jnp.greater_equal(x, y)


@register_op("logical_and")
def logical_and(x, y):
    return jnp.logical_and(x, y)


@register_op("logical_or")
def logical_or(x, y):
    return jnp.logical_or(x, y)


@register_op("logical_xor")
def logical_xor(x, y):
    return jnp.logical_xor(x, y)


@register_op("logical_not")
def logical_not(x):
    return jnp.logical_not(x)


@register_op("allclose")
def allclose(x, y, rtol=1e-5, atol=1e-8):
    return jnp.allclose(x, y, rtol=rtol, atol=atol)


@register_op("pad")
def pad(x, paddings, pad_value=0.0):
    """ref: operators/pad_op.cc — paddings is [(lo, hi), ...] per dim or flat
    [lo0, hi0, lo1, hi1, ...]."""
    if paddings and not isinstance(paddings[0], (tuple, list)):
        paddings = [(paddings[2 * i], paddings[2 * i + 1]) for i in range(len(paddings) // 2)]
    return jnp.pad(x, paddings, constant_values=pad_value)


@register_op("pad2d")
def pad2d(x, paddings, mode="constant", pad_value=0.0, data_format="NCHW"):
    """ref: operators/pad2d_op.cc — pad H/W dims of NCHW/NHWC input."""
    t, b, l, r = paddings
    if data_format == "NCHW":
        pads = [(0, 0), (0, 0), (t, b), (l, r)]
    else:
        pads = [(0, 0), (t, b), (l, r), (0, 0)]
    mode_map = {"constant": "constant", "reflect": "reflect", "edge": "edge"}
    if mode == "constant":
        return jnp.pad(x, pads, constant_values=pad_value)
    return jnp.pad(x, pads, mode=mode_map[mode])


@register_op("meshgrid")
def meshgrid(*xs):
    return jnp.meshgrid(*xs, indexing="ij")


@register_op("take_along_axis")
def take_along_axis(x, idx, axis):
    return jnp.take_along_axis(x, idx, axis=axis)


@register_op("put_along_axis")
def put_along_axis(x, idx, values, axis):
    return jnp.put_along_axis(x, idx, values, axis=axis, inplace=False)


@register_op("numel")
def numel(x):
    return jnp.array(x.size, jnp.int64)


@register_op("rank")
def rank(x):
    return jnp.array(x.ndim, jnp.int32)
