"""Loss ops.

Ref: /root/reference/paddle/fluid/operators/ — cross_entropy_op.cc,
softmax_with_cross_entropy_op.cc, sigmoid_cross_entropy_with_logits_op.cc,
bce_loss / log_loss_op.cc, smooth_l1_loss_op.cc, huber_loss_op.cc,
hinge_loss_op.cc, rank_loss_op.cc, margin_rank_loss_op.cc, bpr_loss_op.cc,
kldiv_loss_op.cc, nce_op.cc, sampled_softmax (sample_logits_op.cc),
warpctc_op.cc, mse via square+mean.

All are jnp expressions; softmax_with_cross_entropy uses the numerically
stable logsumexp form (the reference fuses softmax+CE for the same reason).
"""

import jax
import jax.numpy as jnp
import optax

from paddle_tpu.core.registry import register_op


def _squeeze_label(label):
    if label.ndim > 1 and label.shape[-1] == 1:
        return jnp.squeeze(label, -1)
    return label


@register_op("cross_entropy")
def cross_entropy(input, label, soft_label=False, ignore_index=-100):
    """ref: operators/cross_entropy_op.cc — input is *probabilities*."""
    if soft_label:
        return -jnp.sum(label * jnp.log(jnp.maximum(input, 1e-20)),
                        axis=-1, keepdims=True)
    label = _squeeze_label(label)
    picked = jnp.take_along_axis(
        input, jnp.maximum(label, 0)[..., None], axis=-1)[..., 0]
    loss = -jnp.log(jnp.maximum(picked, 1e-20))
    loss = jnp.where(label == ignore_index, 0.0, loss)
    return loss[..., None]


@register_op("softmax_with_cross_entropy")
def softmax_with_cross_entropy(logits, label, soft_label=False,
                               ignore_index=-100, axis=-1,
                               return_softmax=False):
    """ref: operators/softmax_with_cross_entropy_op.cc — fused stable form."""
    logz = jax.scipy.special.logsumexp(logits, axis=axis, keepdims=True)
    if soft_label:
        loss = -jnp.sum(label * (logits - logz), axis=axis, keepdims=True)
    else:
        # gather logits at the label BEFORE forming log-probs:
        # -log_prob[y] == logz - logits[y]. Gathering from the (logits -
        # logz) fusion would make XLA materialize the full [..., V] tensor
        # just to read one element per row — at LM-head vocab sizes that is
        # an extra GB-scale HBM pass.
        lbl = _squeeze_label(label)
        picked = jnp.take_along_axis(
            logits, jnp.maximum(lbl, 0)[..., None], axis=axis)
        loss = jnp.where(lbl == ignore_index, 0.0,
                         (logz - picked)[..., 0])[..., None]
    if return_softmax:
        return loss, jnp.exp(logits - logz)
    return loss


@register_op("sigmoid_cross_entropy_with_logits")
def sigmoid_cross_entropy_with_logits(x, label, ignore_index=-100,
                                      normalize=False):
    """ref: operators/sigmoid_cross_entropy_with_logits_op.cc"""
    loss = jnp.maximum(x, 0) - x * label + jnp.log1p(jnp.exp(-jnp.abs(x)))
    valid = (label != ignore_index)
    loss = jnp.where(valid, loss, 0.0)
    if normalize:
        loss = loss / jnp.maximum(jnp.sum(valid.astype(loss.dtype)), 1.0)
    return loss


@register_op("bce_loss")
def bce_loss(input, label):
    return -(label * jnp.log(jnp.maximum(input, 1e-12))
             + (1 - label) * jnp.log(jnp.maximum(1 - input, 1e-12)))


@register_op("log_loss")
def log_loss(input, label, epsilon=1e-4):
    """ref: operators/log_loss_op.cc"""
    return -(label * jnp.log(input + epsilon)
             + (1 - label) * jnp.log(1 - input + epsilon))


@register_op("mse_loss")
def mse_loss(input, label):
    return jnp.square(input - label)


@register_op("square_error_cost")
def square_error_cost(input, label):
    """ref: layers/nn.py square_error_cost"""
    return jnp.square(input - label)


@register_op("l1_loss")
def l1_loss(input, label):
    return jnp.abs(input - label)


@register_op("smooth_l1_loss")
def smooth_l1_loss(x, y, sigma=1.0):
    """ref: operators/smooth_l1_loss_op.cc — per-sample sum over features."""
    sigma2 = sigma * sigma
    diff = x - y
    absd = jnp.abs(diff)
    loss = jnp.where(absd < 1.0 / sigma2,
                     0.5 * sigma2 * jnp.square(diff),
                     absd - 0.5 / sigma2)
    return jnp.sum(loss, axis=tuple(range(1, x.ndim)), keepdims=True) \
        if x.ndim > 1 else loss


@register_op("huber_loss")
def huber_loss(input, label, delta=1.0):
    """ref: operators/huber_loss_op.cc"""
    d = jnp.abs(label - input)
    return jnp.where(d <= delta, 0.5 * jnp.square(d),
                     delta * (d - 0.5 * delta))


@register_op("hinge_loss")
def hinge_loss(logits, label):
    """ref: operators/hinge_loss_op.cc — label in {0,1}."""
    y = 2.0 * label - 1.0
    return jnp.maximum(0.0, 1.0 - y * logits)


@register_op("rank_loss")
def rank_loss(label, left, right):
    """ref: operators/rank_loss_op.cc"""
    d = left - right
    return jnp.maximum(d, 0.0) - d * label + jnp.log1p(jnp.exp(-jnp.abs(d)))


@register_op("margin_rank_loss")
def margin_rank_loss(label, left, right, margin=0.1):
    """ref: operators/margin_rank_loss_op.cc"""
    return jnp.maximum(0.0, -label * (left - right) + margin)


@register_op("bpr_loss")
def bpr_loss(input, label):
    """ref: operators/bpr_loss_op.cc — Bayesian personalized ranking over
    softmax inputs."""
    lbl = _squeeze_label(label)
    pos = jnp.take_along_axis(input, lbl[..., None], axis=-1)
    diff = pos - input
    n = input.shape[-1]
    loss = -jnp.sum(jnp.log(jax.nn.sigmoid(diff)), axis=-1, keepdims=True) / (n - 1)
    return loss


@register_op("kldiv_loss")
def kldiv_loss(x, target, reduction="mean"):
    """ref: operators/kldiv_loss_op.cc — x is log-probabilities."""
    loss = target * (jnp.log(jnp.maximum(target, 1e-20)) - x)
    loss = jnp.where(target > 0, loss, 0.0)
    if reduction == "mean":
        return jnp.mean(loss)
    if reduction == "sum":
        return jnp.sum(loss)
    if reduction == "batchmean":
        return jnp.sum(loss) / x.shape[0]
    return loss


@register_op("npair_loss")
def npair_loss(anchor, positive, labels, l2_reg=0.002):
    """ref: python layers npair_loss"""
    sim = anchor @ positive.T
    lbl = labels.reshape(-1)
    targets = (lbl[:, None] == lbl[None, :]).astype(sim.dtype)
    targets = targets / jnp.sum(targets, axis=1, keepdims=True)
    logz = jax.scipy.special.logsumexp(sim, axis=1, keepdims=True)
    ce = jnp.mean(-jnp.sum(targets * (sim - logz), axis=1))
    reg = l2_reg * (jnp.mean(jnp.sum(jnp.square(anchor), 1))
                    + jnp.mean(jnp.sum(jnp.square(positive), 1))) / 2
    return ce + reg


@register_op("cos_sim")
def cos_sim(x, y, epsilon=1e-12):
    """ref: operators/cos_sim_op.cc"""
    xn = jnp.sqrt(jnp.sum(jnp.square(x), -1, keepdims=True) + epsilon)
    yn = jnp.sqrt(jnp.sum(jnp.square(y), -1, keepdims=True) + epsilon)
    return jnp.sum(x * y, -1, keepdims=True) / (xn * yn)


@register_op("ctc_loss")
def ctc_loss(logits, logit_lengths, labels, label_lengths, blank=0):
    """CTC (ref: operators/warpctc_op.cc — wraps warp-ctc). TPU-native:
    optax's pure-XLA CTC. logits [B, T, C]; labels [B, L] padded with
    `blank`."""
    b, t, c = logits.shape
    logit_pad = (jnp.arange(t)[None, :] >= logit_lengths[:, None]).astype(jnp.float32)
    label_pad = (jnp.arange(labels.shape[1])[None, :]
                 >= label_lengths[:, None]).astype(jnp.float32)
    return optax.ctc_loss(logits, logit_pad, labels, label_pad,
                          blank_id=blank)


@register_op("nce_loss")
def nce_loss(key, input, label, weight, bias, num_total_classes,
             num_neg_samples=10):
    """NCE with uniform negative sampling (ref: operators/nce_op.cc).

    input [B, D]; label [B]; weight [C, D]; bias [C]."""
    b = input.shape[0]
    label = _squeeze_label(label)
    neg = jax.random.randint(key, (b, num_neg_samples), 0, num_total_classes)
    pos_w = weight[label]                      # [B, D]
    pos_logit = jnp.sum(input * pos_w, -1) + bias[label]
    neg_w = weight[neg]                        # [B, K, D]
    neg_logit = jnp.einsum("bd,bkd->bk", input, neg_w) + bias[neg]
    # NCE: log Q corrections with uniform q = 1/C
    log_q = -jnp.log(float(num_total_classes))
    pos_loss = -jax.nn.log_sigmoid(pos_logit - log_q)
    neg_loss = -jnp.sum(jax.nn.log_sigmoid(-(neg_logit - log_q)), -1)
    return (pos_loss + neg_loss)[:, None]


@register_op("sampled_softmax_with_cross_entropy")
def sampled_softmax_with_cross_entropy(key, logits_weight, logits_bias, input,
                                       label, num_samples,
                                       num_total_classes):
    """ref: operators/sample_logits_op.cc path."""
    b = input.shape[0]
    label = _squeeze_label(label)
    neg = jax.random.randint(key, (num_samples,), 0, num_total_classes)
    classes = jnp.concatenate([label, neg])          # [B+S]
    w = logits_weight[classes]                       # [B+S, D]
    logit = input @ w.T + logits_bias[classes]       # [B, B+S]
    target = jnp.arange(b)
    logz = jax.scipy.special.logsumexp(logit, -1)
    picked = jnp.take_along_axis(logit, target[:, None], 1)[:, 0]
    return (logz - picked)[:, None]


@register_op("center_loss")
def center_loss(features, label, centers, alpha=0.5):
    """ref: operators/center_loss_op.cc — returns (loss, new_centers)."""
    label = _squeeze_label(label)
    c = centers[label]
    loss = 0.5 * jnp.sum(jnp.square(features - c), axis=-1, keepdims=True)
    diff = c - features
    counts = jnp.zeros((centers.shape[0],), features.dtype).at[label].add(1.0)
    upd = jnp.zeros_like(centers).at[label].add(diff)
    new_centers = centers - alpha * upd / (counts[:, None] + 1.0)
    return loss, new_centers


@register_op("dice_loss")
def dice_loss(input, label, epsilon=1e-5):
    label = _squeeze_label(label).astype(input.dtype)
    if label.ndim < input.ndim:
        label = jax.nn.one_hot(label.astype(jnp.int32), input.shape[-1],
                               dtype=input.dtype)
    reduce_dims = tuple(range(1, input.ndim))
    inter = jnp.sum(input * label, reduce_dims)
    union = jnp.sum(input, reduce_dims) + jnp.sum(label, reduce_dims)
    return jnp.mean(1.0 - (2 * inter + epsilon) / (union + epsilon))


@register_op("hsigmoid")
def hsigmoid_loss(x, weight, label, num_classes, bias=None):
    """Hierarchical sigmoid over the default complete binary tree.

    Ref: operators/hierarchical_sigmoid_op.h + math/matrix_bit_code.h
    SimpleCode — class c encodes as v = c + num_classes; path node weights
    are rows (v >> (bit+1)) - 1 and the binary targets are v's low bits;
    loss = sum over the path of BCE-with-logits(x @ w_node + b_node, bit).

    x: [B, D]; weight: [num_classes - 1, D]; label: [B] int;
    returns per-example loss [B]. Static shapes: every path is padded to
    max_len = bitlength(2*num_classes - 1) - 1 and masked by the true code
    length (TPU-first twin of the reference's per-class path lengths).
    """
    v = label.astype(jnp.int32) + num_classes                 # [B]
    max_len = int((2 * num_classes - 1).bit_length() - 1)
    bits = jnp.arange(max_len)                                # [L]
    # length = floor(log2(v)), integer-exact (float32 log2 rounds up for
    # v = 2^k - 1 once k >= 21 — large-vocab corruption)
    lengths = jnp.sum(
        (v[:, None] >> jnp.arange(1, max_len + 2)[None, :]) > 0,
        axis=1).astype(jnp.int32)
    valid = bits[None, :] < lengths[:, None]                  # [B, L]
    idx = jnp.clip((v[:, None] >> (bits[None, :] + 1)) - 1, 0,
                   num_classes - 2)                           # [B, L]
    target = ((v[:, None] >> bits[None, :]) & 1).astype(x.dtype)
    w_rows = jnp.take(weight, idx, axis=0)                    # [B, L, D]
    pre = jnp.einsum("bd,bld->bl", x, w_rows)
    if bias is not None:
        pre = pre + jnp.take(bias, idx)
    # BCE with logits, summed over the valid path
    per_bit = jnp.maximum(pre, 0) - pre * target + jnp.log1p(
        jnp.exp(-jnp.abs(pre)))
    return jnp.sum(jnp.where(valid, per_bit, 0.0), axis=1)
