"""Vision / detection ops.

Ref: /root/reference/paddle/fluid/operators/detection/ (60 files, ~15.4k LoC):
iou_similarity_op.cc, box_coder_op.cc, prior_box_op.cc, density_prior_box_op.cc,
anchor_generator_op.cc, yolo_box_op.cc, yolov3_loss_op.cc, multiclass_nms_op.cc,
roi_align_op (operators/roi_align_op.cc), roi_pool_op.cc,
generate_proposals_op.cc, bipartite_match_op.cc, target_assign_op.cc,
box_clip_op.cc, and python/paddle/fluid/layers/detection.py wrappers.

TPU-first notes:
  * Everything is STATIC-SHAPE. Ops that in the reference emit variable-length
    LoD outputs (multiclass_nms, generate_proposals) instead return fixed-size
    tensors padded with -1 plus an explicit valid-count/mask — the XLA-friendly
    convention (same trick as TF's combined_non_max_suppression).
  * NMS is a greedy suppression scan over a precomputed IoU matrix — O(N^2)
    vectorized work on the VPU beats data-dependent loops that cannot compile.
  * roi_align requires a positive static `sampling_ratio` (the reference's
    adaptive ceil(roi_h/pooled_h) grid is data-dependent; we default -1 -> 2).
"""

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from paddle_tpu.core.enforce import enforce
from paddle_tpu.core.registry import register_op


# ------------------------------------------------------------------ IoU
@register_op("iou_similarity")
def iou_similarity(x, y, box_normalized=True):
    """Pairwise IoU between x:[N,4] and y:[M,4] -> [N,M].

    ref: detection/iou_similarity_op.{cc,h} (IOUSimilarityFunctor)."""
    offset = 0.0 if box_normalized else 1.0
    ax1, ay1, ax2, ay2 = jnp.split(x, 4, axis=-1)          # [N,1]
    bx1, by1, bx2, by2 = [v.T for v in jnp.split(y, 4, axis=-1)]  # [1,M]
    area_x = (ax2 - ax1 + offset) * (ay2 - ay1 + offset)
    area_y = (bx2 - bx1 + offset) * (by2 - by1 + offset)
    iw = jnp.clip(jnp.minimum(ax2, bx2) - jnp.maximum(ax1, bx1) + offset,
                  0.0, None)
    ih = jnp.clip(jnp.minimum(ay2, by2) - jnp.maximum(ay1, by1) + offset,
                  0.0, None)
    inter = iw * ih
    union = area_x + area_y - inter
    return jnp.where(union > 0, inter / jnp.maximum(union, 1e-10), 0.0)


@register_op("box_clip")
def box_clip(boxes, im_shape):
    """Clip [..,4] boxes to image [h, w]. ref: detection/box_clip_op.cc."""
    h, w = im_shape[0], im_shape[1]
    x1, y1, x2, y2 = jnp.split(boxes, 4, axis=-1)
    x1 = jnp.clip(x1, 0.0, w - 1.0)
    y1 = jnp.clip(y1, 0.0, h - 1.0)
    x2 = jnp.clip(x2, 0.0, w - 1.0)
    y2 = jnp.clip(y2, 0.0, h - 1.0)
    return jnp.concatenate([x1, y1, x2, y2], axis=-1)


# ------------------------------------------------------------------ box_coder
@register_op("box_coder")
def box_coder(prior_box, prior_box_var, target_box, code_type="encode_center_size",
              box_normalized=True, axis=0, bbox_clip=None):
    """Encode/decode boxes against priors. ref: detection/box_coder_op.{cc,h}.

    encode_center_size: target [N,4] x prior [M,4] -> [N,M,4]
    decode_center_size: target [N,M,4]-or-[N,4] deltas + priors -> boxes.
    prior_box_var: None | [4] | same-shape-as-prior variances."""
    norm = 0.0 if box_normalized else 1.0
    pw = prior_box[:, 2] - prior_box[:, 0] + norm
    ph = prior_box[:, 3] - prior_box[:, 1] + norm
    pcx = prior_box[:, 0] + pw * 0.5
    pcy = prior_box[:, 1] + ph * 0.5
    if prior_box_var is None:
        var = jnp.ones((prior_box.shape[0], 4), prior_box.dtype)
    else:
        var = jnp.broadcast_to(jnp.asarray(prior_box_var),
                               (prior_box.shape[0], 4))

    if code_type == "encode_center_size":
        tw = target_box[:, 2] - target_box[:, 0] + norm
        th = target_box[:, 3] - target_box[:, 1] + norm
        tcx = target_box[:, 0] + tw * 0.5
        tcy = target_box[:, 1] + th * 0.5
        # [N,1] vs [1,M] broadcast -> [N,M]
        dx = (tcx[:, None] - pcx[None, :]) / pw[None, :] / var[None, :, 0]
        dy = (tcy[:, None] - pcy[None, :]) / ph[None, :] / var[None, :, 1]
        dw = jnp.log(tw[:, None] / pw[None, :]) / var[None, :, 2]
        dh = jnp.log(th[:, None] / ph[None, :]) / var[None, :, 3]
        return jnp.stack([dx, dy, dw, dh], axis=-1)

    enforce(code_type == "decode_center_size", "unknown code_type %s" % code_type)
    t = target_box
    if t.ndim == 2:
        t = t[:, None, :] if axis == 0 else t[None, :, :]
    if axis == 0:   # priors broadcast along rows
        pcx_, pcy_, pw_, ph_ = pcx[None, :], pcy[None, :], pw[None, :], ph[None, :]
        v = var[None, :, :]
    else:           # axis == 1: priors along the first dim
        pcx_, pcy_, pw_, ph_ = pcx[:, None], pcy[:, None], pw[:, None], ph[:, None]
        v = var[:, None, :]
    cx = v[..., 0] * t[..., 0] * pw_ + pcx_
    cy = v[..., 1] * t[..., 1] * ph_ + pcy_
    dw = v[..., 2] * t[..., 2]
    dh = v[..., 3] * t[..., 3]
    if bbox_clip is not None:  # ref box_decoder_and_assign_op.h bbox_clip
        dw = jnp.minimum(dw, bbox_clip)
        dh = jnp.minimum(dh, bbox_clip)
    w = jnp.exp(dw) * pw_
    h = jnp.exp(dh) * ph_
    return jnp.stack([cx - w * 0.5, cy - h * 0.5,
                      cx + w * 0.5 - norm, cy + h * 0.5 - norm], axis=-1)


# ------------------------------------------------------------------ priors
def expand_aspect_ratios(aspect_ratios, flip):
    """SSD aspect-ratio expansion (dedup + optional 1/ar flip) — shared by
    prior_box and the MultiBoxHead prior-count computation."""
    ars = [1.0]
    for ar in aspect_ratios:
        if not any(abs(ar - e) < 1e-6 for e in ars):
            ars.append(float(ar))
            if flip:
                ars.append(1.0 / float(ar))
    return ars


@register_op("prior_box")
def prior_box(feature_shape, image_shape, min_sizes, max_sizes=None,
              aspect_ratios=(1.0,), variance=(0.1, 0.1, 0.2, 0.2),
              flip=False, clip=False, steps=(0.0, 0.0), offset=0.5,
              min_max_aspect_ratios_order=False):
    """SSD prior boxes for one feature map.

    feature_shape/image_shape: (h, w) statics. Returns (boxes [H,W,P,4],
    variances [H,W,P,4]). ref: detection/prior_box_op.{cc,h} + layers/detection.py
    prior_box()."""
    fh, fw = feature_shape
    ih, iw = image_shape
    ars = expand_aspect_ratios(aspect_ratios, flip)
    step_w = steps[1] if steps[1] > 0 else float(iw) / fw
    step_h = steps[0] if steps[0] > 0 else float(ih) / fh
    max_sizes = list(max_sizes or [])

    whs = []  # static python loop -> baked constants
    for k, ms in enumerate(min_sizes):
        base = [(float(ms), float(ms))]
        rest = [(ms * np.sqrt(ar), ms / np.sqrt(ar)) for ar in ars
                if abs(ar - 1.0) > 1e-6]
        if max_sizes:
            sq = float(np.sqrt(ms * max_sizes[k]))
            if min_max_aspect_ratios_order:
                base = base + [(sq, sq)]
                whs += base + rest
            else:
                whs += base + rest + [(sq, sq)]
        else:
            whs += base + rest
    wh = jnp.asarray(whs, jnp.float32)                      # [P,2]
    cx = (jnp.arange(fw, dtype=jnp.float32) + offset) * step_w  # [W]
    cy = (jnp.arange(fh, dtype=jnp.float32) + offset) * step_h  # [H]
    cxg, cyg = jnp.meshgrid(cx, cy)                         # [H,W]
    c = jnp.stack([cxg, cyg], -1)[:, :, None, :]            # [H,W,1,2]
    half = wh[None, None, :, :] / 2.0
    boxes = jnp.concatenate([(c - half), (c + half)], axis=-1)
    boxes = boxes / jnp.asarray([iw, ih, iw, ih], jnp.float32)
    if clip:
        boxes = jnp.clip(boxes, 0.0, 1.0)
    var = jnp.broadcast_to(jnp.asarray(variance, jnp.float32), boxes.shape)
    return boxes, var


@register_op("density_prior_box")
def density_prior_box(feature_shape, image_shape, fixed_sizes, fixed_ratios,
                      densities, variance=(0.1, 0.1, 0.2, 0.2), clip=False,
                      steps=(0.0, 0.0), offset=0.5):
    """Densified priors (ref: detection/density_prior_box_op.{cc,h}).

    Returns (boxes [H,W,P,4], variances)."""
    fh, fw = feature_shape
    ih, iw = image_shape
    step_w = steps[1] if steps[1] > 0 else float(iw) / fw
    step_h = steps[0] if steps[0] > 0 else float(ih) / fh
    step_avg = int((step_w + step_h) * 0.5)
    entries = []  # (shift_x, shift_y, w, h) per prior, relative to cell center
    for size, density in zip(fixed_sizes, densities):
        shift = int(step_avg / density)
        for ratio in fixed_ratios:
            bw = size * np.sqrt(ratio)
            bh = size / np.sqrt(ratio)
            for di in range(density):
                for dj in range(density):
                    sx = -step_avg / 2.0 + shift / 2.0 + dj * shift
                    sy = -step_avg / 2.0 + shift / 2.0 + di * shift
                    entries.append((sx, sy, bw, bh))
    ent = jnp.asarray(entries, jnp.float32)                 # [P,4]
    cx = (jnp.arange(fw, dtype=jnp.float32) + offset) * step_w
    cy = (jnp.arange(fh, dtype=jnp.float32) + offset) * step_h
    cxg, cyg = jnp.meshgrid(cx, cy)
    center = jnp.stack([cxg, cyg], -1)[:, :, None, :]       # [H,W,1,2]
    ctr = center + ent[None, None, :, :2]
    half = ent[None, None, :, 2:] / 2.0
    boxes = jnp.concatenate([ctr - half, ctr + half], axis=-1)
    boxes = boxes / jnp.asarray([iw, ih, iw, ih], jnp.float32)
    if clip:
        boxes = jnp.clip(boxes, 0.0, 1.0)
    var = jnp.broadcast_to(jnp.asarray(variance, jnp.float32), boxes.shape)
    return boxes, var


@register_op("anchor_generator")
def anchor_generator(feature_shape, anchor_sizes, aspect_ratios, stride,
                     variance=(0.1, 0.1, 0.2, 0.2), offset=0.5):
    """RPN anchors for one level -> ([H,W,A,4] anchors, variances).

    ref: detection/anchor_generator_op.{cc,h}."""
    fh, fw = feature_shape
    whs = []
    for ar in aspect_ratios:
        for s in anchor_sizes:
            area = (stride[0] * stride[1])
            area_ratios = area / ar
            base_w = np.round(np.sqrt(area_ratios))
            base_h = np.round(base_w * ar)
            scale_w = s / stride[0]
            scale_h = s / stride[1]
            whs.append((scale_w * base_w, scale_h * base_h))
    wh = jnp.asarray(whs, jnp.float32)                      # [A,2]
    # pixel-inclusive convention: centers at offset*(stride-1), half-extent
    # (w-1)/2, matching generate_proposals' +1 box widths
    cx = jnp.arange(fw, dtype=jnp.float32) * stride[0] + \
        offset * (stride[0] - 1.0)
    cy = jnp.arange(fh, dtype=jnp.float32) * stride[1] + \
        offset * (stride[1] - 1.0)
    cxg, cyg = jnp.meshgrid(cx, cy)
    c = jnp.stack([cxg, cyg], -1)[:, :, None, :]
    half = (wh[None, None, :, :] - 1.0) / 2.0
    anchors = jnp.concatenate([c - half, c + half], axis=-1)
    var = jnp.broadcast_to(jnp.asarray(variance, jnp.float32), anchors.shape)
    return anchors, var


# ------------------------------------------------------------------ NMS
def _nms_keep_mask(boxes, scores, iou_threshold, box_normalized=True):
    """Greedy NMS over score-sorted boxes -> keep mask in SORTED order plus
    the sort order. Vectorized suppression scan (TPU-friendly O(N^2))."""
    n = boxes.shape[0]
    order = jnp.argsort(-scores)
    b = boxes[order]
    iou = iou_similarity(b, b, box_normalized=box_normalized)
    idx = jnp.arange(n)

    def body(i, keep):
        sup = (iou[i] > iou_threshold) & (idx > i) & keep[i]
        return keep & ~sup

    keep = lax.fori_loop(0, n, body, jnp.ones((n,), bool))
    return keep, order


@register_op("nms")
def nms(boxes, scores, iou_threshold=0.3, score_threshold=-jnp.inf,
        keep_top_k=-1, box_normalized=True):
    """Single-class NMS -> (indices [K], valid mask [K]) with K static.

    K = keep_top_k if >0 else N; invalid slots hold index 0 and mask False."""
    n = boxes.shape[0]
    k = n if keep_top_k is None or keep_top_k < 0 else min(keep_top_k, n)
    keep, order = _nms_keep_mask(boxes, scores, iou_threshold, box_normalized)
    keep = keep & (scores[order] > score_threshold)
    # stable select: kept entries keep their (sorted) rank, dropped go last
    rank = jnp.where(keep, jnp.arange(n), n)
    sel = jnp.argsort(rank)[:k]
    return order[sel], keep[jnp.argsort(rank)][:k]


@register_op("multiclass_nms")
def multiclass_nms(bboxes, scores, score_threshold=0.0, nms_top_k=400,
                   keep_top_k=100, nms_threshold=0.3, background_label=-1,
                   box_normalized=True, return_index=False):
    """Multi-class NMS, static-shape output.

    bboxes: [N, 4] (shared across classes) or [N, C, 4]; scores: [C, N].
    Returns out [keep_top_k, 6] rows (label, score, x1, y1, x2, y2) padded
    with -1, plus valid-count scalar. ref: detection/multiclass_nms_op.cc
    (per-class NMSFast + cross-class keep_top_k)."""
    num_classes, n = scores.shape
    if bboxes.ndim == 2:
        bboxes = jnp.broadcast_to(bboxes[:, None, :], (n, num_classes, 4))
    pre_k = min(nms_top_k, n) if nms_top_k > 0 else n

    def per_class(c_boxes, c_scores):
        # top nms_top_k by score first (ref NMSFast top_k), then greedy NMS
        top_scores, top_idx = lax.top_k(c_scores, pre_k)
        keep, order = _nms_keep_mask(c_boxes[top_idx], top_scores,
                                     nms_threshold, box_normalized)
        keep = keep & (top_scores[order] > score_threshold)
        return top_idx[order], keep, top_scores[order]

    cls_idx, cls_keep, cls_scores = jax.vmap(per_class, in_axes=(1, 0))(
        bboxes, scores)                                     # [C,pre_k]
    labels = jnp.broadcast_to(jnp.arange(num_classes)[:, None],
                              (num_classes, pre_k))
    if background_label >= 0:
        cls_keep = cls_keep & (labels != background_label)
    flat_scores = jnp.where(cls_keep, cls_scores, -jnp.inf).reshape(-1)
    flat_labels = labels.reshape(-1)
    flat_idx = cls_idx.reshape(-1)
    k = min(keep_top_k if keep_top_k > 0 else flat_scores.shape[0],
            flat_scores.shape[0])
    top_scores, top = lax.top_k(flat_scores, k)
    valid = top_scores > -jnp.inf
    sel_label = flat_labels[top]
    sel_idx = flat_idx[top]
    sel_box = bboxes[sel_idx, sel_label]
    out = jnp.concatenate([sel_label[:, None].astype(bboxes.dtype),
                           top_scores[:, None], sel_box], axis=-1)
    out = jnp.where(valid[:, None], out, -1.0)
    if return_index:
        return out, jnp.where(valid, sel_idx, -1).astype(jnp.int32), \
            valid.sum()
    return out, valid.sum()


# ------------------------------------------------------------------ RoI ops
@register_op("roi_align")
def roi_align(x, rois, roi_batch_idx, pooled_height=1, pooled_width=1,
              spatial_scale=1.0, sampling_ratio=-1, aligned=False):
    """RoIAlign. x: [B,C,H,W]; rois: [R,4] (x1,y1,x2,y2 in image coords);
    roi_batch_idx: [R] int. -> [R,C,ph,pw].

    ref: operators/roi_align_op.{cc,cu}. Deviation: sampling_ratio<=0 (the
    reference's adaptive grid) is made static as 2 samples/bin."""
    b, c, h, w = x.shape
    ph, pw = pooled_height, pooled_width
    s = sampling_ratio if sampling_ratio > 0 else 2
    roi_offset = 0.5 if aligned else 0.0

    def one_roi(roi, bidx):
        x1, y1, x2, y2 = roi * spatial_scale - roi_offset
        roi_w = x2 - x1
        roi_h = y2 - y1
        if not aligned:
            roi_w = jnp.maximum(roi_w, 1.0)
            roi_h = jnp.maximum(roi_h, 1.0)
        bin_w = roi_w / pw
        bin_h = roi_h / ph
        # sample coords: [ph, s] and [pw, s]
        sy = y1 + (jnp.arange(ph, dtype=x.dtype)[:, None] * bin_h
                   + (jnp.arange(s, dtype=x.dtype)[None, :] + 0.5) * bin_h / s)
        sx = x1 + (jnp.arange(pw, dtype=x.dtype)[:, None] * bin_w
                   + (jnp.arange(s, dtype=x.dtype)[None, :] + 0.5) * bin_w / s)
        yy = sy.reshape(-1)                                 # [ph*s]
        xx = sx.reshape(-1)                                 # [pw*s]
        img = x[bidx]                                       # [C,H,W]
        vals = _bilinear_sample(img, yy, xx)                # [C, ph*s, pw*s]
        vals = vals.reshape(c, ph, s, pw, s)
        return vals.mean(axis=(2, 4))

    return jax.vmap(one_roi)(rois, roi_batch_idx)


def _bilinear_sample(img, ys, xs):
    """img: [C,H,W]; ys: [Ny], xs: [Nx] -> [C,Ny,Nx] bilinear, zero outside."""
    c, h, w = img.shape
    y_ok = (ys >= -1.0) & (ys <= h)
    x_ok = (xs >= -1.0) & (xs <= w)
    y = jnp.clip(ys, 0.0, h - 1.0)
    x = jnp.clip(xs, 0.0, w - 1.0)
    y0 = jnp.floor(y).astype(jnp.int32)
    x0 = jnp.floor(x).astype(jnp.int32)
    y1 = jnp.minimum(y0 + 1, h - 1)
    x1 = jnp.minimum(x0 + 1, w - 1)
    ly = (y - y0)[None, :, None]
    lx = (x - x0)[None, None, :]
    v00 = img[:, y0][:, :, x0]
    v01 = img[:, y0][:, :, x1]
    v10 = img[:, y1][:, :, x0]
    v11 = img[:, y1][:, :, x1]
    out = (v00 * (1 - ly) * (1 - lx) + v01 * (1 - ly) * lx
           + v10 * ly * (1 - lx) + v11 * ly * lx)
    return out * (y_ok[None, :, None] & x_ok[None, None, :])


@register_op("roi_pool")
def roi_pool(x, rois, roi_batch_idx, pooled_height=1, pooled_width=1,
             spatial_scale=1.0):
    """RoI max-pool (quantized bins, ref: operators/roi_pool_op.{cc,cu}).

    -> [R,C,ph,pw]."""
    b, c, h, w = x.shape
    ph, pw = pooled_height, pooled_width

    def one_roi(roi, bidx):
        x1 = jnp.round(roi[0] * spatial_scale)
        y1 = jnp.round(roi[1] * spatial_scale)
        x2 = jnp.round(roi[2] * spatial_scale)
        y2 = jnp.round(roi[3] * spatial_scale)
        roi_h = jnp.maximum(y2 - y1 + 1, 1.0)
        roi_w = jnp.maximum(x2 - x1 + 1, 1.0)
        bin_h = roi_h / ph
        bin_w = roi_w / pw
        img = x[bidx]                                       # [C,H,W]
        yy = jnp.arange(h, dtype=x.dtype)
        xx = jnp.arange(w, dtype=x.dtype)
        # bin membership masks, [ph,H] and [pw,W]
        pi = jnp.arange(ph, dtype=x.dtype)[:, None]
        pj = jnp.arange(pw, dtype=x.dtype)[:, None]
        ys_lo = jnp.clip(jnp.floor(pi * bin_h + y1), 0, h)
        ys_hi = jnp.clip(jnp.ceil((pi + 1) * bin_h + y1), 0, h)
        xs_lo = jnp.clip(jnp.floor(pj * bin_w + x1), 0, w)
        xs_hi = jnp.clip(jnp.ceil((pj + 1) * bin_w + x1), 0, w)
        my = (yy[None, :] >= ys_lo) & (yy[None, :] < ys_hi)  # [ph,H]
        mx = (xx[None, :] >= xs_lo) & (xx[None, :] < xs_hi)  # [pw,W]
        m = my[:, None, :, None] & mx[None, :, None, :]      # [ph,pw,H,W]
        masked = jnp.where(m[None], img[:, None, None, :, :], -jnp.inf)
        out = masked.max(axis=(-1, -2))                      # [C,ph,pw]
        return jnp.where(jnp.isfinite(out), out, 0.0)

    return jax.vmap(one_roi)(rois, roi_batch_idx)


# ------------------------------------------------------------------ YOLO
@register_op("yolo_box")
def yolo_box(x, img_size, anchors, class_num, conf_thresh=0.01,
             downsample_ratio=32, clip_bbox=True, scale_x_y=1.0):
    """Decode YOLOv3 head. x: [B, A*(5+cls), H, W]; img_size: [B,2] (h,w).
    -> (boxes [B, H*W*A, 4], scores [B, H*W*A, cls]).

    ref: detection/yolo_box_op.{cc,h}."""
    bsz, _, h, w = x.shape
    na = len(anchors) // 2
    an = jnp.asarray(anchors, x.dtype).reshape(na, 2)       # [A,2] (w,h)
    x = x.reshape(bsz, na, 5 + class_num, h, w)
    gx = jnp.arange(w, dtype=x.dtype)[None, None, None, :]
    gy = jnp.arange(h, dtype=x.dtype)[None, None, :, None]
    bias = (scale_x_y - 1.0) * 0.5
    cx = (jax.nn.sigmoid(x[:, :, 0]) * scale_x_y - bias + gx) / w
    cy = (jax.nn.sigmoid(x[:, :, 1]) * scale_x_y - bias + gy) / h
    input_h = downsample_ratio * h
    input_w = downsample_ratio * w
    bw = jnp.exp(x[:, :, 2]) * an[None, :, 0, None, None] / input_w
    bh = jnp.exp(x[:, :, 3]) * an[None, :, 1, None, None] / input_h
    conf = jax.nn.sigmoid(x[:, :, 4])
    conf = jnp.where(conf < conf_thresh, 0.0, conf)
    probs = jax.nn.sigmoid(x[:, :, 5:]) * conf[:, :, None]
    imh = img_size[:, 0].astype(x.dtype)[:, None, None, None]
    imw = img_size[:, 1].astype(x.dtype)[:, None, None, None]
    x1 = (cx - bw / 2.0) * imw
    y1 = (cy - bh / 2.0) * imh
    x2 = (cx + bw / 2.0) * imw
    y2 = (cy + bh / 2.0) * imh
    if clip_bbox:
        x1 = jnp.clip(x1, 0.0, imw - 1)
        y1 = jnp.clip(y1, 0.0, imh - 1)
        x2 = jnp.clip(x2, 0.0, imw - 1)
        y2 = jnp.clip(y2, 0.0, imh - 1)
    boxes = jnp.stack([x1, y1, x2, y2], axis=-1)            # [B,A,H,W,4]
    boxes = boxes.transpose(0, 2, 3, 1, 4).reshape(bsz, h * w * na, 4)
    scores = probs.transpose(0, 3, 4, 1, 2).reshape(bsz, h * w * na, class_num)
    zero = (conf.transpose(0, 2, 3, 1).reshape(bsz, -1) > 0)
    boxes = boxes * zero[..., None]
    return boxes, scores


@register_op("yolov3_loss")
def yolov3_loss(x, gt_box, gt_label, anchors, anchor_mask, class_num,
                ignore_thresh=0.7, downsample_ratio=32, use_label_smooth=False):
    """YOLOv3 training loss for one detection head.

    x: [B, A*(5+cls), H, W]; gt_box: [B, G, 4] (cx, cy, w, h, relative 0-1,
    zero rows = padding); gt_label: [B, G] int. -> scalar-per-image loss [B].

    ref: detection/yolov3_loss_op.{cc,h} — obj/noobj BCE with ignore mask,
    coord SSE weighted by (2 - w*h), class BCE; gt matched to the best anchor
    by wh-IoU, assigned to its grid cell."""
    bsz, _, h, w = x.shape
    namask = len(anchor_mask)
    an_all = jnp.asarray(anchors, jnp.float32).reshape(-1, 2)
    an = an_all[jnp.asarray(anchor_mask)]                   # [A,2]
    x = x.reshape(bsz, namask, 5 + class_num, h, w)
    input_w = downsample_ratio * w
    input_h = downsample_ratio * h

    px = jax.nn.sigmoid(x[:, :, 0])                         # [B,A,H,W]
    py = jax.nn.sigmoid(x[:, :, 1])
    pw_ = x[:, :, 2]
    ph_ = x[:, :, 3]
    pobj = x[:, :, 4]
    pcls = x[:, :, 5:]                                      # [B,A,cls,H,W]

    # decoded pred boxes (normalized cx cy w h) for the ignore mask
    gxx = jnp.arange(w, dtype=jnp.float32)[None, None, None, :]
    gyy = jnp.arange(h, dtype=jnp.float32)[None, None, :, None]
    pred_cx = (px + gxx) / w
    pred_cy = (py + gyy) / h
    pred_w = jnp.exp(pw_) * an[None, :, 0, None, None] / input_w
    pred_h = jnp.exp(ph_) * an[None, :, 1, None, None] / input_h

    gt_valid = (gt_box[..., 2] > 0) & (gt_box[..., 3] > 0)  # [B,G]

    def cxcywh_iou(b1, b2):
        # b1: [...,4] cx cy w h ; b2: [...,4]
        b1x1, b1x2 = b1[..., 0] - b1[..., 2] / 2, b1[..., 0] + b1[..., 2] / 2
        b1y1, b1y2 = b1[..., 1] - b1[..., 3] / 2, b1[..., 1] + b1[..., 3] / 2
        b2x1, b2x2 = b2[..., 0] - b2[..., 2] / 2, b2[..., 0] + b2[..., 2] / 2
        b2y1, b2y2 = b2[..., 1] - b2[..., 3] / 2, b2[..., 1] + b2[..., 3] / 2
        iw = jnp.clip(jnp.minimum(b1x2, b2x2) - jnp.maximum(b1x1, b2x1), 0)
        ih = jnp.clip(jnp.minimum(b1y2, b2y2) - jnp.maximum(b1y1, b2y1), 0)
        inter = iw * ih
        union = (b1[..., 2] * b1[..., 3] + b2[..., 2] * b2[..., 3] - inter)
        return inter / jnp.maximum(union, 1e-10)

    # ignore mask: pred boxes whose best-gt IoU > thresh don't get noobj loss
    pred = jnp.stack([pred_cx, pred_cy, pred_w, pred_h], -1)  # [B,A,H,W,4]
    iou_pg = cxcywh_iou(pred[:, :, :, :, None, :],
                        gt_box[:, None, None, None, :, :])   # [B,A,H,W,G]
    iou_pg = jnp.where(gt_valid[:, None, None, None, :], iou_pg, 0.0)
    ignore = iou_pg.max(-1) > ignore_thresh                  # [B,A,H,W]

    # match each gt to best anchor over ALL anchors by wh IoU at origin
    gwh = gt_box[..., 2:4]                                   # [B,G,2]
    awh_all = an_all / jnp.asarray([input_w, input_h], jnp.float32)
    inter = (jnp.minimum(gwh[:, :, None, 0], awh_all[None, None, :, 0])
             * jnp.minimum(gwh[:, :, None, 1], awh_all[None, None, :, 1]))
    union = (gwh[..., 0] * gwh[..., 1])[:, :, None] + \
        (awh_all[:, 0] * awh_all[:, 1])[None, None, :] - inter
    best_anchor = jnp.argmax(inter / jnp.maximum(union, 1e-10), -1)  # [B,G]
    mask_arr = jnp.asarray(anchor_mask)
    # local index of the matched anchor within this head (or -1)
    local = jnp.argmax(best_anchor[..., None] == mask_arr[None, None, :], -1)
    in_head = (best_anchor[..., None] == mask_arr[None, None, :]).any(-1)
    assigned = gt_valid & in_head                            # [B,G]

    gi = jnp.clip((gt_box[..., 0] * w).astype(jnp.int32), 0, w - 1)  # [B,G]
    gj = jnp.clip((gt_box[..., 1] * h).astype(jnp.int32), 0, h - 1)
    tx = gt_box[..., 0] * w - gi
    ty = gt_box[..., 1] * h - gj
    tw = jnp.log(jnp.maximum(gwh[..., 0] * input_w, 1e-9)
                 / an_all[best_anchor][..., 0])
    th = jnp.log(jnp.maximum(gwh[..., 1] * input_h, 1e-9)
                 / an_all[best_anchor][..., 1])
    box_scale = 2.0 - gwh[..., 0] * gwh[..., 1]

    def bce(logit_or_p, t, from_logit):
        if from_logit:
            return jnp.maximum(logit_or_p, 0) - logit_or_p * t + \
                jnp.log1p(jnp.exp(-jnp.abs(logit_or_p)))
        p = jnp.clip(logit_or_p, 1e-7, 1 - 1e-7)
        return -(t * jnp.log(p) + (1 - t) * jnp.log(1 - p))

    smooth_delta = 1.0 / class_num if use_label_smooth else 0.0

    def per_image(px_, py_, pw2, ph2, pobj_, pcls_, ignore_,
                  gi_, gj_, loc_, asg, tx_, ty_, tw_, th_, bs_, glab):
        obj_t = jnp.zeros((namask, h, w))
        obj_t = obj_t.at[loc_, gj_, gi_].max(asg.astype(jnp.float32))
        # coord + class losses gathered at assigned cells (per-gt)
        g = (loc_, gj_, gi_)
        lx = bce(px_[g], tx_, False)
        ly = bce(py_[g], ty_, False)
        lw = jnp.abs(pw2[g] - tw_)
        lh = jnp.abs(ph2[g] - th_)
        coord = ((lx + ly) * bs_ + (lw + lh) * bs_) * asg
        onehot = jax.nn.one_hot(glab, class_num)
        onehot = onehot * (1 - smooth_delta) + smooth_delta * 0.5
        lcls = bce(pcls_.transpose(0, 2, 3, 1)[g], onehot, True).sum(-1) * asg
        lobj = bce(pobj_, obj_t, True)
        noobj = (obj_t == 0) & ~ignore_
        obj_loss = jnp.where(obj_t > 0, lobj, 0.0).sum() + \
            jnp.where(noobj, lobj, 0.0).sum()
        return coord.sum() + lcls.sum() + obj_loss

    return jax.vmap(per_image)(px, py, pw_, ph_, pobj, pcls, ignore,
                               gi, gj, local, assigned, tx, ty, tw, th,
                               box_scale, gt_label)


# ------------------------------------------------------------------ proposals
@register_op("generate_proposals")
def generate_proposals(scores, bbox_deltas, anchors, variances, im_shape,
                       pre_nms_top_n=6000, post_nms_top_n=1000,
                       nms_thresh=0.7, min_size=0.0):
    """RPN proposal generation for ONE image, static shapes.

    scores: [A] objectness; bbox_deltas: [A,4]; anchors/variances: [A,4];
    im_shape: (h, w). -> (rois [post_nms_top_n,4], roi_scores, valid mask).

    ref: detection/generate_proposals_op.cc (ProposalForOneImage)."""
    a = scores.shape[0]
    pre_k = min(pre_nms_top_n, a)
    top_scores, top = lax.top_k(scores, pre_k)
    deltas = bbox_deltas[top]
    anc = anchors[top]
    var = variances[top]
    # decode (ref box_coder decode_center_size w/ per-anchor variance)
    aw = anc[:, 2] - anc[:, 0] + 1.0
    ah = anc[:, 3] - anc[:, 1] + 1.0
    acx = anc[:, 0] + aw * 0.5
    acy = anc[:, 1] + ah * 0.5
    cx = var[:, 0] * deltas[:, 0] * aw + acx
    cy = var[:, 1] * deltas[:, 1] * ah + acy
    w = jnp.exp(jnp.minimum(var[:, 2] * deltas[:, 2], 10.0)) * aw
    h = jnp.exp(jnp.minimum(var[:, 3] * deltas[:, 3], 10.0)) * ah
    props = jnp.stack([cx - w * 0.5, cy - h * 0.5,
                       cx + w * 0.5 - 1.0, cy + h * 0.5 - 1.0], -1)
    props = box_clip(props, im_shape)
    ww = props[:, 2] - props[:, 0] + 1.0
    hh = props[:, 3] - props[:, 1] + 1.0
    alive = (ww >= max(min_size, 1.0)) & (hh >= max(min_size, 1.0))
    sc = jnp.where(alive, top_scores, -jnp.inf)
    keep, order = _nms_keep_mask(props, sc, nms_thresh, box_normalized=False)
    keep = keep & (sc[order] > -jnp.inf)
    rank = jnp.where(keep, jnp.arange(pre_k), pre_k)
    sel = jnp.argsort(rank)[:post_nms_top_n]
    valid = keep[jnp.argsort(rank)][:post_nms_top_n]
    rois = props[order][sel] * valid[:, None]
    return rois, sc[order][sel] * valid, valid


# ------------------------------------------------------------------ matching
@register_op("bipartite_match")
def bipartite_match(dist, match_type="bipartite", overlap_threshold=0.5):
    """Greedy bipartite matching on similarity [N_gt, M_prior].

    Returns (match_indices [M] int (-1 unmatched), match_dist [M]).
    ref: detection/bipartite_match_op.cc (BipartiteMatchFunctor): repeatedly
    pick the global max, bind that row+col, until rows exhausted; then
    per_prediction mode additionally matches cols with overlap > threshold."""
    n, m = dist.shape
    steps = min(n, m)

    def body(state, _):
        d, midx, mdist = state
        flat = jnp.argmax(d)
        i, j = flat // m, flat % m
        val = d[i, j]
        ok = val > 0
        midx = jnp.where(ok, midx.at[j].set(i), midx)
        mdist = jnp.where(ok, mdist.at[j].set(val), mdist)
        d = jnp.where(ok, d.at[i, :].set(-1.0).at[:, j].set(-1.0), d)
        return (d, midx, mdist), None

    init = (dist, jnp.full((m,), -1, jnp.int32), jnp.zeros((m,), dist.dtype))
    (d, midx, mdist), _ = lax.scan(body, init, None, length=steps)

    if match_type == "per_prediction":
        best_row = jnp.argmax(dist, axis=0)
        best_val = dist.max(axis=0)
        extra = (midx < 0) & (best_val > overlap_threshold)
        midx = jnp.where(extra, best_row.astype(jnp.int32), midx)
        mdist = jnp.where(extra, best_val, mdist)
    return midx, mdist


@register_op("target_assign")
def target_assign(x, match_indices, mismatch_value=0.0):
    """Gather per-prior targets by match indices.

    x: [N_gt, K]; match_indices: [M] (-1 = unmatched) -> (out [M,K], weight
    [M,1]). ref: detection/target_assign_op.{cc,h}."""
    matched = match_indices >= 0
    safe = jnp.clip(match_indices, 0, x.shape[0] - 1)
    out = jnp.where(matched[:, None], x[safe],
                    jnp.asarray(mismatch_value, x.dtype))
    return out, matched.astype(x.dtype)[:, None]


@register_op("mine_hard_examples")
def mine_hard_examples(neg_loss, match_indices, neg_pos_ratio=3.0,
                       min_neg=1):
    """Hard-negative mining mask (max_negative mode).

    neg_loss: [M] per-prior classification loss; match_indices: [M].
    -> bool mask of selected negatives. ref: detection/mine_hard_examples_op.cc."""
    pos = match_indices >= 0
    num_pos = pos.sum()
    num_neg = jnp.maximum((num_pos * neg_pos_ratio).astype(jnp.int32), min_neg)
    masked = jnp.where(pos, -jnp.inf, neg_loss)
    order = jnp.argsort(-masked)
    rank = jnp.argsort(order)
    return (~pos) & (rank < num_neg)


@register_op("ssd_loss")
def ssd_loss(location, confidence, gt_box, gt_label, prior_box_, prior_var=None,
             overlap_threshold=0.5, neg_pos_ratio=3.0, background_label=0,
             loc_loss_weight=1.0, conf_loss_weight=1.0):
    """SSD multibox loss for ONE image (vmap over batch for [B,...]).

    location: [M,4] predicted offsets; confidence: [M,C] logits;
    gt_box: [G,4] normalized x1y1x2y2 (zero rows pad); gt_label: [G] int.
    ref: layers/detection.py ssd_loss() pipeline (iou -> bipartite_match ->
    target_assign -> conf loss + hard mining -> smooth-l1 loc loss)."""
    from paddle_tpu.ops.loss import smooth_l1_loss, softmax_with_cross_entropy
    valid_gt = (gt_box[:, 2] > gt_box[:, 0]) & (gt_box[:, 3] > gt_box[:, 1])
    sim = iou_similarity(gt_box, prior_box_)                 # [G,M]
    sim = jnp.where(valid_gt[:, None], sim, -1.0)
    midx, mdist = bipartite_match(sim, "per_prediction", overlap_threshold)
    # encode gt against priors -> per-prior loc target. Zero-padded gt rows
    # would hit log(0) = -inf inside the encoder and poison gradients
    # through the matched-mask where (inf * 0 = NaN in backward), so swap
    # them for a unit box first — they are never matched anyway.
    safe_gt = jnp.where(valid_gt[:, None], gt_box,
                        jnp.asarray([0.0, 0.0, 1.0, 1.0], gt_box.dtype))
    enc = box_coder(prior_box_, prior_var, safe_gt,
                    code_type="encode_center_size")          # [G,M,4]
    g = jnp.clip(midx, 0, gt_box.shape[0] - 1)
    loc_t = enc[g, jnp.arange(prior_box_.shape[0])]          # [M,4]
    matched = midx >= 0
    # conf target: matched -> gt label, else background
    conf_t = jnp.where(matched, gt_label[g], background_label)
    conf_l = softmax_with_cross_entropy(confidence, conf_t[:, None],
                                        soft_label=False)[:, 0]
    neg_sel = mine_hard_examples(conf_l, midx, neg_pos_ratio)
    conf_loss = jnp.where(matched | neg_sel, conf_l, 0.0).sum()
    loc_l = smooth_l1_loss(location, loc_t)[:, 0]
    loc_loss = jnp.where(matched, loc_l, 0.0).sum()
    n = jnp.maximum(matched.sum(), 1).astype(location.dtype)
    return (conf_loss_weight * conf_loss + loc_loss_weight * loc_loss) / n


@register_op("distribute_fpn_proposals")
def distribute_fpn_proposals(rois, min_level=2, max_level=5, refer_level=4,
                             refer_scale=224):
    """Assign each RoI to an FPN level: level = floor(refer + log2(sqrt(area)
    / refer_scale)). Returns (level [R] int, one-hot mask [R, L]).
    ref: detection/distribute_fpn_proposals_op.cc — static-shape variant
    (masks instead of variable-size per-level lists)."""
    w = rois[:, 2] - rois[:, 0]
    h = rois[:, 3] - rois[:, 1]
    scale = jnp.sqrt(jnp.maximum(w * h, 1e-12))
    lvl = jnp.floor(jnp.log2(scale / refer_scale + 1e-9)) + refer_level
    lvl = jnp.clip(lvl, min_level, max_level).astype(jnp.int32)
    mask = jax.nn.one_hot(lvl - min_level, max_level - min_level + 1)
    return lvl, mask


@register_op("box_decoder_and_assign")
def box_decoder_and_assign(prior_box, prior_box_var, target_box, box_score,
                           bbox_clip=4.135):
    """ref: detection/box_decoder_and_assign_op.h — per-class box decode
    (Cascade R-CNN style) then assign each ROI the decoded box of its
    best non-background class (falling back to the prior when background
    wins).

    prior_box [R, 4]; prior_box_var [4]; target_box [R, C*4] per-class
    deltas; box_score [R, C] (class 0 = background).
    Returns (decode_box [R, C*4], assign_box [R, 4]).
    """
    R = prior_box.shape[0]
    C = box_score.shape[1]
    decode = box_coder(prior_box, prior_box_var,
                       target_box.reshape(R, C, 4),
                       code_type="decode_center_size", box_normalized=False,
                       axis=1, bbox_clip=bbox_clip)                # [R,C,4]
    # best NON-background class (j > 0); background keeps the prior
    fg_scores = box_score[:, 1:]
    has_fg = C > 1
    if has_fg:
        best = jnp.argmax(fg_scores, axis=1) + 1                   # [R]
        assigned = jnp.take_along_axis(
            decode, best[:, None, None].repeat(4, axis=2), axis=1)[:, 0]
        # reference assigns the prior only when no class j>0 exists;
        # with C>1 argmax always yields some j>0 (max_score > -1)
        assign = assigned
    else:
        assign = prior_box
    return decode.reshape(R, C * 4), assign


def _match_anchors(anchors, gt_boxes, gt_valid, pos_threshold,
                   neg_threshold):
    """Shared anchor->gt matching core (threshold + epsilon-tie best-anchor
    rule, ref ScoreAssign rpn_target_assign_op.cc:188): returns
    (pos, neg, argmax_gt, max_iou)."""
    iou = iou_similarity(anchors, gt_boxes, box_normalized=False)
    iou = jnp.where(gt_valid[None, :], iou, -1.0)
    max_iou = jnp.max(iou, axis=1)
    argmax_gt = jnp.argmax(iou, axis=1)
    pos = max_iou >= pos_threshold
    gt_max = jnp.max(iou, axis=0)
    tie = (iou >= gt_max[None, :] - 1e-5) & gt_valid[None, :] & \
        (gt_max[None, :] > -1.0)
    pos = pos | jnp.any(tie, axis=1)
    neg = (max_iou < neg_threshold) & ~pos
    return pos, neg, argmax_gt, max_iou


@register_op("rpn_target_assign")
def rpn_target_assign(key, anchors, gt_boxes, gt_valid=None,
                      rpn_batch_size_per_im=256, rpn_fg_fraction=0.5,
                      rpn_positive_overlap=0.7, rpn_negative_overlap=0.3):
    """Anchor target assignment for RPN training (ref:
    detection/rpn_target_assign_op.cc).

    Rules (single image):
      * positive: IoU(anchor, some GT) >= rpn_positive_overlap, OR the
        anchor is the best-overlap anchor of a GT;
      * negative: max IoU < rpn_negative_overlap and not positive;
      * subsample randomly to rpn_batch_size_per_im with at most
        fg_fraction positives; the rest ignored.

    TPU-first static redesign: instead of gathered index lists (dynamic
    sizes), returns per-anchor dense outputs:
      labels [A] int32: 1 fg, 0 bg, -1 ignore (after subsampling)
      bbox_targets [A, 4]: encode_center_size deltas to the matched GT
        (zeros for non-positive anchors)
    anchors [A, 4]; gt_boxes [G, 4] (zero-padded rows allowed with
    gt_valid [G] mask); key: PRNG key for the random subsample.
    """
    A = anchors.shape[0]
    G = gt_boxes.shape[0]
    if gt_valid is None:
        gt_valid = jnp.ones((G,), bool)
    pos, neg, argmax_gt, _ = _match_anchors(
        anchors, gt_boxes, gt_valid, rpn_positive_overlap,
        rpn_negative_overlap)

    # random subsample via per-anchor random ranks (the static twin of the
    # reference's ReservoirSampling)
    r1, r2 = jax.random.split(key)
    fg_cap = int(rpn_batch_size_per_im * rpn_fg_fraction)
    pos_rand = jnp.where(pos, jax.random.uniform(r1, (A,)), 2.0)
    pos_rank = jnp.argsort(jnp.argsort(pos_rand))
    pos_sel = pos & (pos_rank < fg_cap)
    n_pos = jnp.sum(pos_sel)
    neg_cap = rpn_batch_size_per_im - n_pos
    neg_rand = jnp.where(neg, jax.random.uniform(r2, (A,)), 2.0)
    neg_rank = jnp.argsort(jnp.argsort(neg_rand))
    neg_sel = neg & (neg_rank < neg_cap)

    labels = jnp.where(pos_sel, 1, jnp.where(neg_sel, 0, -1)).astype(
        jnp.int32)
    matched = jnp.take(gt_boxes, argmax_gt, axis=0)                 # [A,4]
    deltas = _encode_center_size(anchors, matched)
    bbox_targets = jnp.where(pos_sel[:, None], deltas, 0.0)
    return labels, bbox_targets


def _encode_center_size(anchors, gts, eps=1e-8):
    """encode_center_size deltas (box_coder_op.h convention, +1 sizes)."""
    aw = anchors[:, 2] - anchors[:, 0] + 1
    ah = anchors[:, 3] - anchors[:, 1] + 1
    acx = anchors[:, 0] + aw / 2
    acy = anchors[:, 1] + ah / 2
    gw = gts[:, 2] - gts[:, 0] + 1
    gh = gts[:, 3] - gts[:, 1] + 1
    gcx = gts[:, 0] + gw / 2
    gcy = gts[:, 1] + gh / 2
    return jnp.stack([
        (gcx - acx) / jnp.maximum(aw, eps),
        (gcy - acy) / jnp.maximum(ah, eps),
        jnp.log(jnp.maximum(gw, eps) / jnp.maximum(aw, eps)),
        jnp.log(jnp.maximum(gh, eps) / jnp.maximum(ah, eps)),
    ], axis=1)


@register_op("generate_proposal_labels")
def generate_proposal_labels(key, rois, gt_classes, gt_boxes, gt_valid=None,
                             batch_size_per_im=512, fg_fraction=0.25,
                             fg_thresh=0.5, bg_thresh_hi=0.5,
                             bg_thresh_lo=0.0, class_num=81,
                             bbox_reg_weights=(0.1, 0.1, 0.2, 0.2)):
    """RoI sampling + classification/regression targets for the RCNN head
    (ref: detection/generate_proposal_labels_op.cc SampleRoisForOneImage).

    Rules: fg if max IoU >= fg_thresh (capped at fg_fraction of the
    batch); bg if bg_thresh_lo <= max IoU < bg_thresh_hi; targets are
    encode_center_size deltas to the matched GT, laid out per-class
    (zeros elsewhere) as the head expects.

    TPU-first static redesign (dense masks, no gathered lists):
      labels [R] int32: class id for sampled fg, 0 for sampled bg,
        -1 ignored
      bbox_targets [R, class_num * 4]
      fg_mask / bg_mask [R] bool
    """
    R = rois.shape[0]
    G = gt_boxes.shape[0]
    if gt_valid is None:
        gt_valid = jnp.ones((G,), bool)
    iou = iou_similarity(rois, gt_boxes, box_normalized=False)
    # padded gt columns mask to 0.0 (not -1): a gt-free image then has
    # max_iou 0 and still yields background samples, matching the
    # reference's [bg_thresh_lo, bg_thresh_hi) rule
    iou = jnp.where(gt_valid[None, :], iou, 0.0)
    max_iou = jnp.max(iou, axis=1)
    argmax_gt = jnp.argmax(iou, axis=1)

    fg = max_iou >= fg_thresh
    bg = (max_iou < bg_thresh_hi) & (max_iou >= bg_thresh_lo) & ~fg

    r1, r2 = jax.random.split(key)
    fg_cap = int(batch_size_per_im * fg_fraction)
    fg_rand = jnp.where(fg, jax.random.uniform(r1, (R,)), 2.0)
    fg_sel = fg & (jnp.argsort(jnp.argsort(fg_rand)) < fg_cap)
    bg_cap = batch_size_per_im - jnp.sum(fg_sel)
    bg_rand = jnp.where(bg, jax.random.uniform(r2, (R,)), 2.0)
    bg_sel = bg & (jnp.argsort(jnp.argsort(bg_rand)) < bg_cap)

    cls = jnp.take(gt_classes.astype(jnp.int32), argmax_gt)
    labels = jnp.where(fg_sel, cls, jnp.where(bg_sel, 0, -1)).astype(
        jnp.int32)
    matched = jnp.take(gt_boxes, argmax_gt, axis=0)
    # ref BoxToDelta divides by bbox_reg_weights
    # (generate_proposal_labels_op.cc:314; Python default [.1,.1,.2,.2])
    deltas = _encode_center_size(rois, matched) / jnp.asarray(
        bbox_reg_weights, rois.dtype)                 # [R, 4]
    # per-class layout: write the 4 deltas into the label's slot
    tgt = jnp.zeros((R, class_num, 4), deltas.dtype)
    safe_cls = jnp.clip(cls, 0, class_num - 1)
    tgt = tgt.at[jnp.arange(R), safe_cls].set(
        jnp.where(fg_sel[:, None], deltas, 0.0))
    return labels, tgt.reshape(R, class_num * 4), fg_sel, bg_sel


@register_op("roi_perspective_transform")
def roi_perspective_transform(x, rois, roi_batch_idx, transformed_height,
                              transformed_width, spatial_scale=1.0):
    """Perspective-warp quadrilateral ROIs to a fixed grid (ref:
    detection/roi_perspective_transform_op.cc — the OCR text-region op).

    x: [N, C, H, W]; rois: [R, 8] quad corners (x0,y0,...,x3,y3 clockwise
    from top-left); roi_batch_idx: [R] image index per roi.
    Returns (out [R, C, th, tw], mask [R, 1, th, tw]) — mask 0 where the
    source coordinate falls outside the image (out is 0 there), matching
    the reference's out-of-range handling; the exact point-in-quad edge
    test is subsumed by the transform (interior grid points map inside
    the quad by construction).
    """
    th, tw = int(transformed_height), int(transformed_width)
    N, C, H, W = x.shape
    q = rois.reshape(-1, 4, 2) * spatial_scale
    rx, ry = q[..., 0], q[..., 1]                      # [R, 4]

    # --- per-roi transform matrix (get_transform_matrix, vectorized) ---
    def lengths(a, b):
        return jnp.sqrt(jnp.sum((q[:, a] - q[:, b]) ** 2, axis=-1))
    est_w = (lengths(0, 1) + lengths(2, 3)) / 2.0
    est_h = (lengths(1, 2) + lengths(3, 0)) / 2.0
    nh = jnp.asarray(float(max(2, th)), x.dtype)
    nw = jnp.clip(jnp.round(est_w * (nh - 1) / jnp.maximum(est_h, 1e-5))
                  .astype(jnp.int32) + 1, 2, tw).astype(x.dtype)
    dx1 = rx[:, 1] - rx[:, 2]
    dx2 = rx[:, 3] - rx[:, 2]
    dx3 = rx[:, 0] - rx[:, 1] + rx[:, 2] - rx[:, 3]
    dy1 = ry[:, 1] - ry[:, 2]
    dy2 = ry[:, 3] - ry[:, 2]
    dy3 = ry[:, 0] - ry[:, 1] + ry[:, 2] - ry[:, 3]
    den = dx1 * dy2 - dx2 * dy1 + 1e-5
    m6 = (dx3 * dy2 - dx2 * dy3) / den / (nw - 1)
    m7 = (dx1 * dy3 - dx3 * dy1) / den / (nh - 1)
    m3 = (ry[:, 1] - ry[:, 0] + m6 * (nw - 1) * ry[:, 1]) / (nw - 1)
    m4 = (ry[:, 3] - ry[:, 0] + m7 * (nh - 1) * ry[:, 3]) / (nh - 1)
    m5 = ry[:, 0]
    m0 = (rx[:, 1] - rx[:, 0] + m6 * (nw - 1) * rx[:, 1]) / (nw - 1)
    m1 = (rx[:, 3] - rx[:, 0] + m7 * (nh - 1) * rx[:, 3]) / (nh - 1)
    m2 = rx[:, 0]

    # --- source coords for the output grid (get_source_coords) ---
    ow = jnp.arange(tw, dtype=x.dtype)[None, None, :]   # [1, 1, tw]
    oh = jnp.arange(th, dtype=x.dtype)[None, :, None]   # [1, th, 1]
    u = m0[:, None, None] * ow + m1[:, None, None] * oh + m2[:, None, None]
    v = m3[:, None, None] * ow + m4[:, None, None] * oh + m5[:, None, None]
    w_ = m6[:, None, None] * ow + m7[:, None, None] * oh + 1.0
    in_w = u / w_                                       # [R, th, tw]
    in_h = v / w_

    # validity: inside the image AND inside the quad's mapped region —
    # columns beyond the per-roi normalized width nw extrapolate past the
    # quad (the reference's in_quad test), and w must stay positive
    col = jnp.arange(tw, dtype=x.dtype)[None, None, :]
    valid = ((in_w >= -0.5) & (in_w <= W - 0.5)
             & (in_h >= -0.5) & (in_h <= H - 0.5)
             & (col <= nw[:, None, None] - 1) & (w_ > 1e-6))

    # --- bilinear sample; border-clamp coords like the reference
    # (roi_perspective_transform_op.cc:197 clamps before interpolating,
    # so edge samples get the full border pixel, not an attenuated one) ---
    feats = jnp.take(x, roi_batch_idx.astype(jnp.int32), axis=0)  # [R,C,H,W]
    in_w_c = jnp.clip(in_w, 0.0, W - 1.0)
    in_h_c = jnp.clip(in_h, 0.0, H - 1.0)
    x0 = jnp.floor(in_w_c)
    y0 = jnp.floor(in_h_c)
    fx = in_w_c - x0
    fy = in_h_c - y0

    # gather via flat indexing (vectorized, no per-tap loops over R)
    def gather(yi, xi):
        flat = feats.reshape(-1, C, H * W)
        idx = (yi * W + xi).reshape(rois.shape[0], 1, -1)
        out = jnp.take_along_axis(flat, idx.repeat(C, 1), axis=2)
        return out.reshape(rois.shape[0], C, th, tw)

    acc = jnp.zeros((rois.shape[0], C, th, tw), x.dtype)
    for dy in (0, 1):
        for dx in (0, 1):
            yy = y0 + dy
            xx = x0 + dx
            ok = (xx >= 0) & (xx < W) & (yy >= 0) & (yy < H)
            wgt = ((fx if dx else 1 - fx) * (fy if dy else 1 - fy))
            wgt = jnp.where(ok, wgt, 0.0)
            xi = jnp.clip(xx, 0, W - 1).astype(jnp.int32)
            yi = jnp.clip(yy, 0, H - 1).astype(jnp.int32)
            acc = acc + gather(yi, xi) * wgt[:, None]
    out = jnp.where(valid[:, None], acc, 0.0)
    return out, valid[:, None].astype(x.dtype)


@register_op("multiclass_nms2")
def multiclass_nms2(bboxes, scores, score_threshold=0.0, nms_top_k=400,
                    keep_top_k=100, nms_threshold=0.3, background_label=-1,
                    box_normalized=True):
    """multiclass_nms that ALSO returns the kept boxes' input indices
    (ref: layers/detection.py multiclass_nms2 / multiclass_nms2 op —
    the index output feeds mask heads). Index layout matches the
    reference: row index into the [N] box axis, -1 for padding."""
    # the NMS pipeline already knows each kept row's source index
    # (flat_idx[top]); expose it instead of reconstructing by coordinates
    return multiclass_nms(bboxes, scores, score_threshold, nms_top_k,
                          keep_top_k, nms_threshold, background_label,
                          box_normalized, return_index=True)


@register_op("detection_output")
def detection_output(loc, scores, prior_box, prior_box_var,
                     nms_threshold=0.3, nms_top_k=400, keep_top_k=200,
                     score_threshold=0.01, background_label=0):
    """SSD post-processing (ref layers/detection.py detection_output):
    decode predicted deltas against priors, then multiclass NMS.

    loc [N, 4] deltas; scores [N, C] class probabilities;
    prior_box [N, 4]; prior_box_var [N, 4] or [4].
    Returns (out [keep_top_k, 6], count) like multiclass_nms.
    """
    # [N,1,4] deltas against per-row priors (axis=1) -> 1:1 decode
    decoded = box_coder(prior_box, prior_box_var, loc[:, None, :],
                        code_type="decode_center_size", axis=1)
    decoded = decoded.reshape(-1, 4)               # [N, 4]
    return multiclass_nms(decoded, scores.T, score_threshold, nms_top_k,
                          keep_top_k, nms_threshold, background_label)


@register_op("retinanet_target_assign")
def retinanet_target_assign(anchors, gt_boxes, gt_labels, gt_valid=None,
                            positive_overlap=0.5, negative_overlap=0.4):
    """Anchor targets for RetinaNet (ref:
    detection/retinanet_target_assign_op.cc): like rpn_target_assign but
    with NO subsampling (focal loss consumes every anchor) and per-anchor
    CLASS labels rather than binary objectness.

    Returns (cls_labels [A] int32: gt class for fg, 0 bg, -1 ignore;
    bbox_targets [A, 4]; fg_mask [A]).
    """
    G = gt_boxes.shape[0]
    if gt_valid is None:
        gt_valid = jnp.ones((G,), bool)
    pos, neg, argmax_gt, _ = _match_anchors(
        anchors, gt_boxes, gt_valid, positive_overlap, negative_overlap)
    cls = jnp.take(gt_labels.astype(jnp.int32), argmax_gt)
    labels = jnp.where(pos, cls, jnp.where(neg, 0, -1)).astype(jnp.int32)
    matched = jnp.take(gt_boxes, argmax_gt, axis=0)
    deltas = _encode_center_size(anchors, matched)
    bbox_targets = jnp.where(pos[:, None], deltas, 0.0)
    return labels, bbox_targets, pos
