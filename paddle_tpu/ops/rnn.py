"""Recurrent ops: LSTM/GRU cells and scanned multi-step RNNs.

Ref: /root/reference/paddle/fluid/operators/ — lstm_op.cc, gru_op.cc,
operators/math/lstm_compute.cc, gru_compute.cc, and the cudnn_lstm_op.cu
fused path; Python DynamicRNN (layers/control_flow.py) handled variable
length via LoD.

TPU-first: one `lax.scan` over time compiles the whole unrolled recurrence
into a single XLA While loop; gates are computed as one fused [4H] / [3H]
matmul per step (MXU-sized), and variable length is handled by a mask that
freezes the state past each row's length — replacing LoD reordering
(math/sequence2batch.cc) with static-shape compute.
"""

import jax
import jax.numpy as jnp
from jax import lax

from paddle_tpu.core.registry import register_op


@register_op("lstm_cell")
def lstm_cell(x, h, c, w_ih, w_hh, b=None, forget_bias=0.0):
    """One LSTM step. x:[B,I], h/c:[B,H], w_ih:[I,4H], w_hh:[H,4H], b:[4H].
    Gate order i,f,g,o (ref: operators/math/lstm_compute gate layout)."""
    gates = x @ w_ih + h @ w_hh
    if b is not None:
        gates = gates + b
    i, f, g, o = jnp.split(gates, 4, axis=-1)
    i = jax.nn.sigmoid(i)
    f = jax.nn.sigmoid(f + forget_bias)
    g = jnp.tanh(g)
    o = jax.nn.sigmoid(o)
    new_c = f * c + i * g
    new_h = o * jnp.tanh(new_c)
    return new_h, new_c


@register_op("gru_cell")
def gru_cell(x, h, w_ih, w_hh, b_ih=None, b_hh=None, origin_mode=False):
    """One GRU step. x:[B,I], h:[B,H], w_ih:[I,3H], w_hh:[H,3H].
    Gate order r,z,n (ref: operators/math/gru_compute.cc).

    origin_mode matches gru_unit_op.h: False (the reference default) gives
    h' = z*n + (1-z)*h; True gives h' = (1-z)*n + z*h."""
    gi = x @ w_ih
    gh = h @ w_hh
    if b_ih is not None:
        gi = gi + b_ih
    if b_hh is not None:
        gh = gh + b_hh
    i_r, i_z, i_n = jnp.split(gi, 3, axis=-1)
    h_r, h_z, h_n = jnp.split(gh, 3, axis=-1)
    r = jax.nn.sigmoid(i_r + h_r)
    z = jax.nn.sigmoid(i_z + h_z)
    n = jnp.tanh(i_n + r * h_n)
    if origin_mode:
        return (1.0 - z) * n + z * h
    return z * n + (1.0 - z) * h


def _masked_scan(cell_step, xs, init, lengths, reverse=False):
    """Scan `cell_step` over time with per-row length masking. xs: [T,B,...]."""
    t = xs.shape[0]
    steps = jnp.arange(t)
    if reverse:
        xs = jnp.flip(xs, 0)
        steps = jnp.flip(steps, 0)

    def step(carry, inp):
        x_t, t_idx = inp
        new_carry = cell_step(carry, x_t)
        if lengths is not None:
            mask = (t_idx < lengths)[:, None]
            new_carry = jax.tree_util.tree_map(
                lambda n, o: jnp.where(mask, n, o), new_carry, carry)
        out = new_carry[0] if isinstance(new_carry, tuple) else new_carry
        return new_carry, out

    final, outs = lax.scan(step, init, (xs, steps))
    if reverse:
        outs = jnp.flip(outs, 0)
    return final, outs


@register_op("lstm")
def lstm(x, h0, c0, w_ih, w_hh, b=None, lengths=None, reverse=False,
         time_major=False):
    """Multi-step LSTM (ref: operators/lstm_op.cc / cudnn_lstm_op.cu).

    x: [B,T,I] (or [T,B,I] when time_major). Returns (out [B,T,H], (h, c)).
    """
    if not time_major:
        x = jnp.swapaxes(x, 0, 1)

    def step(carry, x_t):
        h, c = carry
        return lstm_cell(x_t, h, c, w_ih, w_hh, b)

    (h, c), outs = _masked_scan(step, x, (h0, c0), lengths, reverse)
    if not time_major:
        outs = jnp.swapaxes(outs, 0, 1)
    return outs, (h, c)


@register_op("gru")
def gru(x, h0, w_ih, w_hh, b_ih=None, b_hh=None, lengths=None, reverse=False,
        time_major=False):
    """Multi-step GRU (ref: operators/gru_op.cc)."""
    if not time_major:
        x = jnp.swapaxes(x, 0, 1)

    def step(h, x_t):
        return gru_cell(x_t, h, w_ih, w_hh, b_ih, b_hh)

    h, outs = _masked_scan(step, x, h0, lengths, reverse)
    if not time_major:
        outs = jnp.swapaxes(outs, 0, 1)
    return outs, h


@register_op("bidirectional_lstm")
def bidirectional_lstm(x, h0, c0, params_fwd, params_bwd, lengths=None):
    """Concatenated fwd+bwd LSTM outputs (ref: bidirectional cudnn_lstm)."""
    out_f, (hf, cf) = lstm(x, h0, c0, *params_fwd, lengths=lengths)
    out_b, (hb, cb) = lstm(x, h0, c0, *params_bwd, lengths=lengths,
                           reverse=True)
    return jnp.concatenate([out_f, out_b], -1), ((hf, hb), (cf, cb))


@register_op("beam_search_decode")
def beam_search_decode(log_probs_fn, init_state, bos_id, eos_id, beam_size,
                       max_len, batch_size, vocab_size):
    """Static-shape beam search (ref: operators/beam_search_op.cc,
    beam_search_decode_op.cc, math/beam_search.cc).

    log_probs_fn(tokens [B*K], state) -> (log_probs [B*K, V], new_state).
    Returns (sequences [B, K, max_len], scores [B, K]).
    """
    k = beam_size
    neg_inf = -1e9

    tokens0 = jnp.full((batch_size * k,), bos_id, jnp.int32)
    # only beam 0 active at t=0 so duplicates don't fill the beam
    scores0 = jnp.tile(jnp.concatenate(
        [jnp.zeros((1,)), jnp.full((k - 1,), neg_inf)]), (batch_size,))
    seqs0 = jnp.full((batch_size, k, max_len), eos_id, jnp.int32)
    done0 = jnp.zeros((batch_size * k,), bool)

    def step(carry, t):
        tokens, scores, seqs, done, state = carry
        logp, state = log_probs_fn(tokens, state)
        tok_idx, top_scores, beam_idx = beam_search_step(
            scores.reshape(batch_size, k),
            logp.reshape(batch_size, k, vocab_size), k, eos_id=eos_id,
            done=done.reshape(batch_size, k))
        flat_parent = (jnp.arange(batch_size)[:, None] * k + beam_idx).reshape(-1)
        seqs = seqs.reshape(batch_size * k, max_len)[flat_parent]
        seqs = seqs.reshape(batch_size, k, max_len)
        seqs = seqs.at[:, :, t].set(tok_idx)
        tokens = tok_idx.reshape(-1)
        done = done[flat_parent] | (tokens == eos_id)
        state = jax.tree_util.tree_map(lambda s: s[flat_parent], state)
        return (tokens, top_scores.reshape(-1), seqs, done, state), None

    carry = (tokens0, scores0, seqs0, done0, init_state)
    (tokens, scores, seqs, done, _), _ = lax.scan(
        step, carry, jnp.arange(max_len))
    return seqs, scores.reshape(batch_size, k)


@register_op("beam_search")
def beam_search_step(pre_scores, log_probs, beam_size, eos_id=None,
                     done=None):
    """ONE beam-search selection step — the reference's `beam_search` op
    (operators/beam_search_op.cc + math/beam_search.cc), redesigned from
    its LoD formulation to static shapes; `beam_search_decode` runs this
    op inside its scan.

    pre_scores: [B, K] cumulative log-probs; log_probs: [B, K, V] raw
    next-token log-probs. With `done` [B, K], finished beams are masked
    HERE: they may only extend with `eos_id` at zero cost (so completed
    hypotheses carry at their current score — eos_id is therefore
    required alongside done, matching the reference's end_id attr).
    -> (sel_tokens [B, K] int32, sel_scores [B, K], parent_idx [B, K] int32)
    — parent_idx indexes the source beam for the backtrace.
    """
    from paddle_tpu.core.enforce import enforce
    b, k, v = log_probs.shape
    neg_inf = -1e9
    if done is not None:
        enforce(eos_id is not None,
                "beam_search: done beams need eos_id to carry their "
                "finished hypothesis (the reference's end_id)")
        keep_eos = jnp.full((v,), neg_inf).at[eos_id].set(0.0)
        log_probs = jnp.where(done[:, :, None], keep_eos[None, None],
                              log_probs)
    cand = (pre_scores[:, :, None] + log_probs).reshape(b, k * v)
    sel_scores, top_idx = lax.top_k(cand, beam_size)
    parent = (top_idx // v).astype(jnp.int32)
    tokens = (top_idx % v).astype(jnp.int32)
    return tokens, sel_scores, parent
