"""Operator library.

TPU-native counterpart of /root/reference/paddle/fluid/operators (~480 op
families). Every op is a pure function over jax arrays, registered by name in
the global op registry (core/registry.py) so captured programs remain
serializable/introspectable like the reference's OpDesc graph.

Submodules:
  math          matmul/elementwise/reductions   (ref: operators/*, math/blas.h)
  activations   ~30 activations                 (ref: operators/activation_op.cc)
  tensor_ops    shape/index/creation ops        (ref: concat/split/gather/...)
  nn            conv/pool/norm/dropout/embed    (ref: conv_op.cc, batch_norm_op.cc ...)
  loss          loss functions                  (ref: cross_entropy_op.cc ...)
  sequence      ragged sequence ops             (ref: operators/sequence_ops/)
  control_flow  while/cond/scan/switch          (ref: operators/controlflow/)
  rnn           lstm/gru cells + scans          (ref: lstm_op.cc, gru_op.cc)
  metrics_ops   accuracy/auc/precision_recall   (ref: operators/metrics/)
  attention     fused attention                 (ref: ir multihead_matmul fuse)
  detection     vision/detection ops            (ref: operators/detection/)
  pallas        hand-written TPU kernels        (ref: hand-written CUDA kernels)
"""

from paddle_tpu.ops import (
    activations,
    attention,
    control_flow,
    crf,
    detection,
    graph,
    loss,
    mask,
    math,
    metrics_ops,
    nets,
    nn,
    rnn,
    sequence,
    tail,
    tensor_ops,
    text_match,
    vision,
)
from paddle_tpu.ops.activations import *  # noqa: F401,F403
from paddle_tpu.ops.math import *  # noqa: F401,F403
from paddle_tpu.ops.tensor_ops import *  # noqa: F401,F403
from paddle_tpu.ops.nn import *  # noqa: F401,F403
from paddle_tpu.ops.loss import *  # noqa: F401,F403
from paddle_tpu.core.registry import GLOBAL_OP_REGISTRY
from paddle_tpu.ops import fused  # noqa: F401
from paddle_tpu.ops.fused import register_fused_aliases as _rfa
from paddle_tpu.ops.tail import register_reference_aliases as _rra

_rra()
_rfa()
del _rra


def list_ops():
    """All registered op names (parity audit vs reference's op surface)."""
    return GLOBAL_OP_REGISTRY.list_ops()
