"""Text-matching op family: match_matrix_tensor, var_conv_2d,
sequence_topk_avg_pooling — the reference's LoD-based deep-match stack.

Ref:
  * /root/reference/paddle/fluid/operators/match_matrix_tensor_op.cc —
    per-pair match images out[t,i,j] = x_i^T W_t y_j over LoD sequences.
  * /root/reference/paddle/fluid/operators/var_conv_2d_op.cc — conv over
    per-sample variable-size images (center-padded im2col, zero outside the
    sample's own bounds).
  * /root/reference/paddle/fluid/operators/sequence_ops/
    sequence_topk_avg_pooling_op.h — per-row top-k averages of the match
    image, channels x topks features per row.

TPU-first redesign: LoD jagged layouts become *padded dense + length masks*
(static shapes for XLA). Each op takes [B, ...max-shape] tensors plus
per-sample lengths and reproduces the reference math exactly inside each
sample's valid region; outside it outputs are zero. Everything is
vectorized over the batch (one einsum/conv per op, MXU-friendly) instead of
the reference's per-sequence GEMM loops.
"""

import jax
import jax.numpy as jnp

from paddle_tpu.core.enforce import enforce
from paddle_tpu.core.registry import register_op


def _len_mask(lengths, size):
    return jnp.arange(size)[None, :] < lengths[:, None]     # [B, size]


@register_op("match_matrix_tensor")
def match_matrix_tensor(x, y, w, x_lens, y_lens, act=None):
    """Match images between sequence pairs.

    x: [B, L, D] left sequences (padded), x_lens [B]
    y: [B, R, D] right sequences (padded), y_lens [B]
    w: [D, T, D] per-topic bilinear forms
    Returns out [B, T, L, R]: out[b,t,i,j] = x[b,i] @ w[:,t,:] @ y[b,j],
    zero outside the (x_lens[b], y_lens[b]) valid region.
    (ref match_matrix_tensor_op.cc: per-sample call_gemm over LoD.)
    """
    enforce(w.ndim == 3 and x.shape[-1] == w.shape[0]
            and y.shape[-1] == w.shape[2],
            "match_matrix_tensor: w must be [D, dim_t, D] matching x/y dims")
    out = jnp.einsum("bld,dte,bre->btlr", x, w, y)
    mask = (_len_mask(x_lens, x.shape[1])[:, None, :, None]
            & _len_mask(y_lens, y.shape[1])[:, None, None, :])
    out = jnp.where(mask, out, 0.0)
    if act is not None:
        from paddle_tpu.ops import activations
        out = jnp.where(mask, getattr(activations, act)(out), 0.0)
    return out


@register_op("var_conv_2d")
def var_conv_2d(x, row_lens, col_lens, weight, stride=1):
    """Conv over per-sample variable-size images.

    x: [B, C, H, W] padded images; (row_lens, col_lens): per-sample valid
    height/width; weight: [O, C, kh, kw]. Center padding (half-kernel), and
    the kernel window reads zeros outside the sample's own (h_b, w_b) bounds
    — matching var_conv_2d_op.cc Im2Col exactly. Output [B, O, H', W'] with
    H' = ceil(H/stride); positions beyond ceil(h_b/s) x ceil(w_b/s) are 0.
    """
    from paddle_tpu.ops.nn import _conv2d_g1
    sh = sw = stride
    if isinstance(stride, (tuple, list)):
        sh, sw = stride
    B, C, H, W = x.shape
    kh, kw = weight.shape[2], weight.shape[3]
    # zero outside each sample's bounds so windows read 0 there
    valid = (_len_mask(row_lens, H)[:, None, :, None]
             & _len_mask(col_lens, W)[:, None, None, :])
    xz = jnp.where(valid, x, 0.0)
    # center pad exactly like the reference: window [y-k//2, y-k//2+k-1]
    # (var_conv_2d_op.cc half_kernel = k/2 with C++ integer division)
    pad = ((kh // 2, kh - 1 - kh // 2), (kw // 2, kw - 1 - kw // 2))
    out = _conv2d_g1(xz, weight, (sh, sw), pad, (1, 1), "NCHW")
    oh = out.shape[2]
    ow = out.shape[3]
    out_rows = -(-jnp.maximum(row_lens, 0) // sh)   # ceil(h/s), 0 stays 0
    out_cols = -(-jnp.maximum(col_lens, 0) // sw)
    ovalid = (_len_mask(out_rows, oh)[:, None, :, None]
              & _len_mask(out_cols, ow)[:, None, None, :])
    return jnp.where(ovalid, out, 0.0)


@register_op("sequence_topk_avg_pooling")
def sequence_topk_avg_pooling(x, row_lens, col_lens, topks, channel_num=None):
    """Per-row top-k column averages of match images.

    x: [B, C, H, W]; out [B, H, C*K] where K = len(topks):
    out[b, r, c*K + k] = sum(top_{topks[k]} of x[b, c, r, :col_lens[b]])
                          / topks[k]
    — fewer than k valid columns contribute what exists, divisor stays
    topks[k] (ref sequence_topk_avg_pooling_op.h: sums pad with the last
    partial sum, then /topks[k]). Rows >= row_lens[b] are zero.
    """
    topks = tuple(int(k) for k in topks)
    enforce(list(topks) == sorted(topks), "topks must be ascending")
    B, C, H, W = x.shape
    if channel_num is not None:
        enforce(channel_num == C, "channel_num mismatch with x")
    max_k = min(topks[-1], W) if topks else 0
    col_ok = _len_mask(col_lens, W)[:, None, None, :]       # [B,1,1,W]
    neg = jnp.asarray(jnp.finfo(x.dtype).min, x.dtype)
    masked = jnp.where(col_ok, x, neg)
    vals, _ = jax.lax.top_k(masked, max(max_k, 1))          # [B,C,H,max_k]
    # zero-out positions beyond the sample's valid column count
    kvalid = (jnp.arange(max(max_k, 1))[None, None, None, :]
              < col_lens[:, None, None, None])
    vals = jnp.where(kvalid, vals, 0.0)
    csum = jnp.cumsum(vals, axis=-1)                        # [B,C,H,max_k]
    outs = []
    for k in topks:
        idx = min(k, max_k) - 1
        s = csum[..., idx] if idx >= 0 else jnp.zeros(csum.shape[:-1],
                                                      x.dtype)
        outs.append(s / k)
    out = jnp.stack(outs, axis=-1)                          # [B,C,H,K]
    out = jnp.transpose(out, (0, 2, 1, 3)).reshape(B, H, C * len(topks))
    row_ok = _len_mask(row_lens, H)[:, :, None]
    return jnp.where(row_ok, out, 0.0)
