"""Neural-network primitive ops (functional).

Ref: /root/reference/paddle/fluid/operators/ — conv_op.cc/conv_cudnn_op.cu,
pool_op.cc, batch_norm_op.cc, layer_norm_op.cc, group_norm_op.cc,
instance_norm_op.cc, dropout_op.cc, lookup_table_op.cc, interpolate_op.cc,
lrn_op.cc, pixel_shuffle_op.cc, grid_sampler_op.cc — and the Python wrappers
in python/paddle/fluid/layers/nn.py.

TPU-first notes:
  * Convs lower to XLA `conv_general_dilated` → MXU. Internally we compute in
    NCHW-or-NHWC as given; on TPU, XLA's layout assignment picks the fast
    layout, so no hand-written im2col (ref operators/math/im2col.cc) is needed.
  * Norm ops are fused elementwise chains; XLA fuses them into neighbors.
    A Pallas fused layer_norm lives in ops/pallas/ for the bandwidth-bound
    large-model case.
  * Dropout takes an explicit PRNG key (TPU counter-based RNG).
"""

import jax
import jax.numpy as jnp
from jax import lax

from paddle_tpu.core.enforce import enforce, enforce_eq
from paddle_tpu.core.registry import register_op


def _pair(v):
    return (v, v) if isinstance(v, int) else tuple(v)


# ---------------------------------------------------------------- conv / fc
@register_op("fc")
def fc(x, weight, bias=None, num_flatten_dims=1, act=None):
    """ref: layers/nn.py fc() + operators/mul_op.cc + elementwise_add.

    x: [..., in]; weight: [in, out]; flattens leading dims at
    num_flatten_dims like the reference."""
    lead_shape = x.shape[:num_flatten_dims]
    tail = 1
    for d in x.shape[num_flatten_dims:]:
        tail *= int(d)
    x2 = x.reshape((-1, tail))
    out = x2 @ weight
    if bias is not None:
        out = out + bias
    if act is not None:
        from paddle_tpu.ops import activations
        out = getattr(activations, act)(out)
    return out.reshape(lead_shape + (weight.shape[-1],))


def _conv_dn(data_format, ndim):
    if ndim == 4:
        return (data_format, "OIHW" if data_format == "NCHW" else "HWIO",
                data_format)
    return ("NCDHW", "OIDHW", "NCDHW")


def _explicit_pad(pad, x_sp, k_sp, stride, dilation):
    """Resolve 'SAME'/'VALID'/[(lo,hi),...] to explicit per-dim (lo, hi)."""
    if isinstance(pad, str):
        if pad == "VALID":
            return [(0, 0)] * len(x_sp)
        out = []
        for x, k, s, d in zip(x_sp, k_sp, stride, dilation):
            k_eff = (k - 1) * d + 1
            total = max((-(-x // s) - 1) * s + k_eff - x, 0)
            out.append((total // 2, total - total // 2))
        return out
    return list(pad)


def _conv2d_core(x, weight, stride, pad, dilation, groups, data_format):
    dn = lax.conv_dimension_numbers(x.shape, weight.shape,
                                    _conv_dn(data_format, 4))
    return lax.conv_general_dilated(
        x, weight, window_strides=stride, padding=pad,
        rhs_dilation=dilation, dimension_numbers=dn,
        feature_group_count=groups)


# TPU-first custom backward: jax's built-in conv transpose rule expresses the
# data-grad with relabeled dimension numbers (kernel viewed as 01oi). On TPU
# (v5e, measured) that form runs at ~9-26 TFLOP/s while the canonical
# forward form (kernel physically transposed to HWIO/OIHW) runs at ~40+
# TFLOP/s — the conv emitter's fast path keys on the physical kernel layout.
# So: dx = conv(dy, flip+transpose(w)) in canonical form (the kernel
# transpose is tiny), dw = jax's native rule (already fast).
from functools import partial


@partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4, 5))
def _conv2d_g1(x, weight, stride, pad, dilation, data_format):
    return _conv2d_core(x, weight, stride, pad, dilation, 1, data_format)


def _conv2d_g1_fwd(x, weight, stride, pad, dilation, data_format):
    out = _conv2d_g1(x, weight, stride, pad, dilation, data_format)
    return out, (x, weight)


def _conv2d_g1_bwd(stride, pad, dilation, data_format, res, dy):
    x, weight = res
    if data_format == "NHWC":
        x_sp = (x.shape[1], x.shape[2])
        y_sp = (dy.shape[1], dy.shape[2])
        k_sp = (weight.shape[0], weight.shape[1])
        wT = jnp.transpose(jnp.flip(weight, (0, 1)), (0, 1, 3, 2))
    else:
        x_sp = (x.shape[2], x.shape[3])
        y_sp = (dy.shape[2], dy.shape[3])
        k_sp = (weight.shape[2], weight.shape[3])
        wT = jnp.transpose(jnp.flip(weight, (2, 3)), (1, 0, 2, 3))
    dgrad_pad = []
    for i in range(2):
        k_eff = (k_sp[i] - 1) * dilation[i] + 1
        lo2 = k_eff - 1 - pad[i][0]
        hi2 = (x_sp[i] + k_eff - 1 - lo2
               - ((y_sp[i] - 1) * stride[i] + 1))
        dgrad_pad.append((lo2, hi2))
    dx = lax.conv_general_dilated(
        dy, wT, window_strides=(1, 1), padding=dgrad_pad,
        lhs_dilation=stride, rhs_dilation=dilation,
        dimension_numbers=lax.conv_dimension_numbers(
            dy.shape, wT.shape, _conv_dn(data_format, 4)))
    # weight grad via jax's native transpose rule (fast on TPU already)
    _, pullback = jax.vjp(
        lambda w_: _conv2d_core(x, w_, stride, pad, dilation, 1,
                                data_format), weight)
    dw = pullback(dy)[0]
    return dx, dw


_conv2d_g1.defvjp(_conv2d_g1_fwd, _conv2d_g1_bwd)


@register_op("conv2d")
def conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCHW"):
    """2-D convolution (ref: operators/conv_op.cc, conv_cudnn_op.cu).

    weight: [out_c, in_c/groups, kh, kw] (OIHW) for NCHW, or
    [kh, kw, in_c/groups, out_c] (HWIO) for NHWC.

    groups==1 convs route through a TPU-fast custom backward (see
    _conv2d_g1) which does NOT support forward-mode autodiff; set flag
    conv_custom_vjp=False (or PT_FLAGS_conv_custom_vjp=0) to use jax's
    native rule when you need jvp/hessians through convs."""
    from paddle_tpu.core.flags import get_flag
    stride, dilation = _pair(stride), _pair(dilation)
    if isinstance(padding, str):
        pad = padding.upper()  # 'SAME' | 'VALID'
    else:
        p = _pair(padding)
        if isinstance(p[0], (tuple, list)):  # per-side ((lo,hi),(lo,hi))
            pad = [tuple(p[0]), tuple(p[1])]
        else:
            pad = [(p[0], p[0]), (p[1], p[1])]
    if groups == 1 and get_flag("conv_custom_vjp"):
        if data_format == "NHWC":
            x_sp = (x.shape[1], x.shape[2])
            k_sp = (weight.shape[0], weight.shape[1])
        else:
            x_sp = (x.shape[2], x.shape[3])
            k_sp = (weight.shape[2], weight.shape[3])
        pad_e = tuple(_explicit_pad(pad, x_sp, k_sp, stride, dilation))
        out = _conv2d_g1(x, weight, stride, pad_e, dilation, data_format)
    else:
        out = _conv2d_core(x, weight, stride, pad, dilation, groups,
                           data_format)
    if bias is not None:
        bshape = (1, -1, 1, 1) if data_format == "NCHW" else (1, 1, 1, -1)
        out = out + bias.reshape(bshape)
    return out


@register_op("depthwise_conv2d")
def depthwise_conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1,
                     data_format="NCHW"):
    """ref: operators/conv_op.cc depthwise path + math/depthwise_conv.cu."""
    c = x.shape[1] if data_format == "NCHW" else x.shape[-1]
    return conv2d(x, weight, bias, stride, padding, dilation, groups=c,
                  data_format=data_format)


@register_op("conv3d")
def conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1):
    """ref: operators/conv_op.cc 3-D path."""
    s = (stride,) * 3 if isinstance(stride, int) else tuple(stride)
    d = (dilation,) * 3 if isinstance(dilation, int) else tuple(dilation)
    if isinstance(padding, str):
        pad = padding.upper()
    else:
        p = (padding,) * 3 if isinstance(padding, int) else tuple(padding)
        pad = [(pi, pi) for pi in p]
    dn = lax.conv_dimension_numbers(x.shape, weight.shape,
                                    ("NCDHW", "OIDHW", "NCDHW"))
    out = lax.conv_general_dilated(x, weight, s, pad, rhs_dilation=d,
                                   dimension_numbers=dn,
                                   feature_group_count=groups)
    if bias is not None:
        out = out + bias.reshape(1, -1, 1, 1, 1)
    return out


@register_op("conv2d_transpose")
def conv2d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, dilation=1, groups=1,
                     data_format="NCHW"):
    """ref: operators/conv_transpose_op.cc. weight: [in_c, out_c/groups, kh, kw]."""
    stride, dilation = _pair(stride), _pair(dilation)
    p = _pair(padding) if not isinstance(padding, str) else padding
    op = _pair(output_padding)
    # transpose conv = lhs-dilated conv with flipped kernel
    kh, kw = weight.shape[2], weight.shape[3]
    if isinstance(p, str):
        pad = p.upper()
    else:
        pad = [
            (dilation[0] * (kh - 1) - p[0], dilation[0] * (kh - 1) - p[0] + op[0]),
            (dilation[1] * (kw - 1) - p[1], dilation[1] * (kw - 1) - p[1] + op[1]),
        ]
    w = jnp.flip(weight, axis=(2, 3))
    w = jnp.swapaxes(w, 0, 1)  # [out_c/groups, in_c, kh, kw] -> OIHW w.r.t. output
    if groups > 1:
        # regroup: weight is [in_c, out_c/g, kh, kw]; build [out_c, in_c/g, ...]
        in_c = weight.shape[0]
        ocg = weight.shape[1]
        wg = weight.reshape(groups, in_c // groups, ocg, kh, kw)
        wg = jnp.flip(wg, axis=(3, 4))
        wg = jnp.swapaxes(wg, 1, 2)  # [g, ocg, icg, kh, kw]
        w = wg.reshape(groups * ocg, in_c // groups, kh, kw)
    dn = lax.conv_dimension_numbers(x.shape, w.shape, _conv_dn(data_format, 4))
    out = lax.conv_general_dilated(
        x, w, window_strides=(1, 1), padding=pad,
        lhs_dilation=stride, rhs_dilation=dilation,
        dimension_numbers=dn, feature_group_count=groups)
    if bias is not None:
        bshape = (1, -1, 1, 1) if data_format == "NCHW" else (1, 1, 1, -1)
        out = out + bias.reshape(bshape)
    return out


# ---------------------------------------------------------------- pooling
def _pool(x, pool_size, stride, padding, data_format, init, op, norm=None):
    pool_size, stride = _pair(pool_size), _pair(stride)
    if data_format == "NCHW":
        window = (1, 1) + pool_size
        strides = (1, 1) + stride
    else:
        window = (1,) + pool_size + (1,)
        strides = (1,) + stride + (1,)
    if isinstance(padding, str):
        pad = padding.upper()
    else:
        p = _pair(padding)
        if data_format == "NCHW":
            pad = [(0, 0), (0, 0), (p[0], p[0]), (p[1], p[1])]
        else:
            pad = [(0, 0), (p[0], p[0]), (p[1], p[1]), (0, 0)]
    out = lax.reduce_window(x, init, op, window, strides, pad)
    if norm is not None:
        out = norm(out, window, strides, pad, x.shape)
    return out


@register_op("pool2d")
def pool2d(x, pool_size=2, pool_type="max", stride=None, padding=0,
           global_pooling=False, exclusive=True, data_format="NCHW"):
    """ref: operators/pool_op.cc. exclusive avg excludes padding from count.

    Max pooling's backward is XLA's native SelectAndScatter. An
    argmax scatter-add alternative (flag `maxpool_custom_vjp`) was
    built in r3 and REMOVED after silicon measurement (2026-07-31):
    duplicate-index scatters serialize on TPU — 327 ms/step vs
    48 ms on the ResNet-50 bench — while the native lowering already
    runs near the HBM roofline (874 us for the stem maxpool-grad,
    ~530 GB/s). See BASELINE.md "Second silicon window"."""
    if global_pooling:
        axes = (2, 3) if data_format == "NCHW" else (1, 2)
        if pool_type == "max":
            return jnp.max(x, axis=axes, keepdims=True)
        return jnp.mean(x, axis=axes, keepdims=True)
    stride = stride if stride is not None else pool_size
    if pool_type == "max":
        return _pool(x, pool_size, stride, padding, data_format,
                     -jnp.inf if jnp.issubdtype(x.dtype, jnp.floating)
                     else jnp.iinfo(x.dtype).min, lax.max)
    # avg pool
    def _norm(out, window, strides, pad, in_shape):
        # exclusive avg divides by the unpadded window size; applies to any
        # padding mode that can introduce padding (integer pads or SAME)
        if exclusive and pad != "VALID":
            ones = jnp.ones(in_shape, x.dtype)
            counts = lax.reduce_window(ones, 0.0, lax.add, window, strides, pad)
            return out / jnp.maximum(counts, 1.0)
        k = 1
        for w in window:
            k *= w
        return out / k
    return _pool(x, pool_size, stride, padding, data_format, 0.0, lax.add,
                 _norm)


@register_op("adaptive_pool2d")
def adaptive_pool2d(x, output_size, pool_type="avg", data_format="NCHW"):
    """ref: operators/pool_op.cc adaptive path."""
    oh, ow = _pair(output_size)
    if data_format == "NCHW":
        n, c, h, w = x.shape
        enforce(h % oh == 0 and w % ow == 0,
                "adaptive_pool2d requires divisible sizes on TPU (static shapes)")
        x5 = x.reshape(n, c, oh, h // oh, ow, w // ow)
        red = (3, 5)
    else:
        n, h, w, c = x.shape
        x5 = x.reshape(n, oh, h // oh, ow, w // ow, c)
        red = (2, 4)
    if pool_type == "max":
        return jnp.max(x5, axis=red)
    return jnp.mean(x5, axis=red)


# ---------------------------------------------------------------- norms
@register_op("batch_norm")
def batch_norm(x, scale, bias, mean, variance, epsilon=1e-5, momentum=0.9,
               training=False, data_format="NCHW"):
    """ref: operators/batch_norm_op.cc.

    Returns (out, new_mean, new_variance). In eval mode new stats == inputs.
    """
    axis = 1 if data_format == "NCHW" else x.ndim - 1
    red = tuple(i for i in range(x.ndim) if i != axis)
    shape = [1] * x.ndim
    shape[axis] = x.shape[axis]
    if training:
        # One-pass statistics: E[x] and E[x^2] reduce in a single fused
        # sweep over the activations (jnp.var would be a second full HBM
        # read — BN is bandwidth-bound on TPU, so the pass count is the
        # cost). Accumulate in fp32 regardless of activation dtype.
        xf = x.astype(jnp.float32)
        m = jnp.mean(xf, axis=red)
        m2 = jnp.mean(jnp.square(xf), axis=red)
        v = jnp.maximum(m2 - jnp.square(m), 0.0)
        n = x.size // x.shape[axis]
        unbiased = v * n / max(n - 1, 1)
        one = jnp.asarray(1.0, mean.dtype)
        new_mean = momentum * mean + (one - momentum) * m.astype(mean.dtype)
        new_var = momentum * variance + (one - momentum) * unbiased.astype(
            variance.dtype)
        m, v = m.astype(x.dtype), v.astype(x.dtype)
    else:
        m, v = mean, variance
        new_mean, new_var = mean, variance
    inv = lax.rsqrt(v.astype(x.dtype) + jnp.asarray(epsilon, x.dtype))
    out = (x - m.reshape(shape)) * (inv * scale).reshape(shape) + bias.reshape(shape)
    return out, new_mean, new_var


@register_op("layer_norm")
def layer_norm(x, scale=None, bias=None, begin_norm_axis=1, epsilon=1e-5):
    """ref: operators/layer_norm_op.cc — normalize over dims
    [begin_norm_axis:]; scale/bias are flat over those dims.

    Single implementation: the fused Pallas kernel on TPU (fp32 statistics,
    stats-carrying backward), its XLA twin elsewhere."""
    from paddle_tpu.ops.pallas.layer_norm import layer_norm_fused
    return layer_norm_fused(x, scale, bias, begin_norm_axis=begin_norm_axis,
                            epsilon=epsilon)


@register_op("rms_norm")
def rms_norm(x, scale=None, epsilon=1e-6, axis=-1):
    """RMSNorm (modern LLM norm; not in reference — TPU-era addition)."""
    v = jnp.mean(jnp.square(x.astype(jnp.float32)), axis=axis, keepdims=True)
    out = x * lax.rsqrt(v + epsilon).astype(x.dtype)
    if scale is not None:
        out = out * scale
    return out


@register_op("group_norm")
def group_norm(x, scale=None, bias=None, groups=32, epsilon=1e-5,
               data_format="NCHW"):
    """ref: operators/group_norm_op.cc"""
    enforce_eq(data_format, "NCHW", "group_norm supports NCHW")
    n, c, h, w = x.shape
    xg = x.reshape(n, groups, c // groups, h, w)
    m = jnp.mean(xg, axis=(2, 3, 4), keepdims=True)
    v = jnp.var(xg, axis=(2, 3, 4), keepdims=True)
    out = ((xg - m) * lax.rsqrt(v + epsilon)).reshape(n, c, h, w)
    if scale is not None:
        out = out * scale.reshape(1, c, 1, 1)
    if bias is not None:
        out = out + bias.reshape(1, c, 1, 1)
    return out


@register_op("instance_norm")
def instance_norm(x, scale=None, bias=None, epsilon=1e-5):
    """ref: operators/instance_norm_op.cc"""
    m = jnp.mean(x, axis=tuple(range(2, x.ndim)), keepdims=True)
    v = jnp.var(x, axis=tuple(range(2, x.ndim)), keepdims=True)
    out = (x - m) * lax.rsqrt(v + epsilon)
    c = x.shape[1]
    shp = (1, c) + (1,) * (x.ndim - 2)
    if scale is not None:
        out = out * scale.reshape(shp)
    if bias is not None:
        out = out + bias.reshape(shp)
    return out


@register_op("l2_normalize")
def l2_normalize(x, axis=-1, epsilon=1e-12):
    return x * lax.rsqrt(jnp.sum(jnp.square(x), axis=axis, keepdims=True) + epsilon)


@register_op("lrn")
def lrn(x, n=5, k=1.0, alpha=1e-4, beta=0.75):
    """Local response norm over channels, NCHW (ref: operators/lrn_op.cc)."""
    sq = jnp.square(x)
    half = n // 2
    padded = jnp.pad(sq, [(0, 0), (half, half), (0, 0), (0, 0)])
    acc = jnp.zeros_like(x)
    for i in range(n):
        acc = acc + padded[:, i:i + x.shape[1]]
    return x / jnp.power(k + alpha * acc, beta)


# ---------------------------------------------------------------- dropout / embedding
@register_op("dropout")
def dropout(x, key, rate=0.5, training=True, mode="upscale_in_train"):
    """ref: operators/dropout_op.cc — two modes like the reference:
    'upscale_in_train' (inverted dropout) and 'downgrade_in_infer'."""
    if not training or rate == 0.0:
        if mode == "downgrade_in_infer" and not training:
            return x * (1.0 - rate)
        return x
    keep = 1.0 - rate
    mask = jax.random.bernoulli(key, keep, x.shape)
    if mode == "upscale_in_train":
        return jnp.where(mask, x / keep, 0.0).astype(x.dtype)
    return jnp.where(mask, x, 0.0).astype(x.dtype)


@register_op("lookup_table")
def lookup_table(ids, table, padding_idx=None):
    """Embedding lookup (ref: operators/lookup_table_op.cc). The reference's
    SelectedRows sparse-grad path is replaced by XLA gather + (in DP) sharded
    tables — see parallel/embedding.py."""
    ids = jnp.squeeze(ids, -1) if ids.ndim > 1 and ids.shape[-1] == 1 else ids
    out = jnp.take(table, ids, axis=0)
    if padding_idx is not None:
        mask = (ids != padding_idx)[..., None]
        out = out * mask.astype(out.dtype)
    return out


embedding = lookup_table


# ---------------------------------------------------------------- resize / shuffle
@register_op("interpolate")
def interpolate(x, size=None, scale_factor=None, mode="nearest",
                align_corners=False, data_format="NCHW"):
    """ref: operators/interpolate_op.cc (nearest/bilinear)."""
    if data_format == "NCHW":
        n, c, h, w = x.shape
    else:
        n, h, w, c = x.shape
    if size is None:
        sf = _pair(scale_factor)
        size = (int(h * sf[0]), int(w * sf[1]))
    oh, ow = _pair(size)
    if mode == "nearest":
        ri = (jnp.arange(oh) * (h / oh)).astype(jnp.int32)
        ci = (jnp.arange(ow) * (w / ow)).astype(jnp.int32)
        if data_format == "NCHW":
            return x[:, :, ri][:, :, :, ci]
        return x[:, ri][:, :, ci]
    # bilinear
    if align_corners and oh > 1 and ow > 1:
        ys = jnp.linspace(0.0, h - 1, oh)
        xs = jnp.linspace(0.0, w - 1, ow)
    else:
        ys = (jnp.arange(oh) + 0.5) * (h / oh) - 0.5
        xs = (jnp.arange(ow) + 0.5) * (w / ow) - 0.5
        ys = jnp.clip(ys, 0, h - 1)
        xs = jnp.clip(xs, 0, w - 1)
    y0 = jnp.clip(jnp.floor(ys).astype(jnp.int32), 0, h - 1)
    y1 = jnp.clip(y0 + 1, 0, h - 1)
    x0 = jnp.clip(jnp.floor(xs).astype(jnp.int32), 0, w - 1)
    x1 = jnp.clip(x0 + 1, 0, w - 1)
    wy = (ys - y0).astype(x.dtype)
    wx = (xs - x0).astype(x.dtype)
    if data_format != "NCHW":
        x = jnp.moveaxis(x, -1, 1)
    a = x[:, :, y0][:, :, :, x0]
    b = x[:, :, y0][:, :, :, x1]
    cc = x[:, :, y1][:, :, :, x0]
    d = x[:, :, y1][:, :, :, x1]
    wy_ = wy[None, None, :, None]
    wx_ = wx[None, None, None, :]
    out = (a * (1 - wy_) * (1 - wx_) + b * (1 - wy_) * wx_
           + cc * wy_ * (1 - wx_) + d * wy_ * wx_)
    if data_format != "NCHW":
        out = jnp.moveaxis(out, 1, -1)
    return out


@register_op("pixel_shuffle")
def pixel_shuffle(x, upscale_factor):
    """ref: operators/pixel_shuffle_op.cc"""
    n, c, h, w = x.shape
    r = upscale_factor
    x = x.reshape(n, c // (r * r), r, r, h, w)
    x = jnp.transpose(x, (0, 1, 4, 2, 5, 3))
    return x.reshape(n, c // (r * r), h * r, w * r)


@register_op("affine_channel")
def affine_channel(x, scale, bias, data_format="NCHW"):
    """ref: operators/affine_channel_op.cc"""
    shp = (1, -1, 1, 1) if data_format == "NCHW" else (1, 1, 1, -1)
    return x * scale.reshape(shp) + bias.reshape(shp)


@register_op("unfold")
def unfold(x, kernel_sizes, strides=1, paddings=0, dilations=1):
    """im2col as an op (ref: operators/unfold_op.cc / math/im2col.cc) —
    included for parity; on TPU prefer conv directly."""
    kh, kw = _pair(kernel_sizes)
    s, d, p = _pair(strides), _pair(dilations), _pair(paddings)
    n, c, h, w = x.shape
    x = jnp.pad(x, [(0, 0), (0, 0), (p[0], p[0]), (p[1], p[1])])
    oh = (h + 2 * p[0] - d[0] * (kh - 1) - 1) // s[0] + 1
    ow = (w + 2 * p[1] - d[1] * (kw - 1) - 1) // s[1] + 1
    patches = []
    for i in range(kh):
        for j in range(kw):
            patches.append(
                x[:, :, i * d[0]: i * d[0] + oh * s[0]: s[0],
                  j * d[1]: j * d[1] + ow * s[1]: s[1]])
    out = jnp.stack(patches, axis=2)  # [n, c, kh*kw, oh, ow]
    return out.reshape(n, c * kh * kw, oh * ow)


@register_op("nan_to_num")
def nan_to_num(x, nan=0.0, posinf=None, neginf=None):
    return jnp.nan_to_num(x, nan=nan, posinf=posinf, neginf=neginf)


@register_op("fsp_matrix")
def fsp_matrix(x, y):
    """Flow-of-solution-procedure matrix (distillation feature, ref:
    operators/fsp_op.h — per sample: (1/(H*W)) * X_flat @ Y_flat^T over
    channel-flattened maps). x [B, C1, H, W], y [B, C2, H, W] (same H, W)
    -> [B, C1, C2]."""
    enforce(x.shape[0] == y.shape[0] and x.shape[2:] == y.shape[2:],
            "fsp_matrix requires matching batch and spatial dims")
    hw = x.shape[2] * x.shape[3]
    return jnp.einsum("bchw,bdhw->bcd", x, y) / hw
