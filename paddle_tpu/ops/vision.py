"""Vision ops: sampling grids, deformable conv, 3-D pooling/conv, video ops.

Ref: /root/reference/paddle/fluid/operators/{affine_grid_op.cc,
grid_sampler_op.cc, deformable_conv_op.cc, space_to_depth_op.cc,
shuffle_channel_op.cc, temporal_shift_op.cc, pool_op.cc (pool3d),
conv_transpose_op.cc (conv3d_transpose), unpool_op.cc, spp_op.cc,
data_norm_op.cc, detection/polygon_box_transform_op.cc,
detection/psroi_pool_op.cc}.

TPU-first: everything is expressed as dense gathers / reduce_windows /
conv_general_dilated so XLA can tile onto the MXU; no per-pixel scalar loops.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from paddle_tpu.core.registry import register_op


@register_op("affine_grid")
def affine_grid(theta, out_shape):
    """ref: affine_grid_op.cc — theta [N,2,3] -> sampling grid [N,H,W,2]
    in [-1,1] normalized coords (align_corners=True, the 1.5.x behavior)."""
    N, C, H, W = out_shape
    xs = jnp.linspace(-1.0, 1.0, W)
    ys = jnp.linspace(-1.0, 1.0, H)
    gx, gy = jnp.meshgrid(xs, ys)                            # [H,W]
    ones = jnp.ones_like(gx)
    base = jnp.stack([gx, gy, ones], axis=-1)                # [H,W,3]
    grid = jnp.einsum("hwk,nck->nhwc", base, theta)          # [N,H,W,2]
    return grid


def _bilinear_sample(x, ix, iy):
    """Sample NCHW `x` at float pixel coords ix/iy [N,...]; zero padding."""
    N, C, H, W = x.shape
    x0 = jnp.floor(ix)
    y0 = jnp.floor(iy)
    out = 0.0
    for dx, dy in ((0, 0), (1, 0), (0, 1), (1, 1)):
        xi = x0 + dx
        yi = y0 + dy
        w = (1 - jnp.abs(ix - xi)) * (1 - jnp.abs(iy - yi))
        valid = (xi >= 0) & (xi <= W - 1) & (yi >= 0) & (yi <= H - 1)
        xc = jnp.clip(xi, 0, W - 1).astype(jnp.int32)
        yc = jnp.clip(yi, 0, H - 1).astype(jnp.int32)
        batch = jnp.arange(N).reshape((N,) + (1,) * (ix.ndim - 1))
        vals = x[batch[..., None], jnp.arange(C), yc[..., None], xc[..., None]]
        out = out + jnp.where((valid * w)[..., None] != 0,
                              vals * (w * valid)[..., None], 0.0)
    return out                                               # [N,...,C]


@register_op("grid_sampler")
def grid_sampler(x, grid):
    """ref: grid_sampler_op.cc — bilinear sample NCHW x at grid [N,H,W,2]
    ([-1,1] normalized, align_corners=True, zeros padding) -> [N,C,H,W]."""
    N, C, H, W = x.shape
    gx = (grid[..., 0] + 1.0) * 0.5 * (W - 1)                # [N,Ho,Wo]
    gy = (grid[..., 1] + 1.0) * 0.5 * (H - 1)
    out = _bilinear_sample(x, gx, gy)                        # [N,Ho,Wo,C]
    return jnp.transpose(out, (0, 3, 1, 2))


@register_op("space_to_depth")
def space_to_depth(x, blocksize):
    """ref: space_to_depth_op.cc — NCHW [N,C,H,W] -> [N,C*b*b,H/b,W/b]."""
    N, C, H, W = x.shape
    b = blocksize
    x = x.reshape(N, C, H // b, b, W // b, b)
    x = jnp.transpose(x, (0, 3, 5, 1, 2, 4))
    return x.reshape(N, C * b * b, H // b, W // b)


@register_op("shuffle_channel")
def shuffle_channel(x, group):
    """ref: shuffle_channel_op.cc — ShuffleNet channel shuffle."""
    N, C, H, W = x.shape
    x = x.reshape(N, group, C // group, H, W)
    return jnp.swapaxes(x, 1, 2).reshape(N, C, H, W)


@register_op("temporal_shift")
def temporal_shift(x, seg_num, shift_ratio=0.25):
    """ref: temporal_shift_op.cc — TSM video shift: x [N*T, C, H, W];
    first C*ratio channels shift t-1, next C*ratio shift t+1."""
    NT, C, H, W = x.shape
    N = NT // seg_num
    x = x.reshape(N, seg_num, C, H, W)
    c1 = int(C * shift_ratio)
    c2 = int(C * 2 * shift_ratio)
    pad = jnp.zeros((N, 1, C, H, W), x.dtype)
    prev = jnp.concatenate([pad, x[:, :-1]], axis=1)         # shift forward
    nxt = jnp.concatenate([x[:, 1:], pad], axis=1)           # shift backward
    out = jnp.concatenate(
        [prev[:, :, :c1], nxt[:, :, c1:c2], x[:, :, c2:]], axis=2)
    return out.reshape(NT, C, H, W)


@register_op("pool3d")
def pool3d(x, pool_size, pool_type="max", pool_stride=1, pool_padding=0,
           ceil_mode=False, exclusive=True):
    """ref: pool_op.cc pool3d — NCDHW max/avg pooling via reduce_window."""
    ks = (pool_size,) * 3 if isinstance(pool_size, int) else tuple(pool_size)
    st = (pool_stride,) * 3 if isinstance(pool_stride, int) \
        else tuple(pool_stride)
    pd = (pool_padding,) * 3 if isinstance(pool_padding, int) \
        else tuple(pool_padding)
    window = (1, 1) + ks
    strides = (1, 1) + st
    pads = ((0, 0), (0, 0)) + tuple(
        (p, p + (s - 1 if ceil_mode else 0)) for p, s in zip(pd, st))
    if pool_type == "max":
        init = -jnp.inf
        out = lax.reduce_window(x, init, lax.max, window, strides, pads)
        return out
    ones = jnp.ones_like(x)
    s = lax.reduce_window(x, 0.0, lax.add, window, strides, pads)
    if exclusive:
        cnt = lax.reduce_window(ones, 0.0, lax.add, window, strides, pads)
    else:
        cnt = float(ks[0] * ks[1] * ks[2])
    return s / cnt


@register_op("conv3d_transpose")
def conv3d_transpose(x, weight, stride=1, padding=0, dilation=1, groups=1,
                     bias=None):
    """ref: conv_transpose_op.cc — NCDHW transposed conv.
    weight: [C_in, C_out/groups, kd, kh, kw] (reference layout)."""
    st = (stride,) * 3 if isinstance(stride, int) else tuple(stride)
    pd = (padding,) * 3 if isinstance(padding, int) else tuple(padding)
    dl = (dilation,) * 3 if isinstance(dilation, int) else tuple(dilation)
    kd, kh, kw = weight.shape[2:]
    pads = tuple((dl[i] * (k - 1) - pd[i], dl[i] * (k - 1) - pd[i])
                 for i, k in enumerate((kd, kh, kw)))
    # transposed conv = lhs-dilated conv with flipped kernel
    w = jnp.flip(weight, axis=(2, 3, 4))
    w = jnp.swapaxes(w, 0, 1)                                # [C_out/g, C_in, ...]
    if groups > 1:
        cin = x.shape[1] // groups
        wg = w.reshape(w.shape[0], groups, cin, kd, kh, kw)
        wg = jnp.moveaxis(wg, 1, 0).reshape(
            groups * w.shape[0], cin, kd, kh, kw)
        w = wg
    out = lax.conv_general_dilated(
        x, w, window_strides=(1, 1, 1), padding=pads, lhs_dilation=st,
        rhs_dilation=dl, dimension_numbers=("NCDHW", "OIDHW", "NCDHW"),
        feature_group_count=groups)
    if bias is not None:
        out = out + bias.reshape(1, -1, 1, 1, 1)
    return out


def _maxpool_index_fwd_raw(x, ks, st, pd):
    N, C, H, W = x.shape
    # the index plane is ALWAYS float32 (exact integers to 2^24) — casting
    # it to a bf16/f16 operand dtype would silently corrupt argmax indices
    # past 256/2048; only the value operand's init takes x's dtype
    idx_plane = jnp.arange(H * W, dtype=jnp.float32).reshape(1, 1, H, W)
    idx_plane = jnp.broadcast_to(idx_plane, x.shape)

    def select(a, b):
        av, ai = a
        bv, bi = b
        take_b = bv > av
        return jnp.where(take_b, bv, av), jnp.where(take_b, bi, ai)

    window = (1, 1) + ks
    strides = (1, 1) + st
    pads = ((0, 0), (0, 0)) + tuple((p, p) for p in pd)
    vals, idxs = lax.reduce_window(
        (x, idx_plane),
        (jnp.asarray(-jnp.inf, x.dtype), jnp.float32(-1)),
        lambda a, b: select(a, b), window, strides, pads)
    return vals, idxs.astype(jnp.int32)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2, 3, 4, 5))
def _maxpool_index_core(x, ks, st, pd, x_shape, dtype_name):
    return _maxpool_index_fwd_raw(x, ks, st, pd)


def _maxpool_index_core_fwd(x, ks, st, pd, x_shape, dtype_name):
    vals, idxs = _maxpool_index_fwd_raw(x, ks, st, pd)
    return (vals, idxs), idxs


def _maxpool_index_core_bwd(ks, st, pd, x_shape, dtype_name, idxs, g):
    # paired-tuple reduce_window has no JAX derivative rule — the VJP IS
    # the unpool scatter (index gradients are zero), so reuse it: route
    # dvals to each window's argmax position.
    dvals = g[0].astype(dtype_name)
    H, W = x_shape[2], x_shape[3]
    return (unpool(dvals, idxs, (H, W)),)


_maxpool_index_core.defvjp(_maxpool_index_core_fwd, _maxpool_index_core_bwd)


@register_op("max_pool2d_with_index")
def max_pool2d_with_index(x, pool_size, pool_stride=1, pool_padding=0):
    """ref: pool_with_index_op.cc — returns (pooled, flat argmax index into
    each image's HxW plane), as the reference's unpool consumes.
    Differentiable in the pooled values (custom VJP scatters to the argmax
    positions; found by the registry grad sweep — the raw paired
    reduce_window has no derivative rule)."""
    ks = (pool_size,) * 2 if isinstance(pool_size, int) else tuple(pool_size)
    st = (pool_stride,) * 2 if isinstance(pool_stride, int) \
        else tuple(pool_stride)
    pd = (pool_padding,) * 2 if isinstance(pool_padding, int) \
        else tuple(pool_padding)
    return _maxpool_index_core(x, ks, st, pd, tuple(x.shape),
                               str(x.dtype))


@register_op("unpool")
def unpool(x, indices, out_hw):
    """ref: unpool_op.cc — max-unpool: scatter pooled values back to their
    argmax positions in a zeros [N,C,H,W] output."""
    N, C, Hp, Wp = x.shape
    H, W = out_hw
    flat = jnp.zeros((N, C, H * W), x.dtype)
    idx = indices.reshape(N, C, -1)
    vals = x.reshape(N, C, -1)
    flat = flat.at[jnp.arange(N)[:, None, None], jnp.arange(C)[None, :, None],
                   jnp.clip(idx, 0, H * W - 1)].add(
        jnp.where(idx >= 0, vals, 0.0))
    return flat.reshape(N, C, H, W)


@register_op("spp")
def spp(x, pyramid_height=3, pool_type="max"):
    """ref: spp_op.cc — spatial pyramid pooling: adaptive pools at bin counts
    1,2,4,...,2^(h-1), flattened and concatenated per image."""
    from paddle_tpu.ops.nn import adaptive_pool2d
    N, C = x.shape[:2]
    outs = []
    for level in range(pyramid_height):
        bins = 2 ** level
        p = adaptive_pool2d(x, (bins, bins), pool_type=pool_type)
        outs.append(p.reshape(N, -1))
    return jnp.concatenate(outs, axis=1)


@register_op("data_norm")
def data_norm(x, batch_size, batch_sum, batch_square_sum, epsilon=1e-4):
    """ref: data_norm_op.cc — normalize by accumulated batch statistics
    (CTR models): mean = sum/size, scale = rsqrt(square_sum/size)."""
    means = batch_sum / batch_size
    scales = jnp.sqrt(batch_size / jnp.maximum(batch_square_sum, epsilon))
    return (x - means) * scales, means, scales


@register_op("polygon_box_transform")
def polygon_box_transform(x):
    """ref: detection/polygon_box_transform_op.cc — EAST geometry map:
    even channels: 4*w - v, odd channels: 4*h - v."""
    N, C, H, W = x.shape
    ws = jnp.broadcast_to(jnp.arange(W, dtype=x.dtype), (H, W))
    hs = jnp.broadcast_to(jnp.arange(H, dtype=x.dtype)[:, None], (H, W))
    even = jnp.arange(C) % 2 == 0
    coord = jnp.where(even[:, None, None], 4.0 * ws, 4.0 * hs)
    return coord[None] - x


@register_op("deformable_conv")
def deformable_conv(x, offset, weight, stride=1, padding=0, dilation=1,
                    deformable_groups=1, groups=1, mask=None):
    """ref: deformable_conv_op.cc (v1) / deformable_conv_v2 with mask.

    x: [N, C, H, W]; offset: [N, 2*dg*kh*kw, Ho, Wo] (y,x interleaved per
    tap, reference layout); weight: [C_out, C_in/groups, kh, kw];
    mask (v2): [N, dg*kh*kw, Ho, Wo] modulation in [0,1].

    TPU-first: per-tap bilinear gathers (vectorized) followed by one big
    [N*Ho*Wo, C*kh*kw] @ [C*kh*kw, C_out] matmul on the MXU — the im2col
    formulation of deformable conv, not a scalar loop.
    """
    N, C, H, W = x.shape
    Cout, Cin_g, kh, kw = weight.shape
    st = (stride,) * 2 if isinstance(stride, int) else tuple(stride)
    pd = (padding,) * 2 if isinstance(padding, int) else tuple(padding)
    dl = (dilation,) * 2 if isinstance(dilation, int) else tuple(dilation)
    Ho = (H + 2 * pd[0] - dl[0] * (kh - 1) - 1) // st[0] + 1
    Wo = (W + 2 * pd[1] - dl[1] * (kw - 1) - 1) // st[1] + 1
    dg = deformable_groups

    base_y = (jnp.arange(Ho) * st[0] - pd[0])[:, None]        # [Ho,1]
    base_x = (jnp.arange(Wo) * st[1] - pd[1])[None, :]        # [1,Wo]
    off = offset.reshape(N, dg, kh * kw, 2, Ho, Wo)
    cols = []
    cg = C // dg
    for t in range(kh * kw):
        ky, kx = divmod(t, kw)
        oy = off[:, :, t, 0]                                  # [N,dg,Ho,Wo]
        ox = off[:, :, t, 1]
        iy = base_y[None, None] + ky * dl[0] + oy
        ix = base_x[None, None] + kx * dl[1] + ox
        tap = []
        for g in range(dg):
            xs = x[:, g * cg:(g + 1) * cg]                    # [N,cg,H,W]
            s = _bilinear_sample(xs, ix[:, g], iy[:, g])      # [N,Ho,Wo,cg]
            if mask is not None:
                s = s * mask[:, g * kh * kw + t][..., None]
            tap.append(s)
        cols.append(jnp.concatenate(tap, axis=-1))            # [N,Ho,Wo,C]
    col = jnp.stack(cols, axis=3)                             # [N,Ho,Wo,K,C]
    col = col.reshape(N, Ho, Wo, kh * kw * C)
    wmat = jnp.transpose(weight, (2, 3, 1, 0))                # [kh,kw,Cin_g,Cout]
    if groups == 1:
        wmat = wmat.reshape(kh * kw * C, Cout)
        out = col @ wmat                                      # [N,Ho,Wo,Cout]
    else:
        cing = C // groups
        coutg = Cout // groups
        colg = col.reshape(N, Ho, Wo, kh * kw, groups, cing)
        wg = weight.reshape(groups, coutg, cing, kh, kw)
        # grouped path: per-group matmul (static small loop)
        outs = []
        for g in range(groups):
            cslice = colg[..., g, :].reshape(N, Ho, Wo, kh * kw * cing)
            wslice = jnp.transpose(wg[g], (2, 3, 1, 0)).reshape(
                kh * kw * cing, coutg)
            outs.append(cslice @ wslice)
        out = jnp.concatenate(outs, axis=-1)
    return jnp.transpose(out, (0, 3, 1, 2))                   # [N,Cout,Ho,Wo]


@register_op("psroi_pool")
def psroi_pool(x, rois, roi_batch_ids, output_channels, pooled_height,
               pooled_width, spatial_scale=1.0):
    """ref: detection/psroi_pool_op.cc — position-sensitive ROI average
    pooling (R-FCN): x [N, out_c*ph*pw, H, W], rois [R,4] (x1,y1,x2,y2 in
    image coords) -> [R, out_c, ph, pw]."""
    N, C, H, W = x.shape
    ph, pw = pooled_height, pooled_width
    oc = output_channels
    R = rois.shape[0]
    x1 = jnp.round(rois[:, 0]) * spatial_scale
    y1 = jnp.round(rois[:, 1]) * spatial_scale
    x2 = jnp.round(rois[:, 2] + 1.0) * spatial_scale
    y2 = jnp.round(rois[:, 3] + 1.0) * spatial_scale
    rw = jnp.maximum(x2 - x1, 0.1)
    rh = jnp.maximum(y2 - y1, 0.1)
    bin_w = rw / pw
    bin_h = rh / ph
    xs = jnp.arange(W, dtype=x.dtype)
    ys = jnp.arange(H, dtype=x.dtype)
    out = jnp.zeros((R, oc, ph, pw), x.dtype)
    xr = x[roi_batch_ids]                                     # [R,C,H,W]
    for i in range(ph):
        for j in range(pw):
            hs = jnp.floor(y1 + i * bin_h)
            he = jnp.ceil(y1 + (i + 1) * bin_h)
            ws_ = jnp.floor(x1 + j * bin_w)
            we = jnp.ceil(x1 + (j + 1) * bin_w)
            hmask = ((ys[None, :] >= hs[:, None]) &
                     (ys[None, :] < he[:, None]))              # [R,H]
            wmask = ((xs[None, :] >= ws_[:, None]) &
                     (xs[None, :] < we[:, None]))              # [R,W]
            m = (hmask[:, :, None] & wmask[:, None, :]).astype(x.dtype)
            cnt = jnp.maximum(jnp.sum(m, axis=(1, 2)), 1.0)    # [R]
            # channel group for bin (i,j) — reference indexes c*ph*pw + i*pw+j
            chan = jnp.arange(oc) * ph * pw + i * pw + j
            vals = xr[:, chan]                                 # [R,oc,H,W]
            s = jnp.sum(vals * m[:, None], axis=(2, 3))        # [R,oc]
            out = out.at[:, :, i, j].set(s / cnt[:, None])
    return out


@register_op("prroi_pool")
def prroi_pool(x, rois, roi_batch_ids, pooled_height=1, pooled_width=1,
               spatial_scale=1.0):
    """Precise RoI pooling (PrRoIPool, arXiv:1807.11590) — each output bin
    is the exact integral of the bilinearly-interpolated feature map over
    the (continuous) bin window divided by the bin area; no sampling-grid
    or coordinate quantization anywhere. ref: operators/prroi_pool_op.{cc,h}.

    TPU design: the reference walks every pixel segment per bin with
    PrRoIPoolingMatCalculation; here the separable closed-form integral of
    the hat (bilinear) basis turns each bin into coefficient vectors over H
    and W and the whole op into one einsum — static shapes, MXU-friendly,
    and exact (it is an integral, not a sample sum).

    x: [B,C,H,W]; rois: [R,4] (x1,y1,x2,y2 image coords);
    roi_batch_ids: [R] int -> [R,C,ph,pw].
    """
    B, C, H, W = x.shape
    ph, pw = pooled_height, pooled_width

    def hat_integral(a, b, n):
        """∫_a^b max(0, 1-|t-j|) dt for every integer pixel j in [0,n).
        a,b: [P,1] window bounds per bin -> [P,n]. Pixels outside [0,n)
        contribute zero (the reference's PrRoIPoolingGetData OOB = 0)."""
        j = jnp.arange(n, dtype=x.dtype)[None, :]
        lo = jnp.clip(a, j - 1.0, j)
        hi = jnp.clip(b, j - 1.0, j)
        left = ((hi - (j - 1.0)) ** 2 - (lo - (j - 1.0)) ** 2) * 0.5
        lo2 = jnp.clip(a, j, j + 1.0)
        hi2 = jnp.clip(b, j, j + 1.0)
        right = ((j + 1.0 - lo2) ** 2 - (j + 1.0 - hi2) ** 2) * 0.5
        return left + right

    def one_roi(roi, bidx):
        x1, y1, x2, y2 = roi * spatial_scale
        roi_w = jnp.maximum(x2 - x1, 0.0)
        roi_h = jnp.maximum(y2 - y1, 0.0)
        bin_w = roi_w / pw
        bin_h = roi_h / ph
        win_size = jnp.maximum(bin_w * bin_h, 0.0)
        pi = jnp.arange(ph, dtype=x.dtype)[:, None]
        pj = jnp.arange(pw, dtype=x.dtype)[:, None]
        cy = hat_integral(y1 + pi * bin_h, y1 + (pi + 1.0) * bin_h, H)
        cx = hat_integral(x1 + pj * bin_w, x1 + (pj + 1.0) * bin_w, W)
        out = jnp.einsum("chw,ph,qw->cpq", x[bidx], cy, cx)
        return jnp.where(win_size > 0.0,
                         out / jnp.maximum(win_size, 1e-30), 0.0)

    return jax.vmap(one_roi)(rois.astype(x.dtype), roi_batch_ids)


@register_op("deformable_psroi_pool")
def deformable_psroi_pool(x, rois, roi_batch_ids, trans=None, output_dim=1,
                          group_size=(1, 1), pooled_height=1, pooled_width=1,
                          part_size=(1, 1), sample_per_part=1,
                          spatial_scale=1.0, trans_std=0.1, no_trans=False):
    """Deformable position-sensitive RoI pooling (Deformable ConvNets):
    each bin samples a SxS grid from its dedicated channel group, shifted
    by learned normalized offsets. ref:
    operators/deformable_psroi_pooling_op.{cc,h,cu}.

    x: [B, output_dim*gh*gw, H, W]; rois: [R,4]; roi_batch_ids: [R] int;
    trans: [R, 2*num_classes, part_h, part_w] (channel = class*2 + {x:0,y:1})
    -> (out [R, output_dim, ph, pw], top_count [R, output_dim, ph, pw]).

    TPU design: the per-sample scalar loop becomes a static [ph,pw,S,S]
    sample grid gathered in one vectorized bilinear pass per roi (vmap),
    with the bin->channel-group mapping as an advanced-indexing gather.
    """
    B, C, H, W = x.shape
    gh, gw = group_size
    part_h, part_w = part_size
    ph, pw = pooled_height, pooled_width
    S = sample_per_part
    no_trans = no_trans or trans is None
    num_classes = 1 if no_trans else trans.shape[1] // 2
    channels_each = output_dim // num_classes
    dt = x.dtype

    # static bin -> group / part mappings
    ghi = np.clip(np.floor(np.arange(ph) * gh / ph), 0, gh - 1).astype(int)
    gwi = np.clip(np.floor(np.arange(pw) * gw / pw), 0, gw - 1).astype(int)
    phi = np.floor(np.arange(ph) / ph * part_h).astype(int)     # part row
    pwi = np.floor(np.arange(pw) / pw * part_w).astype(int)     # part col
    # channel of (ctop, bin): (ctop*gh + ghi)*gw + gwi  -> [O, ph, pw]
    cidx = ((np.arange(output_dim)[:, None, None] * gh + ghi[None, :, None])
            * gw + gwi[None, None, :])
    cidx = jnp.asarray(cidx)
    class_id = np.arange(output_dim) // channels_each           # [O]

    def one_roi(roi, bidx, tr):
        x1 = jnp.round(roi[0]) * spatial_scale - 0.5
        y1 = jnp.round(roi[1]) * spatial_scale - 0.5
        x2 = (jnp.round(roi[2]) + 1.0) * spatial_scale - 0.5
        y2 = (jnp.round(roi[3]) + 1.0) * spatial_scale - 0.5
        roi_w = jnp.maximum(x2 - x1, 0.1)
        roi_h = jnp.maximum(y2 - y1, 0.1)
        bin_w = roi_w / pw
        bin_h = roi_h / ph
        sub_w = bin_w / S
        sub_h = bin_h / S
        if no_trans:
            tx = jnp.zeros((output_dim, ph, pw), dt)
            ty = jnp.zeros((output_dim, ph, pw), dt)
        else:
            # tr: [2*num_classes, part_h, part_w]
            tx = tr[2 * class_id][:, phi][:, :, pwi] * trans_std
            ty = tr[2 * class_id + 1][:, phi][:, :, pwi] * trans_std
        # sample positions [O, ph, pw, S, S]
        wstart = (jnp.arange(pw, dtype=dt)[None, None, :] * bin_w + x1
                  + tx * roi_w)[..., None, None]
        hstart = (jnp.arange(ph, dtype=dt)[None, :, None] * bin_h + y1
                  + ty * roi_h)[..., None, None]
        wpos = wstart + jnp.arange(S, dtype=dt)[None, None, None, None, :] \
            * sub_w
        hpos = hstart + jnp.arange(S, dtype=dt)[None, None, None, :, None] \
            * sub_h
        ok = ((wpos >= -0.5) & (wpos <= W - 0.5)
              & (hpos >= -0.5) & (hpos <= H - 0.5))
        wc = jnp.clip(wpos, 0.0, W - 1.0)
        hc = jnp.clip(hpos, 0.0, H - 1.0)
        h0 = jnp.floor(hc).astype(jnp.int32)
        w0 = jnp.floor(wc).astype(jnp.int32)
        h1 = jnp.minimum(h0 + 1, H - 1)
        w1 = jnp.minimum(w0 + 1, W - 1)
        lh = hc - h0
        lw = wc - w0
        img = x[bidx]                                           # [C,H,W]
        ch = jnp.broadcast_to(cidx[..., None, None], h0.shape)
        v00 = img[ch, h0, w0]
        v01 = img[ch, h0, w1]
        v10 = img[ch, h1, w0]
        v11 = img[ch, h1, w1]
        val = (v00 * (1 - lh) * (1 - lw) + v01 * (1 - lh) * lw
               + v10 * lh * (1 - lw) + v11 * lh * lw)
        val = jnp.where(ok, val, 0.0)
        cnt = jnp.sum(ok.astype(dt), axis=(-1, -2))             # [O,ph,pw]
        s = jnp.sum(val, axis=(-1, -2))
        return jnp.where(cnt > 0, s / jnp.maximum(cnt, 1.0), 0.0), cnt

    tr_in = (jnp.zeros((rois.shape[0], 2, part_h, part_w), dt)
             if no_trans else trans.astype(dt))
    return jax.vmap(one_roi)(rois.astype(dt), roi_batch_ids, tr_in)


@register_op("collect_fpn_proposals")
def collect_fpn_proposals(multi_rois, multi_scores, post_nms_top_n):
    """ref: detection/collect_fpn_proposals_op.cc — concat per-level
    proposals and keep the global top-N by score. Lists of [Ni,4]/[Ni]."""
    rois = jnp.concatenate(multi_rois, axis=0)
    scores = jnp.concatenate(multi_scores, axis=0)
    k = min(post_nms_top_n, scores.shape[0])
    top_s, idx = lax.top_k(scores, k)
    return rois[idx], top_s


@register_op("sigmoid_focal_loss")
def sigmoid_focal_loss(logits, labels, fg_num, gamma=2.0, alpha=0.25):
    """ref: detection/sigmoid_focal_loss_op.cc — RetinaNet focal loss.

    logits [N, C]; labels [N] int in [0, C] where 0 = background (reference
    convention: class c maps to logit column c-1); normalized by fg_num.
    """
    N, C = logits.shape
    target = (labels[:, None] == jnp.arange(1, C + 1)[None, :])
    target = target.astype(logits.dtype)
    p = jax.nn.sigmoid(logits)
    ce = (target * jax.nn.softplus(-logits) +
          (1.0 - target) * jax.nn.softplus(logits))
    p_t = target * p + (1.0 - target) * (1.0 - p)
    alpha_t = target * alpha + (1.0 - target) * (1.0 - alpha)
    loss = alpha_t * jnp.power(1.0 - p_t, gamma) * ce
    return loss / jnp.maximum(fg_num, 1.0)


@register_op("retinanet_detection_output")
def retinanet_detection_output(bboxes_list, scores_list, anchors_list, im_info,
                               score_threshold=0.05, nms_top_k=1000,
                               keep_top_k=100, nms_threshold=0.3):
    """ref: detection/retinanet_detection_output_op.cc — decode per-FPN-level
    regression deltas against anchors, merge levels, per-class NMS.

    bboxes_list: per-level [Ai, 4] deltas; scores_list: per-level [Ai, C]
    sigmoid scores; anchors_list: per-level [Ai, 4] (x1,y1,x2,y2).
    Returns [keep_top_k, 6] (label, score, x1..y2) padded with -1 + count.
    """
    from paddle_tpu.ops.detection import multiclass_nms

    def decode(anchors, deltas):
        # elementwise center-size decode (retinanet_detection_output_op.h
        # DeltaBox), box_normalized=False convention
        aw = anchors[:, 2] - anchors[:, 0] + 1.0
        ah = anchors[:, 3] - anchors[:, 1] + 1.0
        acx = anchors[:, 0] + aw * 0.5
        acy = anchors[:, 1] + ah * 0.5
        cx = deltas[:, 0] * aw + acx
        cy = deltas[:, 1] * ah + acy
        w = jnp.exp(deltas[:, 2]) * aw
        h = jnp.exp(deltas[:, 3]) * ah
        return jnp.stack([cx - w * 0.5, cy - h * 0.5,
                          cx + w * 0.5 - 1.0, cy + h * 0.5 - 1.0], axis=1)

    decoded, scs = [], []
    for deltas, scores, anchors in zip(bboxes_list, scores_list, anchors_list):
        k = min(nms_top_k, scores.shape[0])
        best = jnp.max(scores, axis=1)
        _, idx = lax.top_k(best, k)
        d = decode(anchors[idx], deltas[idx])
        h, w = im_info[0], im_info[1]
        d = jnp.stack([jnp.clip(d[:, 0], 0, w - 1), jnp.clip(d[:, 1], 0, h - 1),
                       jnp.clip(d[:, 2], 0, w - 1), jnp.clip(d[:, 3], 0, h - 1)],
                      axis=1)
        decoded.append(d)
        scs.append(scores[idx])
    boxes = jnp.concatenate(decoded, axis=0)                  # [A,4]
    scores = jnp.concatenate(scs, axis=0)                     # [A,C]
    return multiclass_nms(boxes, scores.T, score_threshold=score_threshold,
                          nms_top_k=-1, keep_top_k=keep_top_k,
                          nms_threshold=nms_threshold, background_label=-1,
                          box_normalized=False)
