"""Dense math ops.

Ref: /root/reference/paddle/fluid/operators/ (matmul_op.cc, mul_op.cc,
elementwise/*, reduce_ops/*, cum_op, clip_op …) and operators/math/blas.h —
the reference wraps cuBLAS/MKL per device; here every op lowers through XLA
onto the MXU/VPU, with precision controlled by the `matmul_precision` flag.
"""

import jax
import jax.numpy as jnp
from jax import lax

from paddle_tpu.core.flags import get_flag
from paddle_tpu.core.registry import register_op


def _precision():
    p = get_flag("matmul_precision")
    return {"default": lax.Precision.DEFAULT,
            "high": lax.Precision.HIGH,
            "highest": lax.Precision.HIGHEST}[p]


@register_op("matmul")
def matmul(x, y, transpose_x=False, transpose_y=False, alpha=1.0):
    """Batched matmul (ref: operators/matmul_op.cc; MXU-bound on TPU)."""
    if transpose_x:
        x = jnp.swapaxes(x, -1, -2)
    if transpose_y:
        y = jnp.swapaxes(y, -1, -2)
    out = jnp.matmul(x, y, precision=_precision())
    if alpha != 1.0:
        out = out * alpha
    return out


@register_op("mul")
def mul(x, y, x_num_col_dims=1, y_num_col_dims=1):
    """The reference's `mul` op: flatten x to 2-D at x_num_col_dims, y at
    y_num_col_dims, then matmul (ref: operators/mul_op.cc)."""
    xs, ys = x.shape, y.shape
    x2 = x.reshape((int(jnp.prod(jnp.array(xs[:x_num_col_dims]))), -1))
    y2 = y.reshape((int(jnp.prod(jnp.array(ys[:y_num_col_dims]))), -1))
    out = jnp.matmul(x2, y2, precision=_precision())
    return out.reshape(xs[:x_num_col_dims] + ys[y_num_col_dims:])


@register_op("bmm")
def bmm(x, y):
    return jnp.matmul(x, y, precision=_precision())


@register_op("dot")
def dot(x, y):
    return jnp.sum(x * y, axis=-1, keepdims=True)


# --- elementwise binary (ref: operators/elementwise/elementwise_*_op.cc) ---
# The reference's axis-broadcast semantics ("elementwise_add(x, y, axis=1)")
# align y's dims starting at `axis` of x; numpy broadcasting subsumes this
# when axis==-1. We keep the axis argument for parity.

def _ew_broadcast(x, y, axis):
    if axis == -1 or y.ndim == x.ndim:
        return x, y
    pad = x.ndim - axis - y.ndim
    return x, y.reshape(y.shape + (1,) * pad)


@register_op("elementwise_add")
def elementwise_add(x, y, axis=-1):
    x, y = _ew_broadcast(x, y, axis)
    return x + y


@register_op("elementwise_sub")
def elementwise_sub(x, y, axis=-1):
    x, y = _ew_broadcast(x, y, axis)
    return x - y


@register_op("elementwise_mul")
def elementwise_mul(x, y, axis=-1):
    x, y = _ew_broadcast(x, y, axis)
    return x * y


@register_op("elementwise_div")
def elementwise_div(x, y, axis=-1):
    x, y = _ew_broadcast(x, y, axis)
    return x / y


@register_op("elementwise_max")
def elementwise_max(x, y, axis=-1):
    x, y = _ew_broadcast(x, y, axis)
    return jnp.maximum(x, y)


@register_op("elementwise_min")
def elementwise_min(x, y, axis=-1):
    x, y = _ew_broadcast(x, y, axis)
    return jnp.minimum(x, y)


@register_op("elementwise_pow")
def elementwise_pow(x, y, axis=-1):
    x, y = _ew_broadcast(x, y, axis)
    return jnp.power(x, y)


@register_op("elementwise_mod")
def elementwise_mod(x, y, axis=-1):
    x, y = _ew_broadcast(x, y, axis)
    return jnp.mod(x, y)


@register_op("elementwise_floordiv")
def elementwise_floordiv(x, y, axis=-1):
    x, y = _ew_broadcast(x, y, axis)
    return jnp.floor_divide(x, y)


# --- unary math (ref: operators/activation_op.cc math subset) ---
for _name, _fn in [
    ("exp", jnp.exp), ("log", jnp.log), ("log2", jnp.log2),
    ("log10", jnp.log10), ("log1p", jnp.log1p), ("sqrt", jnp.sqrt),
    ("rsqrt", lax.rsqrt), ("abs", jnp.abs), ("ceil", jnp.ceil),
    ("floor", jnp.floor), ("round", jnp.round), ("sign", jnp.sign),
    ("square", jnp.square), ("reciprocal", jnp.reciprocal),
    ("sin", jnp.sin), ("cos", jnp.cos), ("tan", jnp.tan),
    ("asin", jnp.arcsin), ("acos", jnp.arccos), ("atan", jnp.arctan),
    ("sinh", jnp.sinh), ("cosh", jnp.cosh), ("erf", jax.scipy.special.erf),
]:
    globals()[_name] = register_op(_name)(_fn)


@register_op("pow")
def pow(x, factor=1.0):
    return jnp.power(x, factor)


@register_op("scale")
def scale(x, scale=1.0, bias=0.0, bias_after_scale=True):
    """ref: operators/scale_op.cc"""
    if bias_after_scale:
        return x * scale + bias
    return (x + bias) * scale


@register_op("clip")
def clip(x, min, max):
    return jnp.clip(x, min, max)


@register_op("clip_by_norm")
def clip_by_norm(x, max_norm):
    norm = jnp.sqrt(jnp.sum(jnp.square(x)))
    return jnp.where(norm > max_norm, x * (max_norm / jnp.maximum(norm, 1e-12)), x)


# --- reductions (ref: operators/reduce_ops/) ---
@register_op("reduce_sum")
def reduce_sum(x, dim=None, keep_dim=False):
    return jnp.sum(x, axis=dim, keepdims=keep_dim)


@register_op("reduce_mean")
def reduce_mean(x, dim=None, keep_dim=False):
    return jnp.mean(x, axis=dim, keepdims=keep_dim)


@register_op("reduce_max")
def reduce_max(x, dim=None, keep_dim=False):
    return jnp.max(x, axis=dim, keepdims=keep_dim)


@register_op("reduce_min")
def reduce_min(x, dim=None, keep_dim=False):
    return jnp.min(x, axis=dim, keepdims=keep_dim)


@register_op("reduce_prod")
def reduce_prod(x, dim=None, keep_dim=False):
    return jnp.prod(x, axis=dim, keepdims=keep_dim)


@register_op("reduce_all")
def reduce_all(x, dim=None, keep_dim=False):
    return jnp.all(x, axis=dim, keepdims=keep_dim)


@register_op("reduce_any")
def reduce_any(x, dim=None, keep_dim=False):
    return jnp.any(x, axis=dim, keepdims=keep_dim)


@register_op("logsumexp")
def logsumexp(x, dim=None, keep_dim=False):
    return jax.scipy.special.logsumexp(x, axis=dim, keepdims=keep_dim)


@register_op("mean")
def mean(x):
    return jnp.mean(x)


@register_op("sum")
def sum(xs):
    """Sum a list of tensors (ref: operators/sum_op.cc — grad accumulation)."""
    if isinstance(xs, (list, tuple)):
        out = xs[0]
        for x in xs[1:]:
            out = out + x
        return out
    return jnp.sum(xs)


@register_op("cumsum")
def cumsum(x, axis=None, exclusive=False, reverse=False):
    if axis is None:
        x = x.reshape(-1)
        axis = 0
    if reverse:
        x = jnp.flip(x, axis)
    out = jnp.cumsum(x, axis)
    if exclusive:
        out = out - x
    if reverse:
        out = jnp.flip(out, axis)
    return out


@register_op("cumprod")
def cumprod(x, axis=0):
    return jnp.cumprod(x, axis)


@register_op("norm")
def norm(x, p=2, axis=-1, epsilon=1e-10):
    """l2_normalize-style (ref: operators/norm_op.cc)."""
    if p == 2:
        n = jnp.sqrt(jnp.sum(jnp.square(x), axis=axis, keepdims=True) + epsilon)
    else:
        n = jnp.power(jnp.sum(jnp.power(jnp.abs(x), p), axis=axis, keepdims=True)
                      + epsilon, 1.0 / p)
    return x / n


@register_op("frobenius_norm")
def frobenius_norm(x, dim=None, keep_dim=False):
    return jnp.sqrt(jnp.sum(jnp.square(x), axis=dim, keepdims=keep_dim))


@register_op("kron")
def kron(x, y):
    return jnp.kron(x, y)


@register_op("addmm")
def addmm(input, x, y, alpha=1.0, beta=1.0):
    return beta * input + alpha * jnp.matmul(x, y, precision=_precision())


@register_op("isfinite")
def isfinite(x):
    return jnp.all(jnp.isfinite(x))


@register_op("isnan")
def isnan(x):
    return jnp.isnan(x)


@register_op("isinf")
def isinf(x):
    return jnp.isinf(x)


@register_op("increment")
def increment(x, value=1.0):
    return x + value


@register_op("maximum")
def maximum(x, y):
    return jnp.maximum(x, y)


@register_op("minimum")
def minimum(x, y):
    return jnp.minimum(x, y)
