"""Fused-op surface (ref: /root/reference/paddle/fluid/operators/fused/).

The reference hand-wrote these CPU/CUDA fusion kernels because its executor
ran one op at a time; on TPU, XLA's fusion pass composes the same chains
automatically, so each op here is the *mathematical composition* expressed
in one call — same name, same semantics, compiler-owned fusion. (The truly
bandwidth-bound cases that XLA cannot fuse — flash attention, fused
layer-norm — live in ops/pallas/ as real kernels instead.)

Sequence-typed inputs use the framework's padded-batch + lengths
convention (core/ragged.py) rather than LoD.
"""

import functools
import os

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.core.registry import GLOBAL_OP_REGISTRY, register_op
from paddle_tpu.ops import activations as A


def _act(name, x):
    if name in (None, "", "identity"):
        return x
    return getattr(A, name)(x)


# ---- chunked/fused softmax-cross-entropy over the vocab axis -------------
# The one loss XLA cannot tile on its own: softmax_with_cross_entropy over
# LM-head logits materializes [batch, seq, vocab] f32 (and nmt_loss adds a
# same-shape one_hot) only to reduce to one scalar per row — ~1.6 GB of HBM
# traffic per GPT step at 16 x 512 x 50k. fused_xent fuses the vocab
# projection INTO the loss: logits exist only as [rows, chunk] tiles, the
# label logit is gathered per chunk, logsumexp runs online across chunks
# (flash-attention style), and label smoothing folds into closed form
# ((sp-sn)*(logz-picked) + sn*(V*logz - sum_logits)) so no one-hot tensor
# is ever built. The custom VJP recomputes per-chunk logits instead of
# saving them (the recompute-over-store discipline of the flash kernels);
# grads match the reference composition exactly.


def fused_xent_enabled():
    """PT_FUSED_XENT env (the documented spelling) wins; else the
    ``fused_xent`` flag (PT_FLAGS_fused_xent / set_flags)."""
    env = os.environ.get("PT_FUSED_XENT")
    if env is not None:
        return env.lower() in ("1", "true", "yes")
    from paddle_tpu.core.flags import get_flag
    return get_flag("fused_xent")


def _vocab_chunks(v, chunk):
    return [(c0, min(c0 + chunk, v)) for c0 in range(0, v, chunk)]


def _chunk_logits(h, w, b, c0, c1, layout):
    """f32 logits for vocab columns [c0, c1): the slice feeds the dot
    directly, so no weight copy and no full-vocab logits ever exist."""
    if layout == "vh":
        wc = jax.lax.slice_in_dim(w, c0, c1, axis=0)          # [Vc, H]
        logits = jax.lax.dot_general(
            h, wc, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
    else:                                                      # "hv"
        wc = jax.lax.slice_in_dim(w, c0, c1, axis=1)          # [H, Vc]
        logits = jax.lax.dot_general(
            h, wc, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
    return logits + b[c0:c1].astype(jnp.float32)[None, :]


def _xent_stats_xla(h, w, b, labels, layout, chunk, need_sum):
    """Online (logz, picked, sum_logits) per row, vocab tiled by `chunk`."""
    n = h.shape[0]
    v = w.shape[0] if layout == "vh" else w.shape[1]
    m = jnp.full((n,), -jnp.inf, jnp.float32)
    s = jnp.zeros((n,), jnp.float32)
    picked = jnp.zeros((n,), jnp.float32)
    sl = jnp.zeros((n,), jnp.float32)
    for c0, c1 in _vocab_chunks(v, chunk):
        logits = _chunk_logits(h, w, b, c0, c1, layout)        # [N, Vc]
        m_new = jnp.maximum(m, jnp.max(logits, axis=1))
        s = s * jnp.exp(m - m_new) + jnp.sum(
            jnp.exp(logits - m_new[:, None]), axis=1)
        m = m_new
        local = labels - c0
        inr = (local >= 0) & (local < c1 - c0)
        picked = picked + jnp.where(
            inr, jnp.take_along_axis(
                logits, jnp.clip(local, 0, c1 - c0 - 1)[:, None],
                axis=1)[:, 0], 0.0)
        if need_sum:
            sl = sl + jnp.sum(logits, axis=1)
    return m + jnp.log(s), picked, sl


def _xent_forward(h, w, b, labels, layout, ls, chunk):
    v = w.shape[0] if layout == "vh" else w.shape[1]
    stats = None
    if layout == "vh":
        from paddle_tpu.ops.pallas.xent import xent_stats
        stats = xent_stats(h, w, b, labels)
    if stats is None:
        stats = _xent_stats_xla(h, w, b, labels, layout, chunk,
                                need_sum=ls != 0.0)
    logz, picked, sl = stats
    if ls:
        sn = ls / (v - 1)
        sp = 1.0 - ls
        loss = (sp - sn) * (logz - picked) + sn * (v * logz - sl)
    else:
        loss = logz - picked
    return loss, logz


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6))
def _fused_xent_rows(h, w, b, labels, layout, ls, chunk):
    return _xent_forward(h, w, b, labels, layout, ls, chunk)[0]


def _fx_fwd(h, w, b, labels, layout, ls, chunk):
    loss, logz = _xent_forward(h, w, b, labels, layout, ls, chunk)
    return loss, (h, w, b, labels, logz)


def _fx_bwd(layout, ls, chunk, res, g):
    h, w, b, labels, logz = res
    v = w.shape[0] if layout == "vh" else w.shape[1]
    sn = ls / (v - 1) if ls else 0.0
    sp = 1.0 - ls if ls else 1.0
    g = g.astype(jnp.float32)
    dh = jnp.zeros(h.shape, jnp.float32)
    dw_parts, db_parts = [], []
    for c0, c1 in _vocab_chunks(v, chunk):
        logits = _chunk_logits(h, w, b, c0, c1, layout)
        p = jnp.exp(logits - logz[:, None])
        col = c0 + jnp.arange(c1 - c0, dtype=labels.dtype)
        hit = (col[None, :] == labels[:, None]).astype(jnp.float32)
        # dlogits of the smoothed CE: softmax - smoothed one-hot
        gch = (p - sn - (sp - sn) * hit) * g[:, None]          # [N, Vc] f32
        if layout == "vh":
            wc = jax.lax.slice_in_dim(w, c0, c1, axis=0)
            dh = dh + jax.lax.dot_general(
                gch, wc.astype(jnp.float32), (((1,), (0,)), ((), ())))
            dw_parts.append(jax.lax.dot_general(
                gch, h.astype(jnp.float32),
                (((0,), (0,)), ((), ()))))                     # [Vc, H]
        else:
            wc = jax.lax.slice_in_dim(w, c0, c1, axis=1)
            dh = dh + jax.lax.dot_general(
                gch, wc.astype(jnp.float32), (((1,), (1,)), ((), ())))
            dw_parts.append(jax.lax.dot_general(
                h.astype(jnp.float32), gch,
                (((0,), (0,)), ((), ()))))                     # [H, Vc]
        db_parts.append(jnp.sum(gch, axis=0))
    dw = jnp.concatenate(dw_parts, axis=0 if layout == "vh" else 1)
    db = jnp.concatenate(db_parts, axis=0)
    return (dh.astype(h.dtype), dw.astype(w.dtype), db.astype(b.dtype),
            np.zeros(labels.shape, jax.dtypes.float0))


_fused_xent_rows.defvjp(_fx_fwd, _fx_bwd)


@register_op("fused_xent")
def fused_xent(hidden, weight, labels, bias=None, weight_layout="vh",
               label_smoothing=0.0, chunk=None):
    """Per-position softmax cross entropy WITHOUT materializing logits.

    hidden [..., H]; weight [V, H] ("vh", the tied-embedding layout) or
    [H, V] ("hv", the output-projection layout); labels [...] int (< V);
    bias [V] optional. Returns f32 loss with labels' shape — equal to
    ``softmax_with_cross_entropy(project(hidden), labels)`` (plus the
    label-smoothed soft-label form when label_smoothing > 0), with value
    and gradient fused/tiled over the vocab axis."""
    if chunk is None:
        from paddle_tpu.core.flags import get_flag
        chunk = get_flag("xent_chunk")
    lead = labels.shape
    h2 = hidden.reshape(-1, hidden.shape[-1])
    lbl = labels.reshape(-1).astype(jnp.int32)
    v = weight.shape[0] if weight_layout == "vh" else weight.shape[1]
    b = bias if bias is not None else jnp.zeros((v,), jnp.float32)
    loss = _fused_xent_rows(h2, weight, b, lbl, weight_layout,
                            float(label_smoothing), int(chunk))
    return loss.reshape(lead)


@register_op("fused_elemwise_activation")
def fused_elemwise_activation(x, y, functor_list=("elementwise_add", "relu"),
                              scale=1.0):
    """ref fused/fused_elemwise_activation_op.{cc,h} — exact reference
    composition rules:
      [binary, unary]  ->  Binary(X, Unary(Y))   e.g. add,relu = x+relu(y)
      [unary, binary]  ->  Unary(Binary(X, Y))   e.g. relu,add = relu(x+y)
    Unaries: relu, scale (with the `scale` attr), per the reference's
    supported functor pairs."""
    from paddle_tpu.core.enforce import enforce
    binary_fns = {"elementwise_add": jnp.add, "elementwise_mul": jnp.multiply}

    def unary(name, t):
        if name == "relu":
            return jnp.maximum(t, 0.0)
        if name == "scale":
            return t * scale
        enforce(False, f"unsupported unary functor '{name}' "
                       "(reference supports relu, scale)")

    f0, f1 = functor_list
    if f0 in binary_fns:
        return binary_fns[f0](x, unary(f1, y))    # Binary(X, Unary(Y))
    enforce(f1 in binary_fns,
            f"functor_list {functor_list} has no binary functor")
    return unary(f0, binary_fns[f1](x, y))        # Unary(Binary(X, Y))


@register_op("fused_embedding_seq_pool")
def fused_embedding_seq_pool(table, ids, lengths=None, combiner="sum"):
    """ref fused/fused_embedding_seq_pool_op.cc — lookup + per-sequence sum
    pool. ids: [B, T] padded; lengths: [B] valid counts."""
    emb = jnp.take(table, ids, axis=0)                  # [B, T, D]
    if lengths is not None:
        mask = (jnp.arange(ids.shape[1])[None, :]
                < lengths[:, None]).astype(emb.dtype)
        emb = emb * mask[..., None]
    out = jnp.sum(emb, axis=1)
    if combiner == "mean":
        n = (jnp.maximum(lengths, 1)[:, None].astype(out.dtype)
             if lengths is not None else float(ids.shape[1]))
        out = out / n
    return out


@register_op("fused_fc_elementwise_layernorm")
def fused_fc_elementwise_layernorm(x, w, y, bias=None, scale=None,
                                   shift=None, epsilon=1e-5):
    """ref fused/fused_fc_elementwise_layernorm_op.cc —
    layer_norm(fc(x, w) + y)."""
    h = x @ w
    if bias is not None:
        h = h + bias
    h = h + y
    m = jnp.mean(h, -1, keepdims=True)
    v = jnp.var(h, -1, keepdims=True)
    out = (h - m) * jax.lax.rsqrt(v + epsilon)
    if scale is not None:
        out = out * scale
    if shift is not None:
        out = out + shift
    return out


@register_op("fusion_repeated_fc_relu")
def fusion_repeated_fc_relu(x, weights, biases):
    """ref fused/fusion_repeated_fc_relu_op.cc — a chain of fc+relu."""
    h = x
    for w, b in zip(weights, biases):
        h = jnp.maximum(h @ w + b, 0.0)
    return h


@register_op("fusion_squared_mat_sub")
def fusion_squared_mat_sub(x, y, scalar=1.0):
    """ref fused/fusion_squared_mat_sub_op.cc —
    ((x @ y)^2 - (x^2 @ y^2)) * scalar (the FM interaction trick)."""
    xy = x @ y
    return (xy * xy - (x * x) @ (y * y)) * scalar


@register_op("fusion_transpose_flatten_concat")
def fusion_transpose_flatten_concat(inputs, trans_axis, flatten_axis,
                                    concat_axis=0):
    """ref fused/fusion_transpose_flatten_concat_op.cc — per-input
    transpose -> flatten-from-axis -> concat."""
    outs = []
    for t in inputs:
        t = jnp.transpose(t, trans_axis)
        lead = 1
        for d in t.shape[:flatten_axis]:
            lead *= int(d)
        outs.append(t.reshape(lead, -1))
    return jnp.concatenate(outs, axis=concat_axis)


@register_op("fusion_seqpool_concat")
def fusion_seqpool_concat(inputs, lengths=None, pooltype="SUM"):
    """ref fused/fusion_seqpool_concat_op.cc — seq-pool each input then
    concat along features. inputs: list of [B, T, D] padded;
    pooltype: SUM | AVERAGE | SQRT (sum / sqrt(len), the reference's
    sequence_pool modes)."""
    pooled = []
    for x in inputs:
        n = (jnp.maximum(lengths, 1)[:, None].astype(x.dtype)
             if lengths is not None else float(x.shape[1]))
        if lengths is not None:
            mask = (jnp.arange(x.shape[1])[None, :]
                    < lengths[:, None]).astype(x.dtype)
            x = x * mask[..., None]
        s = jnp.sum(x, axis=1)
        if pooltype == "AVERAGE":
            s = s / n
        elif pooltype == "SQRT":
            s = s / jnp.sqrt(n)
        pooled.append(s)
    return jnp.concatenate(pooled, axis=-1)


@register_op("fusion_seqpool_cvm_concat")
def fusion_seqpool_cvm_concat(inputs, lengths=None, use_cvm=True,
                              pooltype="SUM"):
    """ref fused/fusion_seqpool_cvm_concat_op.cc — seq-pool + CVM transform
    + concat (the Baidu CTR ingest chain)."""
    from paddle_tpu.ops.tail import continuous_value_model
    outs = []
    for x in inputs:
        s = fusion_seqpool_concat([x], lengths, pooltype=pooltype)
        outs.append(continuous_value_model(s, use_cvm=use_cvm))
    return jnp.concatenate(outs, axis=-1)


@register_op("fusion_seqexpand_concat_fc")
def fusion_seqexpand_concat_fc(seq_input, static_inputs, w, bias=None,
                               act="relu"):
    """ref fused/fusion_seqexpand_concat_fc_op.cc — broadcast per-batch
    static features along the sequence, concat with the sequence input,
    one fc + activation. seq_input: [B, T, D0]; static: list of [B, Di]."""
    b, t, _ = seq_input.shape
    parts = [seq_input] + [jnp.broadcast_to(s[:, None, :], (b, t, s.shape[-1]))
                           for s in static_inputs]
    h = jnp.concatenate(parts, axis=-1) @ w
    if bias is not None:
        h = h + bias
    return _act(act, h)


@register_op("fusion_seqconv_eltadd_relu")
def fusion_seqconv_eltadd_relu(x, w, b, context_length, context_start=None,
                               lengths=None):
    """ref fused/fusion_seqconv_eltadd_relu_op.cc —
    relu(sequence_conv(x) + b). x: [B, T, D] padded; w:
    [context_length*D, out]; same window math as ops.sequence.sequence_conv
    (which takes a RaggedBatch)."""
    start = (-((context_length - 1) // 2) if context_start is None
             else context_start)
    B, T, D = x.shape
    if lengths is None:
        lengths = jnp.full((B,), T, jnp.int32)
    mask = jnp.arange(T)[None, :] < lengths[:, None]
    xm = jnp.where(mask[..., None], x, 0.0)
    cols = []
    for k in range(context_length):
        off = start + k
        shifted = jnp.roll(xm, -off, axis=1)
        pos = jnp.arange(T) + off
        valid = (pos >= 0)[None, :] & (pos[None, :] < lengths[:, None])
        cols.append(jnp.where(valid[..., None], shifted, 0.0))
    ctx = jnp.concatenate(cols, axis=-1)
    return jnp.maximum(ctx @ w + b, 0.0)


@register_op("conv_fusion")
def conv_fusion(x, weight, bias=None, residual=None, stride=1, padding=0,
                dilation=1, groups=1, activation="relu",
                data_format="NCHW"):
    """ref fused/conv_fusion_op.cc (cudnnConvolutionBiasActivationForward):
    activation(conv(x, w) + bias + residual)."""
    from paddle_tpu.ops.nn import conv2d
    out = conv2d(x, weight, bias, stride, padding, dilation, groups,
                 data_format=data_format)
    if residual is not None:
        out = out + residual
    return _act(None if activation == "identity" else activation, out)


@register_op("fused_embedding_fc_lstm")
def fused_embedding_fc_lstm(ids, embeddings, h0, c0, w_hh, bias=None,
                            lengths=None):
    """ref fused/fused_embedding_fc_lstm_op.cc — the embedding lookup and
    the LSTM input projection are pre-fused: `embeddings` is the table
    ALREADY multiplied by the input weight ([V, 4H], the op's rearranged
    WeightX@Embeddings input), so the lookup IS the x-projection."""
    from paddle_tpu.ops.rnn import lstm
    xproj = jnp.take(embeddings, ids, axis=0)          # [B, T, 4H]
    ident = jnp.eye(xproj.shape[-1], dtype=xproj.dtype)
    return lstm(xproj, h0, c0, ident, w_hh, b=bias, lengths=lengths)


def register_fused_aliases():
    """Name aliases for fused ops whose base op already covers the fused
    semantics exactly (the hand-fused CPU kernels of the same math)."""
    from paddle_tpu.ops.tail import _alias
    for name, target in (
            ("fusion_gru", "gru"),
            ("fusion_lstm", "lstm"),
            ("fusion_conv_inception", "conv_fusion"),
            ("multihead_matmul", "multihead_attention")):
        _alias(name, target)
