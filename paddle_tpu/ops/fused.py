"""Fused-op surface (ref: /root/reference/paddle/fluid/operators/fused/).

The reference hand-wrote these CPU/CUDA fusion kernels because its executor
ran one op at a time; on TPU, XLA's fusion pass composes the same chains
automatically, so each op here is the *mathematical composition* expressed
in one call — same name, same semantics, compiler-owned fusion. (The truly
bandwidth-bound cases that XLA cannot fuse — flash attention, fused
layer-norm — live in ops/pallas/ as real kernels instead.)

Sequence-typed inputs use the framework's padded-batch + lengths
convention (core/ragged.py) rather than LoD.
"""

import functools
import os

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.core.registry import GLOBAL_OP_REGISTRY, register_op
from paddle_tpu.ops import activations as A


def _act(name, x):
    if name in (None, "", "identity"):
        return x
    return getattr(A, name)(x)


# ---- chunked/fused softmax-cross-entropy over the vocab axis -------------
# The one loss XLA cannot tile on its own: softmax_with_cross_entropy over
# LM-head logits materializes [batch, seq, vocab] f32 (and nmt_loss adds a
# same-shape one_hot) only to reduce to one scalar per row — ~1.6 GB of HBM
# traffic per GPT step at 16 x 512 x 50k. fused_xent fuses the vocab
# projection INTO the loss: logits exist only as [rows, chunk] tiles, the
# label logit is gathered per chunk, logsumexp runs online across chunks
# (flash-attention style), and label smoothing folds into closed form
# ((sp-sn)*(logz-picked) + sn*(V*logz - sum_logits)) so no one-hot tensor
# is ever built. The custom VJP recomputes per-chunk logits instead of
# saving them (the recompute-over-store discipline of the flash kernels);
# grads match the reference composition exactly.


def fused_xent_enabled():
    """PT_FUSED_XENT env (the documented spelling) wins; else the
    ``fused_xent`` flag (PT_FLAGS_fused_xent / set_flags)."""
    env = os.environ.get("PT_FUSED_XENT")
    if env is not None:
        return env.lower() in ("1", "true", "yes")
    from paddle_tpu.core.flags import get_flag
    return get_flag("fused_xent")


def _vocab_chunks(v, chunk):
    return [(c0, min(c0 + chunk, v)) for c0 in range(0, v, chunk)]


def _chunk_logits(h, w, b, c0, c1, layout):
    """f32 logits for vocab columns [c0, c1): the slice feeds the dot
    directly, so no weight copy and no full-vocab logits ever exist."""
    if layout == "vh":
        wc = jax.lax.slice_in_dim(w, c0, c1, axis=0)          # [Vc, H]
        logits = jax.lax.dot_general(
            h, wc, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
    else:                                                      # "hv"
        wc = jax.lax.slice_in_dim(w, c0, c1, axis=1)          # [H, Vc]
        logits = jax.lax.dot_general(
            h, wc, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
    return logits + b[c0:c1].astype(jnp.float32)[None, :]


def _xent_stats_parts(h, w, b, labels, layout, chunk, need_sum):
    """Online (m, s, picked, sum_logits) per row, vocab tiled by `chunk`.

    Out-of-range labels contribute 0 to `picked` — the vocab-sharded
    caller exploits this: each shard passes labels offset by its base, so
    only the owning shard's `picked` is nonzero and a cross-shard psum
    recovers the label logit."""
    n = h.shape[0]
    v = w.shape[0] if layout == "vh" else w.shape[1]
    m = jnp.full((n,), -jnp.inf, jnp.float32)
    s = jnp.zeros((n,), jnp.float32)
    picked = jnp.zeros((n,), jnp.float32)
    sl = jnp.zeros((n,), jnp.float32)
    for c0, c1 in _vocab_chunks(v, chunk):
        logits = _chunk_logits(h, w, b, c0, c1, layout)        # [N, Vc]
        m_new = jnp.maximum(m, jnp.max(logits, axis=1))
        s = s * jnp.exp(m - m_new) + jnp.sum(
            jnp.exp(logits - m_new[:, None]), axis=1)
        m = m_new
        local = labels - c0
        inr = (local >= 0) & (local < c1 - c0)
        picked = picked + jnp.where(
            inr, jnp.take_along_axis(
                logits, jnp.clip(local, 0, c1 - c0 - 1)[:, None],
                axis=1)[:, 0], 0.0)
        if need_sum:
            sl = sl + jnp.sum(logits, axis=1)
    return m, s, picked, sl


def _xent_stats_xla(h, w, b, labels, layout, chunk, need_sum):
    """Online (logz, picked, sum_logits) per row, vocab tiled by `chunk`."""
    m, s, picked, sl = _xent_stats_parts(h, w, b, labels, layout, chunk,
                                         need_sum)
    return m + jnp.log(s), picked, sl


def _loss_from_stats(logz, picked, sl, v, ls):
    """The smoothed-CE closed form from the three per-row reductions. `v`
    is the GLOBAL vocab size — under vocab sharding the stats arrive
    already combined across shards but the smoothing constants still span
    the whole vocab."""
    if ls:
        sn = ls / (v - 1)
        sp = 1.0 - ls
        return (sp - sn) * (logz - picked) + sn * (v * logz - sl)
    return logz - picked


def _xent_forward(h, w, b, labels, layout, ls, chunk):
    v = w.shape[0] if layout == "vh" else w.shape[1]
    stats = None
    if layout == "vh":
        from paddle_tpu.ops.pallas.xent import xent_stats
        stats = xent_stats(h, w, b, labels)
    if stats is None:
        stats = _xent_stats_xla(h, w, b, labels, layout, chunk,
                                need_sum=ls != 0.0)
    logz, picked, sl = stats
    return _loss_from_stats(logz, picked, sl, v, ls), logz


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6))
def _fused_xent_rows(h, w, b, labels, layout, ls, chunk):
    return _xent_forward(h, w, b, labels, layout, ls, chunk)[0]


def _fx_fwd(h, w, b, labels, layout, ls, chunk):
    loss, logz = _xent_forward(h, w, b, labels, layout, ls, chunk)
    return loss, (h, w, b, labels, logz)


def _xent_bwd_impl(h, w, b, labels, logz, g, layout, sn, sp, chunk,
                   context=""):
    """(dh, dw, db) in f32 for per-row cotangent g [N] f32 — the Pallas
    backward kernels when they apply (vh layout, TPU/interpret, flag on),
    else the chunked XLA recompute. Labels may be out of range (the
    vocab-sharded per-shard call): they never hit, so the one-hot term is
    zero on non-owning shards, exactly the sharded math."""
    if layout == "vh":
        from paddle_tpu.ops.pallas.xent import xent_bwd
        out = xent_bwd(h, w, b, labels, logz, g, sn, sp, context=context)
        if out is not None:
            return out
    v = w.shape[0] if layout == "vh" else w.shape[1]
    dh = jnp.zeros(h.shape, jnp.float32)
    dw_parts, db_parts = [], []
    for c0, c1 in _vocab_chunks(v, chunk):
        logits = _chunk_logits(h, w, b, c0, c1, layout)
        p = jnp.exp(logits - logz[:, None])
        col = c0 + jnp.arange(c1 - c0, dtype=labels.dtype)
        hit = (col[None, :] == labels[:, None]).astype(jnp.float32)
        # dlogits of the smoothed CE: softmax - smoothed one-hot
        gch = (p - sn - (sp - sn) * hit) * g[:, None]          # [N, Vc] f32
        if layout == "vh":
            wc = jax.lax.slice_in_dim(w, c0, c1, axis=0)
            dh = dh + jax.lax.dot_general(
                gch, wc.astype(jnp.float32), (((1,), (0,)), ((), ())))
            dw_parts.append(jax.lax.dot_general(
                gch, h.astype(jnp.float32),
                (((0,), (0,)), ((), ()))))                     # [Vc, H]
        else:
            wc = jax.lax.slice_in_dim(w, c0, c1, axis=1)
            dh = dh + jax.lax.dot_general(
                gch, wc.astype(jnp.float32), (((1,), (1,)), ((), ())))
            dw_parts.append(jax.lax.dot_general(
                h.astype(jnp.float32), gch,
                (((0,), (0,)), ((), ()))))                     # [H, Vc]
        db_parts.append(jnp.sum(gch, axis=0))
    dw = jnp.concatenate(dw_parts, axis=0 if layout == "vh" else 1)
    db = jnp.concatenate(db_parts, axis=0)
    return dh, dw, db


def _smooth_consts(v, ls):
    sn = ls / (v - 1) if ls else 0.0
    sp = 1.0 - ls if ls else 1.0
    return sn, sp


def _fx_bwd(layout, ls, chunk, res, g):
    h, w, b, labels, logz = res
    v = w.shape[0] if layout == "vh" else w.shape[1]
    sn, sp = _smooth_consts(v, ls)
    dh, dw, db = _xent_bwd_impl(h, w, b, labels, logz,
                                g.astype(jnp.float32), layout, sn, sp,
                                chunk)
    return (dh.astype(h.dtype), dw.astype(w.dtype), db.astype(b.dtype),
            np.zeros(labels.shape, jax.dtypes.float0))


_fused_xent_rows.defvjp(_fx_fwd, _fx_bwd)


# ---- vocab-sharded (GSPMD / shard_map) fused cross-entropy ---------------
# The same online-logsumexp math lifted one level: each vocab shard runs
# the intra-chip chunk loop over ITS slice of the projection weight (the
# Pallas kernels apply per shard unchanged), then the running (m, s) pair,
# the label-gather term and the logit sum combine across the mesh axis
# with one pmax + three psums of [rows]-sized vectors. No [rows, V] logits
# and no gathered full-vocab weight ever exist — the collective traffic is
# O(rows), not O(rows x V) or O(V x H). The backward mirrors it: each
# shard recomputes its chunk probabilities from the shared logz, keeps
# dw/db local (they are vocab-sharded like w/b) and psums only the [rows,
# H] partial dh. Autodiff never crosses shard_map — the custom VJP wraps
# both shard_map calls, so no reliance on collective transpose rules.


def _shard_specs(layout, vocab_axis, batch_axis):
    from jax.sharding import PartitionSpec as P
    wspec = (P(vocab_axis, None) if layout == "vh"
             else P(None, vocab_axis))
    return P(batch_axis, None), wspec, P(vocab_axis), P(batch_axis)


def _sharded_fwd(h, w, b, labels, layout, ls, chunk, vocab_axis,
                 batch_axis, mesh):
    from jax.sharding import PartitionSpec as P
    from paddle_tpu.parallel.pipeline import shard_map
    v = w.shape[0] if layout == "vh" else w.shape[1]
    need_sum = ls != 0.0

    def local_fwd(h, w, b, lbl):
        vl = w.shape[0] if layout == "vh" else w.shape[1]
        off = (jax.lax.axis_index(vocab_axis) * vl).astype(lbl.dtype)
        lbl_loc = lbl - off
        parts = None
        if layout == "vh":
            from paddle_tpu.ops.pallas.xent import xent_stats
            parts = xent_stats(h, w, b, lbl_loc, return_parts=True,
                               context=f"; requested vocab_axis="
                                       f"{vocab_axis!r} layout={layout!r}")
        if parts is None:
            parts = _xent_stats_parts(h, w, b, lbl_loc, layout, chunk,
                                      need_sum)
        m, s, picked, sl = parts
        m_g = jax.lax.pmax(m, vocab_axis)
        s_g = jax.lax.psum(s * jnp.exp(m - m_g), vocab_axis)
        logz = m_g + jnp.log(s_g)
        picked_g = jax.lax.psum(picked, vocab_axis)
        sl_g = jax.lax.psum(sl, vocab_axis) if need_sum else sl
        return _loss_from_stats(logz, picked_g, sl_g, v, ls), logz

    hspec, wspec, bspec, lspec = _shard_specs(layout, vocab_axis,
                                              batch_axis)
    return shard_map(local_fwd, mesh=mesh,
                     in_specs=(hspec, wspec, bspec, lspec),
                     out_specs=(P(batch_axis), P(batch_axis)),
                     check_vma=False)(h, w, b, labels)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7, 8, 9))
def _fused_xent_rows_sharded(h, w, b, labels, layout, ls, chunk,
                             vocab_axis, batch_axis, mesh):
    return _sharded_fwd(h, w, b, labels, layout, ls, chunk, vocab_axis,
                        batch_axis, mesh)[0]


def _fxs_fwd(h, w, b, labels, layout, ls, chunk, vocab_axis, batch_axis,
             mesh):
    loss, logz = _sharded_fwd(h, w, b, labels, layout, ls, chunk,
                              vocab_axis, batch_axis, mesh)
    return loss, (h, w, b, labels, logz)


def _fxs_bwd(layout, ls, chunk, vocab_axis, batch_axis, mesh, res, g):
    from jax.sharding import PartitionSpec as P
    from paddle_tpu.parallel.pipeline import shard_map
    h, w, b, labels, logz = res
    v = w.shape[0] if layout == "vh" else w.shape[1]
    sn, sp = _smooth_consts(v, ls)
    ctx = f"; requested vocab_axis={vocab_axis!r} layout={layout!r}"

    def local_bwd(h, w, b, lbl, logz, g):
        vl = w.shape[0] if layout == "vh" else w.shape[1]
        off = (jax.lax.axis_index(vocab_axis) * vl).astype(lbl.dtype)
        dh, dw, db = _xent_bwd_impl(h, w, b, lbl - off, logz,
                                    g.astype(jnp.float32), layout, sn, sp,
                                    chunk, context=ctx)
        # dh sums partial per-shard contributions over the vocab axis;
        # dw/db stay vocab-local (sharded exactly like w/b) but sum the
        # row contributions each batch shard computed from its own rows
        if batch_axis is not None:
            dw = jax.lax.psum(dw, batch_axis)
            db = jax.lax.psum(db, batch_axis)
        return jax.lax.psum(dh, vocab_axis), dw, db

    hspec, wspec, bspec, lspec = _shard_specs(layout, vocab_axis,
                                              batch_axis)
    dh, dw, db = shard_map(
        local_bwd, mesh=mesh,
        in_specs=(hspec, wspec, bspec, lspec, P(batch_axis),
                  P(batch_axis)),
        out_specs=(hspec, wspec, bspec), check_vma=False)(
        h, w, b, labels, logz, g)
    return (dh.astype(h.dtype), dw.astype(w.dtype), db.astype(b.dtype),
            np.zeros(labels.shape, jax.dtypes.float0))


_fused_xent_rows_sharded.defvjp(_fxs_fwd, _fxs_bwd)


def _infer_sharded_call(weight, labels, layout):
    """(vocab_axis, batch_axis, mesh) read off CONCRETE array shardings —
    tracers carry no sharding on this jax, so under jit callers pass
    vocab_axis explicitly (the model .loss() entry points plumb it)."""
    try:
        sh = weight.sharding
        spec = tuple(sh.spec)
        mesh = sh.mesh
    except Exception:
        return None, None, None

    def _axis(entry):
        if isinstance(entry, (tuple, list)):
            return entry[0] if entry else None
        return entry

    dim = 0 if layout == "vh" else 1
    vocab_axis = _axis(spec[dim]) if dim < len(spec) else None
    if vocab_axis is None:
        return None, None, None
    batch_axis = None
    try:
        lspec = tuple(labels.sharding.spec)
        batch_axis = _axis(lspec[0]) if lspec else None
    except Exception:
        pass
    return vocab_axis, batch_axis, mesh


@register_op("fused_xent")
def fused_xent(hidden, weight, labels, bias=None, weight_layout="vh",
               label_smoothing=0.0, chunk=None, vocab_axis=None,
               batch_axis=None, mesh=None):
    """Per-position softmax cross entropy WITHOUT materializing logits.

    hidden [..., H]; weight [V, H] ("vh", the tied-embedding layout) or
    [H, V] ("hv", the output-projection layout); labels [...] int (< V);
    bias [V] optional. Returns f32 loss with labels' shape — equal to
    ``softmax_with_cross_entropy(project(hidden), labels)`` (plus the
    label-smoothed soft-label form when label_smoothing > 0), with value
    and gradient fused/tiled over the vocab axis.

    vocab_axis: mesh axis name the VOCAB dim of weight/bias is partitioned
    over (tensor parallelism). The chunk loop then runs per shard inside
    shard_map and the (m, s)/picked/sum stats combine with pmax/psum — no
    full-vocab weight gather, no [rows, V] temporary, O(rows) collective
    traffic. Auto-detected from ``weight.sharding`` when the arrays are
    concrete (eager); under jit pass it explicitly.
    batch_axis: mesh axis the row (batch*seq) dim of hidden/labels is
    sharded over (usually "dp"); None keeps rows replicated per shard.
    mesh: Mesh for the sharded path; defaults to the enclosing
    ``with mesh:`` context, else the weight's own sharding mesh."""
    if chunk is None:
        from paddle_tpu.core.flags import get_flag
        chunk = get_flag("xent_chunk")
    if vocab_axis is None and mesh is None:
        vocab_axis, auto_batch, mesh = _infer_sharded_call(
            weight, labels, weight_layout)
        if batch_axis is None:
            batch_axis = auto_batch
    lead = labels.shape
    h2 = hidden.reshape(-1, hidden.shape[-1])
    lbl = labels.reshape(-1).astype(jnp.int32)
    v = weight.shape[0] if weight_layout == "vh" else weight.shape[1]
    b = bias if bias is not None else jnp.zeros((v,), jnp.float32)
    if vocab_axis is not None:
        from paddle_tpu.core.enforce import enforce
        if mesh is None:
            from paddle_tpu.parallel.mesh import current_mesh
            mesh = current_mesh()
        enforce(mesh is not None,
                "fused_xent(vocab_axis=...) needs a mesh: pass mesh= or "
                "call under `with mesh:`")
        tp = mesh.shape[vocab_axis]
        if tp > 1:
            enforce(v % tp == 0,
                    f"vocab {v} not divisible by mesh axis "
                    f"{vocab_axis!r} size {tp}")
            loss = _fused_xent_rows_sharded(
                h2, weight, b, lbl, weight_layout, float(label_smoothing),
                int(chunk), vocab_axis, batch_axis, mesh)
            return loss.reshape(lead)
    loss = _fused_xent_rows(h2, weight, b, lbl, weight_layout,
                            float(label_smoothing), int(chunk))
    return loss.reshape(lead)


@register_op("fused_elemwise_activation")
def fused_elemwise_activation(x, y, functor_list=("elementwise_add", "relu"),
                              scale=1.0):
    """ref fused/fused_elemwise_activation_op.{cc,h} — exact reference
    composition rules:
      [binary, unary]  ->  Binary(X, Unary(Y))   e.g. add,relu = x+relu(y)
      [unary, binary]  ->  Unary(Binary(X, Y))   e.g. relu,add = relu(x+y)
    Unaries: relu, scale (with the `scale` attr), per the reference's
    supported functor pairs."""
    from paddle_tpu.core.enforce import enforce
    binary_fns = {"elementwise_add": jnp.add, "elementwise_mul": jnp.multiply}

    def unary(name, t):
        if name == "relu":
            return jnp.maximum(t, 0.0)
        if name == "scale":
            return t * scale
        enforce(False, f"unsupported unary functor '{name}' "
                       "(reference supports relu, scale)")

    f0, f1 = functor_list
    if f0 in binary_fns:
        return binary_fns[f0](x, unary(f1, y))    # Binary(X, Unary(Y))
    enforce(f1 in binary_fns,
            f"functor_list {functor_list} has no binary functor")
    return unary(f0, binary_fns[f1](x, y))        # Unary(Binary(X, Y))


@register_op("fused_embedding_seq_pool")
def fused_embedding_seq_pool(table, ids, lengths=None, combiner="sum"):
    """ref fused/fused_embedding_seq_pool_op.cc — lookup + per-sequence sum
    pool. ids: [B, T] padded; lengths: [B] valid counts."""
    emb = jnp.take(table, ids, axis=0)                  # [B, T, D]
    if lengths is not None:
        mask = (jnp.arange(ids.shape[1])[None, :]
                < lengths[:, None]).astype(emb.dtype)
        emb = emb * mask[..., None]
    out = jnp.sum(emb, axis=1)
    if combiner == "mean":
        n = (jnp.maximum(lengths, 1)[:, None].astype(out.dtype)
             if lengths is not None else float(ids.shape[1]))
        out = out / n
    return out


@register_op("fused_fc_elementwise_layernorm")
def fused_fc_elementwise_layernorm(x, w, y, bias=None, scale=None,
                                   shift=None, epsilon=1e-5):
    """ref fused/fused_fc_elementwise_layernorm_op.cc —
    layer_norm(fc(x, w) + y)."""
    h = x @ w
    if bias is not None:
        h = h + bias
    h = h + y
    m = jnp.mean(h, -1, keepdims=True)
    v = jnp.var(h, -1, keepdims=True)
    out = (h - m) * jax.lax.rsqrt(v + epsilon)
    if scale is not None:
        out = out * scale
    if shift is not None:
        out = out + shift
    return out


@register_op("fusion_repeated_fc_relu")
def fusion_repeated_fc_relu(x, weights, biases):
    """ref fused/fusion_repeated_fc_relu_op.cc — a chain of fc+relu."""
    h = x
    for w, b in zip(weights, biases):
        h = jnp.maximum(h @ w + b, 0.0)
    return h


@register_op("fusion_squared_mat_sub")
def fusion_squared_mat_sub(x, y, scalar=1.0):
    """ref fused/fusion_squared_mat_sub_op.cc —
    ((x @ y)^2 - (x^2 @ y^2)) * scalar (the FM interaction trick)."""
    xy = x @ y
    return (xy * xy - (x * x) @ (y * y)) * scalar


@register_op("fusion_transpose_flatten_concat")
def fusion_transpose_flatten_concat(inputs, trans_axis, flatten_axis,
                                    concat_axis=0):
    """ref fused/fusion_transpose_flatten_concat_op.cc — per-input
    transpose -> flatten-from-axis -> concat."""
    outs = []
    for t in inputs:
        t = jnp.transpose(t, trans_axis)
        lead = 1
        for d in t.shape[:flatten_axis]:
            lead *= int(d)
        outs.append(t.reshape(lead, -1))
    return jnp.concatenate(outs, axis=concat_axis)


@register_op("fusion_seqpool_concat")
def fusion_seqpool_concat(inputs, lengths=None, pooltype="SUM"):
    """ref fused/fusion_seqpool_concat_op.cc — seq-pool each input then
    concat along features. inputs: list of [B, T, D] padded;
    pooltype: SUM | AVERAGE | SQRT (sum / sqrt(len), the reference's
    sequence_pool modes)."""
    pooled = []
    for x in inputs:
        n = (jnp.maximum(lengths, 1)[:, None].astype(x.dtype)
             if lengths is not None else float(x.shape[1]))
        if lengths is not None:
            mask = (jnp.arange(x.shape[1])[None, :]
                    < lengths[:, None]).astype(x.dtype)
            x = x * mask[..., None]
        s = jnp.sum(x, axis=1)
        if pooltype == "AVERAGE":
            s = s / n
        elif pooltype == "SQRT":
            s = s / jnp.sqrt(n)
        pooled.append(s)
    return jnp.concatenate(pooled, axis=-1)


@register_op("fusion_seqpool_cvm_concat")
def fusion_seqpool_cvm_concat(inputs, lengths=None, use_cvm=True,
                              pooltype="SUM"):
    """ref fused/fusion_seqpool_cvm_concat_op.cc — seq-pool + CVM transform
    + concat (the Baidu CTR ingest chain)."""
    from paddle_tpu.ops.tail import continuous_value_model
    outs = []
    for x in inputs:
        s = fusion_seqpool_concat([x], lengths, pooltype=pooltype)
        outs.append(continuous_value_model(s, use_cvm=use_cvm))
    return jnp.concatenate(outs, axis=-1)


@register_op("fusion_seqexpand_concat_fc")
def fusion_seqexpand_concat_fc(seq_input, static_inputs, w, bias=None,
                               act="relu"):
    """ref fused/fusion_seqexpand_concat_fc_op.cc — broadcast per-batch
    static features along the sequence, concat with the sequence input,
    one fc + activation. seq_input: [B, T, D0]; static: list of [B, Di]."""
    b, t, _ = seq_input.shape
    parts = [seq_input] + [jnp.broadcast_to(s[:, None, :], (b, t, s.shape[-1]))
                           for s in static_inputs]
    h = jnp.concatenate(parts, axis=-1) @ w
    if bias is not None:
        h = h + bias
    return _act(act, h)


@register_op("fusion_seqconv_eltadd_relu")
def fusion_seqconv_eltadd_relu(x, w, b, context_length, context_start=None,
                               lengths=None):
    """ref fused/fusion_seqconv_eltadd_relu_op.cc —
    relu(sequence_conv(x) + b). x: [B, T, D] padded; w:
    [context_length*D, out]; same window math as ops.sequence.sequence_conv
    (which takes a RaggedBatch)."""
    start = (-((context_length - 1) // 2) if context_start is None
             else context_start)
    B, T, D = x.shape
    if lengths is None:
        lengths = jnp.full((B,), T, jnp.int32)
    mask = jnp.arange(T)[None, :] < lengths[:, None]
    xm = jnp.where(mask[..., None], x, 0.0)
    cols = []
    for k in range(context_length):
        off = start + k
        shifted = jnp.roll(xm, -off, axis=1)
        pos = jnp.arange(T) + off
        valid = (pos >= 0)[None, :] & (pos[None, :] < lengths[:, None])
        cols.append(jnp.where(valid[..., None], shifted, 0.0))
    ctx = jnp.concatenate(cols, axis=-1)
    return jnp.maximum(ctx @ w + b, 0.0)


@register_op("conv_fusion")
def conv_fusion(x, weight, bias=None, residual=None, stride=1, padding=0,
                dilation=1, groups=1, activation="relu",
                data_format="NCHW"):
    """ref fused/conv_fusion_op.cc (cudnnConvolutionBiasActivationForward):
    activation(conv(x, w) + bias + residual)."""
    from paddle_tpu.ops.nn import conv2d
    out = conv2d(x, weight, bias, stride, padding, dilation, groups,
                 data_format=data_format)
    if residual is not None:
        out = out + residual
    return _act(None if activation == "identity" else activation, out)


@register_op("fused_embedding_fc_lstm")
def fused_embedding_fc_lstm(ids, embeddings, h0, c0, w_hh, bias=None,
                            lengths=None):
    """ref fused/fused_embedding_fc_lstm_op.cc — the embedding lookup and
    the LSTM input projection are pre-fused: `embeddings` is the table
    ALREADY multiplied by the input weight ([V, 4H], the op's rearranged
    WeightX@Embeddings input), so the lookup IS the x-projection."""
    from paddle_tpu.ops.rnn import lstm
    xproj = jnp.take(embeddings, ids, axis=0)          # [B, T, 4H]
    ident = jnp.eye(xproj.shape[-1], dtype=xproj.dtype)
    return lstm(xproj, h0, c0, ident, w_hh, b=bias, lengths=lengths)


def register_fused_aliases():
    """Name aliases for fused ops whose base op already covers the fused
    semantics exactly (the hand-fused CPU kernels of the same math)."""
    from paddle_tpu.ops.tail import _alias
    for name, target in (
            ("fusion_gru", "gru"),
            ("fusion_lstm", "lstm"),
            ("fusion_conv_inception", "conv_fusion"),
            ("multihead_matmul", "multihead_attention")):
        _alias(name, target)
