"""Profiling — trace collection + op-level annotation.

Ref: /root/reference/paddle/fluid/platform/profiler.h:81 (RAII RecordEvent
around every op run), :166 EnableProfiler/DisableProfiler with sorted event
tables, CUPTI DeviceTracer → chrome-trace (device_tracer.cc, tools/
timeline.py), and the Python context manager
python/paddle/fluid/profiler.py.

TPU-first: jax.profiler (XPlane) replaces CUPTI — traces open in
TensorBoard/Perfetto; `record_event` maps to TraceAnnotation so framework-
level scopes show up inside device traces; a light host-side EventRecorder
keeps the reference's sorted-table text report.
"""

import contextlib
import os
import time
from collections import defaultdict

import jax

from paddle_tpu.core.flags import get_flag


@contextlib.contextmanager
def profiler(output_dir=None):
    """ref: fluid.profiler.profiler context manager — wraps a region,
    writes a TensorBoard/Perfetto trace."""
    out = output_dir or get_flag("profiler_dir")
    jax.profiler.start_trace(out)
    try:
        yield out
    finally:
        jax.profiler.stop_trace()


def record_event(name):
    """RAII op annotation (ref: platform/profiler.h:81 RecordEvent).
    Shows up as a named range in the XPlane trace."""
    return jax.profiler.TraceAnnotation(name)


def span(name):
    """record_event promoted: the registry-backed span
    (observability/spans.py) — times the scope into the global
    EventRecorder table AND a metrics histogram AND the device trace.
    Lazy import: spans.py imports this module for EventRecorder."""
    from paddle_tpu.observability.spans import span as _span
    return _span(name)


def annotate_fn(name):
    def deco(fn):
        def wrapped(*a, **kw):
            with jax.profiler.TraceAnnotation(name):
                return fn(*a, **kw)
        return wrapped
    return deco


class EventRecorder:
    """Host-side timing table (ref: profiler.cc event tables printed by
    DisableProfiler). Times python-visible spans (incl. dispatch+block).

    This is the recorder behind observability.span(); `add()` is the
    non-context entry those spans feed, `reset()` starts a fresh epoch
    (state is otherwise append-forever), and summary/report carry
    p50/p95 alongside min/max — the tail is where step-time regressions
    live."""

    def __init__(self):
        self._events = defaultdict(list)

    @contextlib.contextmanager
    def record(self, name):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.add(name, time.perf_counter() - t0)

    def add(self, name, seconds):
        """Record one externally-timed occurrence of `name`."""
        self._events[name].append(seconds)

    def reset(self):
        """Drop all recorded events (ref: ResetProfiler)."""
        self._events.clear()

    @staticmethod
    def _pctl(sorted_times, q):
        idx = (len(sorted_times) - 1) * q
        lo, hi = int(idx), min(int(idx) + 1, len(sorted_times) - 1)
        frac = idx - lo
        return sorted_times[lo] * (1.0 - frac) + sorted_times[hi] * frac

    def summary(self, sort_by="total"):
        rows = []
        for name, times in self._events.items():
            ts = sorted(times)
            rows.append({
                "name": name, "calls": len(times),
                "total_s": sum(times),
                "avg_ms": 1e3 * sum(times) / len(times),
                "min_ms": 1e3 * ts[0], "max_ms": 1e3 * ts[-1],
                "p50_ms": 1e3 * self._pctl(ts, 0.50),
                "p95_ms": 1e3 * self._pctl(ts, 0.95),
            })
        rows.sort(key=lambda r: -r["total_s"])
        return rows

    def report(self):
        lines = [f"{'Event':<40}{'Calls':>8}{'Total(s)':>12}{'Avg(ms)':>12}"
                 f"{'p50(ms)':>12}{'p95(ms)':>12}"
                 f"{'Min(ms)':>12}{'Max(ms)':>12}"]
        for r in self.summary():
            lines.append(f"{r['name']:<40}{r['calls']:>8}{r['total_s']:>12.4f}"
                         f"{r['avg_ms']:>12.3f}{r['p50_ms']:>12.3f}"
                         f"{r['p95_ms']:>12.3f}{r['min_ms']:>12.3f}"
                         f"{r['max_ms']:>12.3f}")
        return "\n".join(lines)


def trace_op_table(trace_dir, device_filter="TPU", top=30, steps=1):
    """Aggregate a jax.profiler trace into a per-op duration table.

    Ref: the reference's EnableProfiler/DisableProfiler sorted event tables
    (platform/profiler.h:166, profiler.cc) and tools/timeline.py — here the
    source is the XPlane chrome-trace JSON that jax.profiler writes.

    trace_dir: the directory passed to jax.profiler.trace / pt.profiler.
    device_filter: substring of the process/device lane name to aggregate
    ("TPU" for device ops; "CPU" on the host platform; None = every
    lane, including events whose pid has no process_name metadata —
    some XPlane exports name only a subset of lanes).
    steps: divide totals by this to report per-step time.

    Returns a list of {"name", "total_us", "per_step_us", "count"} sorted
    by time, truncated to `top` (None = all).
    """
    import collections
    import glob
    import gzip
    import json

    files = sorted(glob.glob(os.path.join(
        trace_dir, "plugins/profile/*/*.trace.json.gz")))
    if not files:
        raise FileNotFoundError(
            f"no trace.json.gz under {trace_dir}/plugins/profile/")
    with gzip.open(files[-1]) as f:
        data = json.load(f)
    ev = data.get("traceEvents", [])
    # metadata events may carry no "args" dict at all (observed in real
    # XPlane exports) — e.get("args", {}) instead of e["args"], and a
    # lane without a pid key is simply unnamed
    lanes = {e.get("pid"): e.get("args", {}).get("name", "")
             for e in ev if e.get("ph") == "M"
             and e.get("name") == "process_name"}
    dur = collections.Counter()
    cnt = collections.Counter()
    for e in ev:
        if e.get("ph") != "X" or "name" not in e:
            continue
        if device_filter is not None:
            # events whose pid never got a process_name lane fall
            # through as "" — they match only an empty/None filter
            if device_filter not in lanes.get(e.get("pid"), ""):
                continue
        dur[e["name"]] += e.get("dur", 0)
        cnt[e["name"]] += 1
    rows = [{"name": n, "total_us": d, "per_step_us": d / max(steps, 1),
             "count": cnt[n]} for n, d in dur.most_common(top)]
    return rows


def print_op_table(trace_dir, **kw):
    """Human-readable twin of trace_op_table (the reference's profiler
    report print)."""
    rows = trace_op_table(trace_dir, **kw)
    width = max((len(r["name"]) for r in rows), default=10)
    print(f"{'op':<{width}}  {'total_us':>12}  {'per_step':>10}  {'count':>6}")
    for r in rows:
        print(f"{r['name']:<{width}}  {r['total_us']:>12.0f}  "
              f"{r['per_step_us']:>10.1f}  {r['count']:>6d}")
    return rows
