"""Profiling — trace collection + op-level annotation.

Ref: /root/reference/paddle/fluid/platform/profiler.h:81 (RAII RecordEvent
around every op run), :166 EnableProfiler/DisableProfiler with sorted event
tables, CUPTI DeviceTracer → chrome-trace (device_tracer.cc, tools/
timeline.py), and the Python context manager
python/paddle/fluid/profiler.py.

TPU-first: jax.profiler (XPlane) replaces CUPTI — traces open in
TensorBoard/Perfetto; `record_event` maps to TraceAnnotation so framework-
level scopes show up inside device traces; a light host-side EventRecorder
keeps the reference's sorted-table text report.
"""

import contextlib
import time
from collections import defaultdict

import jax

from paddle_tpu.core.flags import get_flag


@contextlib.contextmanager
def profiler(output_dir=None):
    """ref: fluid.profiler.profiler context manager — wraps a region,
    writes a TensorBoard/Perfetto trace."""
    out = output_dir or get_flag("profiler_dir")
    jax.profiler.start_trace(out)
    try:
        yield out
    finally:
        jax.profiler.stop_trace()


def record_event(name):
    """RAII op annotation (ref: platform/profiler.h:81 RecordEvent).
    Shows up as a named range in the XPlane trace."""
    return jax.profiler.TraceAnnotation(name)


def annotate_fn(name):
    def deco(fn):
        def wrapped(*a, **kw):
            with jax.profiler.TraceAnnotation(name):
                return fn(*a, **kw)
        return wrapped
    return deco


class EventRecorder:
    """Host-side timing table (ref: profiler.cc event tables printed by
    DisableProfiler). Times python-visible spans (incl. dispatch+block)."""

    def __init__(self):
        self._events = defaultdict(list)

    @contextlib.contextmanager
    def record(self, name):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self._events[name].append(time.perf_counter() - t0)

    def summary(self, sort_by="total"):
        rows = []
        for name, times in self._events.items():
            rows.append({
                "name": name, "calls": len(times),
                "total_s": sum(times),
                "avg_ms": 1e3 * sum(times) / len(times),
                "min_ms": 1e3 * min(times), "max_ms": 1e3 * max(times),
            })
        rows.sort(key=lambda r: -r["total_s"])
        return rows

    def report(self):
        lines = [f"{'Event':<40}{'Calls':>8}{'Total(s)':>12}{'Avg(ms)':>12}"
                 f"{'Min(ms)':>12}{'Max(ms)':>12}"]
        for r in self.summary():
            lines.append(f"{r['name']:<40}{r['calls']:>8}{r['total_s']:>12.4f}"
                         f"{r['avg_ms']:>12.3f}{r['min_ms']:>12.3f}"
                         f"{r['max_ms']:>12.3f}")
        return "\n".join(lines)
