"""Data loading with device prefetch.

Ref: /root/reference/python/paddle/fluid/reader.py:73 (DataLoader.
from_generator), :298 GeneratorLoader feeding a C++
LoDTensorBlockingQueue (pybind.cc:893), and the double-buffer device
prefetch reader (operators/reader/create_double_buffer_reader_op.cc).

TPU-first: a background thread pulls host batches and `device_put`s them
ahead of consumption (depth = reader_queue_size flag) — same double-buffer
overlap, no C++ queue needed since PJRT transfers are async. Under a mesh,
batches go straight to their data-parallel sharding.
"""

import collections
import queue
import threading

import jax
import numpy as np

from paddle_tpu.core.flags import get_flag


class DataLoader:
    """Iterable loader over a sample generator with batching + prefetch.

    from_generator mirrors the reference API: feed a python generator of
    numpy samples (tuples), get device-resident batches.
    """

    def __init__(self, batch_reader, prefetch=None, mesh=None,
                 sharding_axis="dp"):
        self._batch_reader = batch_reader
        self._prefetch = prefetch or get_flag("reader_queue_size")
        self._mesh = mesh
        self._axis = sharding_axis

    @staticmethod
    def from_generator(generator=None, batch_size=None, shuffle=False,
                       shuffle_buffer=1024, seed=0, mesh=None, prefetch=None,
                       drop_last=True):
        """Build from a per-sample generator fn (ref: reader.py
        DataLoader.from_generator + set_sample_generator)."""
        def batch_reader():
            rng = np.random.RandomState(seed)
            buf = []
            pool = []
            it = generator()
            for sample in it:
                if shuffle:
                    pool.append(sample)
                    if len(pool) >= shuffle_buffer:
                        idx = rng.randint(len(pool))
                        buf.append(pool.pop(idx))
                else:
                    buf.append(sample)
                if len(buf) == batch_size:
                    yield _collate(buf)
                    buf = []
            while pool:
                idx = rng.randint(len(pool)) if shuffle else 0
                buf.append(pool.pop(idx))
                if len(buf) == batch_size:
                    yield _collate(buf)
                    buf = []
            if buf and not drop_last:
                yield _collate(buf)

        return DataLoader(batch_reader, mesh=mesh, prefetch=prefetch)

    @staticmethod
    def from_batch_generator(generator, mesh=None, prefetch=None):
        """ref: reader.py set_batch_generator"""
        return DataLoader(generator, mesh=mesh, prefetch=prefetch)

    def _place(self, batch):
        if self._mesh is not None:
            from paddle_tpu.parallel.api import shard_batch
            return shard_batch(self._mesh, batch, self._axis)
        return jax.tree_util.tree_map(jax.device_put, batch)

    def place(self, batch):
        """Stage one host batch onto the device(s) through the loader's
        placement path (mesh sharding when configured, plain async
        device_put otherwise). The serving engine uses this at submit()
        time so prompt bytes are already in flight before admission —
        PJRT transfers are async, so this returns immediately and the
        decode step never blocks on host I/O."""
        return self._place(batch)

    def __iter__(self):
        q = queue.Queue(maxsize=self._prefetch)
        stop = object()
        cancelled = threading.Event()
        err = []

        def worker():
            try:
                for batch in self._batch_reader():
                    placed = self._place(batch)
                    # bounded put that notices consumer cancellation, so an
                    # early `break` in the consumer can't leave this thread
                    # blocked holding device buffers
                    while not cancelled.is_set():
                        try:
                            q.put(placed, timeout=0.1)
                            break
                        except queue.Full:
                            continue
                    if cancelled.is_set():
                        return
            except Exception as e:  # propagate to consumer
                err.append(e)
            finally:
                # same cancellable retry as data batches — dropping the
                # sentinel when the queue is momentarily full would leave
                # the consumer blocked on q.get() forever
                while not cancelled.is_set():
                    try:
                        q.put(stop, timeout=0.1)
                        break
                    except queue.Full:
                        continue

        t = threading.Thread(target=worker, daemon=True)
        t.start()
        try:
            while True:
                item = q.get()
                if item is stop:
                    break
                yield item
        finally:
            cancelled.set()
            while not q.empty():
                try:
                    q.get_nowait()
                except queue.Empty:
                    break
            t.join(timeout=5)
        if err:
            raise err[0]


def _collate(samples):
    """Stack a list of tuple-samples into batched numpy arrays."""
    if isinstance(samples[0], (tuple, list)):
        return tuple(np.stack([np.asarray(s[i]) for s in samples])
                     for i in range(len(samples[0])))
    if isinstance(samples[0], dict):
        return {k: np.stack([np.asarray(s[k]) for s in samples])
                for k in samples[0]}
    return np.stack([np.asarray(s) for s in samples])


def batch(reader, batch_size, drop_last=True):
    """Compose a sample reader into a batch reader (ref:
    python/paddle/batch.py)."""
    def batch_reader():
        buf = []
        for sample in reader():
            buf.append(sample)
            if len(buf) == batch_size:
                yield _collate(buf)
                buf = []
        if buf and not drop_last:
            yield _collate(buf)
    return batch_reader


def shuffle(reader, buf_size, seed=None):
    """ref: paddle.reader.shuffle decorator"""
    def shuffled():
        rng = np.random.RandomState(seed)
        pool = []
        for s in reader():
            pool.append(s)
            if len(pool) >= buf_size:
                rng.shuffle(pool)
                yield from pool
                pool = []
        rng.shuffle(pool)
        yield from pool
    return shuffled
