"""Offline dataset-format parsers — the reusable half of the reference's
builtin dataset corpus.

Ref: /root/reference/python/paddle/dataset/{mnist,cifar,imdb,imikolov}.py.
The reference modules pair a downloader with a parser; this sandbox has no
egress, so only the parsers ship here (VERDICT r4 "What's missing" #2):
point them at files you already have and they yield the same sample
streams the reference readers produce, ready for InMemoryDataset /
DataLoader / FileDataset.

Formats covered:
  * IDX (MNIST images/labels; big-endian, magic-typed, optional .gz) —
    ref mnist.py:41 reader_creator's struct walk.
  * CIFAR python pickle batches inside a .tar.gz — ref cifar.py:48.
  * Tokenized text corpora with frequency-cutoff dictionaries
    (<unk>/<s>/<e> conventions) — ref imdb.py:59 / imikolov.py:54.
"""

import collections
import gzip
import pickle
import string
import tarfile

import numpy as np

__all__ = [
    "read_idx", "mnist_reader", "cifar_reader", "tokenize_text",
    "build_dict", "corpus_reader", "ngram_reader",
]

# IDX dtype codes (the format's own table; mnist.py relies on 0x08 only)
_IDX_DTYPES = {
    0x08: np.uint8, 0x09: np.int8, 0x0B: np.int16,
    0x0C: np.int32, 0x0D: np.float32, 0x0E: np.float64,
}


def _read_maybe_gzip(path):
    with open(path, "rb") as f:
        raw = f.read()
    if raw[:2] == b"\x1f\x8b":
        raw = gzip.decompress(raw)
    return raw


def read_idx(path):
    """Parse one IDX file (optionally gzipped) into an ndarray.

    Ref mnist.py:41 — the reference inlines this struct walk for the two
    MNIST layouts; this is the general form: magic = 0x0000 | dtype |
    ndim, then ndim big-endian uint32 dims, then row-major payload.
    """
    buf = _read_maybe_gzip(path)
    if len(buf) < 4 or buf[0] != 0 or buf[1] != 0:
        raise ValueError(f"{path}: not an IDX file (bad magic)")
    dt_code, ndim = buf[2], buf[3]
    if dt_code not in _IDX_DTYPES:
        raise ValueError(f"{path}: unknown IDX dtype 0x{dt_code:02x}")
    dtype = np.dtype(_IDX_DTYPES[dt_code]).newbyteorder(">")
    head = 4 + 4 * ndim
    if len(buf) < head:
        raise ValueError(f"{path}: IDX header truncated")
    dims = [int.from_bytes(buf[4 + 4 * i:8 + 4 * i], "big")
            for i in range(ndim)]
    n = int(np.prod(dims)) if dims else 1
    if len(buf) - head < n * dtype.itemsize:
        raise ValueError(f"{path}: IDX payload truncated "
                         f"({len(buf) - head} of {n * dtype.itemsize} "
                         "bytes)")
    return np.frombuffer(buf, dtype, count=n, offset=head).reshape(dims)


def mnist_reader(image_path, label_path):
    """Yield (image[784] float32 in [-1, 1], label int) pairs.

    Ref mnist.py:41 reader_creator — same normalization
    (x / 255 * 2 - 1) and flat-image convention the book examples feed.
    """
    images = read_idx(image_path)
    labels = read_idx(label_path)
    if images.ndim != 3 or labels.ndim != 1:
        raise ValueError("expected idx3 images + idx1 labels")
    if images.shape[0] != labels.shape[0]:
        raise ValueError(
            f"image/label count mismatch: {images.shape[0]} vs "
            f"{labels.shape[0]}")
    # keep the uint8 array; normalize per sample at yield time (4x less
    # resident memory than materializing the float32 copy up front)
    flat = images.reshape(images.shape[0], -1)

    def reader():
        for x, y in zip(flat, labels):
            yield x.astype(np.float32) / 255.0 * 2.0 - 1.0, int(y)

    return reader


def cifar_reader(tar_path, sub_name):
    """Yield (image[3072] float32 in [0, 1], label int) from a CIFAR
    python-pickle tarball.

    Ref cifar.py:48 reader_creator — same member filter (`sub_name in
    name`, e.g. "data_batch" / "test_batch" / "train"), same bytes-keyed
    pickle protocol, same labels-or-fine_labels fallback and /255 scale.
    """
    def reader():
        with tarfile.open(tar_path, mode="r") as f:
            names = [m.name for m in f if sub_name in m.name]
            for name in sorted(names):
                batch = pickle.load(f.extractfile(name), encoding="bytes")
                data = batch[b"data"]
                labels = batch.get(b"labels",
                                   batch.get(b"fine_labels"))
                if labels is None:
                    raise ValueError(f"{tar_path}:{name}: no labels")
                for sample, label in zip(data, labels):
                    yield (np.asarray(sample, np.float32) / 255.0,
                           int(label))

    return reader


def tokenize_text(path):
    """Yield one token list per line: punctuation stripped, lowercased,
    whitespace-split (ref imdb.py:39 tokenize — same ad-hoc rule, applied
    per line of a local file instead of per tar member)."""
    table = str.maketrans("", "", string.punctuation)
    with open(path, "r", encoding="utf-8") as f:
        for line in f:
            toks = line.rstrip("\n\r").translate(table).lower().split()
            if toks:
                yield toks


def build_dict(paths, cutoff=0, markers=False):
    """word -> id over the corpus, most-frequent-first, ties broken
    alphabetically; words with freq <= cutoff dropped; '<unk>' appended
    last (ref imdb.py:59). markers=True also counts '<s>'/'<e>' once per
    line, the imikolov.py:54 LM convention."""
    freq = collections.defaultdict(int)
    for p in paths:
        for toks in tokenize_text(p):
            for w in toks:
                freq[w] += 1
            if markers:
                freq["<s>"] += 1
                freq["<e>"] += 1
    freq.pop("<unk>", None)
    kept = [kv for kv in freq.items() if kv[1] > cutoff]
    kept.sort(key=lambda kv: (-kv[1], kv[0]))
    word_idx = {w: i for i, (w, _) in enumerate(kept)}
    word_idx["<unk>"] = len(word_idx)
    return word_idx


def corpus_reader(paths, word_idx, label=None):
    """Yield id-sequences (or (ids, label) when label is not None) —
    ref imdb.py:79 reader_creator with the pos/neg tar patterns replaced
    by explicit file lists."""
    unk = word_idx["<unk>"]

    def reader():
        for p in paths:
            for toks in tokenize_text(p):
                ids = [word_idx.get(w, unk) for w in toks]
                yield ids if label is None else (ids, label)

    return reader


def ngram_reader(paths, word_idx, n):
    """Sliding n-gram windows over '<s>' + line + '<e>' — ref
    imikolov.py:92 (the word-embedding book example's feed)."""
    unk = word_idx["<unk>"]

    def reader():
        for p in paths:
            for toks in tokenize_text(p):
                l = ["<s>"] + toks + ["<e>"]
                if len(l) < n:
                    continue
                ids = [word_idx.get(w, unk) for w in l]
                for i in range(n, len(ids) + 1):
                    yield tuple(ids[i - n:i])

    return reader
