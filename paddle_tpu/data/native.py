"""ctypes binding for the native data pipeline (csrc/dataio).

Ref: /root/reference/paddle/fluid/framework/data_feed.cc — the reference's
C++ reader threads feed channels consumed by device workers; pybind exposes
the queues (pybind.cc:893 LoDTensorBlockingQueue). Here the native library
exposes a C ABI consumed via ctypes — record files stream through C++ reader
threads into a bounded ring, off the GIL.

Build: cd csrc && cmake -B build -G Ninja && ninja -C build
"""

import ctypes
import os

import numpy as np

_LIB = None


def _find_lib():
    here = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    cands = [
        os.path.join(here, "csrc", "build", "libptdataio.so"),
        os.environ.get("PT_DATAIO_LIB", ""),
    ]
    for c in cands:
        if c and os.path.exists(c):
            return c
    return None


def available():
    return _find_lib() is not None


def _lib():
    global _LIB
    if _LIB is None:
        path = _find_lib()
        if path is None:
            raise RuntimeError(
                "libptdataio.so not built; run: cd csrc && cmake -B build "
                "-G Ninja && ninja -C build")
        lib = ctypes.CDLL(path)
        lib.ptdio_create.restype = ctypes.c_void_p
        lib.ptdio_create.argtypes = [ctypes.c_uint64]
        lib.ptdio_add_file.restype = ctypes.c_int
        lib.ptdio_add_file.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        lib.ptdio_start.restype = ctypes.c_int
        lib.ptdio_start.argtypes = [ctypes.c_void_p, ctypes.c_int,
                                    ctypes.c_int, ctypes.c_uint64]
        lib.ptdio_next.restype = ctypes.c_int64
        lib.ptdio_next.argtypes = [ctypes.c_void_p,
                                   ctypes.POINTER(ctypes.c_uint8),
                                   ctypes.c_uint64]
        lib.ptdio_destroy.restype = None
        lib.ptdio_destroy.argtypes = [ctypes.c_void_p]
        lib.ptdio_write_records.restype = ctypes.c_int
        lib.ptdio_write_records.argtypes = [
            ctypes.c_char_p, ctypes.POINTER(ctypes.c_uint8),
            ctypes.POINTER(ctypes.c_uint64), ctypes.c_uint64]
        _LIB = lib
    return _LIB


def write_record_file(path, records):
    """Write a list of bytes objects as a record file."""
    lib = _lib()
    blob = b"".join(records)
    lens = (ctypes.c_uint64 * len(records))(*[len(r) for r in records])
    buf = (ctypes.c_uint8 * len(blob)).from_buffer_copy(blob)
    rc = lib.ptdio_write_records(path.encode(), buf, lens, len(records))
    if rc != 0:
        raise IOError(f"cannot write {path}")


class NativeRecordReader:
    """Iterate records from files via the C++ threaded pipeline.

    ref: MultiSlotDataFeed file→channel flow (data_feed.cc); use
    `num_threads` readers and a bounded `capacity` ring.
    """

    def __init__(self, files, num_threads=2, epochs=1, capacity=1024,
                 shuffle_seed=0, max_record_bytes=1 << 22):
        lib = _lib()
        self._lib = lib
        self._h = lib.ptdio_create(capacity)
        for f in files:
            if lib.ptdio_add_file(self._h, f.encode()) != 0:
                lib.ptdio_destroy(self._h)
                self._h = None
                raise IOError(f"cannot open {f}")
        rc = lib.ptdio_start(self._h, num_threads, epochs, shuffle_seed)
        if rc != 0:
            lib.ptdio_destroy(self._h)
            self._h = None
            raise RuntimeError("ptdio_start failed (no files?)")
        self._buf = (ctypes.c_uint8 * max_record_bytes)()
        self._cap = max_record_bytes

    def __iter__(self):
        return self

    def __next__(self):
        n = self._lib.ptdio_next(self._h, self._buf, self._cap)
        if n == -2:
            raise StopIteration
        if n < 0:
            raise IOError("native reader error (record too large or bad file)")
        return bytes(bytearray(self._buf[:n]))

    def close(self):
        if self._h is not None:
            self._lib.ptdio_destroy(self._h)
            self._h = None

    def __del__(self):
        self.close()


def numpy_records(arrays):
    """Pack a tuple-of-ndarrays sample into one record (npz-free fast path)."""
    import io as _io
    buf = _io.BytesIO()
    np.savez(buf, *arrays)
    return buf.getvalue()


def unpack_numpy_record(rec):
    import io as _io
    with np.load(_io.BytesIO(rec)) as z:
        return tuple(z[k] for k in z.files)
