"""In-memory datasets + synthetic data for tests/benchmarks.

Ref: /root/reference/python/paddle/fluid/dataset.py (InMemoryDataset /
QueueDataset for PS training over files) and python/paddle/dataset/* builtin
dataset loaders. Here: a light InMemoryDataset with global-shuffle semantics
plus synthetic generators used by tests and bench.py (no network egress).
"""

import numpy as np


class InMemoryDataset:
    """ref: dataset.py InMemoryDataset — load → (global) shuffle → iterate.
    The reference shuffles via fleet RPC across trainers; here shuffling is
    host-local per process, and multi-host global shuffle is done by seeding
    identically and partitioning by rank (ref: data_set.cc global_shuffle)."""

    def __init__(self, samples=None):
        self._samples = list(samples) if samples is not None else []

    def load(self, samples):
        self._samples.extend(samples)

    def global_shuffle(self, seed=0, rank=0, world=1):
        rng = np.random.RandomState(seed)
        idx = rng.permutation(len(self._samples))
        part = idx[rank::world]
        self._samples = [self._samples[i] for i in part]
        return self

    def reader(self):
        def r():
            yield from self._samples
        return r

    def readers(self, n):
        """n shard readers (round-robin) for multi-threaded ingestion
        (ref data_feed.cc: one DataFeed per DeviceWorker thread)."""
        m = max(n, 1)

        def make(i):
            def r():
                yield from self._samples[i::m]
            return r
        return [make(i) for i in range(m)]

    def __len__(self):
        return len(self._samples)


def synthetic_images(n, shape=(3, 32, 32), num_classes=10, seed=0):
    """CIFAR-like synthetic stream (tests/bench; the reference's book tests
    download CIFAR — zero-egress here)."""
    rng = np.random.RandomState(seed)
    for _ in range(n):
        yield (rng.rand(*shape).astype(np.float32),
               rng.randint(num_classes, size=(1,)).astype(np.int64))


def synthetic_mnist(n, seed=0):
    rng = np.random.RandomState(seed)
    for _ in range(n):
        yield (rng.rand(1, 28, 28).astype(np.float32),
               rng.randint(10, size=(1,)).astype(np.int64))


def synthetic_tokens(n, seq_len=128, vocab=30522, seed=0):
    """BERT-like token stream."""
    rng = np.random.RandomState(seed)
    for _ in range(n):
        ids = rng.randint(vocab, size=(seq_len,)).astype(np.int32)
        yield (ids,)


def synthetic_ctr(n, num_sparse=26, num_dense=13, vocab=10000, seed=0):
    """Criteo-like CTR stream for DeepFM/Wide&Deep (ref: dist_ctr.py
    fixture)."""
    rng = np.random.RandomState(seed)
    for _ in range(n):
        dense = rng.rand(num_dense).astype(np.float32)
        sparse = rng.randint(vocab, size=(num_sparse,)).astype(np.int32)
        label = rng.randint(2, size=(1,)).astype(np.float32)
        yield (dense, sparse, label)


class FileDataset:
    """File-backed dataset over the native (C++) record reader — the
    DataFeed/Dataset successor for real file ingestion (ref data_feed.cc
    MultiSlotDataFeed reading file lists into channels; dataset.py
    QueueDataset).

    samples are numpy-record blobs (data/native.numpy_records); readers(n)
    shards the FILE LIST across ingestion threads like the reference
    assigns filelists to DataFeed instances.
    """

    def __init__(self, files, num_threads=2, decode=None):
        from paddle_tpu.core.enforce import enforce
        from paddle_tpu.data import native
        enforce(len(list(files)) > 0, "FileDataset needs at least one file")
        self._native = native
        self.files = list(files)
        self.num_threads = num_threads
        self.decode = decode or native.unpack_numpy_record

    def _read(self, files, num_threads):
        # remote (gs://-like) entries are staged to the local cache at
        # read time — the C++ reader needs real POSIX paths (ref fs.cc's
        # download-to-tmp pattern); local paths pass through untouched.
        # Shards download CONCURRENTLY (num_threads-wide, matching the
        # reader's own parallelism) so first-record latency is bounded by
        # the largest shard, not the sum.
        from paddle_tpu.io import fs as _fs
        if any(_fs.split_scheme(f)[0] is not None for f in files):
            from concurrent.futures import ThreadPoolExecutor
            with ThreadPoolExecutor(max_workers=max(num_threads, 1)) as ex:
                files = list(ex.map(_fs.ensure_local, files))
        rd = self._native.NativeRecordReader(files, num_threads=num_threads)
        try:
            for rec in rd:
                yield self.decode(rec)
        finally:
            rd.close()  # release C++ reader threads + ring on any exit

    def reader(self):
        return lambda: self._read(self.files, self.num_threads)

    def readers(self, n):
        """min(n, len(files)) shard readers; each shard's native reader
        uses `num_threads` internal threads (total native threads =
        shards x num_threads)."""
        m = max(min(n, len(self.files)), 1)
        return [
            (lambda i=i: self._read(self.files[i::m], self.num_threads))
            for i in range(m)
        ]
