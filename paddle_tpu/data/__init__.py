"""Data pipeline (ref: python/paddle/fluid/reader.py, dataset.py,
framework/data_feed.cc)."""

from paddle_tpu.data.loader import DataLoader, batch, shuffle
from paddle_tpu.data.dataset import (
    FileDataset, InMemoryDataset,
    synthetic_ctr,
    synthetic_images,
    synthetic_mnist,
    synthetic_tokens,
)
from paddle_tpu.data import formats
from paddle_tpu.data.formats import (
    build_dict,
    cifar_reader,
    corpus_reader,
    mnist_reader,
    ngram_reader,
    read_idx,
    tokenize_text,
)


def py_reader(feed_list=None, capacity=8, **kw):
    """ref layers/io.py py_reader — compat shim: the TPU-era reader is
    DataLoader.from_generator (background prefetch = the reference's
    double_buffer + py_reader pipeline)."""
    raise NotImplementedError(
        "py_reader's graph-variable contract does not exist here; use "
        "pt.data.DataLoader.from_generator(generator, batch_size) — it "
        "covers py_reader + double_buffer (background device prefetch)")


def double_buffer(reader, **kw):
    """ref layers/io.py double_buffer — DataLoader already stages batch
    t+1 while t computes; this is the identity on our readers."""
    return reader
