"""Data pipeline (ref: python/paddle/fluid/reader.py, dataset.py,
framework/data_feed.cc)."""

from paddle_tpu.data.loader import DataLoader, batch, shuffle
from paddle_tpu.data.dataset import (
    FileDataset, InMemoryDataset,
    synthetic_ctr,
    synthetic_images,
    synthetic_mnist,
    synthetic_tokens,
)
