"""Content-hashed, refcounted prefix page cache for the serving engine.

Shared prompt prefixes (system prompts, few-shot headers) hash to the
same leading KV pages, so admitting a request whose prefix was already
prefilled should map those pages read-only into the new slot's page
table instead of recomputing them. This module is the host-side index
that makes that safe:

  * Chain keys — page i of a prompt is keyed by
    ``page_key(key_{i-1}, tokens[i*ps:(i+1)*ps])``, a rolling hash over
    the WHOLE prefix, so two prompts share page i only when they agree
    on every token up to and including it. Only full pages are ever
    cached; a partial trailing page is always private.
  * Collision verification — each entry stores the page's token content
    and ``match()`` compares it against the probe. A hash collision
    (astronomically unlikely with sha256, but injectable via the
    ``serve.prefix_cache`` fault point and monkeypatchable through
    ``page_key``) therefore degrades to a miss, never to corrupt K/V.
  * Refcounts — an entry's refcount is the number of live slots whose
    page table maps it. The engine never writes into a page with
    refcount > 0 owned by the cache (copy-on-write diverges first), so
    shared pages are immutable while mapped.
  * LRU-by-refcount-zero eviction — a released entry stays cached
    (refcount 0) so the next same-prefix admission still hits; when the
    engine needs a page and the free list is dry it evicts the
    least-recently-released refcount-zero entry. ``max_idle_pages``
    (the ``serve_prefix_pages`` flag; 0 = bounded only by the pool)
    additionally trims idle retention eagerly on release.

The cache holds page IDS only — the page *content* lives in the paged
KV pools (ops/attention.py); page ids are common across layers, so ONE
cache serves every layer's pool. Quantized pools (serve_kv_dtype=int8)
need no extra handling here: the per-row scales live pool-side, keyed
by the same page ids, so a shared or copy-on-write page carries its
scales wherever its id is mapped. All methods are plain host work; the
engine calls them under its request-table lock.
"""

import hashlib

_ROOT_KEY = b"paddle-tpu/prefix-root"


def page_key(parent_key, tokens):
    """Rolling chain key for one full page: hashes the parent page's key
    plus this page's token content, so the key commits to the entire
    prefix. Module-level so tests can monkeypatch it to force
    collisions."""
    h = hashlib.sha256()
    h.update(parent_key)
    h.update(b"|")
    h.update(",".join(str(int(t)) for t in tokens).encode())
    return h.digest()


class _Entry:
    __slots__ = ("page", "tokens", "refs", "tick")

    def __init__(self, page, tokens, refs, tick):
        self.page = page
        self.tokens = tokens
        self.refs = refs
        self.tick = tick


class PrefixCache:
    """Refcounted chain-hash index from full prompt pages to KV page ids."""

    def __init__(self, page_size, max_idle_pages=0):
        self.page_size = int(page_size)
        self.max_idle_pages = int(max_idle_pages)
        self._entries = {}     # chain key -> _Entry
        self._by_page = {}     # page id -> chain key
        self._tick = 0         # LRU clock (bumped on release-to-idle)
        self.hits = 0          # full pages served from the cache
        self.misses = 0        # full probe pages not in the cache
        self.collisions = 0    # key present but token content mismatched
        self.evictions = 0

    def __len__(self):
        return len(self._entries)

    def keys_for(self, tokens):
        """[(chain_key, page_tokens)] for each FULL page of `tokens`."""
        ps = self.page_size
        out = []
        parent = _ROOT_KEY
        for i in range(len(tokens) // ps):
            content = tuple(int(t) for t in tokens[i * ps:(i + 1) * ps])
            parent = page_key(parent, content)
            out.append((parent, content))
        return out

    def match(self, tokens, cap):
        """Longest cached run of leading full pages of `tokens`, bounded
        so at most `cap` tokens are treated as already-prefilled (the
        engine passes total-1: the final position must still be
        prefilled to produce first-token logits). Returns
        ``(page_ids, matched_tokens)``; the last page is included even
        when only partially covered by `cap` — the engine copy-on-writes
        it before use. Takes NO references: call acquire() on the pages
        actually mapped."""
        pages, matched = [], 0
        probed = 0
        for key, content in self.keys_for(tokens):
            probed += 1
            ent = self._entries.get(key)
            if ent is None:
                break
            if ent.tokens != content:
                self.collisions += 1   # verified mismatch -> miss
                break
            if matched >= cap:
                break
            pages.append(ent.page)
            matched = min(matched + self.page_size, cap)
        self.hits += len(pages)
        self.misses += len(tokens) // self.page_size - len(pages)
        return pages, matched

    def lookup_depth(self, tokens):
        """Number of leading full pages of `tokens` present (verified) in
        the cache — the fleet router's affinity probe. Read-only: no
        refcounts, no LRU touch, no hit/miss accounting."""
        depth = 0
        for key, content in self.keys_for(tokens):
            ent = self._entries.get(key)
            if ent is None or ent.tokens != content:
                break
            depth += 1
        return depth

    def acquire(self, pages):
        """Take one reference per page id in `pages` (pages just mapped
        into a slot's table by a match)."""
        for pid in pages:
            self._entries[self._by_page[int(pid)]].refs += 1

    def release(self, pages):
        """Drop one reference per page id. Entries hitting refcount zero
        stay cached (LRU-recent) unless `max_idle_pages` forces a trim.
        Returns the page ids the cache no longer owns — the engine must
        put those back on its free list. Ids the cache does not know
        (cleared meanwhile) are returned as free too."""
        freed = []
        for pid in pages:
            pid = int(pid)
            key = self._by_page.get(pid)
            if key is None:
                freed.append(pid)
                continue
            ent = self._entries[key]
            ent.refs -= 1
            if ent.refs <= 0:
                ent.refs = 0
                self._tick += 1
                ent.tick = self._tick
        if self.max_idle_pages:
            while self.evictable() > self.max_idle_pages:
                freed.extend(self.evict(1))
        return freed

    def insert(self, tokens, row_pages):
        """Register the full pages of a just-prefilled prompt, whose
        page-table row maps them to `row_pages` (index order). Ownership
        of newly-registered pages moves to the cache (refcount 1 — the
        inserting slot maps them); the engine moves those ids from the
        request's private list to its shared list. A page whose key is
        already cached under the SAME id was shared by match() — skipped.
        A key cached under a DIFFERENT id means this row holds a private
        duplicate (copy-on-write divergence or a degraded match): stop
        there so the shared run stays a contiguous row prefix. Returns
        the newly-owned page ids."""
        out = []
        for (key, content), pid in zip(self.keys_for(tokens), row_pages):
            pid = int(pid)
            ent = self._entries.get(key)
            if ent is not None:
                if ent.page == pid:
                    continue
                break
            self._tick += 1
            self._entries[key] = _Entry(pid, content, 1, self._tick)
            self._by_page[pid] = key
            out.append(pid)
        return out

    def evictable(self):
        """How many cached pages could be evicted right now (refcount 0)."""
        return sum(1 for e in self._entries.values() if e.refs == 0)

    def evict(self, n=1):
        """Evict up to `n` least-recently-released refcount-zero entries;
        returns their page ids (now engine-owned)."""
        idle = sorted((e.tick, k) for k, e in self._entries.items()
                      if e.refs == 0)
        out = []
        for _, key in idle[:n]:
            ent = self._entries.pop(key)
            del self._by_page[ent.page]
            self.evictions += 1
            out.append(ent.page)
        return out

    def pages_shared(self):
        """Cached pages currently mapped by at least one slot (the
        serve.pages_shared gauge)."""
        return sum(1 for e in self._entries.values() if e.refs > 0)

    def clear(self):
        """Forget everything — crash recovery rebuilds the device pools,
        so every cached page id points at zeroed K/V. The engine resets
        its free list wholesale alongside this."""
        self._entries.clear()
        self._by_page.clear()
