"""Continuous-batching serving engine over the paged KV cache.

Architecture (the three serving invariants):

  * ONE jitted decode step, fixed slot count, donated page pools — its
    shapes never depend on which requests are live, so admissions,
    completions and ragged lengths never retrace it (`decode_traces`
    counts trace-time entries; tools/compile_smoke.py asserts == 1
    across admission waves).
  * Paged KV memory — requests own pages, not a [B, Tmax] rectangle.
    A finished request frees its pages between steps; an admitted one
    takes pages for its prompt and grows one page at a time as it
    decodes. The page table / length / active arrays are tiny host
    numpy state, re-fed to the step each call (values change, shapes
    don't).
  * Prefill-on-admit — a second fixed-shape jit (prompts padded to
    `prefill_len`) runs once per admission, writes the prompt K/V into
    the request's pages and samples the first token, so time-to-first-
    token is one forward, not `prompt_len` decode steps.

Telemetry (PR-4 registry): serve.queue_depth / serve.active_slots
gauges, serve.ttft_s + serve.token_latency_s histograms, serve.tokens +
serve.requests{status} + serve.page_stalls counters; optional per-step
RunLog records (`ServeConfig.run_log`) that tools/run_report.py renders.

Live observability plane (this layer's serving half):

  * per-request lifecycle traces — every request carries a trace id and
    emits timestamped RunLog events (`submitted`, `admitted`,
    `prefill_done`, `first_token`, `preempted`, `resumed`,
    `retired{reason}`). Pure host work (a clock read + a JSONL append at
    request-rate, not token-rate): no device sync is added to the decode
    hot path, asserted by a flush-spy test. `tools/run_report.py
    --serve` reconstructs per-slot timelines from these events.
  * SLO/goodput accounting — `ServeConfig.slo_ttft_s` /
    `slo_token_latency_s` (flag-resolvable) classify every retirement;
    `serve.goodput` (gauge: fraction of retired requests inside every
    SLO) and `serve.slo_violations{kind}` are the objective function the
    ROADMAP's SLO-aware scheduler optimizes.
  * `jit.retraces{fn=serve.decode|serve.prefill}` — the traced-once
    invariant as a counter: any steady-state recompile is visible to
    the watchdog and /metrics, not just to compile-smoke tests.
  * `ServeConfig.metrics_port` starts the /metrics exporter
    (observability/exporter.py) for the run; `ServeConfig.watchdog`
    attaches the anomaly watchdog (observability/watchdog.py).

Resilience layer (degraded conditions produce degraded service, never
lost requests — terminal statuses: done | rejected | shed | cancelled |
failed):

  * chunked prefill — prompts up to max_len are admitted as
    ceil(len / prefill_len) calls of the ONE prefill trace
    (GPTDecoder.paged_prefill_chunk), page tables grown per chunk; the
    long-prompt rejection class is gone (`serve_chunked_prefill` flag).
  * bounded admission — submit() takes optional deadline_s / priority;
    the `serve_queue_limit` flag bounds the queue, and over-limit or
    infeasible-deadline submissions get a terminal `rejected` status
    with `req.retriable = True` (back off and resubmit). Admission picks
    highest-priority / earliest-deadline first; the pool-deadlock
    preemption victim becomes lowest-priority / latest-deadline (the
    old youngest-first order is the all-defaults special case).
  * crash-isolated step recovery — `fault_point("serve.prefill")` /
    `fault_point("serve.step")` hooks plus an exception barrier around
    both jitted calls: on failure the engine quarantines device state
    (page pools are donated, hence poisoned), rebuilds them, and
    re-admits every in-flight request recompute-style — the host-side
    prompt + generated tokens are the durable state, so a recovered
    greedy request finishes token-exact. Bounded by a RetryPolicy
    budget (`serve_step_retries` consecutive failures, then the
    engine fails every request and re-raises). A runtime Pallas decode
    failure additionally latches a permanent per-process XLA fallback
    through the shared pallas.fallback wiring.
  * watchdog mitigation — goodput_collapse / ingest_stall anomalies
    invoke the engine's load-shedding action: expired-deadline queued
    requests are shed first, else the single lowest-priority one
    (terminal `shed` status, serve.shed{cause}).
"""

import collections
import dataclasses
import itertools
import threading
import time
import typing
import uuid

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.core.enforce import enforce
from paddle_tpu.core.flags import get_flag, set_flags
from paddle_tpu.observability import flight as _flight
from paddle_tpu.observability import metrics as _metrics
from paddle_tpu.testing.chaos import fault_point


@dataclasses.dataclass
class ServeConfig:
    num_slots: int = None        # None -> serve_slots flag
    page_size: int = None        # None -> serve_page_size flag
    max_len: int = 256           # per-request cap: prompt + generated
    prefill_len: int = 64        # padded admission prompt length (fixed)
    num_pages: int = None        # None -> num_slots * ceil(max_len/page)
    cache_dtype: typing.Any = jnp.float32
    kv_dtype: typing.Any = None  # None -> serve_kv_dtype flag; jnp.int8
    #                              stores paged K/V quantized (per-token
    #                              symmetric scales ride the page pool)
    temperature: float = 0.0     # 0 = greedy; >0 samples per step
    top_k: int = None            # default per-request top-k (None -> flag)
    top_p: float = None          # default per-request top-p (None -> flag)
    seed: int = 0
    prefix_cache: bool = None    # None -> flag serve_prefix_cache
    prefix_pages: int = None     # None -> flag serve_prefix_pages
    #                              (max idle cached pages; 0 = pool-bounded)
    eos_id: int = None           # default EOS (submit() can override)
    default_max_new: int = 32
    run_log: str = None          # per-step RunLog JSONL path
    prefetch: int = None         # host->device staging depth (None->flag)
    slo_ttft_s: float = None     # None -> flag; 0 = unbounded
    slo_token_latency_s: float = None   # None -> flag; 0 = unbounded
    metrics_port: int = None     # None -> flag metrics_port; 0 = off
    watchdog: object = None      # None -> flag; True or WatchdogConfig
    queue_limit: int = None      # None -> flag serve_queue_limit; 0 = off
    default_deadline_s: float = None   # None -> flag; 0 = none
    step_retries: int = None     # None -> flag serve_step_retries
    chunked_prefill: bool = None  # None -> flag serve_chunked_prefill
    model_version: str = None    # "model_id@version" identity tag the
    #                              fleet router stamps on a replica's
    #                              engine; surfaces in slo_stats() and
    #                              trace records (per-version SLO plane)
    # speculative decoding: a draft model proposes spec_k tokens per
    # active slot per round; ONE jitted verify step scores every window
    # position against the paged cache and the engine emits the
    # accepted prefix + one target token — token-identical to the plain
    # path by construction (emitted tokens are always the target's own
    # per-position samples under the fold(seed, count) keys)
    draft: bool = None           # None -> serve_draft flag
    spec_k: int = None           # None -> serve_spec_k flag
    draft_spec: typing.Any = None   # GPTConfig of the draft model;
    #                                 None + draft=True = self-draft
    #                                 (draft == target: the plumbing
    #                                 probe with ~100% acceptance)
    draft_variables: typing.Any = None  # draft weights ({"params": ...})
    draft_checkpoint: str = None  # else: restore newest step from this
    #                               CheckpointManager path

    def resolve(self):
        if self.num_slots is None:
            self.num_slots = get_flag("serve_slots")
        if self.page_size is None:
            self.page_size = get_flag("serve_page_size")
        if self.slo_ttft_s is None:
            self.slo_ttft_s = get_flag("slo_ttft_s")
        if self.slo_token_latency_s is None:
            self.slo_token_latency_s = get_flag("slo_token_latency_s")
        if self.queue_limit is None:
            self.queue_limit = int(get_flag("serve_queue_limit"))
        if self.default_deadline_s is None:
            self.default_deadline_s = float(
                get_flag("serve_default_deadline_s"))
        if self.step_retries is None:
            self.step_retries = int(get_flag("serve_step_retries"))
        if self.chunked_prefill is None:
            self.chunked_prefill = bool(get_flag("serve_chunked_prefill"))
        if self.top_k is None:
            self.top_k = int(get_flag("serve_top_k"))
        if self.top_p is None:
            self.top_p = float(get_flag("serve_top_p"))
        if self.prefix_cache is None:
            self.prefix_cache = bool(get_flag("serve_prefix_cache"))
        if self.prefix_pages is None:
            self.prefix_pages = int(get_flag("serve_prefix_pages"))
        if self.kv_dtype is None:
            f = str(get_flag("serve_kv_dtype")).lower()
            if f == "int8":
                self.kv_dtype = jnp.int8
            elif f not in ("", "f32", "float32"):
                raise ValueError(f"serve_kv_dtype={f!r}: expected "
                                 "'int8' or ''/'f32'")
        elif self.kv_dtype in ("", "f32", "float32"):
            self.kv_dtype = None       # explicit f32 = the plain pool
        elif isinstance(self.kv_dtype, str):
            self.kv_dtype = jnp.dtype(self.kv_dtype).type
        if self.draft is None:
            self.draft = bool(get_flag("serve_draft"))
        if self.draft_spec is not None or self.draft_checkpoint:
            self.draft = True    # an explicit draft model implies draft
        if self.spec_k is None:
            self.spec_k = int(get_flag("serve_spec_k"))
        if self.draft:
            enforce(self.spec_k >= 1,
                    f"serve_spec_k={self.spec_k}: speculative decoding "
                    "needs at least one draft proposal per round")
        pages_per_slot = -(-self.max_len // self.page_size)
        if self.num_pages is None:
            self.num_pages = self.num_slots * pages_per_slot
        enforce(self.prefill_len <= self.max_len,
                "prefill_len must not exceed max_len")
        enforce(self.num_pages >= pages_per_slot,
                f"num_pages={self.num_pages} cannot hold even one "
                f"max_len={self.max_len} request "
                f"({pages_per_slot} pages of {self.page_size}) — the "
                "preemption guarantee needs a lone request to fit")
        return self


@dataclasses.dataclass
class Request:
    id: int
    prompt: np.ndarray            # true (unpadded) prompt, int32 [L]
    max_new: int
    eos_id: int = None
    tokens: list = dataclasses.field(default_factory=list)
    status: str = "queued"        # queued -> running -> terminal (done |
    #                               rejected | shed | cancelled | failed)
    slot: int = None
    pages: list = dataclasses.field(default_factory=list)
    # prefix-cache pages mapped read-only into the slot's table; ALWAYS a
    # contiguous row prefix: table row = shared_pages ++ pages
    shared_pages: list = dataclasses.field(default_factory=list)
    temperature: float = 0.0      # per-request sampling (set at submit)
    top_k: int = 0
    top_p: float = 0.0
    seed: int = 0                 # per-request PRNG seed: token i of this
    #                               request samples with fold(seed, i), so
    #                               replay after preemption / recovery /
    #                               re-route re-draws identically
    submit_t: float = None
    first_token_t: float = None
    done_t: float = None
    device_prompt: typing.Any = None   # staged [1, Lp] chunks (async put)
    trace_id: str = None          # lifecycle trace id: engine-run-scoped
    #                               when minted here, fleet-durable when
    #                               adopt() received a router context
    span_id: str = None           # this hop's span in the fleet trace
    parent_span_id: str = None    # the causal parent hop (None = root)
    trace: list = dataclasses.field(default_factory=list)  # (event, t)
    preemptions: int = 0
    retire_reason: str = None     # "eos"|"length" or the terminal cause
    slo_ok: bool = None           # every configured SLO met at retire
    priority: int = 0             # higher admits first, evicts last
    deadline_t: float = None      # absolute clock() deadline, or None
    retriable: bool = False       # rejected-but-worth-resubmitting hint
    recoveries: int = 0           # times re-admitted after a step crash
    spec_tokens: int = 0          # tokens this request gained beyond
    #                               one-per-target-step (accepted draft
    #                               proposals) — the per-request
    #                               speculative-vs-plain accounting

    @property
    def output(self):
        """prompt + generated tokens (the generate()-shaped sequence)."""
        return np.concatenate([self.prompt,
                               np.asarray(self.tokens, np.int32)])


class ServingEngine:
    """submit()/step()/drain() continuous batching for a GPTDecoder."""

    def __init__(self, model, variables, config=None, clock=time.perf_counter):
        self.cfg = (config or ServeConfig()).resolve()
        cfg = self.cfg
        self._model = model
        self._params = variables["params"]
        self.version = cfg.model_version
        self._clock = clock
        self._pages_per_slot = -(-cfg.max_len // cfg.page_size)
        self._caches = model.init_paged_caches(
            cfg.num_pages, cfg.page_size, dtype=cfg.cache_dtype,
            kv_dtype=cfg.kv_dtype)
        # speculative decoding: the draft model keeps its OWN page
        # pools, built with the SAME page count/size so one page table
        # indexes both (draft pages and target pages for a slot live at
        # identical pool indices)
        self._spec_on = bool(cfg.draft)
        self._draft_model = None
        self._draft_params = None
        self._draft_caches = None
        if self._spec_on:
            self._draft_model, self._draft_params = self._resolve_draft()
            self._draft_caches = self._draft_model.init_paged_caches(
                cfg.num_pages, cfg.page_size, dtype=cfg.cache_dtype,
                kv_dtype=cfg.kv_dtype)

        s = cfg.num_slots
        self._page_table = np.zeros((s, self._pages_per_slot), np.int32)
        self._lengths = np.zeros(s, np.int32)
        self._active = np.zeros(s, bool)
        self._last_tokens = np.zeros(s, np.int32)
        self._free_slots = list(range(s))
        self._free_pages = collections.deque(range(cfg.num_pages))
        # per-slot sampling state: traced [slots] VALUES of the one
        # decode jit (updated on admit, never retrace axes)
        self._temps = np.zeros(s, np.float32)
        self._top_ks = np.zeros(s, np.int32)
        self._top_ps = np.zeros(s, np.float32)
        self._seeds = np.zeros(s, np.uint32)
        self._gen_counts = np.zeros(s, np.int32)
        from paddle_tpu.serving.prefix_cache import PrefixCache
        # refcounted content-hash index over the page pool; None = off
        self._prefix_cache = (      # graft-guard: self._lock
            PrefixCache(cfg.page_size, max_idle_pages=cfg.prefix_pages)
            if cfg.prefix_cache else None)
        self.prefill_tokens_skipped = 0   # prompt tokens never prefilled
        #                                   (covered by prefix-cache hits)
        # One reentrant lock guards the request tables: clients may
        # submit()/cancel() from their own threads while step()/drain()
        # run elsewhere, and the watchdog's anomaly callback re-enters
        # shed_queued() from under a step already holding the lock.
        self._lock = threading.RLock()
        self._queue = collections.deque()   # graft-guard: self._lock
        self._running = {}                  # graft-guard: self._lock
        self.requests = {}   # id -> Request; graft-guard: self._lock
        self._ids = itertools.count()
        self._step_no = 0
        self._base_key = jax.random.key(cfg.seed)
        self.decode_traces = 0
        self.prefill_traces = 0
        self.draft_traces = 0
        self.draft_prefill_traces = 0
        self.verify_traces = 0
        self.spec_proposed = 0        # draft tokens offered to verify
        self.spec_accepted = 0        # proposals the target confirmed
        self.spec_rollbacks = 0       # proposals rejected (length edit)
        self.spec_rounds = 0          # speculative rounds run
        self.spec_slot_rounds = 0     # per-slot round participations
        #                               (denominator of the per-slot
        #                               tokens-per-target-step win)
        self.target_steps = 0         # target-model steps (decode OR
        #                               verify) — tokens/target_steps is
        #                               the speculation win
        self.recoveries = 0           # step crashes recovered (engine-wide)
        self._trace_credit = 0        # legitimate re-traces (jit rebuild
        #                               after a latched Pallas fallback)
        from paddle_tpu.core.retry import RetryBudget, RetryPolicy
        self._retry_budget = RetryBudget(
            RetryPolicy(max_attempts=cfg.step_retries + 1), "serve.step")

        # host->device prompt staging reuses the DataLoader placement path
        # (async device_put; depth knob = the reader_queue_size flag), so
        # admission never pays the transfer inside step()
        from paddle_tpu.data.loader import DataLoader
        self._stager = DataLoader(None, prefetch=cfg.prefetch)

        self.anomaly_sink = None      # fleet router watchdog uplink
        self.replica = None           # fleet replica index; stamps every
        #                               trace event once the router sets it
        self._run_log = None
        self._own_run_log = False
        if cfg.run_log:
            if isinstance(cfg.run_log, str):
                from paddle_tpu.observability.runlog import RunLog
                self._run_log = RunLog(cfg.run_log)
                self._own_run_log = True
            else:                      # an already-open RunLog (bench.py)
                self._run_log = cfg.run_log
        if self._run_log is not None:
            # wall/monotonic anchor: the fleet-trace merge rebases this
            # log's perf_counter event times onto the wall clock with it
            from paddle_tpu.observability import trace as _trace
            _trace.write_anchor(self._run_log,
                                model_version=cfg.model_version)

        # live observability plane: preregister the serve metric family
        # (so /metrics advertises HELP/TYPE before any traffic), SLO
        # tallies, the optional exporter, and the anomaly watchdog
        from paddle_tpu.observability import catalog as _catalog
        _catalog.preregister([
            "serve.queue_depth", "serve.active_slots", "serve.ttft_s",
            "serve.token_latency_s", "serve.tokens", "serve.requests",
            "serve.page_stalls", "serve.preemptions", "serve.goodput",
            "serve.slo_violations", "serve.recoveries", "serve.shed",
            "serve.prefix_hits", "serve.prefix_misses",
            "serve.cow_copies", "serve.pages_shared",
            "serve.kv_quant_pages", "serve.spec_proposed",
            "serve.spec_accepted", "serve.spec_rollbacks",
            "jit.retraces"])
        self._retired = 0
        self._retired_ok = 0
        self._viol_base = dict(
            _metrics.counter("serve.slo_violations").snapshot())
        self._trace_run = uuid.uuid4().hex[:8]
        self._aot_trace = False
        from paddle_tpu.observability.exporter import start_metrics_server
        self._metrics_server = start_metrics_server(cfg.metrics_port)
        from paddle_tpu.observability.watchdog import maybe_watchdog
        self._watchdog = maybe_watchdog(cfg.watchdog,
                                        run_log=self._run_log,
                                        action=self._on_anomaly)

        base_key = self._base_key

        def _sample(logits, temps, top_ks, top_ps, seeds, counts):
            """Per-request masked sampling, one trace for every mix of
            greedy / temperature / top-k / top-p rows. logits [B, V];
            the knobs are traced [B] VALUES (batch-size-shaped, so
            admissions never retrace). Row b's key is
            fold(fold(base, seeds[b]), counts[b]) — counts[b] is how
            many tokens request b has generated, so token i of a
            request always draws with the same key, making sampled
            replay (preemption / recovery / re-route) deterministic.
            temperature == 0 rows take jnp.argmax, bit-exact with the
            pre-sampling greedy path."""
            greedy = jnp.argmax(logits, -1).astype(jnp.int32)
            v = logits.shape[-1]
            scaled = (logits.astype(jnp.float32)
                      / jnp.maximum(temps, 1e-6)[:, None])
            desc = -jnp.sort(-scaled, axis=-1)              # descending
            k_eff = jnp.where(top_ks > 0,
                              jnp.minimum(top_ks, v), v).astype(jnp.int32)
            kth = jnp.take_along_axis(desc, (k_eff - 1)[:, None], axis=1)
            probs = jax.nn.softmax(desc, axis=-1)
            cum = jnp.cumsum(probs, axis=-1)
            p_eff = jnp.where((top_ps > 0.0) & (top_ps < 1.0),
                              top_ps.astype(jnp.float32), 1.0)
            # smallest set of top rows whose mass reaches p (the nucleus
            # always keeps at least the argmax row)
            n_keep = jnp.maximum(
                jnp.sum((cum - probs) < p_eff[:, None], axis=-1), 1)
            pth = jnp.take_along_axis(desc, (n_keep - 1)[:, None], axis=1)
            masked = jnp.where((scaled >= kth) & (scaled >= pth),
                               scaled, -1e30)

            def row_key(s, c):
                return jax.random.fold_in(
                    jax.random.fold_in(base_key, s), c)

            keys = jax.vmap(row_key)(seeds, counts)
            drawn = jax.vmap(jax.random.categorical)(keys, masked)
            return jnp.where(temps > 0.0, drawn.astype(jnp.int32), greedy)

        self._sample = _sample
        self._build_jits()

    def _resolve_draft(self):
        """(draft model, draft params). No draft_spec = self-draft (the
        target model drafts for itself — ~100% acceptance, the plumbing
        and determinism probe). With a draft_spec, weights come from
        cfg.draft_variables, else the newest step under
        cfg.draft_checkpoint (the checkpoint manager's verified-restore
        path), else a deterministic seeded init."""
        cfg = self.cfg
        if cfg.draft_spec is None:
            return self._model, self._params
        from paddle_tpu.models.gpt import GPTDecoder
        draft = GPTDecoder(cfg.draft_spec)
        variables = cfg.draft_variables
        if variables is None and cfg.draft_checkpoint:
            from paddle_tpu.io.checkpoint import CheckpointManager
            template = draft.init(jax.random.key(cfg.seed))
            state, step = CheckpointManager(
                cfg.draft_checkpoint).restore(template)
            enforce(state is not None,
                    f"draft_checkpoint={cfg.draft_checkpoint!r} holds "
                    "no restorable step")
            variables = state
        if variables is None:
            variables = draft.init(jax.random.key(cfg.seed))
        return draft, variables["params"]

    def _build_jits(self):
        """(Re)create the two jitted closures. Called once at
        construction and again when a recovery latches the Pallas->XLA
        decode fallback (the flag is read at trace time, so a fresh jit
        cache is the only way to honor the flip); `_trace_credit`
        absorbs those deliberate re-traces so they don't count as
        `jit.retraces`."""
        model = self._model
        _sample = self._sample

        def _count_trace(attr, fn):
            n = getattr(self, attr) + 1
            setattr(self, attr, n)
            if n > 1 and not self._aot_trace:
                if self._trace_credit > 0:
                    self._trace_credit -= 1
                else:
                    # traced-once invariant broken in live serving —
                    # visible to /metrics and the watchdog, not just
                    # compile smokes
                    _metrics.counter("jit.retraces").inc(fn=fn)

        def decode(params, caches, tokens, page_table, lengths, active,
                   temps, top_ks, top_ps, seeds, counts):
            _count_trace("decode_traces", "serve.decode")

            def run(tok):
                logits, new_caches = model.paged_decode_step(
                    tok, caches, page_table, lengths, active)
                return _sample(logits, temps, top_ks, top_ps, seeds,
                               counts), new_caches

            return model.apply({"params": params, "state": {}}, tokens,
                               method=run)

        def prefill(params, caches, prompt, starts, lengths, page_rows,
                    floors, temps, top_ks, top_ps, seeds, counts):
            _count_trace("prefill_traces", "serve.prefill")

            def run(pr):
                logits, new_caches = model.paged_prefill_chunk(
                    pr, starts, lengths, caches, page_rows,
                    write_floor=floors)
                return _sample(logits, temps, top_ks, top_ps, seeds,
                               counts), new_caches

            return model.apply({"params": params, "state": {}}, prompt,
                               method=run)

        def copy_pages(caches, src, dst):
            # copy-on-write divergence: duplicate whole pages src -> dst
            # in every layer's pool ([1]-shaped ids -> one trace ever)
            from paddle_tpu.ops import attention as _att
            return [_att.copy_pages(pool, src, dst) for pool in caches]

        self._decode_jit = jax.jit(decode, donate_argnums=(1,))
        self._prefill_jit = jax.jit(prefill, donate_argnums=(1,))
        self._copy_jit = jax.jit(copy_pages, donate_argnums=(0,))

        if not self._spec_on:
            return
        draft_model = self._draft_model
        spec_w = self.cfg.spec_k + 1

        def draft_decode(params, caches, tokens, page_table, lengths,
                         active, temps, top_ks, top_ps, seeds, counts):
            # one draft proposal step: decode-shaped, called spec_k
            # times per round with lengths+i / counts+i — same shapes
            # every call, ONE trace
            _count_trace("draft_traces", "serve.draft")

            def run(tok):
                logits, new_caches = draft_model.paged_decode_step(
                    tok, caches, page_table, lengths, active)
                return _sample(logits, temps, top_ks, top_ps, seeds,
                               counts), new_caches

            return draft_model.apply({"params": params, "state": {}},
                                     tokens, method=run)

        def draft_prefill(params, caches, prompt, starts, lengths,
                          page_rows, floors):
            # admission-time draft cache fill (no sampling — only the
            # written K/V matters; the target's prefill emits the token)
            _count_trace("draft_prefill_traces", "serve.draft_prefill")

            def run(pr):
                _, new_caches = draft_model.paged_prefill_chunk(
                    pr, starts, lengths, caches, page_rows,
                    write_floor=floors)
                return new_caches

            return draft_model.apply({"params": params, "state": {}},
                                     prompt, method=run)

        def verify(params, caches, window, starts, win_lens, page_rows,
                   temps, top_ks, top_ps, seeds, counts):
            # ONE batched verify step: score every window position
            # against the paged cache (gathered-prefix chunk attention),
            # then sample position i with the SAME fold(seed, count+i)
            # key the plain path would use — emitted tokens are the
            # target's own draws, so speculation is token-exact by
            # construction. The head + sampling run per position:
            # temporaries stay [slots, V], never a dense
            # [slots, window, V] lattice.
            _count_trace("verify_traces", "serve.verify")

            def run(wt):
                hidden, new_caches = model.paged_verify_chunk(
                    wt, starts, win_lens, caches, page_rows)
                cols = [_sample(model.verify_head(hidden[:, i]), temps,
                                top_ks, top_ps, seeds, counts + i)
                        for i in range(spec_w)]
                return jnp.stack(cols, 1), new_caches

            return model.apply({"params": params, "state": {}}, window,
                               method=run)

        self._draft_jit = jax.jit(draft_decode, donate_argnums=(1,))
        self._draft_prefill_jit = jax.jit(draft_prefill,
                                          donate_argnums=(1,))
        self._draft_copy_jit = jax.jit(copy_pages, donate_argnums=(0,))
        self._verify_jit = jax.jit(verify, donate_argnums=(1,))

    # --- public API ---

    def submit(self, prompt, max_new=None, eos_id=None, deadline_s=None,
               priority=0, temperature=None, top_k=None, top_p=None,
               seed=None):
        """Queue a prompt; returns the request id. The padded prompt is
        staged host->device immediately (async), so admission inside a
        later step() issues no host transfer. Prompts longer than
        prefill_len stage as multiple fixed-shape chunks (chunked
        prefill).

        Bounded admission: `deadline_s` (None resolves the
        serve_default_deadline_s flag; 0 there means none) sets an
        absolute deadline — a queued request past it is shed, and a
        non-positive explicit value is rejected up front as infeasible.
        `priority` (higher first) orders admission and inverts the
        preemption victim choice. When the serve_queue_limit flag bounds
        the queue, over-limit submissions get a terminal `rejected`
        status with `req.retriable = True` instead of queueing — check
        `engine.requests[rid].status` after submit.

        Per-request sampling: `temperature` / `top_k` / `top_p` default
        to the ServeConfig values (themselves flag-resolvable) and ride
        per-slot traced arrays of the ONE decode trace — mixing greedy
        and sampled requests in a batch never retraces. `seed` pins the
        request's sampling stream (None derives one from cfg.seed and
        the request id); token i always draws with fold(seed, i), so a
        sampled request replays deterministically after preemption,
        recovery, or a fleet re-route."""
        cfg = self.cfg
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        max_new = max_new if max_new is not None else cfg.default_max_new
        cap = cfg.max_len if cfg.chunked_prefill else cfg.prefill_len
        enforce(1 <= prompt.size <= cap,
                f"prompt length {prompt.size} not in [1, {cap}] "
                + ("(max_len)" if cfg.chunked_prefill
                   else "(prefill_len; serve_chunked_prefill is off)"))
        enforce(prompt.size + max_new <= cfg.max_len,
                f"prompt {prompt.size} + max_new {max_new} exceeds "
                f"max_len {cfg.max_len}")
        with self._lock:
            req = Request(id=next(self._ids), prompt=prompt,
                          max_new=max_new,
                          eos_id=eos_id if eos_id is not None
                          else cfg.eos_id,
                          priority=int(priority))
            self._resolve_sampling(req, temperature, top_k, top_p, seed)
            req.trace_id = f"{self._trace_run}/{req.id}"
            self.requests[req.id] = req
            extra = {}
            if priority:
                extra["priority"] = int(priority)
            if deadline_s is not None:
                extra["deadline_s"] = float(deadline_s)
            req.submit_t = self._trace_event(req, "submitted",
                                             prompt_len=int(prompt.size),
                                             max_new=int(max_new), **extra)
            _metrics.counter("serve.requests").inc(status="submitted")
            if deadline_s is None and cfg.default_deadline_s > 0:
                deadline_s = cfg.default_deadline_s
            if deadline_s is not None:
                if deadline_s <= 0:
                    self._reject(req, "infeasible_deadline")
                    return req.id
                req.deadline_t = req.submit_t + float(deadline_s)
            if cfg.queue_limit and len(self._queue) >= cfg.queue_limit:
                self._reject(req, "queue_full")
                return req.id
            req.device_prompt = self._stage_chunks(prompt)
            self._queue.append(req)
            _metrics.gauge("serve.queue_depth").set(len(self._queue))
            return req.id

    def adopt(self, prompt, tokens=(), max_new=None, eos_id=None,
              priority=0, deadline_t=None, submit_t=None,
              first_token_t=None, origin="fleet", temperature=None,
              top_k=None, top_p=None, seed=None, trace=None):
        """Failover/dispatch entry for the fleet router: queue a request
        whose generation may already be `tokens` deep, preserving the
        caller's accounting clock — submit_t, first_token_t and the
        ABSOLUTE deadline_t survive verbatim, so TTFT/SLO classification
        lands on the engine that completes the request, not the one that
        first saw it. The full replay sequence (prompt + tokens) is
        staged exactly like a crash-recovery requeue: greedy adoption
        finishes token-exact. Bypasses the queue_limit bound — the
        router does its own dispatch bounding, and a failover re-route
        must never be rejected. Returns the request id.

        ``trace`` is the router-minted durable trace context (a
        TraceContext wire dict); when present the adopted request KEEPS
        the fleet trace id across the hop instead of re-minting an
        engine-run-scoped one, so one id covers the request's whole
        life across replicas."""
        cfg = self.cfg
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        tokens = [int(t) for t in tokens]
        max_new = max_new if max_new is not None else cfg.default_max_new
        cap = cfg.max_len if cfg.chunked_prefill else cfg.prefill_len
        enforce(1 <= prompt.size <= cap,
                f"prompt length {prompt.size} not in [1, {cap}]")
        enforce(prompt.size + max_new <= cfg.max_len,
                f"prompt {prompt.size} + max_new {max_new} exceeds "
                f"max_len {cfg.max_len}")
        enforce(len(tokens) <= max_new,
                f"adopted with {len(tokens)} tokens > max_new {max_new}")
        with self._lock:
            req = Request(id=next(self._ids), prompt=prompt,
                          max_new=max_new,
                          eos_id=eos_id if eos_id is not None
                          else cfg.eos_id,
                          priority=int(priority))
            self._resolve_sampling(req, temperature, top_k, top_p, seed)
            req.tokens = tokens
            req.deadline_t = deadline_t
            req.first_token_t = first_token_t
            from paddle_tpu.observability.trace import TraceContext
            ctx = TraceContext.from_wire(trace) if trace else None
            if ctx is not None:
                req.trace_id = ctx.trace_id
                req.span_id = ctx.span_id
                req.parent_span_id = ctx.parent_span_id
            else:          # legacy: no router context, engine-run scope
                req.trace_id = f"{self._trace_run}/{req.id}"
            self.requests[req.id] = req
            t = self._trace_event(req, "adopted", origin=origin,
                                  prompt_len=int(prompt.size),
                                  tokens_kept=len(tokens))
            req.submit_t = submit_t if submit_t is not None else t
            _metrics.counter("serve.requests").inc(status="adopted")
            req.device_prompt = self._stage_chunks(req.output if tokens
                                                   else prompt)
            self._queue.append(req)
            _metrics.gauge("serve.queue_depth").set(len(self._queue))
            return req.id

    def export_inflight(self):
        """Replica-side export of every non-terminal request's durable
        host state — the fleet router's failover mirror, refreshed each
        healthy round so a later kill replays token-exact from the last
        synced point. Host-only reads (no device sync): the prompt stays
        with the router, so entries carry ids, token mirrors, and the
        accounting clocks `adopt()` preserves."""
        out = []
        with self._lock:
            live = list(self._queue) + sorted(self._running.values(),
                                              key=lambda r: r.id)
            for req in live:
                out.append(dict(
                    rid=req.id, status=req.status,
                    tokens=list(req.tokens),
                    prompt_len=int(req.prompt.size),
                    priority=req.priority, submit_t=req.submit_t,
                    first_token_t=req.first_token_t,
                    deadline_t=req.deadline_t))
        return out

    def cancel(self, request_id):
        """Client-initiated cancellation: a first-class terminal status.
        A queued request leaves the queue; a running one frees its slot
        and pages immediately. Returns True if cancelled, False when the
        id is unknown or already terminal. Cancelled requests do not
        count against goodput (the client walked away; the engine did
        not fail them)."""
        with self._lock:
            req = self.requests.get(request_id)
            if req is None or req.status not in ("queued", "running"):
                return False
            if req.status == "queued":
                try:
                    self._queue.remove(req)
                except ValueError:
                    pass
            else:
                self._free_slot_state(req)
            self._retire_terminal(req, "cancelled", "cancelled",
                                  account=False)
            _metrics.gauge("serve.queue_depth").set(len(self._queue))
            _metrics.gauge("serve.active_slots").set(len(self._running))
            return True

    def step(self):
        """One scheduling round: free finished slots happened last round;
        admit queued prompts into free slots (prefill-on-admit), grow
        page tables where the next token opens a page, run ONE jitted
        decode step over all slots, and retire requests that hit EOS or
        their token budget. Returns the requests finished this round."""
        with self._lock:
            t0 = self._clock()
            finished = []
            self._shed_expired(finished)
            self._admit(finished)
            stalled = self._grow_pages()
            while stalled and not self._active.any():
                # pool deadlock: every live slot needs a fresh page and
                # none is free. Preempt the lowest-priority /
                # latest-deadline stalled request (free its pages,
                # requeue it for re-prefill) so higher-value work always
                # makes progress — with all-default requests this
                # reduces to the youngest. Greedy decoding regenerates
                # the dropped tokens exactly; sampled runs re-draw
                # (recompute preemption).
                victim = min((self._running[s] for s in stalled),
                             key=self._victim_key)
                self._preempt(victim)
                stalled = self._grow_pages()
            new_tokens = 0
            toks = None
            spec = None
            spec_proposed = spec_accepted = None
            if self._active.any():
                use_spec = self._spec_on
                if use_spec:
                    try:
                        fault_point("spec.verify")
                    except Exception:
                        # chaos degrade: this round runs as ONE plain
                        # decode step — token-exact either way (the
                        # emitted token follows the same sample law)
                        use_spec = False
                try:
                    fault_point("serve.step")
                    if use_spec:
                        spec = self._spec_round()
                    else:
                        toks_dev, self._caches = self._decode_jit(
                            self._params, self._caches, self._last_tokens,
                            self._page_table, self._lengths, self._active,
                            self._temps, self._top_ks, self._top_ps,
                            self._seeds, self._gen_counts)
                        toks = np.asarray(toks_dev)  # graft-lint: disable=hot-path-sync (the one deliberate sync per decode round: the python scheduler needs this step's tokens to advance/free slots)
                except Exception as e:
                    self._recover("serve.step", e)
            if spec is not None:
                # speculative round: per slot, accept the leading run of
                # draft proposals that match the target's own samples
                # and emit accepted + 1 tokens; rejection rollback is
                # the length simply advancing fewer positions than the
                # verify window wrote (stale KV/scale rows beyond the
                # accepted prefix are overwritten by later writes)
                self._retry_budget.success()
                self.spec_rounds += 1
                self.target_steps += 1
                dt = self._clock() - t0
                lat = _metrics.histogram("serve.token_latency_s")
                sampled, props, win = spec
                spec_proposed = spec_accepted = 0
                for slot, req in list(self._running.items()):
                    if not self._active[slot]:
                        continue               # page-stalled this round
                    w = int(win[slot])
                    self.spec_slot_rounds += 1
                    a = 0
                    while (a < w - 1
                           and int(props[slot, a]) == int(sampled[slot, a])):
                        a += 1
                    m = a + 1                  # tokens safe to emit
                    spec_proposed += w - 1
                    spec_accepted += a
                    emitted = 0
                    for j in range(m):
                        tok = int(sampled[slot, j])
                        self._lengths[slot] += 1   # its KV is cached
                        req.tokens.append(tok)
                        self._gen_counts[slot] += 1
                        self._last_tokens[slot] = tok
                        lat.observe(dt / m)
                        new_tokens += 1
                        emitted += 1
                        reason = self._done_reason(req, tok)
                        if reason:
                            self._release(req, finished, reason)
                            break
                    req.spec_tokens += max(0, emitted - 1)
                self.spec_proposed += spec_proposed
                self.spec_accepted += spec_accepted
                self.spec_rollbacks += spec_proposed - spec_accepted
                _metrics.counter("serve.spec_proposed").inc(spec_proposed)
                _metrics.counter("serve.spec_accepted").inc(spec_accepted)
                _metrics.counter("serve.spec_rollbacks").inc(
                    spec_proposed - spec_accepted)
            elif toks is not None:
                self._retry_budget.success()   # consecutive-failure reset
                self.target_steps += 1
                dt = self._clock() - t0
                lat = _metrics.histogram("serve.token_latency_s")
                for slot, req in list(self._running.items()):
                    if not self._active[slot]:
                        continue               # page-stalled this round
                    self._lengths[slot] += 1   # pending token now cached
                    tok = int(toks[slot])
                    req.tokens.append(tok)
                    self._gen_counts[slot] += 1  # next draw = fold(seed, i)
                    self._last_tokens[slot] = tok
                    lat.observe(dt)
                    new_tokens += 1
                    reason = self._done_reason(req, tok)
                    if reason:
                        self._release(req, finished, reason)
            _metrics.counter("serve.tokens").inc(new_tokens)
            _metrics.gauge("serve.active_slots").set(len(self._running))
            _metrics.gauge("serve.queue_depth").set(len(self._queue))
            if self.cfg.kv_dtype is not None:
                _metrics.gauge("serve.kv_quant_pages").set(
                    self.cfg.num_pages - len(self._free_pages))
            wall_s = self._clock() - t0
            if self._run_log is not None:
                rec = {
                    "phase": "serve", "step": self._step_no,
                    "wall_s": wall_s, "new_tokens": new_tokens,
                    "active": len(self._running),
                    "queue_depth": len(self._queue),
                    "goodput": round(self.goodput(), 4)}
                if spec_proposed is not None:
                    # speculative round: per-round acceptance so
                    # tools/run_report.py --serve can plot the
                    # acceptance-rate trajectory
                    rec["spec_proposed"] = spec_proposed
                    rec["spec_accepted"] = spec_accepted
                self._run_log.write(rec)
            if self._watchdog is not None:
                self._watchdog.tick(self._step_no, wall_s=wall_s,
                                    goodput=self.goodput(),
                                    retired=self._retired)
            self._step_no += 1
            return finished

    def drain(self, max_steps=100000):
        """Run step() until every submitted request finishes; returns the
        finished requests in completion order."""
        out = []
        # the lock is released between rounds so client threads can
        # still reach submit()/cancel() while the drain loop runs
        for _ in range(max_steps):
            with self._lock:
                more = bool(self._queue or self._running)
            if not more:
                break
            out.extend(self.step())
        else:
            with self._lock:
                queued, running = len(self._queue), len(self._running)
            raise RuntimeError(
                f"drain: {queued} queued / {running} "
                f"running requests left after {max_steps} steps")
        if self._run_log is not None:
            snap = _metrics.snapshot()
            self._run_log.write({"final": True, "phase": "serve",
                                 "kv_dtype": self.kv_dtype_name(),
                                 "kv_pool_bytes": self.kv_pool_bytes(),
                                 "counters": snap.get("counters", {}),
                                 "gauges": snap.get("gauges", {}),
                                 "slo": self.slo_stats()})
        return out

    def close(self):
        if self._metrics_server is not None:
            self._metrics_server.stop()
            self._metrics_server = None
        if self._run_log is not None and self._own_run_log:
            self._run_log.close()
        self._run_log = None

    def compiled_decode(self):
        """AOT-compile the decode step (one extra trace) and return the
        compiled executable — compile-smoke greps its HLO, bench prewarms
        with it."""
        cfg = self.cfg
        s = cfg.num_slots
        self._aot_trace = True    # a deliberate extra trace, not a retrace
        try:
            return self._decode_jit.lower(
                self._params, self._caches,
                np.zeros(s, np.int32), self._page_table,
                np.zeros(s, np.int32), np.zeros(s, bool),
                np.zeros(s, np.float32), np.zeros(s, np.int32),
                np.zeros(s, np.float32), np.zeros(s, np.uint32),
                np.zeros(s, np.int32)).compile()
        finally:
            self._aot_trace = False

    def compiled_verify(self):
        """AOT-compile the speculative verify step (one extra trace,
        absorbed like compiled_decode's) and return the compiled
        executable — compile-smoke greps its HLO for the no-dense-
        lattice and budget contracts."""
        enforce(self._spec_on, "compiled_verify() needs draft=True")
        cfg = self.cfg
        s, w = cfg.num_slots, cfg.spec_k + 1
        self._aot_trace = True    # a deliberate extra trace, not a retrace
        try:
            return self._verify_jit.lower(
                self._params, self._caches,
                np.zeros((s, w), np.int32), np.zeros(s, np.int32),
                np.zeros(s, np.int32), self._page_table,
                np.zeros(s, np.float32), np.zeros(s, np.int32),
                np.zeros(s, np.float32), np.zeros(s, np.uint32),
                np.zeros(s, np.int32)).compile()
        finally:
            self._aot_trace = False

    def spec_stats(self):
        """Speculation accounting for bench rows / reports. Per-slot
        semantics: in every round each active slot costs ONE target-model
        evaluation (decode or verify); a slot's speculative round emits
        1 + accepted tokens. tokens_per_target_step > 1.0 is the whole
        point of the feature."""
        prop, acc = self.spec_proposed, self.spec_accepted
        sr = self.spec_slot_rounds
        return {
            "enabled": self._spec_on,
            "spec_k": self.cfg.spec_k if self._spec_on else 0,
            "rounds": self.spec_rounds,
            "target_steps": self.target_steps,
            "proposed": prop,
            "accepted": acc,
            "rollbacks": self.spec_rollbacks,
            "acceptance_rate": round(acc / prop, 4) if prop else None,
            "tokens_per_target_step":
                round((sr + acc) / sr, 4) if sr else None}

    def export_decode(self, path):
        """Export ONE greedy serve step as a StableHLO / jax.export
        artifact through io.inference.save_train_program's
        state-feedback contract: state = (params, page pools) fed back
        output->input each iteration, batch = (tokens, page_table,
        lengths, active) — so the C++ predictor loop (csrc/) can run the
        continuous-batching decode with no Python at serve time (the
        host scheduler only rewrites the tiny page_table/lengths/active
        inputs between steps)."""
        from paddle_tpu.io.inference import save_train_program
        model = self._model
        cfg = self.cfg

        def step(state, tokens, page_table, lengths, active):
            params, caches = state

            def run(tok):
                logits, new_caches = model.paged_decode_step(
                    tok, caches, page_table, lengths, active)
                return jnp.argmax(logits, -1).astype(jnp.int32), \
                    new_caches

            nxt, new_caches = model.apply(
                {"params": params, "state": {}}, tokens, method=run)
            return nxt, (params, new_caches)

        example = (np.zeros(cfg.num_slots, np.int32), self._page_table,
                   np.zeros(cfg.num_slots, np.int32),
                   np.zeros(cfg.num_slots, bool))
        return save_train_program(path, step,
                                  (self._params, self._caches), example)

    def kv_dtype_name(self):
        """"int8" for a quantized page pool, else "f32" — the bench /
        report label for the serve_kv_dtype choice in effect."""
        return "int8" if self.cfg.kv_dtype is not None else "f32"

    def kv_pool_bytes(self):
        """Device bytes held by the paged KV pools across layers (value
        tensors plus, for quantized pools, their scale tensors) —
        shape/dtype metadata only, never a device sync."""
        return int(sum(arr.nbytes for pool in list(self._caches)
                       for arr in pool.values()))

    def goodput(self):
        """Fraction of retired requests that met every configured SLO
        (1.0 before the first retirement) — the SLO scheduler's
        objective."""
        return self._retired_ok / self._retired if self._retired else 1.0

    def slo_stats(self):
        """SLO accounting for bench rows / reports: goodput, targets,
        and violation counts since construction (or reset_stats)."""
        viol = _metrics.counter("serve.slo_violations").snapshot()
        delta = {k.split("=", 1)[1]: v - self._viol_base.get(k, 0)
                 for k, v in viol.items()}
        return {"goodput": round(self.goodput(), 4),
                "retired": self._retired,
                "version": self.version,
                "slo_ttft_s": self.cfg.slo_ttft_s or None,
                "slo_token_latency_s":
                    self.cfg.slo_token_latency_s or None,
                "violations": {"ttft": delta.get("ttft", 0),
                               "token_latency":
                                   delta.get("token_latency", 0)}}

    def reset_stats(self):
        """Zero the serve latency histograms, this engine's SLO tallies
        and its speculation counters (bench warmup isolation:
        compile-time TTFTs and warmup acceptance must not poison the
        timed window's row)."""
        for name in ("serve.ttft_s", "serve.token_latency_s"):
            h = _metrics.registry().get(name)
            if h is not None:
                h.reset()
        self.spec_proposed = self.spec_accepted = 0
        self.spec_rollbacks = self.spec_rounds = 0
        self.spec_slot_rounds = self.target_steps = 0
        self._retired = self._retired_ok = 0
        self._viol_base = dict(
            _metrics.counter("serve.slo_violations").snapshot())
        _metrics.gauge("serve.goodput").set(1.0)

    def latency_stats(self):
        """{"ttft_ms": {p50,p95,n}, "token_ms": {...}} from the registry
        histograms (the bench row's telemetry-backed percentiles)."""
        out = {}
        for name, hist in (("ttft_ms", "serve.ttft_s"),
                           ("token_ms", "serve.token_latency_s")):
            h = _metrics.registry().get(hist)
            st = h.stats() if h is not None else None
            if st:
                out[name] = {"p50": round(st["p50"] * 1e3, 3),
                             "p95": round(st["p95"] * 1e3, 3),
                             "n": st["count"]}
        return out

    # --- scheduling internals ---

    def _trace_event(self, req, event, **extra):
        """One lifecycle trace point: a host clock read, a list append,
        a bounded-ring append, and (when a RunLog is configured) a JSONL
        write — never a device sync (the flush-spy test's contract).
        Returns the timestamp."""
        t = self._clock()
        req.trace.append((event, t))
        rec = {"event": event, "req": req.id, "trace": req.trace_id,
               "t": t, "at_step": self._step_no}
        if req.slot is not None:
            rec["slot"] = req.slot
        if self.version is not None:
            rec["version"] = self.version
        if self.replica is not None:
            rec["replica"] = self.replica
        if req.span_id is not None:
            rec["span"] = req.span_id
            rec["parent_span"] = req.parent_span_id
        rec.update(extra)
        if self._run_log is not None:
            self._run_log.write(rec)
        fl = _flight.recorder()
        if fl is not None:           # deque append — no I/O, no sync
            fl.note(rec)
        return t

    def _stage_chunks(self, seq):
        """Stage `seq` host->device (async) as ceil(len / prefill_len)
        padded [1, prefill_len] chunk arrays — one for an ordinary
        prompt, more under chunked prefill or a recovery replay. Staging
        MORE than currently needed is harmless: each prefill call masks
        by its chunk length, so a preempted request (tokens dropped)
        reuses the same chunk list without restaging."""
        lp = self.cfg.prefill_len
        seq = np.asarray(seq, np.int32).reshape(-1)
        n = max(1, -(-seq.size // lp))
        padded = np.zeros((n * lp,), np.int32)
        padded[:seq.size] = seq
        return [self._stager.place(padded[i * lp:(i + 1) * lp][None, :])
                for i in range(n)]

    # --- page allocation + prefix cache ---------------------------------

    def _pages_available(self):
        """Pages an admission could obtain right now: the free list plus
        idle (refcount-zero) prefix-cache pages, which _alloc_page
        reclaims LRU-first."""
        n = len(self._free_pages)
        if self._prefix_cache is not None:
            n += self._prefix_cache.evictable()
        return n

    def _alloc_page(self):
        """One free page id, evicting the least-recently-released idle
        prefix-cache page when the free list is dry. None when nothing
        is reclaimable (true pool famine)."""
        if self._free_pages:
            return self._free_pages.popleft()
        if self._prefix_cache is not None:
            for page in self._prefix_cache.evict(1):
                return page
        return None

    def _return_pages(self, req):
        """Give a request's pages back: private pages to the free list,
        shared pages to the cache (refcount drop — they STAY cached for
        future hits unless the serve_prefix_pages cap trims them)."""
        self._free_pages.extend(req.pages)
        req.pages = []
        if req.shared_pages:
            if self._prefix_cache is not None:
                self._free_pages.extend(
                    self._prefix_cache.release(req.shared_pages))
                _metrics.gauge("serve.pages_shared").set(
                    self._prefix_cache.pages_shared())
            else:
                self._free_pages.extend(req.shared_pages)
            req.shared_pages = []

    def _map_prefix(self, req, total):
        """Match the request's prompt against the prefix cache and map
        the hit pages read-only into the slot's table row. Returns the
        number of leading tokens whose K/V is already cached (prefill
        below that position is skipped / write-masked). The match is
        capped at total - 1 so the final position always prefills (its
        logits produce the first token); when the cap cuts into the last
        matched page, that page is copy-on-write duplicated up front —
        the slot's next writes land in the private copy. Any cache
        failure (the serve.prefix_cache fault point injects them)
        degrades to a full miss: private pages, never corruption."""
        if self._prefix_cache is None or total <= 1:
            return 0
        cache = self._prefix_cache
        try:
            fault_point("serve.prefix_cache")
            shared, matched = cache.match(req.prompt, cap=total - 1)
        except Exception:
            shared, matched = [], 0
        full = req.prompt.size // self.cfg.page_size
        _metrics.counter("serve.prefix_hits").inc(len(shared))
        _metrics.counter("serve.prefix_misses").inc(full - len(shared))
        if not shared:
            return 0
        cache.acquire(shared)
        req.shared_pages = list(shared)
        for idx, page in enumerate(shared):
            self._page_table[req.slot, idx] = page
        if matched % self.cfg.page_size:
            if not self._cow_last_shared(req):
                # no page for the private copy: shrink the match to the
                # page boundary and let the tail prefill normally
                drop = req.shared_pages.pop()
                self._free_pages.extend(cache.release([drop]))
                matched = (matched // self.cfg.page_size) \
                    * self.cfg.page_size
        _metrics.gauge("serve.pages_shared").set(cache.pages_shared())
        return matched

    def _cow_last_shared(self, req):
        """Copy-on-write divergence: duplicate the request's LAST shared
        page into a fresh private page (device-side whole-page copy) and
        remap the table row. Returns False when no page is allocatable —
        the caller degrades the match instead."""
        dst = self._alloc_page()
        if dst is None:
            return False
        src = req.shared_pages.pop()     # held: refcount protects it
        self._caches = self._copy_jit(
            self._caches, np.asarray([src], np.int32),
            np.asarray([dst], np.int32))
        if self._spec_on:
            # the draft pools index by the same page ids — divergence
            # must carry the draft K/V too or the draft's view of the
            # shared prefix goes stale
            self._draft_caches = self._draft_copy_jit(
                self._draft_caches, np.asarray([src], np.int32),
                np.asarray([dst], np.int32))
        self._free_pages.extend(self._prefix_cache.release([src]))
        req.pages.append(dst)
        self._page_table[req.slot, len(req.shared_pages)] = dst
        _metrics.counter("serve.cow_copies").inc()
        return True

    def _publish_prefix(self, req):
        """Register a just-prefilled prompt's full pages in the cache so
        later admissions share them. Newly-registered pages change owner
        (private -> shared) but keep their table positions; the cache
        skips pages already shared into this row and stops at a private
        duplicate, so the shared run stays a contiguous row prefix."""
        if self._prefix_cache is None:
            return
        row = self._page_table[req.slot]
        full = req.prompt.size // self.cfg.page_size
        for page in self._prefix_cache.insert(req.prompt, row[:full]):
            req.pages.remove(page)
            req.shared_pages.append(page)
        _metrics.gauge("serve.pages_shared").set(
            self._prefix_cache.pages_shared())

    def prefix_lookup_depth(self, prompt):
        """Leading full prompt pages this engine's prefix cache holds —
        the fleet router's affinity probe (read-only, lock-held)."""
        with self._lock:
            if self._prefix_cache is None:
                return 0
            prompt = np.asarray(prompt, np.int32).reshape(-1)
            return self._prefix_cache.lookup_depth(prompt)

    # --- per-request sampling -------------------------------------------

    def _resolve_sampling(self, req, temperature, top_k, top_p, seed):
        """Fill a request's sampling knobs: explicit values win, else the
        ServeConfig defaults; a missing seed derives deterministically
        from the engine seed and the request id."""
        cfg = self.cfg
        req.temperature = (cfg.temperature if temperature is None
                           else float(temperature))
        req.top_k = cfg.top_k if top_k is None else int(top_k)
        req.top_p = cfg.top_p if top_p is None else float(top_p)
        req.seed = ((cfg.seed * 1_000_003 + req.id) & 0xFFFFFFFF
                    if seed is None else int(seed) & 0xFFFFFFFF)

    def _sampling_rows(self, req):
        """The prefill jit's [1]-shaped sampling arguments for one
        request (count = tokens generated so far, so a replayed
        request's next draw reuses its original key)."""
        return (np.asarray([req.temperature], np.float32),
                np.asarray([req.top_k], np.int32),
                np.asarray([req.top_p], np.float32),
                np.asarray([req.seed], np.uint32),
                np.asarray([len(req.tokens)], np.int32))

    def _admission_key(self, req):
        """Admission order: highest priority, then earliest deadline
        (None last), then FIFO — all-default traffic stays pure FIFO."""
        dl = req.deadline_t if req.deadline_t is not None else float("inf")
        return (-req.priority, dl, req.id)

    def _victim_key(self, req):
        """Preemption/shed victim order: LOWEST priority, then latest
        deadline (None counts as latest), then youngest — the exact
        inverse of admission, so the all-defaults case reduces to the
        old youngest-first rule."""
        dl = req.deadline_t if req.deadline_t is not None else float("inf")
        return (req.priority, -dl, -req.id)

    def _admit(self, finished):
        cfg = self.cfg
        while self._queue and self._free_slots:
            req = min(self._queue, key=self._admission_key)
            total = req.prompt.size + len(req.tokens)  # recovery replays
            first = min(cfg.prefill_len, total)        # prompt + tokens
            if -(-first // cfg.page_size) > self._pages_available():
                _metrics.counter("serve.page_stalls").inc(where="admit")
                break                      # head-of-line waits for pages
            self._queue.remove(req)
            if not self._prefill_request(req, total, finished):
                break          # mid-admission page stall or a recovery
        _metrics.gauge("serve.queue_depth").set(len(self._queue))

    def _prefill_request(self, req, total, finished):
        """Admit one request: take a slot, match the prompt's leading
        full pages against the prefix cache (hits map read-only shared
        pages into the table — prefill for those tokens is SKIPPED),
        then for each remaining prefill_len chunk of the replay sequence
        grow the page table and run the ONE prefill trace; only the
        final chunk's sampled token is consumed. On the way out the
        prompt's own full pages are registered in the cache so later
        admissions share them. Returns False when admission must back
        off (pages ran out between chunks, or a prefill failure
        triggered recovery)."""
        cfg = self.cfg
        ps = cfg.page_size
        slot = self._free_slots.pop()
        req.slot = slot
        self._trace_event(
            req, "resumed" if (req.preemptions or req.recoveries)
            else "admitted")
        self._page_table[slot] = 0
        req.pages = []
        req.shared_pages = []
        quant_ok = True
        if self.cfg.kv_dtype is not None:
            try:
                fault_point("quant.kv_write")
            except Exception:
                # quantized-write fault: degrade THIS admission to
                # private pages only (no cache mapping, no publish on
                # the way out) so a suspect write can never be shared
                # into another request's table row
                _metrics.counter("serve.kv_quant_degraded").inc()
                quant_ok = False
        matched = self._map_prefix(req, total) if quant_ok else 0
        tok = None
        skipped = 0
        for ci in range(-(-total // cfg.prefill_len)):
            start = ci * cfg.prefill_len
            clen = min(cfg.prefill_len, total - start)
            if start + clen <= matched:
                skipped += clen   # fully cache-covered: no prefill call
                continue
            need = -(-(start + clen) // ps)
            while len(req.shared_pages) + len(req.pages) < need:
                page = self._alloc_page()
                if page is None:
                    # pool drained between chunks: undo this admission
                    # (pages already written are masked by length and
                    # will be overwritten on retry) and wait
                    _metrics.counter("serve.page_stalls").inc(
                        where="admit")
                    self._abort_admission(req)
                    return False
                self._page_table[
                    slot, len(req.shared_pages) + len(req.pages)] = page
                req.pages.append(page)
            starts = np.asarray([start], np.int32)
            lens = np.asarray([clen], np.int32)
            floors = np.asarray([matched], np.int32)
            try:
                fault_point("serve.prefill")
                tok_dev, self._caches = self._prefill_jit(
                    self._params, self._caches, req.device_prompt[ci],
                    starts, lens, self._page_table[slot][None, :],
                    floors, *self._sampling_rows(req))
                if self._spec_on:
                    # mirror the chunk into the draft pools (same pages,
                    # same write floor — shared prefix pages keep their
                    # published draft K/V) so the first speculative
                    # round sees a fully warm draft cache
                    self._draft_caches = self._draft_prefill_jit(
                        self._draft_params, self._draft_caches,
                        req.device_prompt[ci], starts, lens,
                        self._page_table[slot][None, :], floors)
                tok = int(np.asarray(tok_dev)[0])  # graft-lint: disable=hot-path-sync (admission-time sync, once per prefill chunk: the slot table needs the first token before decode rounds start)
            except Exception as e:
                self._recover("serve.prefill", e, pending=req)
                return False
        self.prefill_tokens_skipped += skipped
        if quant_ok:
            self._publish_prefix(req)
        self._lengths[slot] = total
        self._trace_event(req, "prefill_done")
        t = self._trace_event(req, "first_token")
        if req.first_token_t is None:     # recovery replay keeps the 1st
            req.first_token_t = t
            _metrics.histogram("serve.ttft_s").observe(t - req.submit_t)
        req.tokens.append(tok)
        req.status = "running"
        self._running[slot] = req
        self._temps[slot] = req.temperature
        self._top_ks[slot] = req.top_k
        self._top_ps[slot] = req.top_p
        self._seeds[slot] = req.seed
        self._gen_counts[slot] = len(req.tokens)
        self._last_tokens[slot] = tok
        self._active[slot] = True
        _metrics.counter("serve.tokens").inc()
        reason = self._done_reason(req, tok)
        if reason:
            self._release(req, finished, reason)
        return True

    def _abort_admission(self, req):
        """Undo a half-done admission (mid-chunk page famine): free the
        slot and pages (shared ones back to the cache), requeue at the
        front."""
        slot = req.slot
        self._return_pages(req)
        self._page_table[slot] = 0
        self._lengths[slot] = 0
        self._active[slot] = False
        self._running.pop(slot, None)
        self._free_slots.append(slot)
        req.slot = None
        req.status = "queued"
        self._queue.appendleft(req)

    def _grow_pages(self):
        """Allocate the page each slot's next token write needs where
        lengths crossed a boundary; slots that cannot get one stall
        (deactivate) for this round and retry next step. Returns the
        stalled slots. Idempotent — safe to re-run after a preemption
        freed pages."""
        stalled = []
        ps = self.cfg.page_size
        for slot, req in self._running.items():
            self._active[slot] = True
            ln = int(self._lengths[slot])
            owned = len(req.shared_pages) + len(req.pages)
            if ln % ps or ln // ps < owned:
                continue                   # room in the current page
            page = self._alloc_page()
            if page is not None:
                req.pages.append(page)
                self._page_table[slot, ln // ps] = page
            else:
                _metrics.counter("serve.page_stalls").inc(where="decode")
                self._active[slot] = False
                stalled.append(slot)
        return stalled

    def _spec_round(self):
        """One speculative round: the draft model proposes up to spec_k
        tokens per active slot (spec_k decode-shaped calls of the ONE
        draft trace, lengths+i / counts+i), then the target scores the
        whole [slots, spec_k+1] window — pending token + proposals — in
        ONE batched verify step against the paged cache and re-draws
        every position with the exact fold(seed, count+i) key the plain
        path would use. Returns (sampled [S, W], proposals [S, K],
        win [S]) as host arrays; step() accepts the leading run of
        matching proposals and emits accepted + 1 target draws.

        Window sizing: win[slot] = min(spec_k+1, remaining token
        budget), then shrunk to what the slot's pages can hold when the
        pool is drained (never below 1 — _grow_pages already made the
        pending position writable, so a famine degrades the slot to
        plain-decode behavior instead of stalling it)."""
        cfg = self.cfg
        ps = cfg.page_size
        k = cfg.spec_k
        win = np.zeros(cfg.num_slots, np.int32)
        for slot, req in self._running.items():
            if not self._active[slot]:
                continue               # page-stalled this round
            w = min(k + 1, req.max_new - len(req.tokens))
            ln = int(self._lengths[slot])
            while w > 1:
                owned = len(req.shared_pages) + len(req.pages)
                if (ln + w - 1) // ps < owned:
                    break              # window fully covered
                page = self._alloc_page()
                if page is None:
                    # pool famine: shrink the window to the pages the
                    # slot already owns (>= 1 position past _grow_pages)
                    w = owned * ps - ln
                    break
                req.pages.append(page)
                self._page_table[slot, owned] = page
            win[slot] = w
        # draft phase: proposal i+1 is drawn with count+i — the same
        # key verify re-draws position i+1 with, so a well-matched
        # draft's proposals survive acceptance token-for-token. Tokens
        # feed back as device arrays; nothing syncs until the window is
        # scored.
        props_dev = []
        tok = self._last_tokens
        for i in range(k):
            step_act = self._active & (win > i + 1)
            tok, self._draft_caches = self._draft_jit(
                self._draft_params, self._draft_caches, tok,
                self._page_table, self._lengths + i, step_act,
                self._temps, self._top_ks, self._top_ps,
                self._seeds, self._gen_counts + i)
            props_dev.append(tok)
        window = jnp.stack([jnp.asarray(self._last_tokens)] + props_dev,
                           axis=1)
        sampled_dev, self._caches = self._verify_jit(
            self._params, self._caches, window, self._lengths, win,
            self._page_table, self._temps, self._top_ks, self._top_ps,
            self._seeds, self._gen_counts)
        props = np.stack([np.asarray(p) for p in props_dev], axis=1)
        sampled = np.asarray(sampled_dev)  # graft-lint: disable=hot-path-sync (the speculative round's one deliberate sync point, fetching proposals + verify draws together: acceptance is a host-side compare, and the scheduler needs this round's tokens to advance/free slots)
        return sampled, props, win

    def _free_slot_state(self, req):
        """Return a request's slot and pages to the free lists (shared
        pages back to the prefix cache) and zero the slot's scheduler
        rows. Leaves req.slot set (terminal trace events carry it);
        requeue paths null it themselves."""
        slot = req.slot
        self._return_pages(req)
        self._page_table[slot] = 0
        self._lengths[slot] = 0
        self._active[slot] = False
        self._last_tokens[slot] = 0
        self._temps[slot] = 0.0
        self._top_ks[slot] = 0
        self._top_ps[slot] = 0.0
        self._seeds[slot] = 0
        self._gen_counts[slot] = 0
        self._running.pop(slot, None)
        self._free_slots.append(slot)

    def _preempt(self, req):
        """Recompute preemption: drop the request's device state and
        requeue it at the FRONT of the queue (its staged prompt is still
        device-resident, so re-admission pays only the prefill)."""
        self._trace_event(req, "preempted",
                          tokens_dropped=len(req.tokens))
        self._free_slot_state(req)
        req.slot = None
        req.tokens = []
        req.status = "queued"
        req.preemptions += 1
        self._queue.appendleft(req)
        _metrics.counter("serve.preemptions").inc()

    def _recover(self, where, exc, pending=None):
        """Crash-isolated step recovery. The decode/prefill jits donate
        the page pools, so after ANY failure inside them the device
        state is suspect — quarantine it: rebuild the pools, zero the
        scheduler arrays, and re-admit every in-flight request
        recompute-style (host-side prompt + generated tokens are the
        durable state; a greedy request finishes token-exact). A runtime
        Pallas decode failure additionally latches the permanent
        per-process XLA fallback. Bounded: `serve_step_retries`
        consecutive failures, then every request is failed and `exc`
        re-raised."""
        cfg = self.cfg
        self.recoveries += 1
        _metrics.counter("serve.recoveries").inc(where=where)
        victims = sorted(self._running.values(), key=lambda r: r.id)
        if pending is not None:
            victims.append(pending)
        if self._run_log is not None:
            self._run_log.write({
                "phase": "serve", "recovery": where,
                "step": self._step_no, "in_flight": len(victims),
                "error": f"{type(exc).__name__}: {exc}"[:200]})
        msg = f"{type(exc).__name__}: {exc}".lower()
        if get_flag("use_pallas_decode") and any(
                s in msg for s in ("pallas", "mosaic", "custom_call",
                                   "custom call")):
            # runtime kernel failure: latch the per-process XLA fallback
            # (flag read at trace time -> fresh jit caches required; the
            # trace credit keeps the deliberate re-traces out of
            # jit.retraces)
            from paddle_tpu.ops.pallas import log_fallback
            set_flags({"use_pallas_decode": False})
            log_fallback("decode_attention",
                         f"runtime decode failure ({type(exc).__name__})"
                         " — latched permanent per-process XLA fallback")
            # decode + prefill, plus draft/draft-prefill/verify when
            # speculation is on — all read the flag at trace time
            self._trace_credit += 2 + (3 if self._spec_on else 0)
            self._build_jits()
        # quarantine: drop the (donated, possibly poisoned) pools
        self._caches = self._model.init_paged_caches(
            cfg.num_pages, cfg.page_size, dtype=cfg.cache_dtype,
            kv_dtype=cfg.kv_dtype)
        if self._spec_on:
            # the draft pools were donated to the same failed round
            self._draft_caches = self._draft_model.init_paged_caches(
                cfg.num_pages, cfg.page_size, dtype=cfg.cache_dtype,
                kv_dtype=cfg.kv_dtype)
        self._page_table[:] = 0
        self._lengths[:] = 0
        self._active[:] = False
        self._last_tokens[:] = 0
        self._temps[:] = 0.0
        self._top_ks[:] = 0
        self._top_ps[:] = 0.0
        self._seeds[:] = 0
        self._gen_counts[:] = 0
        self._free_slots = list(range(cfg.num_slots))
        self._free_pages = collections.deque(range(cfg.num_pages))
        if self._prefix_cache is not None:
            # every cached page id now points at zeroed pools — forget
            # the index (sharing degrades; re-admissions re-publish)
            self._prefix_cache.clear()
            _metrics.gauge("serve.pages_shared").set(0)
        self._running = {}
        for req in reversed(victims):      # appendleft keeps id order
            req.slot = None
            req.pages = []
            req.shared_pages = []
            req.status = "queued"
            req.recoveries += 1
            if req.tokens or req.device_prompt is None:
                # the staged chunks hold only the prompt — restage the
                # full replay sequence (prompt + generated tokens, the
                # durable host-side state)
                req.device_prompt = self._stage_chunks(req.output)
            self._trace_event(req, "requeued", cause=where,
                              tokens_kept=len(req.tokens))
            self._queue.appendleft(req)
        _metrics.gauge("serve.active_slots").set(0)
        _metrics.gauge("serve.queue_depth").set(len(self._queue))
        try:
            self._retry_budget.failure(exc)   # backoff sleep, or raise
        except Exception:
            self._fail_all(exc)
            raise

    def _fail_all(self, exc):
        """Recovery budget spent: retire every queued + running request
        with terminal status `failed` before the engine re-raises, so no
        caller is left waiting on a request that can never finish."""
        doomed = list(self._queue) + list(self._running.values())
        self._queue.clear()
        for req in doomed:
            if req.slot is not None:
                self._free_slot_state(req)
            self._retire_terminal(req, "failed", "engine_error")
        _metrics.gauge("serve.queue_depth").set(0)
        _metrics.gauge("serve.active_slots").set(0)

    # --- terminal statuses beyond completion -----------------------------

    def _retire_terminal(self, req, status, why, finished=None,
                         account=True):
        """Retire a request on a non-completion terminal path (rejected |
        shed | cancelled | failed). `account=True` counts it as an
        SLO-failed retirement (lowering goodput — the engine failed the
        client); cancel passes False."""
        req.status = status
        req.retire_reason = why
        req.done_t = self._clock()
        req.device_prompt = None
        if account:
            req.slo_ok = False
            self._retired += 1
            _metrics.gauge("serve.goodput").set(self.goodput())
        self._trace_event(req, "retired", reason=status, why=why,
                          tokens=len(req.tokens),
                          slo_ok=bool(req.slo_ok),
                          preemptions=req.preemptions)
        _metrics.counter("serve.requests").inc(status=status)
        if finished is not None:
            finished.append(req)

    def _reject(self, req, why):
        """Terminal `rejected` at submit time — with the retriable hint:
        the request was never started, so resubmitting (after backoff,
        or with a feasible deadline) is the right client move."""
        req.retriable = True
        self._retire_terminal(req, "rejected", why)

    def _shed_expired(self, finished):
        """Drop every queued request whose deadline has passed (terminal
        `shed`) — serving a request that can no longer meet its deadline
        wastes pages the live ones need."""
        if not self._queue:
            return 0
        now = self._clock()
        expired = [r for r in self._queue
                   if r.deadline_t is not None and now > r.deadline_t]
        for req in expired:
            self._queue.remove(req)
            _metrics.counter("serve.shed").inc(cause="deadline")
            self._retire_terminal(req, "shed", "deadline_expired",
                                  finished)
        return len(expired)

    def shed_queued(self, cause="overload"):
        """Load shedding (the watchdog's mitigation action): shed every
        expired queued request; when none is expired, shed the single
        lowest-priority / latest-deadline one. Returns the shed ids."""
        with self._lock:
            shed = []
            now = self._clock()
            for req in [r for r in self._queue
                        if r.deadline_t is not None
                        and now > r.deadline_t]:
                self._queue.remove(req)
                shed.append((req, "deadline_expired"))
            if not shed and self._queue:
                victim = min(self._queue, key=self._victim_key)
                self._queue.remove(victim)
                shed.append((victim, cause))
            for req, why in shed:
                _metrics.counter("serve.shed").inc(cause=cause)
                self._retire_terminal(req, "shed", why)
            _metrics.gauge("serve.queue_depth").set(len(self._queue))
            return [req.id for req, _ in shed]

    def _on_anomaly(self, event):
        """Watchdog mitigation hook: a goodput collapse or ingest stall
        sheds queued load instead of only latching a counter. When a
        fleet router owns this engine it installs `anomaly_sink` so the
        same signal also sheds expired/lowest-priority work fleet-wide
        (a supervisor decision no single replica can make) — and owns
        the flight-recorder dump, fanned out across every replica; a
        STANDALONE engine dumps its own evidence bundle here."""
        fl = _flight.recorder()
        if fl is not None:
            fl.note_event("anomaly", **{k: v for k, v in event.items()
                                        if k not in ("event", "t")})
        if event.get("anomaly") in ("goodput_collapse", "ingest_stall"):
            self.shed_queued(cause=event["anomaly"])
        if self.anomaly_sink is not None:
            self.anomaly_sink(event)
        elif fl is not None:
            _flight.dump_bundle(
                reason=str(event.get("anomaly", "anomaly")),
                run_logs=(self._run_log,) if self._run_log else (),
                config=dict(serve_config=self.config_summary(),
                            model_version=self.version),
                extra=dict(anomaly=event))

    def config_summary(self):
        """Shallow JSON-friendly view of the active ServeConfig (the
        flight bundle's config section; non-scalar fields repr)."""
        return {f.name: getattr(self.cfg, f.name)
                for f in dataclasses.fields(self.cfg)}

    def _done_reason(self, req, tok):
        """Retirement reason for the token just emitted, or None."""
        if req.eos_id is not None and tok == req.eos_id:
            return "eos"
        if len(req.tokens) >= req.max_new:
            return "length"
        return None

    def _account_slo(self, req):
        """Classify one retirement against the configured SLOs and
        refresh serve.goodput (SLO targets of 0 are unbounded)."""
        cfg = self.cfg
        ok = True
        if req.first_token_t is not None:
            ttft = req.first_token_t - req.submit_t
            if cfg.slo_ttft_s and ttft > cfg.slo_ttft_s:
                _metrics.counter("serve.slo_violations").inc(kind="ttft")
                ok = False
            if cfg.slo_token_latency_s and len(req.tokens) > 1:
                per_tok = ((req.done_t - req.first_token_t)
                           / (len(req.tokens) - 1))
                if per_tok > cfg.slo_token_latency_s:
                    _metrics.counter("serve.slo_violations").inc(
                        kind="token_latency")
                    ok = False
        req.slo_ok = ok
        self._retired += 1
        self._retired_ok += int(ok)
        _metrics.gauge("serve.goodput").set(self.goodput())

    def _release(self, req, finished, reason="length"):
        self._free_slot_state(req)
        req.status = "done"
        req.retire_reason = reason
        req.done_t = self._clock()
        req.device_prompt = None
        self._account_slo(req)
        self._trace_event(req, "retired", reason=reason,
                          tokens=len(req.tokens), slo_ok=req.slo_ok,
                          preemptions=req.preemptions,
                          spec_tokens=req.spec_tokens)
        finished.append(req)
        _metrics.counter("serve.requests").inc(status="completed")
