"""Continuous-batching serving engine over the paged KV cache.

Architecture (the three serving invariants):

  * ONE jitted decode step, fixed slot count, donated page pools — its
    shapes never depend on which requests are live, so admissions,
    completions and ragged lengths never retrace it (`decode_traces`
    counts trace-time entries; tools/compile_smoke.py asserts == 1
    across admission waves).
  * Paged KV memory — requests own pages, not a [B, Tmax] rectangle.
    A finished request frees its pages between steps; an admitted one
    takes pages for its prompt and grows one page at a time as it
    decodes. The page table / length / active arrays are tiny host
    numpy state, re-fed to the step each call (values change, shapes
    don't).
  * Prefill-on-admit — a second fixed-shape jit (prompts padded to
    `prefill_len`) runs once per admission, writes the prompt K/V into
    the request's pages and samples the first token, so time-to-first-
    token is one forward, not `prompt_len` decode steps.

Telemetry (PR-4 registry): serve.queue_depth / serve.active_slots
gauges, serve.ttft_s + serve.token_latency_s histograms, serve.tokens +
serve.requests{status} + serve.page_stalls counters; optional per-step
RunLog records (`ServeConfig.run_log`) that tools/run_report.py renders.

Live observability plane (this layer's serving half):

  * per-request lifecycle traces — every request carries a trace id and
    emits timestamped RunLog events (`submitted`, `admitted`,
    `prefill_done`, `first_token`, `preempted`, `resumed`,
    `retired{reason}`). Pure host work (a clock read + a JSONL append at
    request-rate, not token-rate): no device sync is added to the decode
    hot path, asserted by a flush-spy test. `tools/run_report.py
    --serve` reconstructs per-slot timelines from these events.
  * SLO/goodput accounting — `ServeConfig.slo_ttft_s` /
    `slo_token_latency_s` (flag-resolvable) classify every retirement;
    `serve.goodput` (gauge: fraction of retired requests inside every
    SLO) and `serve.slo_violations{kind}` are the objective function the
    ROADMAP's SLO-aware scheduler optimizes.
  * `jit.retraces{fn=serve.decode|serve.prefill}` — the traced-once
    invariant as a counter: any steady-state recompile is visible to
    the watchdog and /metrics, not just to compile-smoke tests.
  * `ServeConfig.metrics_port` starts the /metrics exporter
    (observability/exporter.py) for the run; `ServeConfig.watchdog`
    attaches the anomaly watchdog (observability/watchdog.py).
"""

import collections
import dataclasses
import itertools
import time
import typing
import uuid

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.core.enforce import enforce
from paddle_tpu.core.flags import get_flag
from paddle_tpu.observability import metrics as _metrics


@dataclasses.dataclass
class ServeConfig:
    num_slots: int = None        # None -> serve_slots flag
    page_size: int = None        # None -> serve_page_size flag
    max_len: int = 256           # per-request cap: prompt + generated
    prefill_len: int = 64        # padded admission prompt length (fixed)
    num_pages: int = None        # None -> num_slots * ceil(max_len/page)
    cache_dtype: typing.Any = jnp.float32
    temperature: float = 0.0     # 0 = greedy; >0 samples per step
    seed: int = 0
    eos_id: int = None           # default EOS (submit() can override)
    default_max_new: int = 32
    run_log: str = None          # per-step RunLog JSONL path
    prefetch: int = None         # host->device staging depth (None->flag)
    slo_ttft_s: float = None     # None -> flag; 0 = unbounded
    slo_token_latency_s: float = None   # None -> flag; 0 = unbounded
    metrics_port: int = None     # None -> flag metrics_port; 0 = off
    watchdog: object = None      # None -> flag; True or WatchdogConfig

    def resolve(self):
        if self.num_slots is None:
            self.num_slots = get_flag("serve_slots")
        if self.page_size is None:
            self.page_size = get_flag("serve_page_size")
        if self.slo_ttft_s is None:
            self.slo_ttft_s = get_flag("slo_ttft_s")
        if self.slo_token_latency_s is None:
            self.slo_token_latency_s = get_flag("slo_token_latency_s")
        pages_per_slot = -(-self.max_len // self.page_size)
        if self.num_pages is None:
            self.num_pages = self.num_slots * pages_per_slot
        enforce(self.prefill_len <= self.max_len,
                "prefill_len must not exceed max_len")
        enforce(self.num_pages >= pages_per_slot,
                f"num_pages={self.num_pages} cannot hold even one "
                f"max_len={self.max_len} request "
                f"({pages_per_slot} pages of {self.page_size}) — the "
                "preemption guarantee needs a lone request to fit")
        return self


@dataclasses.dataclass
class Request:
    id: int
    prompt: np.ndarray            # true (unpadded) prompt, int32 [L]
    max_new: int
    eos_id: int = None
    tokens: list = dataclasses.field(default_factory=list)
    status: str = "queued"        # queued -> running -> done
    slot: int = None
    pages: list = dataclasses.field(default_factory=list)
    submit_t: float = None
    first_token_t: float = None
    done_t: float = None
    device_prompt: typing.Any = None   # staged padded [1, Lp] (async put)
    trace_id: str = None          # engine-run-scoped lifecycle trace id
    trace: list = dataclasses.field(default_factory=list)  # (event, t)
    preemptions: int = 0
    retire_reason: str = None     # "eos" | "length"
    slo_ok: bool = None           # every configured SLO met at retire

    @property
    def output(self):
        """prompt + generated tokens (the generate()-shaped sequence)."""
        return np.concatenate([self.prompt,
                               np.asarray(self.tokens, np.int32)])


class ServingEngine:
    """submit()/step()/drain() continuous batching for a GPTDecoder."""

    def __init__(self, model, variables, config=None, clock=time.perf_counter):
        self.cfg = (config or ServeConfig()).resolve()
        cfg = self.cfg
        self._model = model
        self._params = variables["params"]
        self._clock = clock
        self._pages_per_slot = -(-cfg.max_len // cfg.page_size)
        self._caches = model.init_paged_caches(
            cfg.num_pages, cfg.page_size, dtype=cfg.cache_dtype)

        s = cfg.num_slots
        self._page_table = np.zeros((s, self._pages_per_slot), np.int32)
        self._lengths = np.zeros(s, np.int32)
        self._active = np.zeros(s, bool)
        self._last_tokens = np.zeros(s, np.int32)
        self._free_slots = list(range(s))
        self._free_pages = collections.deque(range(cfg.num_pages))
        self._queue = collections.deque()
        self._running = {}
        self._ids = itertools.count()
        self._step_no = 0
        self._base_key = jax.random.key(cfg.seed)
        self.decode_traces = 0
        self.prefill_traces = 0

        # host->device prompt staging reuses the DataLoader placement path
        # (async device_put; depth knob = the reader_queue_size flag), so
        # admission never pays the transfer inside step()
        from paddle_tpu.data.loader import DataLoader
        self._stager = DataLoader(None, prefetch=cfg.prefetch)

        self._run_log = None
        self._own_run_log = False
        if cfg.run_log:
            if isinstance(cfg.run_log, str):
                from paddle_tpu.observability.runlog import RunLog
                self._run_log = RunLog(cfg.run_log)
                self._own_run_log = True
            else:                      # an already-open RunLog (bench.py)
                self._run_log = cfg.run_log

        # live observability plane: preregister the serve metric family
        # (so /metrics advertises HELP/TYPE before any traffic), SLO
        # tallies, the optional exporter, and the anomaly watchdog
        from paddle_tpu.observability import catalog as _catalog
        _catalog.preregister([
            "serve.queue_depth", "serve.active_slots", "serve.ttft_s",
            "serve.token_latency_s", "serve.tokens", "serve.requests",
            "serve.page_stalls", "serve.preemptions", "serve.goodput",
            "serve.slo_violations", "jit.retraces"])
        self._retired = 0
        self._retired_ok = 0
        self._viol_base = dict(
            _metrics.counter("serve.slo_violations").snapshot())
        self._trace_run = uuid.uuid4().hex[:8]
        self._aot_trace = False
        from paddle_tpu.observability.exporter import start_metrics_server
        self._metrics_server = start_metrics_server(cfg.metrics_port)
        from paddle_tpu.observability.watchdog import maybe_watchdog
        self._watchdog = maybe_watchdog(cfg.watchdog,
                                        run_log=self._run_log)

        temp = float(cfg.temperature)

        def _sample(logits, key):
            if temp > 0.0:
                return jax.random.categorical(
                    key, logits / temp, -1).astype(jnp.int32)
            return jnp.argmax(logits, -1).astype(jnp.int32)

        self._sample = _sample

        def decode(params, caches, tokens, page_table, lengths, active,
                   key):
            self.decode_traces += 1   # trace-time only: counts compiles
            if self.decode_traces > 1 and not self._aot_trace:
                # traced-once invariant broken in live serving — visible
                # to /metrics and the watchdog, not just compile smokes
                _metrics.counter("jit.retraces").inc(fn="serve.decode")

            def run(tok):
                logits, new_caches = model.paged_decode_step(
                    tok, caches, page_table, lengths, active)
                return _sample(logits, key), new_caches

            return model.apply({"params": params, "state": {}}, tokens,
                               method=run)

        def prefill(params, caches, prompt, lengths, page_rows, key):
            self.prefill_traces += 1
            if self.prefill_traces > 1 and not self._aot_trace:
                _metrics.counter("jit.retraces").inc(fn="serve.prefill")

            def run(pr):
                logits, new_caches = model.paged_prefill(
                    pr, lengths, caches, page_rows)
                return _sample(logits, key), new_caches

            return model.apply({"params": params, "state": {}}, prompt,
                               method=run)

        self._decode_jit = jax.jit(decode, donate_argnums=(1,))
        self._prefill_jit = jax.jit(prefill, donate_argnums=(1,))

    # --- public API ---

    def submit(self, prompt, max_new=None, eos_id=None):
        """Queue a prompt; returns the request id. The padded prompt is
        staged host->device immediately (async), so admission inside a
        later step() issues no host transfer."""
        cfg = self.cfg
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        max_new = max_new if max_new is not None else cfg.default_max_new
        enforce(1 <= prompt.size <= cfg.prefill_len,
                f"prompt length {prompt.size} not in [1, "
                f"{cfg.prefill_len}] (prefill_len)")
        enforce(prompt.size + max_new <= cfg.max_len,
                f"prompt {prompt.size} + max_new {max_new} exceeds "
                f"max_len {cfg.max_len}")
        req = Request(id=next(self._ids), prompt=prompt, max_new=max_new,
                      eos_id=eos_id if eos_id is not None else cfg.eos_id)
        req.trace_id = f"{self._trace_run}/{req.id}"
        req.submit_t = self._trace_event(req, "submitted",
                                         prompt_len=int(prompt.size),
                                         max_new=int(max_new))
        padded = np.zeros((1, cfg.prefill_len), np.int32)
        padded[0, :prompt.size] = prompt
        req.device_prompt = self._stager.place(padded)
        self._queue.append(req)
        _metrics.gauge("serve.queue_depth").set(len(self._queue))
        _metrics.counter("serve.requests").inc(status="submitted")
        return req.id

    def step(self):
        """One scheduling round: free finished slots happened last round;
        admit queued prompts into free slots (prefill-on-admit), grow
        page tables where the next token opens a page, run ONE jitted
        decode step over all slots, and retire requests that hit EOS or
        their token budget. Returns the requests finished this round."""
        t0 = self._clock()
        finished = []
        self._admit(finished)
        stalled = self._grow_pages()
        while stalled and not self._active.any():
            # pool deadlock: every live slot needs a fresh page and none
            # is free. Preempt the YOUNGEST stalled request (free its
            # pages, requeue it for re-prefill) so the oldest always
            # makes progress — greedy decoding regenerates the dropped
            # tokens exactly; sampled runs re-draw (recompute preemption)
            victim = max(stalled, key=lambda s: self._running[s].id)
            self._preempt(self._running[victim])
            stalled = self._grow_pages()
        new_tokens = 0
        if self._active.any():
            key = jax.random.fold_in(self._base_key, self._step_no)
            toks_dev, self._caches = self._decode_jit(
                self._params, self._caches, self._last_tokens,
                self._page_table, self._lengths, self._active, key)
            toks = np.asarray(toks_dev)        # host sync: the scheduler
            dt = self._clock() - t0            # needs the tokens
            lat = _metrics.histogram("serve.token_latency_s")
            for slot, req in list(self._running.items()):
                if not self._active[slot]:
                    continue                   # page-stalled this round
                self._lengths[slot] += 1       # pending token now cached
                tok = int(toks[slot])
                req.tokens.append(tok)
                self._last_tokens[slot] = tok
                lat.observe(dt)
                new_tokens += 1
                reason = self._done_reason(req, tok)
                if reason:
                    self._release(req, finished, reason)
        _metrics.counter("serve.tokens").inc(new_tokens)
        _metrics.gauge("serve.active_slots").set(len(self._running))
        _metrics.gauge("serve.queue_depth").set(len(self._queue))
        wall_s = self._clock() - t0
        if self._run_log is not None:
            self._run_log.write({
                "phase": "serve", "step": self._step_no,
                "wall_s": wall_s, "new_tokens": new_tokens,
                "active": len(self._running),
                "queue_depth": len(self._queue),
                "goodput": round(self.goodput(), 4)})
        if self._watchdog is not None:
            self._watchdog.tick(self._step_no, wall_s=wall_s,
                                goodput=self.goodput(),
                                retired=self._retired)
        self._step_no += 1
        return finished

    def drain(self, max_steps=100000):
        """Run step() until every submitted request finishes; returns the
        finished requests in completion order."""
        out = []
        for _ in range(max_steps):
            if not (self._queue or self._running):
                break
            out.extend(self.step())
        else:
            raise RuntimeError(
                f"drain: {len(self._queue)} queued / {len(self._running)} "
                f"running requests left after {max_steps} steps")
        if self._run_log is not None:
            snap = _metrics.snapshot()
            self._run_log.write({"final": True, "phase": "serve",
                                 "counters": snap.get("counters", {}),
                                 "gauges": snap.get("gauges", {}),
                                 "slo": self.slo_stats()})
        return out

    def close(self):
        if self._metrics_server is not None:
            self._metrics_server.stop()
            self._metrics_server = None
        if self._run_log is not None and self._own_run_log:
            self._run_log.close()
        self._run_log = None

    def compiled_decode(self):
        """AOT-compile the decode step (one extra trace) and return the
        compiled executable — compile-smoke greps its HLO, bench prewarms
        with it."""
        cfg = self.cfg
        key = jax.random.fold_in(self._base_key, 0)
        self._aot_trace = True    # a deliberate extra trace, not a retrace
        try:
            return self._decode_jit.lower(
                self._params, self._caches,
                np.zeros(cfg.num_slots, np.int32), self._page_table,
                np.zeros(cfg.num_slots, np.int32),
                np.zeros(cfg.num_slots, bool), key).compile()
        finally:
            self._aot_trace = False

    def export_decode(self, path):
        """Export ONE greedy serve step as a StableHLO / jax.export
        artifact through io.inference.save_train_program's
        state-feedback contract: state = (params, page pools) fed back
        output->input each iteration, batch = (tokens, page_table,
        lengths, active) — so the C++ predictor loop (csrc/) can run the
        continuous-batching decode with no Python at serve time (the
        host scheduler only rewrites the tiny page_table/lengths/active
        inputs between steps)."""
        from paddle_tpu.io.inference import save_train_program
        model = self._model
        cfg = self.cfg

        def step(state, tokens, page_table, lengths, active):
            params, caches = state

            def run(tok):
                logits, new_caches = model.paged_decode_step(
                    tok, caches, page_table, lengths, active)
                return jnp.argmax(logits, -1).astype(jnp.int32), \
                    new_caches

            nxt, new_caches = model.apply(
                {"params": params, "state": {}}, tokens, method=run)
            return nxt, (params, new_caches)

        example = (np.zeros(cfg.num_slots, np.int32), self._page_table,
                   np.zeros(cfg.num_slots, np.int32),
                   np.zeros(cfg.num_slots, bool))
        return save_train_program(path, step,
                                  (self._params, self._caches), example)

    def goodput(self):
        """Fraction of retired requests that met every configured SLO
        (1.0 before the first retirement) — the SLO scheduler's
        objective."""
        return self._retired_ok / self._retired if self._retired else 1.0

    def slo_stats(self):
        """SLO accounting for bench rows / reports: goodput, targets,
        and violation counts since construction (or reset_stats)."""
        viol = _metrics.counter("serve.slo_violations").snapshot()
        delta = {k.split("=", 1)[1]: v - self._viol_base.get(k, 0)
                 for k, v in viol.items()}
        return {"goodput": round(self.goodput(), 4),
                "retired": self._retired,
                "slo_ttft_s": self.cfg.slo_ttft_s or None,
                "slo_token_latency_s":
                    self.cfg.slo_token_latency_s or None,
                "violations": {"ttft": delta.get("ttft", 0),
                               "token_latency":
                                   delta.get("token_latency", 0)}}

    def reset_stats(self):
        """Zero the serve latency histograms and this engine's SLO
        tallies (bench warmup isolation: compile-time TTFTs must not
        poison the timed window's goodput)."""
        for name in ("serve.ttft_s", "serve.token_latency_s"):
            h = _metrics.registry().get(name)
            if h is not None:
                h.reset()
        self._retired = self._retired_ok = 0
        self._viol_base = dict(
            _metrics.counter("serve.slo_violations").snapshot())
        _metrics.gauge("serve.goodput").set(1.0)

    def latency_stats(self):
        """{"ttft_ms": {p50,p95,n}, "token_ms": {...}} from the registry
        histograms (the bench row's telemetry-backed percentiles)."""
        out = {}
        for name, hist in (("ttft_ms", "serve.ttft_s"),
                           ("token_ms", "serve.token_latency_s")):
            h = _metrics.registry().get(hist)
            st = h.stats() if h is not None else None
            if st:
                out[name] = {"p50": round(st["p50"] * 1e3, 3),
                             "p95": round(st["p95"] * 1e3, 3),
                             "n": st["count"]}
        return out

    # --- scheduling internals ---

    def _trace_event(self, req, event, **extra):
        """One lifecycle trace point: a host clock read, a list append,
        and (when a RunLog is configured) a JSONL write — never a device
        sync (the flush-spy test's contract). Returns the timestamp."""
        t = self._clock()
        req.trace.append((event, t))
        if self._run_log is not None:
            rec = {"event": event, "req": req.id, "trace": req.trace_id,
                   "t": t, "at_step": self._step_no}
            if req.slot is not None:
                rec["slot"] = req.slot
            rec.update(extra)
            self._run_log.write(rec)
        return t

    def _admit(self, finished):
        cfg = self.cfg
        ttft = _metrics.histogram("serve.ttft_s")
        while self._queue and self._free_slots:
            req = self._queue[0]
            need = -(-req.prompt.size // cfg.page_size)
            if need > len(self._free_pages):
                _metrics.counter("serve.page_stalls").inc(where="admit")
                break                      # head-of-line waits for pages
            self._queue.popleft()
            slot = self._free_slots.pop()
            req.slot = slot
            self._trace_event(
                req, "resumed" if req.preemptions else "admitted")
            req.pages = [self._free_pages.popleft() for _ in range(need)]
            row = np.zeros(self._pages_per_slot, np.int32)
            row[:need] = req.pages
            self._page_table[slot] = row
            self._lengths[slot] = req.prompt.size
            lens = np.asarray([req.prompt.size], np.int32)
            key = jax.random.fold_in(self._base_key,
                                     1_000_000 + req.id)
            tok_dev, self._caches = self._prefill_jit(
                self._params, self._caches, req.device_prompt, lens,
                self._page_table[slot][None, :], key)
            tok = int(np.asarray(tok_dev)[0])
            self._trace_event(req, "prefill_done")
            req.first_token_t = self._trace_event(req, "first_token")
            ttft.observe(req.first_token_t - req.submit_t)
            req.tokens.append(tok)
            req.status = "running"
            self._running[slot] = req
            self._last_tokens[slot] = tok
            self._active[slot] = True
            _metrics.counter("serve.tokens").inc()
            reason = self._done_reason(req, tok)
            if reason:
                self._release(req, finished, reason)

    def _grow_pages(self):
        """Allocate the page each slot's next token write needs where
        lengths crossed a boundary; slots that cannot get one stall
        (deactivate) for this round and retry next step. Returns the
        stalled slots. Idempotent — safe to re-run after a preemption
        freed pages."""
        stalled = []
        ps = self.cfg.page_size
        for slot, req in self._running.items():
            self._active[slot] = True
            ln = int(self._lengths[slot])
            if ln % ps or ln // ps < len(req.pages):
                continue                   # room in the current page
            if self._free_pages:
                page = self._free_pages.popleft()
                req.pages.append(page)
                self._page_table[slot, ln // ps] = page
            else:
                _metrics.counter("serve.page_stalls").inc(where="decode")
                self._active[slot] = False
                stalled.append(slot)
        return stalled

    def _preempt(self, req):
        """Recompute preemption: drop the request's device state and
        requeue it at the FRONT of the queue (its staged prompt is still
        device-resident, so re-admission pays only the prefill)."""
        slot = req.slot
        self._trace_event(req, "preempted",
                          tokens_dropped=len(req.tokens))
        self._free_pages.extend(req.pages)
        req.pages = []
        self._page_table[slot] = 0
        self._lengths[slot] = 0
        self._active[slot] = False
        self._last_tokens[slot] = 0
        self._running.pop(slot, None)
        self._free_slots.append(slot)
        req.slot = None
        req.tokens = []
        req.status = "queued"
        req.preemptions += 1
        self._queue.appendleft(req)
        _metrics.counter("serve.preemptions").inc()

    def _done_reason(self, req, tok):
        """Retirement reason for the token just emitted, or None."""
        if req.eos_id is not None and tok == req.eos_id:
            return "eos"
        if len(req.tokens) >= req.max_new:
            return "length"
        return None

    def _account_slo(self, req):
        """Classify one retirement against the configured SLOs and
        refresh serve.goodput (SLO targets of 0 are unbounded)."""
        cfg = self.cfg
        ok = True
        if req.first_token_t is not None:
            ttft = req.first_token_t - req.submit_t
            if cfg.slo_ttft_s and ttft > cfg.slo_ttft_s:
                _metrics.counter("serve.slo_violations").inc(kind="ttft")
                ok = False
            if cfg.slo_token_latency_s and len(req.tokens) > 1:
                per_tok = ((req.done_t - req.first_token_t)
                           / (len(req.tokens) - 1))
                if per_tok > cfg.slo_token_latency_s:
                    _metrics.counter("serve.slo_violations").inc(
                        kind="token_latency")
                    ok = False
        req.slo_ok = ok
        self._retired += 1
        self._retired_ok += int(ok)
        _metrics.gauge("serve.goodput").set(self.goodput())

    def _release(self, req, finished, reason="length"):
        slot = req.slot
        self._free_pages.extend(req.pages)
        req.pages = []
        self._page_table[slot] = 0
        self._lengths[slot] = 0
        self._active[slot] = False
        self._last_tokens[slot] = 0
        self._running.pop(slot, None)
        self._free_slots.append(slot)
        req.status = "done"
        req.retire_reason = reason
        req.done_t = self._clock()
        req.device_prompt = None
        self._account_slo(req)
        self._trace_event(req, "retired", reason=reason,
                          tokens=len(req.tokens), slo_ok=req.slo_ok,
                          preemptions=req.preemptions)
        finished.append(req)
        _metrics.counter("serve.requests").inc(status="completed")
