"""Fleet router — fault-tolerant multi-replica serving.

Ref: the reference framework's fleet runtime (fleet_wrapper / the PSLib
server) keeps a job alive through worker death and stragglers, but its
serving story stops at one predictor per process. This module is the
serving-side fleet layer our ROADMAP names ("Fleet-scale serving front
door"): a `FleetRouter` owning N `ServingEngine` replicas, so one
replica loss degrades capacity instead of availability. Placement
follows the hierarchical-supervisor argument of arxiv 2110.10548:
routing and recovery decisions live in the one component that sees the
whole topology, never inside a single engine.

What the router does:

  dispatch   least-loaded + priority-aware (the engine's admission key,
             fleet-wide): per-replica bounded queues, a global
             admission limit, expired work shed before it wastes pages.
  liveness   per-replica heartbeat (parallel/heartbeat.py) pinged every
             healthy round through `fault_point("fleet.heartbeat")`;
             a silent replica goes `stalled` (no new dispatch) and,
             past `heartbeat_dead_factor x heartbeat_s`, dead.
  failover   on replica death (step crash past the engine's own retry
             budget, a killed process, heartbeat loss) every in-flight
             request is re-routed to a healthy replica with PR-7's
             token-exact replay: the router keeps a durable host-side
             mirror (prompt + tokens synced each round from
             `engine.export_inflight()`), and `engine.adopt()` restages
             it with submit_t / first_token_t / deadline / priority
             preserved — greedy failover completions are bit-exact and
             SLO accounting lands on the completing replica.
  respawn    dead replicas respawn under a per-replica `RetryBudget`
             (core/retry.py backoff pacing, `fleet.respawn` fault
             point); a replica past its budget stays dead, the fleet
             serves on. A fresh engine re-traces its jits once — that
             first trace is per-engine, so `jit.retraces{fn=
             serve.decode}` stays flat across failover.
  degrade    engine watchdog anomalies (goodput collapse) propagate up
             through `anomaly_sink`, and the router sheds expired /
             lowest-priority pending work fleet-wide.
  drain      `drain()` quiesces replicas one at a time — no new
             dispatch to a draining replica while the rest absorb the
             backlog — and retires every accepted request.
  deploy     `deploy(ckpt, version=)` is a zero-downtime rolling weight
             hot-swap: the checkpoint is loaded and crc32-verified
             against its PR-15 manifest BEFORE any replica is touched
             (a corrupt manifest aborts with the fleet still serving
             the old version), then each replica is drained and
             respawned on the new weights one at a time while the rest
             absorb the traffic; a mid-swap failure rolls the touched
             replica back to its old version. Every retirement carries
             a version tag (`fleet.version_retirements{version}`), so
             goodput/SLO are attributable per model version.
  canary     `deploy(..., canary=True)` swaps ONE replica and routes a
             `fleet_canary_weight` fraction of fresh traffic to the new
             version (`model_id@version` dispatch; a request never
             switches versions mid-stream — failover re-routes stay on
             the version that generated their tokens). Per-version
             goodput is tracked from the version-tagged retirements;
             when the canary's falls below the baseline's by
             `FleetConfig.canary_margin` the canary is aborted and
             rolled back automatically (`fleet.canary_aborts`).
  autoscale  queue-depth/goodput signals (the same plane that feeds
             `anomaly_sink`) spawn and drain replicas against offered
             load between `fleet_autoscale_min` and
             `fleet_autoscale_max` under a `fleet_scale_cooldown_s`
             cooldown; scale-downs always go through graceful drain —
             in-flight work finishes or is re-routed, never dropped
             (`fleet.scale_events{direction}`).
  disagg     `fleet_prefill_replicas=N` carves the first N replicas
             out as dedicated PREFILL replicas; the rest serve decode.
             A prefill-heavy request (prompt longer than the engine's
             prefill_len — its admission is multiple chunked-prefill
             calls) runs its prefill plus exactly the first token on a
             prefill replica (max_new capped to 1), then hands the
             remainder off to a decode replica through the SAME
             token-exact adopt/replay path failover uses (prompt +
             tokens=[t0], real max_new, pinned seed/version) —
             `fleet.handoffs` counts the hop and the request's durable
             trace grows a `handoff`-origin span. A `fleet.handoff`
             fault degrades that one request to mixed routing (it
             finishes wherever capacity exists); a role with no alive
             replica degrades new admissions to mixed routing entirely
             — disaggregation trades goodput, never availability. The
             autoscaler never retires the last alive replica of a
             role.

Replicas are in-process by default (N engines, one process — the test
and bench shape). `SubprocessReplica` + `replica_worker_loop` run an
engine in a child process over the `parallel/launch.py host_allgather`
file transport (one command/response exchange per round, generation-
suffixed so a respawned worker never reads its dead predecessor's
exchange files), with `parallel/elastic.py`-style respawn pacing.

    router = FleetRouter(model, variables, FleetConfig(num_replicas=3))
    fid = router.submit([1, 2, 3], max_new=32)
    finished = router.drain()
"""

import collections
import dataclasses
import itertools
import json
import os
import subprocess
import threading
import time

import numpy as np

from paddle_tpu.core.enforce import enforce
from paddle_tpu.core.flags import get_flag
from paddle_tpu.core.retry import RetryBudget, RetryPolicy
from paddle_tpu.observability import flight as _flight
from paddle_tpu.observability import metrics as _metrics
from paddle_tpu.observability import trace as _trace
from paddle_tpu.parallel.heartbeat import STALLED, HeartBeatMonitor
from paddle_tpu.serving.engine import ServeConfig, ServingEngine
from paddle_tpu.testing.chaos import fault_point

_TERMINAL = ("done", "rejected", "shed", "cancelled", "failed")


class ReplicaDead(RuntimeError):
    """A replica handle was used after its process/engine died."""


class DeployAborted(RuntimeError):
    """A rolling weight deploy was aborted (corrupt manifest, rejected
    while draining, or a mid-swap failure that rolled the touched
    replica back). The fleet keeps serving on the versions it had."""


@dataclasses.dataclass
class FleetConfig:
    num_replicas: int = None      # None -> serve_replicas flag
    heartbeat_s: float = None     # None -> fleet_heartbeat_s flag
    heartbeat_dead_factor: float = 10.0   # silent this many heartbeats
    #                               past the stall mark -> declared dead
    respawn_budget: int = None    # None -> fleet_respawn_budget flag
    drain_timeout_s: float = None  # None -> fleet_drain_timeout_s flag
    admission_limit: int = 0      # pending + dispatched cap; 0 = off
    replica_queue_limit: int = 0  # queued-per-replica dispatch bound;
    #                               0 -> 2 x the engine's decode slots
    metrics_port: int = None      # None -> metrics_port flag; 0 = off
    model_id: str = "model"       # dispatch identity: model_id@version
    baseline_version: str = "v0"  # version tag of the construction-time
    #                               weights (deploys move the baseline)
    canary_weight: float = None   # None -> fleet_canary_weight flag
    canary_margin: float = 0.1    # canary goodput this far below the
    #                               baseline's -> automatic abort
    canary_min_retired: int = 5   # per-version retirements before the
    #                               canary comparison is trusted
    autoscale_min: int = None     # None -> fleet_autoscale_min flag
    autoscale_max: int = None     # None -> fleet_autoscale_max flag;
    #                               0 = autoscaling off
    scale_cooldown_s: float = None   # None -> fleet_scale_cooldown_s
    deploy_verify: bool = None    # None -> fleet_deploy_verify flag
    prefill_replicas: int = None  # None -> fleet_prefill_replicas flag;
    #                               first N replicas = dedicated prefill
    #                               role, rest = decode; 0 = every
    #                               replica mixed-mode (no disagg)

    def resolve(self):
        if self.num_replicas is None:
            self.num_replicas = int(get_flag("serve_replicas"))
        if self.heartbeat_s is None:
            self.heartbeat_s = float(get_flag("fleet_heartbeat_s"))
        if self.respawn_budget is None:
            self.respawn_budget = int(get_flag("fleet_respawn_budget"))
        if self.drain_timeout_s is None:
            self.drain_timeout_s = float(get_flag("fleet_drain_timeout_s"))
        if self.metrics_port is None:
            self.metrics_port = int(get_flag("metrics_port"))
        if self.canary_weight is None:
            self.canary_weight = float(get_flag("fleet_canary_weight"))
        if self.autoscale_min is None:
            self.autoscale_min = int(get_flag("fleet_autoscale_min"))
        if self.autoscale_max is None:
            self.autoscale_max = int(get_flag("fleet_autoscale_max"))
        if self.scale_cooldown_s is None:
            self.scale_cooldown_s = float(
                get_flag("fleet_scale_cooldown_s"))
        if self.deploy_verify is None:
            self.deploy_verify = bool(get_flag("fleet_deploy_verify"))
        if self.prefill_replicas is None:
            self.prefill_replicas = int(
                get_flag("fleet_prefill_replicas"))
        enforce(self.num_replicas >= 1, "fleet needs at least 1 replica")
        enforce(self.prefill_replicas >= 0,
                "fleet_prefill_replicas must be >= 0")
        enforce(self.heartbeat_s > 0, "fleet_heartbeat_s must be > 0")
        enforce(0.0 <= self.canary_weight <= 1.0,
                "fleet_canary_weight must be in [0, 1]")
        return self


@dataclasses.dataclass
class FleetRequest:
    """The router's durable record of one accepted request — the
    failover mirror. `tokens` is synced from the owning replica every
    healthy round, so a later replica death replays prompt + tokens
    token-exact even though the dead engine's state is gone."""
    id: int
    prompt: np.ndarray
    max_new: int
    eos_id: int = None
    tokens: list = dataclasses.field(default_factory=list)
    status: str = "pending"       # pending -> dispatched -> terminal
    priority: int = 0
    temperature: float = None     # per-request sampling; None = engine
    top_k: int = None             #   defaults. The router pins `seed` at
    top_p: float = None           #   submit so a failover re-route
    seed: int = None              #   re-draws the same sample stream.
    deadline_t: float = None      # absolute router-clock deadline
    submit_t: float = None
    first_token_t: float = None
    done_t: float = None
    replica: int = None           # owning (then completing) replica
    replica_rid: int = None       # the replica-local request id
    version: str = None           # model version serving this request —
    #                               chosen at routing time, then PINNED:
    #                               a failover re-route never switches
    #                               versions once tokens were generated
    reroutes: int = 0             # failover re-dispatches survived
    phase: str = None             # disaggregation phase: None = mixed
    #                               routing, "prefill" = running its
    #                               prefill+first-token leg on a prefill
    #                               replica, "decode" = handed off (the
    #                               role filter keeps failover re-routes
    #                               on decode replicas too)
    retire_reason: str = None
    slo_ok: bool = None
    retriable: bool = False
    trace_id: str = None          # durable fleet trace id, minted ONCE
    #                               at submit and carried across every
    #                               dispatch/failover hop
    next_span: int = 0            # hop counter: each dispatch becomes
    #                               span "hop<N>" under the root span

    @property
    def output(self):
        """prompt + generated tokens (the generate()-shaped sequence)."""
        return np.concatenate([self.prompt,
                               np.asarray(self.tokens, np.int32)])


# --------------------------------------------------------------------------
# replica handles
# --------------------------------------------------------------------------


def _newly_terminal(engine, reported):
    """Engine requests that reached a terminal status and have not been
    reported to the router yet (`reported` is mutated). Includes
    retirements that happened OUTSIDE engine.step() — watchdog load
    shedding, engine-side deadline sheds — so the router's mirror never
    orphans a dispatched record."""
    out = [r for rid, r in engine.requests.items()
           if r.status in _TERMINAL and rid not in reported]
    reported.update(r.id for r in out)
    return sorted(out, key=lambda r: r.id)


class InProcessReplica:
    """A ServingEngine behind the replica-handle surface the router
    drives (dispatch/step/load/kill/respawn). `kill()` freezes the
    handle the way a process death would — the engine object survives
    for post-mortem, but every call raises ReplicaDead and the router
    recovers from its own mirror, never from the corpse."""

    def __init__(self, factory, anomaly_sink=None):
        self._factory = factory
        self._sink = anomaly_sink
        self.engine = None
        self._dead = False
        self._reported = set()
        self.respawn()

    def respawn(self):
        """Fresh engine, fresh jits — the respawned replica's first
        decode trace is its own TracedOnce baseline, not a retrace."""
        self.engine = self._factory()
        if self._sink is not None:
            self.engine.anomaly_sink = self._sink
        self._dead = False
        self._reported = set()

    def alive(self):
        return not self._dead

    def kill(self):
        self._dead = True

    def _check(self):
        if self._dead:
            raise ReplicaDead("in-process replica killed")

    def dispatch(self, specs):
        self._check()
        return [self.engine.adopt(
            spec["prompt"], tokens=spec["tokens"],
            max_new=spec["max_new"], eos_id=spec["eos_id"],
            priority=spec["priority"], deadline_t=spec["deadline_t"],
            submit_t=spec["submit_t"],
            first_token_t=spec["first_token_t"],
            origin=spec.get("origin", "fleet"),
            temperature=spec.get("temperature"),
            top_k=spec.get("top_k"), top_p=spec.get("top_p"),
            seed=spec.get("seed"),
            trace=spec.get("trace")) for spec in specs]

    def cancel(self, rid):
        self._check()
        return self.engine.cancel(rid)

    def step(self):
        self._check()
        eng = self.engine
        if eng._queue or eng._running:
            eng.step()
        # report every retirement since the last round, not only this
        # step() call's — the watchdog's shed_queued (and any other
        # out-of-band retirement) must reach the router's mirror too
        fin = _newly_terminal(eng, self._reported)
        return {
            "finished": [dict(rid=r.id, status=r.status,
                              reason=r.retire_reason,
                              tokens=list(r.tokens), slo_ok=r.slo_ok,
                              first_token_t=r.first_token_t)
                         for r in fin],
            "inflight": eng.export_inflight(),
            "queued": len(eng._queue),
            "active": len(eng._running),
        }

    def queued(self):
        return 0 if self._dead else len(self.engine._queue)

    def load(self):
        if self._dead:
            return 0
        return len(self.engine._queue) + len(self.engine._running)

    def telemetry(self):
        eng = self.engine
        return dict(goodput=round(eng.goodput(), 4), slo=eng.slo_stats(),
                    decode_traces=eng.decode_traces,
                    recoveries=eng.recoveries, queued=self.queued(),
                    active=0 if self._dead else len(eng._running),
                    alive=self.alive(),
                    version=getattr(eng, "version", None))

    def close(self):
        if self.engine is not None:
            self.engine.close()


def _pack(obj):
    return np.frombuffer(json.dumps(obj).encode(), dtype=np.uint8).copy()


def _unpack(arr):
    return json.loads(bytes(np.asarray(arr, np.uint8).tolist()).decode())


class SubprocessReplica:
    """A replica over the host_allgather file transport: the engine runs
    in a child process (the realistic failure domain — a replica kill is
    a process kill), and the router drives one command/response exchange
    per round (rank 0 = router, rank 1 = worker). `generation` (the
    respawn count) suffixes every exchange file, so a respawned worker
    restarting its sequence at 0 never reads its dead predecessor's
    payloads — the stale-incarnation case host_allgather cleans up.

    Wire times are relative (ages / seconds-remaining): the child's
    perf_counter shares no epoch with the router's, so absolute router
    times are converted at this boundary in both directions."""

    def __init__(self, argv, exchange_dir, replica=0, env=None,
                 timeout_s=60.0, clock=time.perf_counter):
        self.argv = list(argv)
        self.exchange_dir = exchange_dir
        self.replica = replica
        self.timeout_s = timeout_s
        self._clock = clock
        self._env = dict(env or {})
        self.generation = -1
        self._proc = None
        self._seq = 0
        self._lids = itertools.count()
        self._outbox = []            # (lid, wire spec) awaiting next round
        self._rid_to_lid = {}
        self._counts = (0, 0)        # (queued, active) from last response
        self.respawn()

    def respawn(self):
        if self._proc is not None and self._proc.poll() is None:
            self._proc.kill()
            self._proc.wait()
        self.generation += 1
        self._seq = 0
        self._outbox = []
        self._rid_to_lid = {}
        self._counts = (0, 0)
        env = dict(os.environ)
        env.update(self._env)
        env.update({
            "PT_FLEET_XDIR": self.exchange_dir,
            "PT_FLEET_REPLICA": str(self.replica),
            "PT_FLEET_GENERATION": str(self.generation),
        })
        self._proc = subprocess.Popen(self.argv, env=env)

    def alive(self):
        return self._proc is not None and self._proc.poll() is None

    def kill(self):
        if self._proc is not None and self._proc.poll() is None:
            self._proc.kill()

    def dispatch(self, specs):
        lids = []
        now = self._clock()
        for spec in specs:
            lid = next(self._lids)
            wire = dict(
                key=lid,
                prompt=np.asarray(spec["prompt"]).astype(int).tolist(),
                tokens=[int(t) for t in spec["tokens"]],
                max_new=int(spec["max_new"]),
                eos_id=(None if spec["eos_id"] is None
                        else int(spec["eos_id"])),
                priority=int(spec["priority"]),
                origin=spec.get("origin", "fleet"),
                temperature=(None if spec.get("temperature") is None
                             else float(spec["temperature"])),
                top_k=(None if spec.get("top_k") is None
                       else int(spec["top_k"])),
                top_p=(None if spec.get("top_p") is None
                       else float(spec["top_p"])),
                seed=(None if spec.get("seed") is None
                      else int(spec["seed"])),
                deadline_in_s=(None if spec["deadline_t"] is None
                               else spec["deadline_t"] - now),
                submit_age_s=(0.0 if spec["submit_t"] is None
                              else now - spec["submit_t"]),
                first_token_age_s=(None if spec["first_token_t"] is None
                                   else now - spec["first_token_t"]),
                trace=spec.get("trace"))   # durable context: the wire
            #                                dict is already JSON-safe
            self._outbox.append(wire)
            lids.append(lid)
        return lids

    def cancel(self, rid):
        return False                  # not plumbed over the wire (yet)

    def _exchange(self, tag, payload):
        from paddle_tpu.parallel import launch
        gathered = launch.host_allgather(
            payload, 0, 2, self.exchange_dir,
            f"p{self.replica}.{tag}", timeout=self.timeout_s,
            generation=self.generation, ragged=True)
        return gathered[1]

    def step(self):
        if not self.alive():
            raise ReplicaDead(
                f"subprocess replica {self.replica} exited "
                f"rc={self._proc.returncode}")
        cmd = {"op": "round", "submit": self._outbox}
        seq = self._seq
        try:
            self._exchange(f"q{seq}", _pack(cmd))
            resp = _unpack(self._exchange(f"r{seq}", _pack({})))
        except TimeoutError as e:
            raise ReplicaDead(
                f"subprocess replica {self.replica} unresponsive: "
                f"{e}") from e
        self._seq += 1
        self._outbox = []
        now = self._clock()

        def abs_t(age):
            return None if age is None else now - age

        for sub in resp.get("submitted", []):
            self._rid_to_lid[sub["rid"]] = sub["key"]
        report = {"finished": [], "inflight": [],
                  "queued": int(resp.get("queued", 0)),
                  "active": int(resp.get("active", 0))}
        for fin in resp.get("finished", []):
            lid = self._rid_to_lid.pop(fin["rid"], None)
            if lid is None:
                continue
            report["finished"].append(dict(
                rid=lid, status=fin["status"], reason=fin["reason"],
                tokens=fin["tokens"], slo_ok=fin["slo_ok"],
                first_token_t=abs_t(fin.get("first_token_age_s"))))
        for inf in resp.get("inflight", []):
            lid = self._rid_to_lid.get(inf["rid"])
            if lid is None:
                continue
            report["inflight"].append(dict(
                rid=lid, status=inf["status"], tokens=inf["tokens"],
                first_token_t=abs_t(inf.get("first_token_age_s"))))
        self._counts = (report["queued"], report["active"])
        return report

    def queued(self):
        return self._counts[0] + len(self._outbox)

    def load(self):
        return self._counts[0] + self._counts[1] + len(self._outbox)

    def telemetry(self):
        return dict(alive=self.alive(), generation=self.generation,
                    queued=self._counts[0], active=self._counts[1])

    def close(self):
        if self.alive():
            try:
                self._exchange(f"q{self._seq}",
                               _pack({"op": "stop", "submit": []}))
                self._proc.wait(timeout=self.timeout_s)
            except Exception:
                self.kill()
        self._proc = None


def replica_worker_loop(engine, exchange_dir=None, replica=None,
                        generation=None, timeout_s=60.0,
                        clock=time.perf_counter):
    """Child-process side of SubprocessReplica: gather one command per
    round, adopt()/step() the local engine, publish the response.
    Defaults resolve from the PT_FLEET_* env the parent set, so a
    worker script is just `replica_worker_loop(ServingEngine(...))`."""
    from paddle_tpu.parallel import launch

    xdir = exchange_dir or os.environ["PT_FLEET_XDIR"]
    rep = int(os.environ.get("PT_FLEET_REPLICA", 0)
              if replica is None else replica)
    gen = int(os.environ.get("PT_FLEET_GENERATION", 0)
              if generation is None else generation)
    engine.replica = rep          # stamps every trace event
    seq = 0
    reported = set()
    while True:
        gathered = launch.host_allgather(
            _pack({}), 1, 2, xdir, f"p{rep}.q{seq}", timeout=timeout_s,
            generation=gen, ragged=True)
        cmd = _unpack(gathered[0])
        now = clock()
        submitted = []
        for spec in cmd.get("submit", []):
            rid = engine.adopt(
                np.asarray(spec["prompt"], np.int32),
                tokens=spec["tokens"], max_new=spec["max_new"],
                eos_id=spec["eos_id"], priority=spec["priority"],
                deadline_t=(None if spec["deadline_in_s"] is None
                            else now + spec["deadline_in_s"]),
                submit_t=now - spec["submit_age_s"],
                first_token_t=(None if spec["first_token_age_s"] is None
                               else now - spec["first_token_age_s"]),
                origin=spec.get("origin", "fleet"),
                temperature=spec.get("temperature"),
                top_k=spec.get("top_k"), top_p=spec.get("top_p"),
                seed=spec.get("seed"), trace=spec.get("trace"))
            submitted.append({"key": spec["key"], "rid": rid})
        if engine._queue or engine._running:
            engine.step()
        if cmd.get("op") == "stop":
            return                # close() never gathers a response —
            #                       publishing one would block on a
            #                       rank-0 file that never appears
        fin = _newly_terminal(engine, reported)
        now = clock()

        def age(t):
            return None if t is None else now - t

        resp = {
            "submitted": submitted,
            "finished": [dict(rid=r.id, status=r.status,
                              reason=r.retire_reason,
                              tokens=list(r.tokens), slo_ok=r.slo_ok,
                              first_token_age_s=age(r.first_token_t))
                         for r in fin],
            "inflight": [dict(rid=e["rid"], status=e["status"],
                              tokens=e["tokens"],
                              first_token_age_s=age(e["first_token_t"]))
                         for e in engine.export_inflight()],
            "queued": len(engine._queue),
            "active": len(engine._running),
        }
        launch.host_allgather(_pack(resp), 1, 2, xdir,
                              f"p{rep}.r{seq}", timeout=timeout_s,
                              generation=gen, ragged=True)
        seq += 1


# --------------------------------------------------------------------------
# the router
# --------------------------------------------------------------------------


class FleetRouter:
    """submit()/step()/drain() over N engine replicas with failover."""

    def __init__(self, model=None, variables=None, config=None,
                 serve_config=None, replicas=None,
                 clock=time.perf_counter):
        self.cfg = (config or FleetConfig()).resolve()
        cfg = self.cfg
        self._clock = clock
        from paddle_tpu.observability import catalog as _catalog
        _catalog.preregister([
            "fleet.replicas", "fleet.failovers", "fleet.rerouted",
            "fleet.dispatch_depth", "fleet.respawns",
            "fleet.affinity_hits", "fleet.version_retirements",
            "fleet.deploys", "fleet.scale_events",
            "fleet.canary_aborts", "fleet.handoffs"])
        # One reentrant lock guards the router mirror: submit()/cancel()
        # arrive on client threads while step()/drain() run the round
        # thread, and the engine watchdog's anomaly callback re-enters
        # shed_pending() from under a step that already holds the lock.
        # Created before the replicas: the version-aware engine factory
        # reads the per-replica weight assignment under it.
        self._lock = threading.RLock()
        # deploy()/drain() are whole-fleet operations that drive many
        # rounds; this mutex serializes them so a drain arriving during
        # a rollout waits for the swap to finish (or abort) before
        # quiescing — they never interleave half-done.
        self._ops_lock = threading.Lock()
        self._model = model
        self._serve_template = serve_config or ServeConfig()
        # version -> weights; every respawn/swap rebuilds its engine
        # from this store, so a failure mid-rollout comes back on the
        # version the replica was serving
        self._weights = {}            # graft-guard: self._lock
        if variables is not None:
            self._weights[cfg.baseline_version] = variables
        self._baseline_version = cfg.baseline_version   # graft-guard: self._lock
        self._canary_version = None   # graft-guard: self._lock
        self._deploying = None        # graft-guard: self._lock
        self._pending_swaps = {}      # replica -> version|None (None =
        #                               scale-down retire); graft-guard: self._lock
        self._version_stats = {}      # version -> [retired, slo_ok];
        #                               graft-guard: self._lock
        self._last_scale_t = None     # graft-guard: self._lock
        self.ops_log = []             # deploy/scale/canary event records;
        #                               graft-guard: self._lock
        if replicas is not None:
            self._replicas = list(replicas)
            self._versions = [cfg.baseline_version] * len(self._replicas)
            for i, h in enumerate(list(self._replicas)):
                # user-built engines miss the _engine_factory stamps;
                # the router owns replica index + version identity
                eng = getattr(h, "engine", None)
                if eng is not None:
                    if eng.replica is None:
                        eng.replica = i
                    if eng.version is None:
                        eng.version = (f"{cfg.model_id}"
                                       f"@{cfg.baseline_version}")
        else:
            enforce(model is not None and variables is not None,
                    "FleetRouter needs (model, variables) or explicit "
                    "replica handles")
            self._versions = [cfg.baseline_version] * cfg.num_replicas
            self._replicas = [
                InProcessReplica(
                    self._engine_factory(i),
                    anomaly_sink=self._sink_for(i))
                for i in range(cfg.num_replicas)]
        # graft-guard: self._lock (self._versions: per-replica serving
        # version, read by the engine factory and the dispatch filter)
        n = len(self._replicas)
        # submit() mirrors ServingEngine.submit defaults, so max_new must
        # fall back to the replicas' OWN serve config, not a fresh one
        self._default_max_new = int(next(
            (h.engine.cfg.default_max_new for h in list(self._replicas)
             if isinstance(h, InProcessReplica)),
            serve_config.default_max_new if serve_config is not None
            else ServeConfig().default_max_new))
        if cfg.replica_queue_limit <= 0:
            slots = max((h.engine.cfg.num_slots
                         for h in list(self._replicas)
                         if isinstance(h, InProcessReplica)), default=4)
            cfg.replica_queue_limit = max(2, 2 * slots)
        self._states = ["live"] * n   # graft-guard: self._lock
        # prefill/decode disaggregation: the first prefill_replicas
        # indices are the prefill role, the rest decode. An empty list
        # means every replica is mixed-mode (disagg off) — the roles
        # list stays parallel to self._replicas when non-empty.
        # graft-guard: self._lock
        if cfg.prefill_replicas > 0:
            enforce(cfg.prefill_replicas < n,
                    "fleet_prefill_replicas must leave at least one "
                    "decode replica")
            self._roles = ["prefill" if i < cfg.prefill_replicas
                           else "decode" for i in range(n)]
        else:
            self._roles = []
        # prefill-heavy threshold: a prompt longer than this needs
        # multiple chunked-prefill calls, so its admission cost is what
        # disaggregation moves off the decode replicas
        self._prefill_cut = int(next(
            (h.engine.cfg.prefill_len for h in list(self._replicas)
             if isinstance(h, InProcessReplica)),
            serve_config.prefill_len if serve_config is not None
            else ServeConfig().prefill_len))
        self.handoffs = 0
        self._monitor = HeartBeatMonitor(
            n, timeout_s=cfg.heartbeat_s, interval_s=cfg.heartbeat_s,
            clock=clock)
        for i in range(n):
            self._monitor.update(i)
        self._budgets = [
            RetryBudget(RetryPolicy(max_attempts=cfg.respawn_budget + 1),
                        "fleet.respawn") for _ in range(n)]
        self.requests = {}            # fid -> FleetRequest; graft-guard: self._lock
        self._pending = collections.deque()   # graft-guard: self._lock
        self._by_replica = {}   # (replica, replica_rid) -> fid; graft-guard: self._lock
        self._ids = itertools.count()
        self._step_no = 0
        self._draining = False        # graft-guard: self._lock
        self.failovers = 0
        # durable trace plane: one run prefix for every trace id this
        # router mints; ids survive dispatch/failover hops (trace_fleet)
        self._trace_run = _trace.mint_run()
        self._flight_dumped = set()   # anomaly kinds already bundled;
        #                               graft-guard: self._lock
        from paddle_tpu.observability.exporter import start_metrics_server
        self._metrics_server = start_metrics_server(cfg.metrics_port)
        self._publish()

    def _engine_factory(self, i):
        """Factory for replica i's engine, bound to the replica's
        CURRENT version assignment: a failure respawn comes back on the
        version the replica was serving, and a deploy swap changes
        `self._versions[i]` first, then respawns through this."""
        def build():
            sc = dataclasses.replace(self._serve_template)
            sc.metrics_port = 0      # ONE exporter, owned by the router
            if isinstance(sc.run_log, str) and sc.run_log:
                # per-replica RunLogs: N engines in one process must not
                # interleave one JSONL — the fleet-trace merge wants one
                # anchored log per replica ("{replica}" templates, else
                # an .r<i> suffix; non-digit, so rotation reads skip it)
                sc.run_log = (sc.run_log.format(replica=i)
                              if "{replica}" in sc.run_log
                              else f"{sc.run_log}.r{i}")
            with self._lock:
                version = self._versions[i]
                variables = self._weights[version]
            sc.model_version = f"{self.cfg.model_id}@{version}"
            eng = ServingEngine(self._model, variables, sc)
            eng.replica = i          # stamps every trace event
            return eng
        return build

    def _sink_for(self, i):
        return lambda event: self._on_replica_anomaly(i, event)

    # -- client surface ---------------------------------------------------

    def submit(self, prompt, max_new=None, eos_id=None, deadline_s=None,
               priority=0, temperature=None, top_k=None, top_p=None,
               seed=None):
        """Accept a request fleet-wide; returns the fleet request id.
        Mirrors ServingEngine.submit semantics (default deadline from
        the serve_default_deadline_s flag, infeasible deadlines rejected
        up front, retriable rejection hints) with the global admission
        limit in place of the per-engine queue bound. Per-request
        sampling knobs pass through to the owning engine; the SEED is
        pinned here (derived from the fleet id when not given) so a
        failover re-route onto another replica re-draws the same
        sample stream."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        with self._lock:
            rec = FleetRequest(id=next(self._ids), prompt=prompt,
                               max_new=(max_new if max_new is not None
                                        else self._default_max_new),
                               eos_id=eos_id, priority=int(priority))
            rec.temperature = temperature
            rec.top_k = top_k
            rec.top_p = top_p
            rec.seed = ((1_000_003 * rec.id + 12_345) & 0xFFFFFFFF
                        if seed is None else int(seed) & 0xFFFFFFFF)
            if get_flag("trace_fleet"):
                # the durable context: minted HERE, once; every
                # dispatch/failover hop derives a child span of it
                rec.trace_id = f"{self._trace_run}/{rec.id}"
            rec.submit_t = self._clock()
            self.requests[rec.id] = rec
            _metrics.counter("serve.requests").inc(status="submitted")
            if self._draining:
                rec.retriable = True
                self._retire(rec, "rejected", "draining")
                return rec.id
            if deadline_s is None:
                default = float(get_flag("serve_default_deadline_s"))
                deadline_s = default if default > 0 else None
            if deadline_s is not None:
                if deadline_s <= 0:
                    rec.retriable = True
                    self._retire(rec, "rejected", "infeasible_deadline")
                    return rec.id
                rec.deadline_t = rec.submit_t + float(deadline_s)
            # rec already sits in self.requests as "pending", so the
            # count includes this request: admit while count <= limit
            if self.cfg.admission_limit and (
                    self._outstanding() > self.cfg.admission_limit):
                rec.retriable = True
                self._retire(rec, "rejected", "fleet_admission_limit")
                return rec.id
            self._pending.append(rec)
            self._dispatch([])
            return rec.id

    def cancel(self, fid):
        """Cancel a fleet request: pending records retire directly, a
        dispatched in-process one cancels at its replica."""
        with self._lock:
            rec = self.requests.get(fid)
            if rec is None or rec.status in _TERMINAL:
                return False
            if rec.status == "pending":
                self._pending.remove(rec)
                self._retire(rec, "cancelled", "cancelled", account=False)
                return True
            handle = self._replicas[rec.replica]
            if handle.cancel(rec.replica_rid):
                self._by_replica.pop((rec.replica, rec.replica_rid), None)
                self._retire(rec, "cancelled", "cancelled", account=False,
                             count=False)
                return True
            return False

    def step(self):
        """One router round: dispatch pending work, step every live
        replica (syncing the failover mirror), ping heartbeats, scan
        for stalls/deaths. Returns the fleet requests that reached a
        terminal status this round."""
        with self._lock:
            finished = []
            self._dispatch(finished)
            for i, handle in enumerate(list(self._replicas)):
                if self._states[i] in ("dead", "retired"):
                    continue
                if not handle.alive():
                    self._on_replica_failure(
                        i, ReplicaDead(f"replica {i} process died"),
                        finished)
                    continue
                if (handle.load() == 0
                        and not self._replica_outstanding(i)):
                    self._ping(i)
                    continue
                # load > 0, or the mirror still shows dispatched work
                # the replica's load no longer does (an out-of-band
                # retirement like watchdog shedding) — a round fetches
                # the report
                try:
                    report = handle.step()
                except Exception as e:
                    self._on_replica_failure(i, e, finished)
                    continue
                self._budgets[i].success()
                self._ping(i)
                self._sync(i, report, finished)
            self._scan_heartbeats(finished)
            self._advance_swaps(finished)
            self._check_canary()
            self._autoscale()
            self._publish()
            self._step_no += 1
            return finished

    def drain(self, max_steps=200000):
        """Retire every accepted request, quiescing replicas one at a
        time: replica i stops receiving new dispatch (state `draining`)
        and is stepped until idle while later replicas absorb the
        backlog; once every replica is draining, leftover pending work
        still dispatches to the least-loaded draining (alive) replica,
        so nothing accepted is dropped. New submissions during drain
        are rejected retriable. Bounded by fleet_drain_timeout_s.

        Serialized against deploy() on the ops mutex: a drain arriving
        during an in-progress rollout BLOCKS until the swap finishes or
        aborts deterministically, then quiesces — the two whole-fleet
        operations never interleave half-done."""
        with self._ops_lock:
            return self._drain_locked(max_steps)

    def _drain_locked(self, max_steps):
        with self._lock:
            self._draining = True
        t0 = self._clock()
        budget = self.cfg.drain_timeout_s
        out = []

        def check(i=None):
            if budget > 0 and self._clock() - t0 > budget:
                with self._lock:
                    left = [r.id for r in self.requests.values()
                            if r.status not in _TERMINAL]
                raise RuntimeError(
                    f"fleet drain: {len(left)} requests not terminal "
                    f"after {budget}s"
                    + (f" (quiescing replica {i})" if i is not None
                       else ""))

        # the lock is released between rounds so late client threads can
        # still reach submit() (and get the retriable draining reject)
        for _ in range(max_steps):
            with self._lock:
                if all(s != "live" for s in self._states):
                    break
                target = next(i for i, s in enumerate(self._states)
                              if s == "live")
                self._states[target] = "draining"
            while True:
                with self._lock:
                    more = (self._states[target] == "draining"
                            and self._replica_outstanding(target))
                if not more:
                    break
                out.extend(self.step())
                check(target)
        while True:
            with self._lock:
                left = any(r.status not in _TERMINAL
                           for r in self.requests.values())
            if not left:
                break
            out.extend(self.step())
            check()
        with self._lock:
            self._publish()
        return out

    def kill_replica(self, i):
        """Drill/test hook — simulate replica process death mid-decode.
        The next step() discovers the corpse and runs the exact failover
        path a real crash takes."""
        self._replicas[i].kill()

    def shed_pending(self, cause="overload"):
        """Fleet-wide load shedding (the watchdog escalation): shed
        every expired pending request; when none is expired, shed the
        single lowest-priority / latest-deadline one — the fleet-level
        mirror of ServingEngine.shed_queued."""
        with self._lock:
            now = self._clock()
            shed = [(r, "deadline_expired") for r in self._pending
                    if r.deadline_t is not None and now > r.deadline_t]
            if not shed and self._pending:
                shed = [(min(self._pending, key=self._victim_key), cause)]
            for rec, why in shed:
                self._pending.remove(rec)
                _metrics.counter("serve.shed").inc(cause=cause)
                self._retire(rec, "shed", why)
            return [rec.id for rec, _ in shed]

    def goodput(self):
        """Fleet goodput: SLO-met fraction of accountable retirements
        (cancellations excluded), wherever each request completed."""
        with self._lock:
            done = [r for r in self.requests.values()
                    if r.status in _TERMINAL and r.status != "cancelled"]
            if not done:
                return 1.0
            return sum(1 for r in done if r.slo_ok) / len(done)

    def telemetry(self):
        """Per-replica + fleet-level snapshot (the bench row payload)."""
        with self._lock:
            return {
                "replicas": [h.telemetry()
                             for h in list(self._replicas)],
                "states": list(self._states),
                "failovers": self.failovers,
                "rerouted": int(sum(r.reroutes
                                    for r in self.requests.values())),
                "respawn_failures": [b.failures
                                     for b in list(self._budgets)],
                "goodput": round(self.goodput(), 4),
                "versions": list(self._versions),
                "roles": list(self._roles),
                "handoffs": self.handoffs,
                "baseline_version": self._baseline_version,
                "canary_version": self._canary_version,
                "version_stats": {
                    v: {"retired": s[0], "slo_ok": s[1],
                        "goodput": round(s[1] / s[0], 4) if s[0] else 1.0}
                    for v, s in sorted(self._version_stats.items())},
                "ops_log": [dict(e) for e in self.ops_log],
            }

    def close(self):
        for handle in list(self._replicas):
            try:
                handle.close()
            except Exception:
                pass
        if self._metrics_server is not None:
            self._metrics_server.stop()
            self._metrics_server = None

    # -- dispatch ---------------------------------------------------------

    def _admission_key(self, rec):
        dl = rec.deadline_t if rec.deadline_t is not None else float("inf")
        return (-rec.priority, dl, rec.id)

    def _victim_key(self, rec):
        dl = rec.deadline_t if rec.deadline_t is not None else float("inf")
        return (rec.priority, -dl, -rec.id)

    def _outstanding(self):
        return sum(1 for r in self.requests.values()
                   if r.status in ("pending", "dispatched"))

    def _replica_outstanding(self, i):
        return sum(1 for r in self.requests.values()
                   if r.status == "dispatched" and r.replica == i)

    def _eligible_replicas(self):
        live = [i for i, s in enumerate(self._states) if s == "live"]
        if live:
            return live
        # every survivor is draining (late drain, or failover under
        # drain): accepted work still has to land somewhere alive.
        # Replicas quiescing toward a pending swap/retire are excluded:
        # feeding one fresh work would extend its drain by the whole
        # backlog (a single-replica deploy would never converge under
        # load) — the work waits pending and lands on the rebuilt
        # replica a few rounds later instead.
        return [i for i, s in enumerate(self._states)
                if s == "draining" and self._replicas[i].alive()
                and i not in self._pending_swaps]

    def _affinity_depth(self, handle, rec):
        """Leading full prompt pages of `rec` already in a replica's
        prefix cache — the placement signal (cf. PAPERS.md 2110.10548:
        put the work where its data already lives). In-process replicas
        probe the engine's cache directly; subprocess replicas return 0
        (the probe is not plumbed over the wire)."""
        probe = getattr(getattr(handle, "engine", None),
                        "prefix_lookup_depth", None)
        if probe is None:
            return 0
        try:
            return probe(rec.prompt)
        except Exception:
            return 0

    def _choose_version(self, rec):
        """Routing version for a fresh request: the canary version for a
        `fleet_canary_weight` fraction of traffic (deterministic per
        fleet id, so drills replay identically), else the baseline. The
        choice PINS `rec.version` — per-version SLO accounting starts at
        routing, and a later re-route stays on the pinned version."""
        if rec.version is not None:
            return rec.version
        version = self._baseline_version
        canary = self._canary_version
        if canary is not None and self.cfg.canary_weight > 0:
            try:
                fault_point("fleet.canary")
                draw = ((1103515245 * (rec.id + 1) + 12345) >> 7) % 1000
                if draw < int(self.cfg.canary_weight * 1000):
                    version = canary
            except Exception:
                pass      # injected canary-router fault: the request
                #           falls back to the baseline version
        rec.version = version
        return version

    def _pick_replica(self, rec=None):
        """Dispatch target for `rec`: the least-loaded eligible replica
        SERVING THE REQUEST'S VERSION (model_id@version routing), unless
        some replica's prefix cache already holds the request's leading
        prompt pages — then the least-loaded such replica wins
        (fleet.affinity_hits), provided it is not overloaded relative
        to the fleet minimum (imbalance fallback: affinity never starves
        a cold replica of its fair share). A re-routed request that
        already generated tokens is HARD-pinned: only replicas serving
        its version qualify (draining ones included — a failover landing
        must never adopt tokens onto different weights); a fresh request
        soft-prefers its routed version but may re-route to whatever
        capacity exists."""
        if rec is not None and rec.version is not None and rec.tokens:
            # hard pin: mid-stream work never switches versions. Live
            # same-version replicas are preferred; draining ones are
            # the fallback only (a swap target mid-quiesce may be the
            # sole holder of the pinned version)
            live, draining = [], []
            for i, s in enumerate(self._states):
                if (s in ("live", "draining")
                        and self._replicas[i].alive()
                        and self._versions[i] == rec.version):
                    (live if s == "live" else draining).append(
                        (self._replicas[i].load(), i,
                         self._replicas[i]))
            # role-matching capacity wins within each liveness tier;
            # a role with none degrades to mixed rather than wedge the
            # hard-pinned record
            candidates = (self._role_filter(live, rec)
                          or self._role_filter(draining, rec)
                          or live or draining)
            return min(candidates)[1:] if candidates else None
        candidates = []
        for i in self._eligible_replicas():
            handle = self._replicas[i]
            if handle.queued() >= self.cfg.replica_queue_limit:
                continue
            candidates.append((handle.load(), i, handle))
        candidates = self._role_filter(candidates, rec) or candidates
        if not candidates:
            return None
        if rec is not None:
            want = self._choose_version(rec)
            versioned = [c for c in candidates
                         if self._versions[c[1]] == want]
            if versioned:
                candidates = versioned
        least = min(candidates)
        if rec is not None:
            affine = [c for c in candidates
                      if self._affinity_depth(c[2], rec) > 0]
            if affine:
                load, i, handle = min(affine)
                slack = max(1, self.cfg.replica_queue_limit // 2)
                if load - least[0] <= slack:
                    _metrics.counter("fleet.affinity_hits").inc()
                    return i, handle
        return least[1:]

    def _role_filter(self, candidates, rec):
        """Keep the `(load, i, handle)` candidates whose replica role
        matches the request's disaggregation phase. An empty result
        means the wanted role has no capacity — callers fall back to
        the unfiltered list (mixed routing): disaggregation degrades,
        it never starves a routable request."""
        if not self._roles or rec is None:
            return candidates
        want = rec.phase
        if want not in ("prefill", "decode"):
            return candidates
        return [c for c in candidates
                if c[1] < len(self._roles)
                and self._roles[c[1]] == want]

    def _role_alive(self, role):
        """Does any non-retired, alive replica carry `role`?"""
        return any(r == role
                   and self._states[i] not in ("dead", "retired")
                   and self._replicas[i].alive()
                   for i, r in enumerate(list(self._roles)))

    def _classify_phase(self, rec):
        """Route-time disaggregation classification, once per fresh
        request: prefill-heavy work (prompt past prefill_len — a
        multi-chunk admission) starts on a prefill replica when BOTH
        roles have alive capacity. A dead role leaves new requests in
        mixed routing — availability beats the split. Requests that
        already hold tokens (failover re-routes, handed-off work) are
        never reclassified."""
        if (not self._roles or rec.phase is not None or rec.tokens
                or rec.max_new <= 1
                or rec.prompt.size <= self._prefill_cut):
            return
        if self._role_alive("prefill") and self._role_alive("decode"):
            rec.phase = "prefill"

    def _dispatch(self, finished):
        now = self._clock()
        for rec in [r for r in self._pending
                    if r.deadline_t is not None and now > r.deadline_t]:
            self._pending.remove(rec)
            _metrics.counter("serve.shed").inc(cause="deadline")
            self._retire(rec, "shed", "deadline_expired", finished)
        while self._pending:
            rec = min(self._pending, key=self._admission_key)
            self._classify_phase(rec)
            target = self._pick_replica(rec)
            if target is None:
                if rec.version is not None and rec.tokens:
                    # mid-stream work hard-pinned to a version no alive
                    # replica serves: it can never adopt safely (its
                    # tokens came from those weights), so it fails now
                    # rather than wedge the queue behind an unroutable
                    # record
                    self._pending.remove(rec)
                    self._retire(rec, "failed", "version_retired",
                                 finished)
                    continue
                break
            i, handle = target
            try:
                fault_point("fleet.dispatch")
            except Exception:
                break         # injected dispatch failure: the record
                #               stays pending and retries next round
            try:
                rid = handle.dispatch([self._spec_of(rec)])[0]
            except Exception as e:
                self._on_replica_failure(i, e, finished)
                continue
            self._pending.remove(rec)
            rec.status = "dispatched"
            rec.replica = i
            rec.replica_rid = rid
            # pin to the LANDING replica's version: the soft preference
            # may have fallen back to off-version capacity for a fresh
            # request, and accounting must tag what actually served it
            rec.version = self._versions[i]
            self._by_replica[(i, rid)] = rec.id

    def _spec_of(self, rec, origin="fleet"):
        trace = None
        if rec.trace_id is not None:
            # each hop is a child span of the router's root: hop0 =
            # first dispatch, hop1 = the failover re-route, ... — the
            # trace id itself NEVER changes across hops
            ctx = _trace.TraceContext(
                rec.trace_id, span_id=f"hop{rec.next_span}",
                parent_span_id="root" if rec.next_span == 0
                else f"hop{rec.next_span - 1}")
            rec.next_span += 1
            trace = ctx.to_wire()
        max_new = rec.max_new
        if rec.reroutes:
            origin = "failover"
        elif rec.phase == "decode":
            origin = "handoff"    # the disaggregation hop's trace tag
        if rec.phase == "prefill":
            # the prefill leg: chunked prefill + exactly the first
            # token; the remainder re-stages on a decode replica at
            # handoff with the request's real budget
            max_new = 1
            if not rec.reroutes:
                origin = "prefill"
        return dict(prompt=rec.prompt, tokens=list(rec.tokens),
                    max_new=max_new, eos_id=rec.eos_id,
                    priority=rec.priority, deadline_t=rec.deadline_t,
                    submit_t=rec.submit_t,
                    first_token_t=rec.first_token_t,
                    temperature=rec.temperature, top_k=rec.top_k,
                    top_p=rec.top_p, seed=rec.seed, trace=trace,
                    origin=origin)

    # -- live ops: deploy / canary / autoscale ----------------------------

    def _ops_event(self, event, **kw):
        """Append one record to the ops log (`run_report --fleet` renders
        the deploy timeline from these)."""
        with self._lock:
            rec = dict(event=event, t=self._clock(),
                       at_step=self._step_no, **kw)
            self.ops_log.append(rec)
            return rec

    def version_goodput(self, version):
        """SLO-met fraction of the version's accountable retirements
        (1.0 until the version has retired anything)."""
        with self._lock:
            st = self._version_stats.get(version)
            if not st or st[0] == 0:
                return 1.0
            return st[1] / st[0]

    def _account_version(self, rec):
        """Stamp the retirement with the version that served (or was
        routed for) it and feed the per-version SLO tally the canary
        comparison reads. Cancellations are tagged but not tallied —
        same accountability rule as goodput()."""
        if rec.version is None:
            rec.version = self._baseline_version
        _metrics.counter("fleet.version_retirements").inc(
            version=rec.version)
        if rec.status != "cancelled":
            st = self._version_stats.setdefault(rec.version, [0, 0])
            st[0] += 1
            if rec.slo_ok:
                st[1] += 1

    def deploy(self, ckpt, version=None, step=None, verify=None,
               canary=False, budget_s=None):
        """Zero-downtime rolling weight hot-swap.

        `ckpt` is a checkpoint path (loaded through CheckpointManager
        and crc32-verified against its PR-15 manifest BEFORE any replica
        is touched — a corrupt manifest raises DeployAborted with the
        fleet untouched), a raw variables pytree (tests/drills; then
        `version` is required), or None to promote an already-stored
        version (canary -> full rollout). Each replica then drains and
        rebuilds on the new weights one at a time while the rest absorb
        the traffic; a mid-swap failure rolls the touched replica back
        to its old version, aborts the rollout, and rolls back any
        replica already swapped. `canary=True` swaps exactly ONE replica
        and starts weighted canary routing instead of moving the
        baseline. Serialized against drain() (and other deploys) by the
        ops mutex; a fleet already draining rejects the deploy."""
        deploys = _metrics.counter("fleet.deploys")
        with self._ops_lock:
            with self._lock:
                if self._draining:
                    deploys.inc(status="rejected")
                    raise DeployAborted("fleet is draining")
                template_v = self._baseline_version
                template = self._weights.get(template_v)
            if verify is None:
                verify = self.cfg.deploy_verify
            got = None
            if ckpt is None:
                enforce(version is not None,
                        "deploy(None) promotes a stored version: "
                        "pass version=")
                with self._lock:
                    variables = self._weights.get(version)
                if variables is None:
                    deploys.inc(status="aborted")
                    raise DeployAborted(
                        f"no stored weights for version {version!r}")
            elif isinstance(ckpt, str):
                from paddle_tpu.io.checkpoint import CheckpointManager
                try:
                    fault_point("fleet.deploy")
                    mgr = CheckpointManager(ckpt)
                    variables, got = mgr.restore(
                        template, step=step, verify=verify)
                    if variables is None:
                        raise RuntimeError(
                            f"no restorable checkpoint under {ckpt}")
                except Exception as e:
                    deploys.inc(status="aborted")
                    self._ops_event("deploy_abort", ckpt=str(ckpt),
                                    version=version, error=repr(e))
                    raise DeployAborted(
                        f"checkpoint load/verify failed: {e}") from e
                if version is None:
                    version = (mgr.read_meta(got) or {}).get(
                        "model_version") or f"ckpt-{got}"
            else:
                # raw pytree: trusted caller (tests, drills), unverified
                enforce(version is not None,
                        "deploy(variables) needs an explicit version=")
                variables = ckpt
            with self._lock:
                self._weights[version] = variables
                self._deploying = version
                old_baseline = self._baseline_version
                if canary:
                    live = [i for i, s in enumerate(self._states)
                            if s == "live"
                            and self._versions[i] != version]
                    if not live:
                        self._deploying = None
                        deploys.inc(status="aborted")
                        raise DeployAborted(
                            "no live replica available for a canary")
                    targets = [min(live, key=lambda i: (
                        self._replicas[i].load(), i))]
                else:
                    # skip replicas already queued for a scale-down
                    # retire (pending swap target None): a deploy must
                    # not resurrect a replica the autoscaler is
                    # removing
                    targets = [i for i, s in enumerate(self._states)
                               if s not in ("dead", "retired")
                               and self._versions[i] != version
                               and self._pending_swaps.get(i, "")
                               is not None]
            self._ops_event("deploy_start", version=version,
                            canary=bool(canary), step=got,
                            targets=list(targets))
            deadline = self._clock() + (
                budget_s if budget_s is not None
                else max(self.cfg.drain_timeout_s, 1.0))
            swapped = []              # (replica, its pre-swap version)
            try:
                for i in targets:
                    with self._lock:
                        prev = self._versions[i]
                    if self._swap_replica(i, version, deadline):
                        swapped.append((i, prev))
                        continue
                    # abort: roll already-swapped replicas back
                    # (best-effort, bounded by a fresh budget)
                    back_by = self._clock() + max(
                        self.cfg.drain_timeout_s, 1.0)
                    for j, prev_j in swapped:
                        self._swap_replica(j, prev_j, back_by)
                    status = "rolled_back" if swapped else "aborted"
                    deploys.inc(status=status)
                    self._ops_event("deploy_abort", version=version,
                                    failed_replica=i, status=status)
                    raise DeployAborted(
                        f"swap of replica {i} to {version!r} failed; "
                        f"{len(swapped)} replica(s) rolled back")
            finally:
                with self._lock:
                    self._deploying = None
            with self._lock:
                if canary:
                    self._canary_version = version
                else:
                    self._baseline_version = version
                    if self._canary_version == version:
                        self._canary_version = None
            deploys.inc(status="canary" if canary else "ok")
            self._ops_event("deploy_done", version=version,
                            canary=bool(canary),
                            baseline=(old_baseline if canary
                                      else version),
                            replicas=[i for i, _ in swapped])
            return version

    def _swap_replica(self, i, version, deadline):
        """Queue replica i for a drain-then-rebuild onto `version` and
        drive router rounds until the swap lands (True) or fails —
        replica dead past its budget, rollback by _advance_swaps, or
        the deadline (False). The fleet keeps serving throughout: this
        only steps the normal round loop."""
        with self._lock:
            if self._states[i] == "live":
                self._states[i] = "draining"
            self._pending_swaps[i] = version
        while True:
            with self._lock:
                if i not in self._pending_swaps:
                    break
                if self._states[i] in ("dead", "retired"):
                    # dead: failover already ran inside step() and the
                    # budget is spent; retired: a scale-down landed
                    # first — either way the swap can never land
                    self._pending_swaps.pop(i, None)
                    return False
                if self._clock() > deadline:
                    self._pending_swaps.pop(i, None)
                    if (self._states[i] == "draining"
                            and not self._draining):
                        self._states[i] = "live"
                    return False
            self.step()
        with self._lock:
            return (self._versions[i] == version
                    and self._replicas[i].alive())

    def _advance_swaps(self, finished):
        """Execute queued replica transitions whose replica has quiesced
        (idle engine AND no dispatched mirror records): a version target
        rebuilds the engine on the new weights (`fleet.deploy` fault
        point; a failure rolls THIS replica back to the version it was
        serving), a None target retires the replica (scale-down)."""
        for i, target in list(self._pending_swaps.items()):
            if self._states[i] in ("dead", "retired"):
                continue
            handle = self._replicas[i]
            if not handle.alive():
                continue      # failover will respawn it (old version)
            if handle.load() > 0 or self._replica_outstanding(i):
                continue      # still draining toward the swap
            del self._pending_swaps[i]
            if target is None:
                others = [j for j, s in enumerate(self._states)
                          if j != i and s in ("live", "stalled",
                                              "draining")
                          and self._replicas[j].alive()]
                if not others:
                    # the fleet shrank under the queued retire (deaths,
                    # other retires): never remove the last alive
                    # replica — cancel the scale-down instead
                    self._states[i] = ("draining" if self._draining
                                       else "live")
                    self._ops_event("scale_down_cancelled", replica=i)
                    continue
                try:
                    handle.close()
                except Exception:
                    pass
                handle.kill()
                self._states[i] = "retired"
                self._monitor.update(i)
                _metrics.counter("fleet.scale_events").inc(
                    direction="down")
                self._ops_event("scale_down", replica=i)
                continue
            old = self._versions[i]
            self._versions[i] = target
            try:
                fault_point("fleet.deploy")
                handle.respawn()
            except Exception as e:
                # mid-swap failure: never trade a failed swap for a
                # lost replica — back onto the old weights
                self._versions[i] = old
                self._ops_event("swap_fail", replica=i, version=target,
                                error=repr(e))
                if handle.alive():
                    # in-process factory failure leaves the old engine
                    # untouched and serving
                    self._states[i] = ("draining" if self._draining
                                       else "live")
                else:
                    self._respawn(i, e, "live", finished)
                continue
            self._monitor.update(i)
            self._states[i] = "draining" if self._draining else "live"
            self._ops_event("swap", replica=i, version=target, prev=old)

    def _check_canary(self):
        """Automatic canary abort: once both versions have enough
        accountable retirements, a canary goodput below the baseline's
        by more than canary_margin rolls every canary replica back to
        the baseline (graceful, via the swap queue) and stops canary
        routing."""
        canary = self._canary_version
        if canary is None:
            return
        cs = self._version_stats.get(canary)
        bs = self._version_stats.get(self._baseline_version)
        need = self.cfg.canary_min_retired
        if not cs or cs[0] < need or not bs or bs[0] < need:
            return
        c_good, b_good = cs[1] / cs[0], bs[1] / bs[0]
        if c_good >= b_good - self.cfg.canary_margin:
            return
        _metrics.counter("fleet.canary_aborts").inc()
        self._ops_event("canary_abort", version=canary,
                        canary_goodput=round(c_good, 4),
                        baseline_goodput=round(b_good, 4))
        self._canary_version = None
        for i, v in enumerate(list(self._versions)):
            if v == canary and self._states[i] not in ("dead",
                                                       "retired"):
                if self._states[i] == "live":
                    self._states[i] = "draining"
                self._pending_swaps[i] = self._baseline_version

    def _autoscale(self):
        """Load-driven replica count: pending backlog with headroom
        under fleet_autoscale_max spawns a baseline replica; sustained
        slack above the floor queues a graceful drain-then-retire of
        the least-loaded one. One action per fleet_scale_cooldown_s;
        parked during deploys and drains."""
        cfg = self.cfg
        if not cfg.autoscale_max or cfg.autoscale_max <= 0:
            return
        if (self._model is None or self._deploying is not None
                or self._draining):
            return
        now = self._clock()
        if (self._last_scale_t is not None
                and now - self._last_scale_t < cfg.scale_cooldown_s):
            return
        live = [i for i, s in enumerate(self._states) if s == "live"]
        backlog = len(self._pending)
        if backlog > 0 and len(live) < cfg.autoscale_max:
            try:
                fault_point("fleet.scale")
                i = self._spawn_replica(self._baseline_version)
            except Exception as e:
                self._ops_event("scale_up_fail", error=repr(e))
                self._last_scale_t = now   # failed spawns cool down too
                return
            self._last_scale_t = now
            _metrics.counter("fleet.scale_events").inc(direction="up")
            self._ops_event("scale_up", replica=i, backlog=backlog)
            return
        floor = max(1, cfg.autoscale_min or 1)
        if len(live) <= floor:
            return
        out = backlog + sum(self._replica_outstanding(i) for i in live)
        if out * 2 > (len(live) - 1) * cfg.replica_queue_limit:
            return            # the survivors couldn't absorb the load
        victims = [i for i in live
                   if self._canary_version is None
                   or self._versions[i] != self._canary_version]
        if self._roles:
            # role minimums: a scale-down must never retire the last
            # live replica of a role — that would collapse the
            # disaggregated topology instead of shedding slack
            victims = [i for i in victims
                       if sum(1 for j in live
                              if self._roles[j] == self._roles[i]) > 1]
        if not victims:
            return
        try:
            fault_point("fleet.scale")
        except Exception as e:
            self._ops_event("scale_down_fail", error=repr(e))
            self._last_scale_t = now
            return
        victim = min(victims,
                     key=lambda i: (self._replicas[i].load(), -i))
        self._states[victim] = "draining"
        self._pending_swaps[victim] = None
        self._last_scale_t = now
        self._ops_event("scale_down_begin", replica=victim,
                        outstanding=out)

    def _spawn_replica(self, version):
        """Grow the fleet by one in-process replica on `version`. The
        per-replica registries are appended BEFORE the engine is built
        (the version-aware factory reads self._versions[i]); a factory
        failure unwinds them."""
        i = len(self._replicas)
        self._versions.append(version)
        self._states.append("live")
        if self._roles:
            # load-driven growth adds decode capacity; the prefill
            # carve-out is the static front of the fleet
            self._roles.append("decode")
        try:
            handle = InProcessReplica(self._engine_factory(i),
                                      anomaly_sink=self._sink_for(i))
        except Exception:
            self._versions.pop()
            self._states.pop()
            if self._roles:
                self._roles.pop()
            raise
        self._replicas.append(handle)
        self._budgets.append(RetryBudget(
            RetryPolicy(max_attempts=self.cfg.respawn_budget + 1),
            "fleet.respawn"))
        self._monitor.add_worker(i)
        self._monitor.update(i)
        return i

    # -- liveness + failover ----------------------------------------------

    def _ping(self, i):
        try:
            fault_point("fleet.heartbeat")
        except Exception:
            return            # heartbeat publisher wedged: ping dropped,
            #                   the monitor's age keeps growing
        self._monitor.update(i)

    def _scan_heartbeats(self, finished):
        dead_after = self.cfg.heartbeat_s * self.cfg.heartbeat_dead_factor
        for w, (st, age) in self._monitor.check().items():
            if self._states[w] in ("dead", "retired"):
                continue
            if age > dead_after:
                self._on_replica_failure(
                    w, ReplicaDead(
                        f"replica {w} heartbeat silent {age:.3f}s"),
                    finished)
            elif st == STALLED and self._states[w] == "live":
                self._states[w] = "stalled"
            elif st != STALLED and self._states[w] == "stalled":
                self._states[w] = "live"

    def _on_replica_failure(self, i, exc, finished):
        """The failover path: count it, re-route the dead replica's
        in-flight work from the router-side mirror, respawn under the
        replica's RetryBudget, and re-dispatch immediately."""
        self.failovers += 1
        _metrics.counter("fleet.failovers").inc()
        was = self._states[i]
        self._states[i] = "dead"
        self._replicas[i].kill()
        victims = sorted(
            (self.requests[fid]
             for (rep, _), fid in list(self._by_replica.items())
             if rep == i
             and self.requests[fid].status == "dispatched"),
            key=lambda r: r.id)
        for key in [k for k in self._by_replica if k[0] == i]:
            del self._by_replica[key]
        for rec in victims:
            rec.status = "pending"
            rec.replica = None
            rec.replica_rid = None
            rec.reroutes += 1
            _metrics.counter("fleet.rerouted").inc()
            self._pending.append(rec)
        self._respawn(i, exc, was, finished)
        self._dispatch(finished)

    def _respawn(self, i, exc, prev_state, finished):
        budget = self._budgets[i]
        while True:
            try:
                budget.failure(exc)   # backoff pacing; raises when spent
            except Exception:
                # budget exhausted: this replica stays dead
                if not self._eligible_replicas():
                    self._fail_all(exc, finished)
                    raise
                return False
            try:
                fault_point("fleet.respawn")
                self._replicas[i].respawn()
            except Exception as e:
                exc = e
                continue
            _metrics.counter("fleet.respawns").inc(replica=str(i))
            self._states[i] = ("draining" if prev_state == "draining"
                               or self._draining else "live")
            self._monitor.update(i)
            return True

    def _fail_all(self, exc, finished):
        """No replica left alive: every outstanding request gets the
        terminal `failed` status before the router re-raises, so no
        client waits on a request that can never finish."""
        doomed = [r for r in self.requests.values()
                  if r.status in ("pending", "dispatched")]
        self._pending.clear()
        self._by_replica.clear()
        for rec in doomed:
            self._retire(rec, "failed", "fleet_dead", finished)

    # -- record sync ------------------------------------------------------

    def _sync(self, i, report, finished):
        for fin in report["finished"]:
            fid = self._by_replica.pop((i, fin["rid"]), None)
            if fid is None:
                continue
            rec = self.requests[fid]
            if (rec.phase == "prefill" and fin["status"] == "done"
                    and fin["reason"] == "length"
                    and len(fin["tokens"]) < rec.max_new):
                # the prefill leg hit its max_new=1 cap, not the
                # request's own budget: this is the disaggregation
                # handoff, not a retirement. (eos / shed / failed legs
                # fall through and retire normally — the request was
                # genuinely done or dead.)
                self._handoff(rec, fin)
                continue
            rec.tokens = list(fin["tokens"])
            rec.status = fin["status"]
            rec.retire_reason = fin["reason"]
            rec.slo_ok = fin["slo_ok"]
            if fin["first_token_t"] is not None:
                rec.first_token_t = fin["first_token_t"]
            rec.done_t = self._clock()
            self._account_version(rec)
            finished.append(rec)
        for inf in report["inflight"]:
            fid = self._by_replica.get((i, inf["rid"]))
            if fid is None:
                continue
            rec = self.requests[fid]
            rec.tokens = list(inf["tokens"])       # the failover mirror
            if inf["first_token_t"] is not None:
                rec.first_token_t = inf["first_token_t"]

    def _handoff(self, rec, fin):
        """The prefill->decode hop: the prefill replica produced the
        prompt's KV plus exactly the first token; the remainder
        re-stages on a decode replica through the SAME token-exact
        adopt/replay path failover uses (prompt + tokens=[t0], the
        request's real max_new, pinned seed and version — the decode
        replica's sample stream continues at fold-in count 1, so the
        completion is bit-identical to a mixed-mode run). An injected
        `fleet.handoff` fault degrades THIS request to mixed routing:
        it goes back to pending with no role preference and finishes
        wherever capacity exists."""
        rec.tokens = list(fin["tokens"])           # [t0]
        if fin["first_token_t"] is not None:
            rec.first_token_t = fin["first_token_t"]
        rec.status = "pending"
        rec.replica = None
        rec.replica_rid = None
        try:
            fault_point("fleet.handoff")
            rec.phase = "decode"
            self.handoffs += 1
            _metrics.counter("fleet.handoffs").inc()
        except Exception:
            # handoff machinery faulted: finish mixed — correctness
            # (token-exact completion) is never hostage to the split
            rec.phase = None
        self._pending.append(rec)

    def _on_replica_anomaly(self, replica, event):
        # fleet-level flight dump FIRST — evidence before mitigation
        # mutates the state it should document. One bundle per anomaly
        # kind per router (the engine watchdog latches per kind too):
        # every replica's RunLog tail + the fleet state land in ONE dir.
        kind = str(event.get("anomaly", "anomaly"))
        with self._lock:
            fresh = kind not in self._flight_dumped
            self._flight_dumped.add(kind)
        if fresh and _flight.recorder() is not None:
            self._flight_fanout(replica, kind, event)
        if event.get("anomaly") in ("goodput_collapse", "ingest_stall"):
            # same signal plane drives both relief valves: spare
            # capacity spawns first (the autoscaler's cooldown and
            # bounds apply), then expired/low-priority pending sheds
            with self._lock:
                self._autoscale()
            self.shed_pending(cause=event["anomaly"])

    def _flight_fanout(self, replica, kind, event):
        """One fleet-level evidence bundle: every replica's RunLog tail,
        the fleet topology/state summary, and the local event ring —
        the drill artifact is complete even though only one replica's
        watchdog fired."""
        run_logs = []
        with self._lock:
            for h in list(self._replicas):
                eng = getattr(h, "engine", None)
                rl = getattr(eng, "_run_log", None) if eng else None
                if rl is not None:
                    run_logs.append(rl)
            summary = dict(
                states=list(self._states),
                versions=list(self._versions),
                baseline_version=self._baseline_version,
                canary_version=self._canary_version,
                pending=len(self._pending),
                outstanding=self._outstanding(),
                failovers=self.failovers,
                num_replicas=len(self._replicas))
        _flight.dump_bundle(
            reason=kind, run_logs=run_logs,
            config=dict(fleet=summary,
                        fleet_config=dataclasses.asdict(self.cfg)),
            extra=dict(anomaly=event, source_replica=replica))

    def _retire(self, rec, status, why, finished=None, account=True,
                count=True):
        rec.status = status
        rec.retire_reason = why
        rec.done_t = self._clock()
        if account:
            rec.slo_ok = False
        if count:
            _metrics.counter("serve.requests").inc(status=status)
        self._account_version(rec)
        if finished is not None:
            finished.append(rec)

    def _publish(self):
        counts = collections.Counter(self._states)
        g = _metrics.gauge("fleet.replicas")
        for st in ("live", "stalled", "draining", "dead", "retired"):
            g.set(counts.get(st, 0), state=st)
        depth = _metrics.gauge("fleet.dispatch_depth")
        for i, handle in enumerate(list(self._replicas)):
            depth.set(self._replica_outstanding(i)
                      + sum(1 for r in self._pending
                            if r.replica == i), replica=str(i))
