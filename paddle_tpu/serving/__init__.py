"""Serving — the continuous-batching inference engine.

The reference stack ships a standalone inference engine (AnalysisPredictor
+ the server-side runtime); its Python-visible surface is
load_inference_model → run loops over fixed-shape batches. This package is
the TPU-native successor for autoregressive decoding: a slot/page-pool KV
cache (ops/attention.py), a Pallas decode-attention kernel
(ops/pallas/decode_attention.py), and a request scheduler that admits new
prompts into freed slots between decode steps — mixed prompt lengths, one
jitted fixed-shape serve step, no per-admission retrace.

    engine = ServingEngine(model, variables, ServeConfig(num_slots=8))
    rid = engine.submit([1, 2, 3], max_new=32)
    finished = engine.drain()

serving/fleet.py layers the multi-replica front door on top: a
FleetRouter spreading traffic over N engine replicas with heartbeat
liveness, token-exact failover replay, bounded respawn, and graceful
drain.

    router = FleetRouter(model, variables, FleetConfig(num_replicas=3))
"""

from paddle_tpu.serving.engine import Request, ServeConfig, ServingEngine
from paddle_tpu.serving.fleet import (DeployAborted, FleetConfig,
                                      FleetRequest, FleetRouter,
                                      InProcessReplica,
                                      SubprocessReplica,
                                      replica_worker_loop)
from paddle_tpu.serving.prefix_cache import PrefixCache

__all__ = ["DeployAborted", "Request", "ServeConfig", "ServingEngine",
           "FleetConfig", "FleetRequest", "FleetRouter",
           "InProcessReplica", "PrefixCache", "SubprocessReplica",
           "replica_worker_loop"]
