"""Fleet-wide distributed tracing: durable trace contexts and the
clock-skew-corrected cross-replica timeline merge.

The serving fleet re-homes requests across engine replicas — dispatch,
``adopt()`` failover, deploy drains, re-admission after crash recovery —
and before this module every hop re-minted the engine-run-scoped trace
id, so no single id covered a request's life. Here the ROUTER mints the
durable context (``trace_id`` / ``span_id`` / ``parent_span_id``) once
at ``FleetRouter.submit()``; the context rides the ``FleetRequest``
through every dispatch path (including the subprocess JSON wire) and
lands in ``engine.adopt()``, which stamps it on the engine-local
request instead of minting a fresh one. Every trace event then carries
the same ``trace`` across replicas, plus ``replica`` and
``model_version`` tags identifying who served the hop.

Merging is the second half: each process's RunLog event times come off
``time.perf_counter()`` — monotonic, but with a per-process epoch — so
per-replica logs cannot be interleaved by raw ``t``. Every RunLog
therefore opens with an ANCHOR record pairing one ``time.time()`` wall
reading with one ``perf_counter()`` reading taken back-to-back;
``merge_fleet_trace`` rebases each log's events onto the wall clock via
its anchor offset and returns one causally ordered timeline plus a
skew report. Rendering lives in ``tools/run_report.py --fleet-trace``.

Everything here is host-side stdlib: no jax imports, no device work —
the ``hot-path-sync`` lint runs over this module.
"""

import os
import threading
import time
import uuid

# --------------------------------------------------------------------------
# event catalog
# --------------------------------------------------------------------------

# Every event kind the trace plane writes — engine ``_trace_event``
# sites and flight-ring ``note_event`` sites. The ``event-drift``
# graft-lint rule checks this dict against the literal call sites in
# both directions: an unregistered emit is invisible to the collector's
# consumers, and a registered kind with no emitter documents nothing.
EVENTS = {
    "adopted": "request adopted by an engine (fleet dispatch, failover "
               "re-route, or drain re-admission)",
    "admitted": "request admitted to a decode slot for its first prefill",
    "anchor": "per-process wall/monotonic clock anchor (skew correction)",
    "anomaly": "watchdog anomaly observed by the flight recorder",
    "first_token": "first generated token left the engine",
    "flight_dump": "flight-recorder bundle dump started",
    "prefill_done": "prompt (+ replayed tokens) fully prefilled",
    "preempted": "running request preempted back to the queue",
    "requeued": "request returned to the queue after a recovery",
    "resumed": "preempted/recovered request re-admitted to a slot",
    "retired": "request reached a terminal status",
    "span": "host-side span completion linked into the active context",
    "submitted": "request accepted (engine-local or fleet submit)",
}


# --------------------------------------------------------------------------
# trace context
# --------------------------------------------------------------------------


class TraceContext:
    """One hop's identity inside a trace: the durable ``trace_id`` plus
    this hop's ``span_id`` and its causal parent. Contexts are value
    objects — ``child()`` derives the next hop, ``to_wire()`` /
    ``from_wire()`` cross the subprocess JSON exchange."""

    __slots__ = ("trace_id", "span_id", "parent_span_id")

    def __init__(self, trace_id, span_id="root", parent_span_id=None):
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_span_id = parent_span_id

    def child(self, span_id):
        return TraceContext(self.trace_id, span_id,
                            parent_span_id=self.span_id)

    def to_wire(self):
        return {"trace_id": self.trace_id, "span_id": self.span_id,
                "parent_span_id": self.parent_span_id}

    @classmethod
    def from_wire(cls, wire):
        if not wire or not wire.get("trace_id"):
            return None
        return cls(wire["trace_id"], wire.get("span_id", "root"),
                   wire.get("parent_span_id"))

    def __repr__(self):
        return (f"TraceContext({self.trace_id!r}, {self.span_id!r}, "
                f"parent={self.parent_span_id!r})")


def mint_run():
    """Short run id prefixing every trace id minted by one process
    (router or standalone engine) — collision-safe across restarts."""
    return uuid.uuid4().hex[:8]


# thread-local stack of active contexts: the Trainer (and tools)
# activate a context around a region so span completions link into it
_TLS = threading.local()


def current():
    """The innermost active TraceContext on this thread, else None."""
    stack = getattr(_TLS, "stack", None)
    return stack[-1] if stack else None


class activate:
    """``with trace.activate(ctx):`` — installs ``ctx`` as the thread's
    active trace context for the duration; nests."""

    def __init__(self, ctx):
        self._ctx = ctx

    def __enter__(self):
        stack = getattr(_TLS, "stack", None)
        if stack is None:
            stack = _TLS.stack = []
        stack.append(self._ctx)
        return self._ctx

    def __exit__(self, *exc):
        _TLS.stack.pop()
        return False


def note_span(name, dt):
    """Link a completed host-side span into the active context by
    feeding the flight ring (a bounded deque append — no I/O). Called
    from ``spans.span()``'s exit path; returns fast when the flight
    recorder is off."""
    from paddle_tpu.observability import flight
    rec = flight.recorder()
    if rec is None:
        return
    ctx = current()
    rec.note_event("span", name=name, dt=dt,
                   trace=ctx.trace_id if ctx else None,
                   span=ctx.span_id if ctx else None)


# --------------------------------------------------------------------------
# clock anchors + the cross-replica merge
# --------------------------------------------------------------------------


def anchor_record(**tags):
    """One wall/monotonic clock pair taken back-to-back, tagged with
    the writing process — the per-RunLog record ``merge_fleet_trace``
    uses to rebase that log's monotonic event times onto the wall
    clock."""
    return dict(anchor=dict(wall=time.time(), mono=time.perf_counter()),
                pid=os.getpid(), **tags)


def write_anchor(run_log, **tags):
    """Write an anchor record to ``run_log`` (and mirror it into the
    flight ring when recording). Safe to call with run_log=None."""
    rec = anchor_record(**tags)
    if run_log is not None:
        run_log.write(rec)
    from paddle_tpu.observability import flight
    fl = flight.recorder()
    if fl is not None:
        fl.note_event("anchor", wall=rec["anchor"]["wall"],
                      mono=rec["anchor"]["mono"], pid=rec["pid"])
    return rec


def _anchor_offset(records):
    """wall - mono from the log's first anchor record, else None."""
    for rec in records:
        a = rec.get("anchor")
        if isinstance(a, dict) and "wall" in a and "mono" in a:
            return float(a["wall"]) - float(a["mono"])
    return None


def merge_fleet_trace(record_lists):
    """Merge per-replica RunLog record lists into one causally ordered
    timeline.

    ``record_lists`` maps a source name (e.g. ``"r0"``) to that log's
    records (as from ``runlog.read_records``). Each log's trace events
    (records with an ``event`` key) are rebased onto the wall clock via
    the log's anchor offset; a log without an anchor keeps raw times
    and is called out in the skew report rather than silently mixed in.

    Returns ``{"events": [...], "skew": {...}}`` where every event
    gains ``source`` (which log) and ``wall_t`` (corrected time), and
    ``skew`` reports each source's anchor offset plus the spread of
    wall-clock epochs ("skew_s" is relative to the earliest-anchored
    source — large values mean the logs disagree about when 'now' is).
    """
    offsets = {src: _anchor_offset(recs)
               for src, recs in record_lists.items()}
    anchored = {s: o for s, o in offsets.items() if o is not None}
    base = min(anchored.values()) if anchored else 0.0
    events = []
    for src, recs in record_lists.items():
        off = offsets[src]
        for rec in recs:
            if "event" not in rec or "t" not in rec:
                continue
            ev = dict(rec)
            ev["source"] = src
            ev["wall_t"] = (float(rec["t"]) + off if off is not None
                            else float(rec["t"]))
            events.append(ev)
    events.sort(key=lambda e: (e["wall_t"], e["source"]))
    skew = {src: dict(offset=off,
                      skew_s=(off - base if off is not None else None),
                      anchored=off is not None)
            for src, off in offsets.items()}
    return {"events": events, "skew": skew}


def group_by_trace(events):
    """{trace_id: [events...]} preserving merged order; events with no
    trace stamp group under None."""
    out = {}
    for ev in events:
        out.setdefault(ev.get("trace"), []).append(ev)
    return out
