"""Live /metrics exporter — Prometheus text exposition of the whole
metrics registry over a stdlib HTTP server.

Ref: the reference framework's monitoring was pull-at-exit only
(profiler tables printed on DisableProfiler); a production trainer or
server is operated from a scrape endpoint instead. This module renders
every Counter/Gauge/Histogram in a MetricsRegistry as Prometheus text
exposition (format 0.0.4) and serves it on `/metrics` (plus a trivial
`/healthz`) from a daemon ThreadingHTTPServer, so Prometheus / curl can
watch a live run:

    srv = start_metrics_server()          # honors the metrics_port flag
    ...
    srv.stop()

Rendering rules:
  * metric names sanitize to the Prometheus charset ('.' -> '_'):
    serve.goodput is exported as serve_goodput; the HELP line carries
    the registry name so the mapping stays greppable.
  * counters/gauges export as-is per label set; histograms export as
    summaries: {quantile="0.5|0.9|0.99"} series over the reservoir plus
    _count and _sum.
  * label values escape backslash, double-quote, and newline per the
    exposition spec.
  * registered-but-unobserved metrics still emit HELP/TYPE (no samples),
    so dashboards can discover the full surface before traffic.

Stdlib-only (no jax): the server thread must never contend with the
device loop, and early importers can pull it in without cycles.
"""

import http.server
import re
import threading

from paddle_tpu.observability import catalog as _catalog
from paddle_tpu.observability import metrics as _metrics

_NAME_BAD = re.compile(r"[^a-zA-Z0-9_:]")
_QUANTILES = (("0.5", 0.50), ("0.9", 0.90), ("0.99", 0.99))


def prom_name(name):
    """Registry name -> Prometheus metric name ([a-zA-Z_:][a-zA-Z0-9_:]*)."""
    out = _NAME_BAD.sub("_", str(name))
    if not out or not (out[0].isalpha() or out[0] in "_:"):
        out = "_" + out
    return out


def escape_label_value(value):
    """Escape a label value per the text exposition format."""
    return (str(value).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _escape_help(text):
    return str(text).replace("\\", "\\\\").replace("\n", "\\n")


def _fmt_value(v):
    f = float(v)
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


def _label_str(labels, extra=()):
    parts = [f'{prom_name(k)}="{escape_label_value(v)}"'
             for k, v in list(extra) + sorted(labels.items())]
    return "{" + ",".join(parts) + "}" if parts else ""


def render_prometheus(registry=None):
    """The whole registry as Prometheus text exposition (str)."""
    reg = registry if registry is not None else _metrics.registry()
    lines = []
    for name in reg.names():
        m = reg.get(name)
        if m is None:
            continue                      # raced a concurrent reset
        pname = prom_name(name)
        help_txt = _catalog.help_for(name) or m.help or ""
        lines.append(f"# HELP {pname} {_escape_help(f'{name} {help_txt}'.strip())}")
        ptype = {"counter": "counter", "gauge": "gauge",
                 "histogram": "summary"}[m.kind]
        lines.append(f"# TYPE {pname} {ptype}")
        snap = m.snapshot()
        for key in sorted(snap):
            labels = _metrics.parse_label_key(key)
            if m.kind in ("counter", "gauge"):
                lines.append(
                    f"{pname}{_label_str(labels)} {_fmt_value(snap[key])}")
            else:
                st = snap[key]
                for qname, q in _QUANTILES:
                    v = m.percentile(q, **labels)
                    if v is None:
                        continue
                    lines.append(
                        f"{pname}{_label_str(labels, [('quantile', qname)])}"
                        f" {_fmt_value(v)}")
                lines.append(f"{pname}_count{_label_str(labels)} "
                             f"{_fmt_value(st['count'])}")
                lines.append(f"{pname}_sum{_label_str(labels)} "
                             f"{_fmt_value(st['sum'])}")
    return "\n".join(lines) + "\n"


class _Handler(http.server.BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"

    def do_GET(self):
        path = self.path.split("?", 1)[0]
        if path == "/metrics":
            body = render_prometheus(self.server.registry).encode()
            ctype = "text/plain; version=0.0.4; charset=utf-8"
        elif path == "/healthz":
            body, ctype = b"ok\n", "text/plain; charset=utf-8"
        else:
            self.send_error(404)
            return
        self.server.registry.counter(
            "exporter.scrapes",
            _catalog.help_for("exporter.scrapes")).inc(path=path)
        self.send_response(200)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def log_message(self, *args):    # scrapes must not spam stdout
        pass


class MetricsServer:
    """A /metrics + /healthz endpoint over one MetricsRegistry.

    `port` here is the literal bind port (0 = OS-assigned ephemeral —
    what tests use; read `.port` after start() for the real one). The
    flag-level convention that metrics_port=0 means "exporter off" is
    enforced by `start_metrics_server`, not by this class.
    """

    def __init__(self, port=0, registry=None, host="0.0.0.0"):
        self._bind = (host, int(port))
        self.registry = (registry if registry is not None
                         else _metrics.registry())
        # lifecycle lock: start()/stop() may race between the run loop
        # and an atexit/close path; the scrape threads never take it
        self._lock = threading.Lock()
        self._httpd = None     # graft-guard: self._lock
        self._thread = None    # graft-guard: self._lock

    @property
    def port(self):
        with self._lock:
            return (self._httpd.server_address[1]
                    if self._httpd else None)

    def start(self):
        with self._lock:
            if self._httpd is not None:
                return self
            httpd = http.server.ThreadingHTTPServer(self._bind, _Handler)
            httpd.daemon_threads = True
            httpd.registry = self.registry
            self._httpd = httpd
            self._thread = threading.Thread(
                target=httpd.serve_forever, name="metrics-exporter",
                daemon=True)
            self._thread.start()
            return self

    def stop(self):
        with self._lock:
            if self._httpd is None:
                return
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
            self._thread = None

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()
        return False


def start_metrics_server(port=None, registry=None):
    """Start the exporter with flag-resolvable gating: `port=None` reads
    the `metrics_port` flag, and a resolved port of 0 means DISABLED
    (returns None). TelemetryConfig / ServeConfig route through here, so
    PT_FLAGS_metrics_port=9090 live-instruments any run."""
    if port is None:
        from paddle_tpu.core.flags import get_flag
        port = get_flag("metrics_port")
    port = int(port)
    if port == 0:
        return None
    return MetricsServer(port=port, registry=registry).start()
