"""Metric catalog — the one table of every metric this framework emits.

Every `counter("...")` / `gauge("...")` / `histogram("...")` call site in
the tree must name a metric registered here (a tier-1 test greps the
source and fails on drift), so the exporter's HELP lines, dashboards,
and alert rules never chase renamed or ad-hoc metrics. Names ending in
'.' are prefixes for dynamically-composed families (span.<path>).

Stdlib-only, like metrics.py: early importers (core/retry.py) may pull
it in transitively through the exporter.
"""

import collections

MetricSpec = collections.namedtuple("MetricSpec", ["kind", "labels", "help"])

# name -> (kind, label names, help). Keep alphabetized within each group.
CATALOG = {
    # parallel/autoplan/search.py
    "autoplan.candidates": MetricSpec(
        "counter", ("outcome",),
        "Mesh factorizations considered by the auto-parallelism search, "
        "by outcome (scored vs pruned-with-reason)."),
    "autoplan.plan_s": MetricSpec(
        "histogram", (),
        "Wall time of one autoplan search (enumerate + price + rank)."),
    # ops/pallas/autotune.py
    "autotune.cache": MetricSpec(
        "counter", ("event",),
        "Autotune tile-cache lookups by event (hit | miss | corrupt)."),
    "autotune.sweeps": MetricSpec(
        "counter", ("kernel",),
        "Tile-shape sweeps run by the Pallas autotuner (first eager "
        "contact with a kernel/shape/chip triple)."),
    # amp.py (published host-side by the guardian's ScalerObserver bridge)
    "amp.loss_scale": MetricSpec(
        "gauge", (), "Current dynamic loss scale of the amp.LossScaler."),
    "amp.skipped_steps": MetricSpec(
        "counter", (),
        "Optimizer updates the loss scaler skipped on non-finite "
        "gradients (delta-published from the scaler state's cumulative "
        "skip count)."),
    # bench.py
    "bench.step_time_s": MetricSpec(
        "histogram", (), "Per-step wall time of a timed bench window."),
    # io/checkpoint.py
    "checkpoint.corrupt_leaves": MetricSpec(
        "counter", (),
        "Restored checkpoint leaves whose crc32 disagreed with the "
        "step's integrity manifest."),
    "checkpoint.integrity_fallbacks": MetricSpec(
        "counter", (),
        "Checkpoint steps abandoned at restore (corrupt or unreadable "
        "even after a mirror re-fetch), degrading to the previous "
        "committed step."),
    "checkpoint.mirror_degraded": MetricSpec(
        "counter", (),
        "Checkpoint mirror pushes that failed after retries and degraded "
        "to queue-and-continue."),
    "checkpoint.restores": MetricSpec(
        "counter", (), "Checkpoint restores served."),
    "checkpoint.saves": MetricSpec(
        "counter", (), "Checkpoint saves committed."),
    "checkpoint.torn_skips": MetricSpec(
        "counter", (),
        "Uncommitted (torn) checkpoint steps skipped at discovery."),
    # parallel/communicator.py
    "collective.quant_bytes": MetricSpec(
        "counter", ("direction",),
        "Bytes the quantized dp all-reduce moved on the wire (int8 "
        "payload plus per-chunk scales), by direction (send | recv) — "
        "compare against grad elements x 4 for the f32 baseline."),
    "collective.quant_degraded": MetricSpec(
        "counter", (),
        "Gradient syncs that degraded from the quantized int8 "
        "all-reduce to plain f32 psum (the collective.quant fault "
        "point, or guardian-driven parity fallback)."),
    # tools/graft_lint.py
    "contracts.violations": MetricSpec(
        "counter", ("contract",),
        "Compile-contract violations reported by a graft-lint "
        "--contracts run, by CONTRACTS row name."),
    # observability/exporter.py
    "exporter.scrapes": MetricSpec(
        "counter", ("path",),
        "HTTP requests served by the /metrics exporter."),
    # serving/fleet.py
    "fleet.affinity_hits": MetricSpec(
        "counter", (),
        "Dispatches routed by prefix affinity — the chosen replica's "
        "prefix cache already held the request's leading prompt pages "
        "(least-loaded remains the tiebreak and the imbalance "
        "fallback)."),
    "fleet.canary_aborts": MetricSpec(
        "counter", (),
        "Automatic canary aborts: the canary version's goodput fell "
        "below the baseline's by more than the configured margin, so "
        "canary routing stopped and its replicas rolled back."),
    "fleet.deploys": MetricSpec(
        "counter", ("status",),
        "FleetRouter.deploy() outcomes: ok (baseline moved), canary "
        "(one replica swapped, weighted routing started), rejected "
        "(fleet draining), aborted (corrupt manifest or failed first "
        "swap; fleet untouched), rolled_back (mid-rollout failure; "
        "already-swapped replicas restored)."),
    "fleet.dispatch_depth": MetricSpec(
        "gauge", ("replica",),
        "Requests dispatched to a replica and not yet terminal, by "
        "replica index — the single /metrics endpoint's per-replica "
        "aggregation label."),
    "fleet.handoffs": MetricSpec(
        "counter", (),
        "Prefill->decode disaggregation handoffs: a prefill-role "
        "replica finished a request's chunked prefill plus first "
        "token and the router re-dispatched the remainder to a "
        "decode replica via the token-exact adopt() replay path."),
    "fleet.failovers": MetricSpec(
        "counter", (),
        "Replica deaths handled by the fleet router (step crash past "
        "the engine budget, killed process, heartbeat loss); each one "
        "re-routes in-flight work and respawns the replica."),
    "fleet.replicas": MetricSpec(
        "gauge", ("state",),
        "Fleet replicas by state (live | stalled | draining | dead | "
        "retired — retired = permanently removed by a scale-down)."),
    "fleet.rerouted": MetricSpec(
        "counter", (),
        "In-flight requests re-routed to a healthy replica after a "
        "replica death (token-exact failover replay)."),
    "fleet.respawns": MetricSpec(
        "counter", ("replica",),
        "Replica respawns performed under the fleet RetryBudget."),
    "fleet.scale_events": MetricSpec(
        "counter", ("direction",),
        "Fleet autoscaling actions: up = a replica spawned against "
        "pending backlog, down = a replica gracefully drained and "
        "retired against sustained slack."),
    "fleet.version_retirements": MetricSpec(
        "counter", ("version",),
        "Fleet request retirements by the model version that served "
        "(or was routed for) the request — the per-version SLO plane "
        "the canary comparison reads."),
    # observability/flight.py
    "flight.dumps": MetricSpec(
        "counter", ("status",),
        "Flight-recorder bundle dumps by outcome (ok = a complete "
        "bundle landed, error = the dump failed or was fault-injected "
        "and was swallowed — anomaly handlers never raise)."),
    # parallel/heartbeat.py
    "heartbeat.barrier_wait_s": MetricSpec(
        "counter", ("barrier",),
        "Wall seconds spent waiting in heartbeat barriers."),
    "heartbeat.missed": MetricSpec(
        "counter", ("worker",),
        "Peers declared stalled by a heartbeat monitor (latched once per "
        "stall)."),
    # jit trace accounting (serving/engine.py + observability/watchdog.py)
    "jit.retraces": MetricSpec(
        "counter", ("fn",),
        "Traces beyond the first of a function the runtime asserts is "
        "traced once (serve decode/prefill, the Trainer step)."),
    # tools/graft_lint.py
    "lint.findings": MetricSpec(
        "counter", ("rule",),
        "Findings reported by a graft-lint run, by rule name — scraped "
        "from CI runs to trend which detectors fire."),
    # ops/pallas
    "pallas.fallback": MetricSpec(
        "counter", ("kernel",),
        "Pallas kernel refusals that fell back to the XLA formulation."),
    # parallel/communicator.py
    "quant.overflow_clamps": MetricSpec(
        "counter", (),
        "Gradient values the quantized all-reduce clamped at the int8 "
        "rail (|round(x/scale)| > 127). Zero in healthy operation — the "
        "shared absmax scale covers every rank's range; non-zero flags "
        "non-finite or scale-corrupting gradients for the guardian."),
    # core/retry.py
    "retry.attempts": MetricSpec(
        "counter", ("op",), "Retried attempts of remote I/O operations."),
    "retry.giveups": MetricSpec(
        "counter", ("op",),
        "Remote I/O operations that exhausted their retry budget."),
    # serving/engine.py
    "serve.active_slots": MetricSpec(
        "gauge", (), "Decode slots holding a live request."),
    "serve.cow_copies": MetricSpec(
        "counter", (),
        "Copy-on-write divergences: a prefix-cache-shared page "
        "duplicated to a private page before a slot's first write "
        "into it."),
    "serve.goodput": MetricSpec(
        "gauge", (),
        "Fraction of retired requests that met every configured SLO "
        "(slo_ttft_s / slo_token_latency_s)."),
    "serve.kv_quant_degraded": MetricSpec(
        "counter", (),
        "Quantized-KV admissions degraded to private pages by the "
        "quant.kv_write fault point (no prefix-cache mapping or "
        "publish for that request)."),
    "serve.kv_quant_pages": MetricSpec(
        "gauge", (),
        "KV pages currently allocated out of an int8-quantized page "
        "pool (0 / absent when serve_kv_dtype is f32)."),
    "serve.page_stalls": MetricSpec(
        "counter", ("where",),
        "Admissions or decode growths that waited on a free KV page."),
    "serve.pages_shared": MetricSpec(
        "gauge", (),
        "Prefix-cache pages currently mapped read-only by at least one "
        "slot."),
    "serve.preemptions": MetricSpec(
        "counter", (),
        "Requests preempted (pages freed, requeued) on pool deadlock."),
    "serve.prefix_hits": MetricSpec(
        "counter", (),
        "Full prompt pages served read-only from the prefix cache at "
        "admission — prefill for those tokens is skipped entirely."),
    "serve.prefix_misses": MetricSpec(
        "counter", (),
        "Full prompt pages that missed the prefix cache at admission "
        "and were prefilled into private pages."),
    "serve.queue_depth": MetricSpec(
        "gauge", (), "Requests waiting for a decode slot."),
    "serve.recoveries": MetricSpec(
        "counter", ("where",),
        "Serve-step failures recovered by quarantining device state and "
        "re-admitting in-flight requests (where: serve.prefill | "
        "serve.step)."),
    "serve.requests": MetricSpec(
        "counter", ("status",),
        "Request lifecycle tallies (status: submitted | adopted | "
        "completed | rejected | shed | cancelled | failed; adopted = "
        "fleet dispatch / failover replay into an engine)."),
    "serve.shed": MetricSpec(
        "counter", ("cause",),
        "Queued requests shed by deadline expiry or watchdog-driven "
        "load shedding (cause: deadline | goodput_collapse | "
        "ingest_stall)."),
    "serve.spec_accepted": MetricSpec(
        "counter", (),
        "Draft proposals the speculative verify step accepted (the "
        "leading run where the draft token equals the target's own "
        "per-position sample); acceptance_rate = spec_accepted / "
        "spec_proposed."),
    "serve.spec_proposed": MetricSpec(
        "counter", (),
        "Draft tokens proposed to the speculative verify step (up to "
        "serve_spec_k per active slot per round, clamped by each "
        "slot's page/window budget)."),
    "serve.spec_rollbacks": MetricSpec(
        "counter", (),
        "Draft proposals rejected by the verify step and rolled back "
        "(a host-side length edit — stale KV beyond the accepted "
        "prefix is overwritten by later writes)."),
    "serve.slo_violations": MetricSpec(
        "counter", ("kind",),
        "Retired requests that missed an SLO (kind: ttft | "
        "token_latency)."),
    "serve.token_latency_s": MetricSpec(
        "histogram", (), "Per-token decode-step latency."),
    "serve.tokens": MetricSpec(
        "counter", (), "Tokens emitted by the serving engine."),
    "serve.ttft_s": MetricSpec(
        "histogram", (), "Time from submit() to a request's first token."),
    # observability/spans.py (dynamic family: span.<path>)
    "span.": MetricSpec(
        "histogram", (), "Host-side span timings (spans.span scopes)."),
    # static/trainer.py + observability/telemetry.py
    "trainer.channel_depth": MetricSpec(
        "gauge", (), "Ingest channel occupancy sampled at each dequeue."),
    "trainer.ingest_errors": MetricSpec(
        "counter", ("reason",),
        "Ingest reader threads that died, by exception type."),
    "trainer.ingest_stall_s": MetricSpec(
        "counter", (),
        "Wall time the device loop spent blocked on the ingest channel."),
    "trainer.loss_spikes": MetricSpec(
        "counter", (),
        "Loss-spike episodes latched by the training guardian (a finite "
        "loss above spike_factor x the rolling median; counted once per "
        "episode, watchdog-style)."),
    "trainer.nonfinite_skips": MetricSpec(
        "counter", (),
        "Train steps whose update was skipped in-trace because the loss "
        "or global update norm was non-finite (state kept bit-identical; "
        "counted from the trailing fetch)."),
    "trainer.preempted": MetricSpec(
        "counter", (), "Preemption signals honored at a step boundary."),
    "trainer.rollbacks": MetricSpec(
        "counter", (),
        "Guardian rollbacks: restore the last good checkpoint and replay "
        "the data stream to the same cursor."),
    "trainer.step_s": MetricSpec(
        "histogram", (), "Per-step wall time seen by the Trainer."),
    # observability/watchdog.py
    "watchdog.anomalies": MetricSpec(
        "counter", ("kind",),
        "Anomalies latched by the runtime watchdog (kind: slow_step | "
        "ingest_stall | retrace | goodput_collapse | ingest_error | "
        "loss_spike)."),
}


def lookup(name):
    """The MetricSpec for a metric name — exact match first, then the
    longest registered prefix (names registered with a trailing '.').
    None when uncataloged."""
    spec = CATALOG.get(name)
    if spec is not None:
        return spec
    best = None
    for key, s in CATALOG.items():
        if key.endswith(".") and name.startswith(key):
            if best is None or len(key) > len(best[0]):
                best = (key, s)
    return best[1] if best else None


def help_for(name):
    """HELP text for the exporter: cataloged help, or ''."""
    spec = lookup(name)
    return spec.help if spec else ""


def preregister(names, registry=None):
    """Instantiate cataloged metrics ahead of first use so /metrics
    advertises them (HELP/TYPE) before any traffic — the serving engine
    does this for the serve.* family at construction."""
    from paddle_tpu.observability import metrics as _metrics
    reg = registry if registry is not None else _metrics.registry()
    out = []
    for name in names:
        spec = lookup(name)
        if spec is None:
            raise KeyError(f"metric {name!r} is not in the catalog")
        out.append(getattr(reg, spec.kind)(name, help=spec.help))
    return out
