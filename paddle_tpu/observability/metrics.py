"""Process-global metrics registry — Counter / Gauge / Histogram.

Ref: the reference framework's monitor surface was scattered — profiler
event tables (platform/profiler.h:166), pserver-side counters inside
HeartBeatMonitor, and ad-hoc VLOG lines; none of it was queryable at run
end. Here every degraded path (retries, Pallas fallbacks, torn-checkpoint
skips, missed heartbeats, preemptions) increments a named metric in ONE
registry, and a training run's final RunLog record carries the snapshot —
a bench row or postmortem can state *which* slow paths fired without
grepping logs.

Design: deliberately stdlib-only (no jax, no paddle_tpu imports) so hot
and early-importing modules (core/retry.py, ops/pallas) can depend on it
without cycles. Thread-safe: ingestion threads, heartbeat monitors, and
the train loop all write concurrently.

    from paddle_tpu.observability import metrics

    metrics.counter("retry.attempts").inc(op="copy_one")
    metrics.gauge("trainer.channel_depth").set(3)
    metrics.histogram("trainer.step_s").observe(0.012)
    snap = metrics.snapshot()      # {"counters": ..., "gauges": ...,
                                   #  "histograms": {name: {p50/p95/...}}}
    metrics.reset_all()            # zero values, keep registrations
"""

import math
import random
import re
import threading
import zlib


def _label_key(labels):
    """Stable flat key for a label set: 'k1=v1,k2=v2' ('' when unlabeled)."""
    if not labels:
        return ""
    return ",".join(f"{k}={labels[k]}" for k in sorted(labels))


# a ',' only separates pairs when what follows looks like a new 'key='
# (label values may themselves contain commas — the exporter renders them)
_PAIR_SEP = re.compile(r",(?=[A-Za-z_][A-Za-z0-9_]*=)")


def parse_label_key(key):
    """Inverse of _label_key: 'k1=v1,k2=v2' -> {'k1': 'v1', ...}."""
    if not key:
        return {}
    return dict(p.split("=", 1) for p in _PAIR_SEP.split(key))


def _percentile(sorted_vals, q):
    """Linear-interpolated percentile of a pre-sorted list; q in [0, 1]."""
    if not sorted_vals:
        return None
    idx = (len(sorted_vals) - 1) * q
    lo, hi = int(math.floor(idx)), int(math.ceil(idx))
    if lo == hi:
        return sorted_vals[lo]
    frac = idx - lo
    return sorted_vals[lo] * (1.0 - frac) + sorted_vals[hi] * frac


class Counter:
    """Monotonic additive metric; one value per label set."""

    kind = "counter"

    def __init__(self, name, help=""):
        self.name = name
        self.help = help
        self._lock = threading.Lock()
        self._vals = {}  # graft-guard: self._lock

    def inc(self, n=1, **labels):
        k = _label_key(labels)
        with self._lock:
            self._vals[k] = self._vals.get(k, 0) + n

    def value(self, **labels):
        with self._lock:
            return self._vals.get(_label_key(labels), 0)

    def total(self):
        """Sum across every label set."""
        with self._lock:
            return sum(self._vals.values())

    def snapshot(self):
        with self._lock:
            return dict(self._vals)

    def reset(self):
        with self._lock:
            self._vals.clear()


class Gauge:
    """Last-write-wins level metric; one value per label set."""

    kind = "gauge"

    def __init__(self, name, help=""):
        self.name = name
        self.help = help
        self._lock = threading.Lock()
        self._vals = {}  # graft-guard: self._lock

    def set(self, value, **labels):
        with self._lock:
            self._vals[_label_key(labels)] = value

    def value(self, **labels):
        with self._lock:
            return self._vals.get(_label_key(labels))

    def snapshot(self):
        with self._lock:
            return dict(self._vals)

    def reset(self):
        with self._lock:
            self._vals.clear()


class Histogram:
    """Distribution metric: exact count/sum/min/max plus percentiles over
    a bounded UNIFORM reservoir of at most `max_samples` observations
    (Vitter's algorithm R). The reservoir keeps memory flat over
    million-step runs while every observation stays equally likely to be
    retained — the old keep-the-most-recent window silently biased
    percentiles toward the tail of the run. Observations that fell out
    of (or never entered) the reservoir are reported as `dropped` in
    stats()/snapshot(), so a consumer can tell sampled percentiles from
    exact ones. The reservoir RNG is seeded from the (name, label) pair:
    identical observation sequences give identical percentiles."""

    kind = "histogram"

    def __init__(self, name, help="", max_samples=2048):
        self.name = name
        self.help = help
        self.max_samples = max_samples
        self._lock = threading.Lock()
        # label key -> dict(count, sum, min, max, reservoir, rng)
        self._series = {}  # graft-guard: self._lock

    def _slot(self, k):
        s = self._series.get(k)
        if s is None:
            seed = zlib.crc32(f"{self.name}|{k}".encode())
            s = self._series[k] = {
                "count": 0, "sum": 0.0, "min": None, "max": None,
                "reservoir": [], "rng": random.Random(seed)}
        return s

    def observe(self, value, **labels):
        v = float(value)
        with self._lock:
            s = self._slot(_label_key(labels))
            s["count"] += 1
            s["sum"] += v
            s["min"] = v if s["min"] is None else min(s["min"], v)
            s["max"] = v if s["max"] is None else max(s["max"], v)
            res = s["reservoir"]
            if len(res) < self.max_samples:
                res.append(v)
            else:
                # algorithm R: observation i (0-based: count-1) replaces a
                # reservoir entry with probability max_samples / count
                j = s["rng"].randrange(s["count"])
                if j < self.max_samples:
                    res[j] = v

    def count(self, **labels):
        with self._lock:
            s = self._series.get(_label_key(labels))
            return s["count"] if s else 0

    def percentile(self, q, **labels):
        """q in [0, 1], over the retained reservoir."""
        with self._lock:
            s = self._series.get(_label_key(labels))
            vals = sorted(s["reservoir"]) if s else []
        return _percentile(vals, q)

    def stats(self, **labels):
        """{"count", "sum", "mean", "min", "max", "dropped", "p50",
        "p95"} or None. `dropped` = observations not retained in the
        reservoir (0 means the percentiles are exact)."""
        with self._lock:
            s = self._series.get(_label_key(labels))
            if s is None or s["count"] == 0:
                return None
            vals = sorted(s["reservoir"])
            out = {"count": s["count"], "sum": s["sum"],
                   "mean": s["sum"] / s["count"],
                   "min": s["min"], "max": s["max"],
                   "dropped": s["count"] - len(vals)}
        out["p50"] = _percentile(vals, 0.50)
        out["p95"] = _percentile(vals, 0.95)
        return out

    def snapshot(self):
        with self._lock:
            keys = list(self._series)
        out = {}
        for k in keys:
            st = self.stats(**parse_label_key(k))
            if st is not None:
                out[k] = st
        return out

    def reset(self):
        with self._lock:
            self._series.clear()


class MetricsRegistry:
    """Name -> metric map with get-or-create accessors. One process-global
    default instance (`registry()`); tests may build private ones."""

    _KINDS = {"counter": Counter, "gauge": Gauge, "histogram": Histogram}

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics = {}  # graft-guard: self._lock

    def _get_or_make(self, cls, name, help, **kw):
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = self._metrics[name] = cls(name, help=help, **kw)
            elif not isinstance(m, cls):
                raise ValueError(
                    f"metric {name!r} already registered as {m.kind}, "
                    f"requested {cls.kind}")
            return m

    def counter(self, name, help=""):
        return self._get_or_make(Counter, name, help)

    def gauge(self, name, help=""):
        return self._get_or_make(Gauge, name, help)

    def histogram(self, name, help="", max_samples=2048):
        return self._get_or_make(Histogram, name, help,
                                 max_samples=max_samples)

    def get(self, name):
        with self._lock:
            return self._metrics.get(name)

    def names(self):
        with self._lock:
            return sorted(self._metrics)

    def snapshot(self):
        """JSON-ready nested view. Unlabeled metrics flatten to scalars:
        {"counters": {"checkpoint.saves": 2,
                      "retry.attempts": {"op=copy_one": 3}}, ...}"""
        with self._lock:
            items = list(self._metrics.items())
        out = {"counters": {}, "gauges": {}, "histograms": {}}
        section = {"counter": "counters", "gauge": "gauges",
                   "histogram": "histograms"}
        for name, m in items:
            snap = m.snapshot()
            if not snap:
                continue
            if set(snap) == {""}:
                snap = snap[""]
            out[section[m.kind]][name] = snap
        return out

    def reset(self):
        """Zero every metric; registrations (and helper text) survive."""
        with self._lock:
            items = list(self._metrics.values())
        for m in items:
            m.reset()


_DEFAULT = MetricsRegistry()


def registry():
    """The process-global registry every framework counter lives in."""
    return _DEFAULT


def counter(name, help=""):
    return _DEFAULT.counter(name, help)


def gauge(name, help=""):
    return _DEFAULT.gauge(name, help)


def histogram(name, help="", max_samples=2048):
    return _DEFAULT.histogram(name, help, max_samples=max_samples)


def snapshot():
    return _DEFAULT.snapshot()


def reset_all():
    _DEFAULT.reset()
