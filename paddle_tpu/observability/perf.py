"""Hardware-peak and cost-analysis helpers shared by bench.py and the
Trainer's step telemetry.

Moved out of bench.py (which keeps thin delegating wrappers) so MFU
arithmetic has ONE home: the bench rows, the per-step RunLog records, and
tools/run_report.py all compute achieved/peak from the same table.

jax is imported lazily — bench.py's outer driver path (tunnel probe,
captured-row fallback) must stay importable without touching the backend.
"""

import os


def peak_flops():
    """Per-chip peak bf16 FLOP/s; override with PT_PEAK_FLOPS."""
    if "PT_PEAK_FLOPS" in os.environ:
        return float(os.environ["PT_PEAK_FLOPS"])
    import jax
    d = jax.devices()[0]
    kind = getattr(d, "device_kind", "").lower()
    # bf16 peaks: v5e (v5 lite) 197 TFLOP/s (394 is the int8 number);
    # v5p: 459; v4: 275; v6e: 918
    if "v5 lite" in kind or "v5e" in kind or "lite" in kind:
        return 197e12
    if "v5p" in kind or "v5" in kind:
        return 459e12
    if "v6" in kind:
        return 918e12
    if "v4" in kind:
        return 275e12
    return 197e12


def cost_flops(jitted, *args):
    """FLOPs per call from XLA cost analysis; 0.0 when unavailable (non-
    jitted callables, backends without cost analysis, tracing failures)."""
    try:
        c = jitted.lower(*args).compile().cost_analysis()
        if isinstance(c, (list, tuple)):
            c = c[0]
        return float(c.get("flops", 0.0))
    except Exception:
        return 0.0


def mfu(flops_per_step, step_s):
    """Achieved fraction of the chip's peak for one step, or None."""
    if not flops_per_step or not step_s or step_s <= 0:
        return None
    return flops_per_step / step_s / peak_flops()


# memory_stats keys worth carrying in a step record (full dict is noisy)
_MEM_KEYS = ("bytes_in_use", "peak_bytes_in_use", "bytes_limit",
             "largest_alloc_size")


def device_memory_stats(device=None):
    """Compact HBM stats for one device ({'peak_bytes_in_use': ...}), or
    None where the backend has no allocator stats (CPU)."""
    try:
        import jax
        d = device if device is not None else jax.local_devices()[0]
        ms = d.memory_stats()
    except Exception:
        return None
    if not ms:
        return None
    out = {k: int(ms[k]) for k in _MEM_KEYS if k in ms}
    return out or {k: int(v) for k, v in ms.items()
                   if isinstance(v, (int, float))} or None
