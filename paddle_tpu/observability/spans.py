"""Trace spans — nestable host-side scopes backed by the metrics registry
AND the device trace.

Ref: /root/reference/paddle/fluid/platform/profiler.h:81 — the RAII
``RecordEvent`` the reference wrapped around every op run, feeding both
the sorted event tables (profiler.h:166) and the chrome-trace timeline
(tools/timeline.py). Here one ``span()`` context manager feeds all three
successors at once:

  * the process-global `EventRecorder` text table (`span_report()`),
  * a `span.<path>` Histogram in the metrics registry (p50/p95 land in
    RunLog final snapshots and bench telemetry), and
  * `jax.profiler.TraceAnnotation`, so the scope shows up as a named
    range inside an XPlane trace next to the device ops it contains.

Nesting concatenates names with '/': a span("ingest") inside
span("step") records as "step/ingest" (per-thread stacks — ingestion
threads and the device loop don't interleave each other's paths).

    from paddle_tpu import observability as obs

    with obs.span("step"):
        with obs.span("stage"):
            ...
    print(obs.span_report())
"""

import contextlib
import threading
import time

import jax

from paddle_tpu.observability import metrics as _metrics
from paddle_tpu.observability import trace as _trace
from paddle_tpu.profiler import EventRecorder

_TLS = threading.local()
_RECORDER = EventRecorder()


def recorder():
    """The process-global EventRecorder behind span()."""
    return _RECORDER


@contextlib.contextmanager
def span(name):
    """Time a scope into the span table + metrics registry and annotate
    the device trace. Nestable; cheap enough for per-step use (a
    perf_counter pair and a TraceAnnotation — no device sync)."""
    stack = getattr(_TLS, "stack", None)
    if stack is None:
        stack = _TLS.stack = []
    stack.append(str(name))
    full = "/".join(stack)
    t0 = time.perf_counter()
    try:
        with jax.profiler.TraceAnnotation(str(name)):
            yield
    finally:
        dt = time.perf_counter() - t0
        stack.pop()
        _RECORDER.add(full, dt)
        _metrics.histogram("span." + full).observe(dt)
        _trace.note_span(full, dt)   # links into the active trace
        #                              context via the flight ring


def annotate_span(name):
    """Decorator twin of span() (ref: profiler.annotate_fn)."""
    def deco(fn):
        def wrapped(*a, **kw):
            with span(name):
                return fn(*a, **kw)
        return wrapped
    return deco


def span_summary(sort_by="total"):
    """Structured rows of every recorded span (EventRecorder.summary)."""
    return _RECORDER.summary(sort_by=sort_by)


def span_report():
    """The sorted text table (ref: DisableProfiler's event table)."""
    return _RECORDER.report()


def reset_spans():
    """Drop recorded span timings (registry histograms are reset
    separately via metrics.reset_all)."""
    _RECORDER.reset()
