"""Anomaly-triggered flight recorder: a bounded in-memory ring of
recent trace events and metric deltas, dumped as one self-contained
evidence bundle the moment a watchdog anomaly fires.

Watchdog anomalies used to fire with zero evidence captured — by the
time a human looked, the engine had shed load, respawned, or moved on,
and the metrics that explained the collapse were gone. The recorder
keeps the last ``flight_ring`` events per process (deque appends, no
I/O — recording costs nothing on the hot path) and ``dump_bundle()``
materializes everything into a timestamped directory:

  - ``metrics.json``     full metrics-registry snapshot
  - ``ring.jsonl``       the event ring, oldest first
  - ``runlog_tail.jsonl``tails of the RunLogs handed in (rotation-aware)
  - ``config.json``      active ServeConfig / MeshPlan / fleet summary
  - ``profile/``         optional ``flight_profile_s``-second XPlane
                         capture (jax.profiler; skipped when 0 or jax
                         is unavailable)
  - ``MANIFEST.json``    reason, wall time, and the section list —
                         written LAST, so a complete manifest certifies
                         a complete bundle

Wiring: the engine watchdog's ``action`` hook dumps locally for a
standalone engine; a fleet-owned engine forwards through its
``anomaly_sink`` and ``FleetRouter`` fans one fleet-level dump out
across every replica so the drill artifact is complete. The dump path
carries a ``flight.dump`` chaos fault point and never raises — an
anomaly handler that crashes the engine is worse than no handler.

Host-side stdlib only (jax imported lazily for the optional profile
capture); the ``hot-path-sync`` lint runs over this module.
"""

import collections
import itertools
import json
import os
import time

from paddle_tpu.core.flags import get_flag
from paddle_tpu.observability import metrics as _metrics
from paddle_tpu.testing.chaos import fault_point

_SEQ = itertools.count()
_LAST_BUNDLE = None


class FlightRecorder:
    """Bounded ring of recent events. Appends are single deque ops
    (thread-safe under the GIL, no lock, no I/O); ``snapshot()`` copies
    the ring for a dump."""

    def __init__(self, size):
        self.size = int(size)
        self._ring = collections.deque(maxlen=self.size)

    def note_event(self, kind, **fields):
        self._ring.append(dict(event=kind, t=time.perf_counter(),
                               **fields))

    def note(self, rec):
        """Append an already-formed trace record (the engine's
        ``_trace_event`` feeds the ring the same record it logs — the
        kind was already stamped, and the event-drift lint checked it
        at that call site)."""
        self._ring.append(rec)

    def note_metric_delta(self, name, value, **labels):
        """Record a metric observation worth keeping in the ring (the
        engine's per-step deltas feed this alongside the counter)."""
        self._ring.append(dict(metric=name, value=value,
                               t=time.perf_counter(), **labels))

    def snapshot(self):
        return list(self._ring)

    def __len__(self):
        return len(self._ring)


_RECORDER = None


def recorder():
    """The process-global ring, sized by the ``flight_ring`` flag;
    None when the flag is 0 (recording off). Resizing the flag builds
    a fresh ring."""
    global _RECORDER
    size = int(get_flag("flight_ring"))
    if size <= 0:
        return None
    if _RECORDER is None or _RECORDER.size != size:
        _RECORDER = FlightRecorder(size)
    return _RECORDER


def last_bundle():
    """Path of the most recent bundle this process dumped, else None."""
    return _LAST_BUNDLE


def _jsonable(obj):
    """Best-effort JSON view of a config-ish object: dicts/lists
    recurse, scalars pass through, everything else reprs."""
    if isinstance(obj, dict):
        return {str(k): _jsonable(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_jsonable(v) for v in obj]
    if obj is None or isinstance(obj, (bool, int, float, str)):
        return obj
    return repr(obj)


def _write_json(path, obj):
    with open(path, "w") as fh:
        json.dump(_jsonable(obj), fh, indent=2, sort_keys=True)
        fh.write("\n")


def _write_jsonl(path, records):
    with open(path, "w") as fh:
        for rec in records:
            fh.write(json.dumps(_jsonable(rec)) + "\n")


def _capture_profile(path, seconds):
    """Optional XPlane capture; returns True when a trace landed."""
    try:
        import jax
        jax.profiler.start_trace(path)
        time.sleep(seconds)
        jax.profiler.stop_trace()
        return True
    except Exception:
        return False


def dump_bundle(reason, run_logs=(), config=None, extra=None,
                out_dir=None, tail=200, profile_s=None):
    """Materialize one flight bundle; returns its path, or None when
    the dump failed (fault-injected or real — the failure is counted on
    ``flight.dumps{status=error}`` and never propagates: this runs from
    anomaly handlers that must not take the engine down with them).

    ``run_logs`` is an iterable of RunLog paths (or objects with a
    ``path``) whose tails join the bundle; ``config`` is the active
    ServeConfig/MeshPlan/fleet summary; ``extra`` merges into the
    manifest (the anomaly event, fleet state, ...)."""
    global _LAST_BUNDLE
    try:
        fault_point("flight.dump")
        base = out_dir or str(get_flag("flight_dir"))
        stamp = time.strftime("%Y%m%d-%H%M%S")
        path = os.path.join(
            base, f"flight-{stamp}-p{os.getpid()}-{next(_SEQ)}")
        os.makedirs(path)
        sections = []

        _write_json(os.path.join(path, "metrics.json"),
                    _metrics.snapshot())
        sections.append("metrics.json")

        rec = recorder()
        ring = rec.snapshot() if rec is not None else []
        if rec is not None:
            rec.note_event("flight_dump", reason=reason)
        _write_jsonl(os.path.join(path, "ring.jsonl"), ring)
        sections.append("ring.jsonl")

        from paddle_tpu.observability.runlog import tail_records
        tails = []
        for rl in run_logs:
            p = getattr(rl, "path", rl)
            if not p:
                continue
            try:
                tails.extend(dict(r, _runlog=str(p))
                             for r in tail_records(p, limit=tail))
            except Exception as e:
                tails.append(dict(_runlog=str(p), _error=repr(e)))
        _write_jsonl(os.path.join(path, "runlog_tail.jsonl"), tails)
        sections.append("runlog_tail.jsonl")

        _write_json(os.path.join(path, "config.json"), config or {})
        sections.append("config.json")

        secs = (float(get_flag("flight_profile_s"))
                if profile_s is None else float(profile_s))
        if secs > 0 and _capture_profile(
                os.path.join(path, "profile"), secs):
            sections.append("profile")

        manifest = dict(reason=reason, wall=time.time(),
                        pid=os.getpid(), ring_events=len(ring),
                        sections=sections)
        if extra:
            manifest.update(_jsonable(extra))
        _write_json(os.path.join(path, "MANIFEST.json"), manifest)
        _metrics.counter("flight.dumps").inc(status="ok")
        _LAST_BUNDLE = path
        return path
    except Exception:
        _metrics.counter("flight.dumps").inc(status="error")
        return None


def read_manifest(bundle_dir):
    """The bundle's manifest dict, or None when the bundle is
    incomplete (the manifest is written last)."""
    p = os.path.join(bundle_dir, "MANIFEST.json")
    if not os.path.exists(p):
        return None
    with open(p) as fh:
        return json.load(fh)


def list_bundles(base=None):
    """Complete bundles (manifest present) under the flight dir,
    oldest first."""
    base = base or str(get_flag("flight_dir"))
    if not os.path.isdir(base):
        return []
    out = [os.path.join(base, d) for d in sorted(os.listdir(base))
           if d.startswith("flight-")]
    return [d for d in out if read_manifest(d) is not None]
