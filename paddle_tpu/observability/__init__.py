"""Observability — the run-scoped telemetry subsystem.

Reference-framework ancestry (what each piece re-architects):

  metrics.py    counters/gauges/histograms in ONE process registry — the
                successor of the reference's scattered monitor state
                (pserver HeartBeatMonitor tallies, profiler totals,
                ad-hoc VLOG counters); every degraded path in this
                framework (core/retry.py attempts, ops/pallas fallbacks,
                io/checkpoint.py torn-commit skips and mirror
                degradations, parallel/heartbeat.py missed beats,
                static/trainer.py preemptions + ingest stalls) now
                increments a named metric here.
  runlog.py     JSONL step-record sink with rotation — the durable,
                machine-readable run artifact the reference never had
                (DeviceWorker VLOG lines were the closest thing).
  spans.py      nestable span() scopes — platform/profiler.h:81
                RecordEvent, feeding the sorted text table
                (profiler.h:166 EnableProfiler), the metrics registry,
                and jax.profiler.TraceAnnotation (the chrome-trace
                timeline role of tools/timeline.py).
  perf.py       peak-FLOPs table + XLA cost-analysis + device memory
                stats (moved from bench.py so bench rows, step records,
                and tools/run_report.py share one MFU arithmetic).
  telemetry.py  TelemetryConfig/StepTelemetry — opt-in per-step records
                (wall time, tokens/s, MFU, trailing-fetch loss, HBM
                peaks) emitted from static/trainer.py with no device
                sync on the hot path.
  catalog.py    the one table of every metric name/type/labels/help;
                exporter HELP lines come from it and a tier-1 lint
                fails on call sites naming uncataloged metrics.
  exporter.py   Prometheus text exposition of the whole registry +
                a stdlib /metrics + /healthz HTTP server (flag
                metrics_port; start_metrics_server()).
  watchdog.py   rolling-window anomaly monitor (slow-step, ingest
                stall, steady-state retrace, goodput collapse) latching
                watchdog.anomalies{kind} + RunLog events; fed by the
                Trainer loop and the serving engine.
  trace.py      fleet-wide distributed tracing — durable trace contexts
                minted at FleetRouter.submit() and carried across
                dispatch/failover hops, per-process clock anchors, and
                the skew-corrected cross-replica timeline merge behind
                tools/run_report.py --fleet-trace.
  flight.py     anomaly-triggered flight recorder — bounded ring of
                recent trace events + dump_bundle() evidence bundles
                (metrics, ring, RunLog tails, config, optional XPlane)
                fired from the watchdog action hook.

tools/run_report.py joins a RunLog with an optional XPlane trace dir
into the human-readable run report (the EnableProfiler/DisableProfiler
report + timeline.py join, in one CLI).

`metrics` and `runlog` are import-light (stdlib only) so early modules
(core/retry.py) can use them without cycles; the jax-importing members
(span, TelemetryConfig, ...) load lazily on first attribute access.
"""

from paddle_tpu.observability import metrics, runlog
from paddle_tpu.observability.metrics import (Counter, Gauge, Histogram,
                                              MetricsRegistry, counter,
                                              gauge, histogram, registry,
                                              reset_all, snapshot)
from paddle_tpu.observability.runlog import (RunLog, read_records,
                                             tail_records)

# lazily-resolved members -> defining submodule (PEP 562): these pull in
# jax/profiler, which early importers of the metrics registry must not
_LAZY = {
    "span": "spans", "annotate_span": "spans", "span_summary": "spans",
    "span_report": "spans", "reset_spans": "spans", "recorder": "spans",
    "spans": None, "telemetry": None, "perf": None,
    "catalog": None, "exporter": None, "watchdog": None,
    "trace": None, "flight": None,
    "TraceContext": "trace", "merge_fleet_trace": "trace",
    "write_anchor": "trace",
    "FlightRecorder": "flight", "dump_bundle": "flight",
    "last_bundle": "flight",
    "TelemetryConfig": "telemetry", "StepTelemetry": "telemetry",
    "default_tokens": "telemetry",
    "peak_flops": "perf", "cost_flops": "perf", "mfu": "perf",
    "device_memory_stats": "perf",
    "MetricsServer": "exporter", "render_prometheus": "exporter",
    "start_metrics_server": "exporter",
    "Watchdog": "watchdog", "WatchdogConfig": "watchdog",
    "maybe_watchdog": "watchdog",
}


def __getattr__(name):
    import importlib
    target = _LAZY.get(name, KeyError)
    if target is KeyError:
        raise AttributeError(
            f"module 'paddle_tpu.observability' has no attribute {name!r}")
    if target is None:      # the submodule itself
        return importlib.import_module(f"paddle_tpu.observability.{name}")
    mod = importlib.import_module(f"paddle_tpu.observability.{target}")
    val = getattr(mod, name)
    globals()[name] = val   # cache: subsequent accesses skip __getattr__
    return val


def bench_telemetry():
    """The self-describing `telemetry` field for bench.py JSON rows:
    the registry's counter snapshot plus step-time p50/p95 (ms) from the
    `bench.step_time_s` histogram `_timed_steps` feeds."""
    snap = metrics.snapshot()
    out = {"counters": snap.get("counters", {})}
    h = metrics.registry().get("bench.step_time_s")
    st = h.stats() if h is not None else None
    if st:
        out["step_time_ms"] = {
            "p50": round(st["p50"] * 1e3, 3),
            "p95": round(st["p95"] * 1e3, 3),
            "n": st["count"]}
    return out
