"""Runtime anomaly watchdog — rolling-window detection of the four ways
a healthy run goes quietly bad.

Ref: the reference framework noticed nothing at runtime — a wedged
reader, a recompiling graph, or a collapsed server showed up only in
post-hoc log archaeology. The watchdog consumes the timings the Trainer
and ServingEngine already produce (no device sync, no new hot-path
work beyond a deque append and a few comparisons) and LATCHES structured
anomaly events into the metrics registry (`watchdog.anomalies{kind}`)
and the RunLog:

  slow_step         step wall time > slow_factor x rolling-window median
  ingest_stall      one step waited > stall_s on the ingest channel
  retrace           `jit.retraces` grew past the warmup steps — a
                    traced-once function recompiled in steady state
                    (shape drift, weak-type flip, donation miss)
  goodput_collapse  serve.goodput < goodput_min once enough requests
                    retired

Two further kinds are fed externally through `alert()` by components
that detect their own conditions but want the same latch + counter +
RunLog + mitigation-dispatch path: ingest_error (a Trainer reader thread
died) and loss_spike (the training guardian's rolling-median detector).

Latch semantics: a level-triggered kind (slow_step, ingest_stall,
goodput_collapse) fires ONCE when the condition appears and re-arms when
it clears, so a 500-step stall is one event, not 500. retrace is
edge-triggered per observed recompile.

`jit.retraces{fn}` itself is fed two ways: the serving engine counts
trace-time entries of its decode/prefill closures directly, and
`Watchdog.watch_jit` polls `_cache_size()` of any jitted callable (the
Trainer step) from the host loop.

Stdlib-only: consumers on the hot path import nothing heavy.
"""

import collections
import dataclasses
import threading
import time

from paddle_tpu.observability import metrics as _metrics
from paddle_tpu.observability.catalog import help_for as _help

KINDS = ("slow_step", "ingest_stall", "retrace", "goodput_collapse",
         "ingest_error", "loss_spike")


@dataclasses.dataclass
class WatchdogConfig:
    """None fields resolve from the watchdog_* flags, so a run can tune
    detection with env vars alone (PT_FLAGS_watchdog=1
    PT_FLAGS_watchdog_slow_factor=5)."""

    window: int = None          # None -> flag watchdog_window
    slow_factor: float = None   # None -> flag watchdog_slow_factor
    stall_s: float = None       # None -> flag watchdog_stall_s
    goodput_min: float = None   # None -> flag watchdog_goodput_min
    min_samples: int = 8        # median needs this many steps first
    warmup_steps: int = 2       # retraces at/below this step are compile,
    #                             not anomaly
    min_retired: int = 8        # goodput needs this many retirements

    def resolve(self):
        from paddle_tpu.core import flags as F
        c = dataclasses.replace(self)
        if c.window is None:
            c.window = int(F.get_flag("watchdog_window"))
        if c.slow_factor is None:
            c.slow_factor = float(F.get_flag("watchdog_slow_factor"))
        if c.stall_s is None:
            c.stall_s = float(F.get_flag("watchdog_stall_s"))
        if c.goodput_min is None:
            c.goodput_min = float(F.get_flag("watchdog_goodput_min"))
        c.window = max(2, c.window)
        return c


class Watchdog:
    """One instance per run loop (Trainer or ServingEngine). Feed it
    with `tick()` once per step; read `anomalies` (structured dicts) or
    the `watchdog.anomalies{kind}` counter."""

    def __init__(self, config=None, run_log=None, registry=None,
                 clock=time.time, action=None):
        self.cfg = (config or WatchdogConfig()).resolve()
        self._reg = (registry if registry is not None
                     else _metrics.registry())
        self._run_log = run_log
        self._clock = clock
        self._action = action       # mitigation callback: (event) -> None
        # The lock covers detector state only; mitigation callbacks fire
        # after it is released, so the watchdog never holds its lock
        # while re-entering the loop it protects (no watchdog->engine
        # lock-order edge).
        self._lock = threading.Lock()
        self._steps = collections.deque(
            maxlen=self.cfg.window)     # graft-guard: self._lock
        self._latched = set()           # graft-guard: self._lock
        # fn name -> [probe, last cache size]; graft-guard: self._lock
        self._watched = {}
        self._retraces_seen = 0     # last-seen jit.retraces total
        self.anomalies = []             # graft-guard: self._lock

    # -- wiring ------------------------------------------------------------
    def watch_jit(self, name, fn):
        """Poll `fn`'s jit cache size each tick; growth past 1 entry
        counts jit.retraces{fn=name}. Callables without a _cache_size
        probe (non-jit wrappers) are ignored."""
        probe = getattr(fn, "_cache_size", None)
        if callable(probe):
            with self._lock:
                self._watched[str(name)] = [probe, None]
        return self

    # -- per-step ----------------------------------------------------------
    def tick(self, step, wall_s=None, stall_s=None, goodput=None,
             retired=0):
        """One scheduling round: check every detector this loop feeds.
        Any argument left None skips its detector."""
        cfg = self.cfg
        fired = []
        with self._lock:
            if wall_s is not None:
                median = self._median()
                if (median is not None
                        and wall_s > cfg.slow_factor * median):
                    self._fire(fired, "slow_step", step, wall_s=wall_s,
                               median_s=median)
                else:
                    self._clear("slow_step")
                self._steps.append(float(wall_s))
            if stall_s is not None:
                if stall_s > cfg.stall_s:
                    self._fire(fired, "ingest_stall", step,
                               stall_s=stall_s)
                else:
                    self._clear("ingest_stall")
            self._poll_jit()
            self._check_retraces(step, fired)
            if goodput is not None and retired >= cfg.min_retired:
                if goodput < cfg.goodput_min:
                    self._fire(fired, "goodput_collapse", step,
                               goodput=goodput, retired=retired)
                else:
                    self._clear("goodput_collapse")
        for event in fired:
            self._dispatch(event)

    # -- external anomalies ------------------------------------------------
    def alert(self, kind, step, latch=True, **detail):
        """Latch an externally-detected anomaly (ingest_error from the
        Trainer's reader threads, loss_spike from the guardian) through
        the same counter/RunLog/dispatch path as the built-in detectors.
        Returns True when a new event fired (False = already latched)."""
        fired = []
        with self._lock:
            self._fire(fired, str(kind), step, latch=latch, **detail)
        for event in fired:
            self._dispatch(event)
        return bool(fired)

    def resolve(self, kind):
        """Re-arm a latched externally-fed anomaly kind (the guardian
        calls this when losses return to the healthy band)."""
        with self._lock:
            self._clear(str(kind))

    # -- detectors ---------------------------------------------------------
    def _median(self):
        if len(self._steps) < self.cfg.min_samples:
            return None
        vals = sorted(self._steps)
        n = len(vals)
        mid = n // 2
        return vals[mid] if n % 2 else 0.5 * (vals[mid - 1] + vals[mid])

    def _poll_jit(self):
        ctr = self._reg.counter("jit.retraces", _help("jit.retraces"))
        for name, slot in self._watched.items():
            probe, last = slot
            try:
                size = int(probe())
            except Exception:
                continue
            if last is not None and size > max(last, 1):
                ctr.inc(size - max(last, 1), fn=name)
            slot[1] = size

    def _check_retraces(self, step, fired):
        ctr = self._reg.get("jit.retraces")
        total = ctr.total() if ctr is not None else 0
        grew = total - self._retraces_seen
        self._retraces_seen = total
        if grew > 0 and step > self.cfg.warmup_steps:
            # edge-triggered: every steady-state recompile is an event
            self._fire(fired, "retrace", step, new_retraces=grew,
                       latch=False)

    # -- latch + emit ------------------------------------------------------
    def _fire(self, fired, kind, step, latch=True, **detail):
        """Record one anomaly (caller holds the lock) and queue it on
        `fired` for post-release dispatch to the mitigation callback."""
        if latch:
            if kind in self._latched:
                return
            self._latched.add(kind)
        event = {"anomaly": kind, "step": int(step),
                 "time": self._clock(), **detail}
        self.anomalies.append(event)
        self._reg.counter("watchdog.anomalies",
                          _help("watchdog.anomalies")).inc(kind=kind)
        if self._run_log is not None:
            self._run_log.write(event)
        fired.append(event)

    def _dispatch(self, event):
        if self._action is not None:
            # mitigation must never take down the loop it protects
            try:
                self._action(event)
            except Exception as e:
                if self._run_log is not None:
                    self._run_log.write({"anomaly_action_error":
                                         f"{type(e).__name__}: {e}"[:200]})

    def _clear(self, kind):
        self._latched.discard(kind)


def maybe_watchdog(setting, run_log=None, registry=None, action=None):
    """Resolve a Trainer/ServeConfig `watchdog` field into a Watchdog or
    None: a WatchdogConfig is used as-is, True builds defaults, None
    honors the global `watchdog` flag, False disables. `action` is an
    optional mitigation callback invoked with each fired anomaly event
    (the serving engine passes its load-shedding handler)."""
    if setting is None:
        from paddle_tpu.core.flags import get_flag
        setting = bool(get_flag("watchdog"))
    if not setting:
        return None
    cfg = setting if isinstance(setting, WatchdogConfig) else None
    return Watchdog(cfg, run_log=run_log, registry=registry, action=action)
