"""RunLog — append-only JSONL sink for per-step telemetry records.

Ref: the reference framework printed step stats to stdout from each
DeviceWorker thread and kept nothing machine-readable; its profiler wrote
a one-shot chrome-trace (tools/timeline.py). The RunLog is the durable
middle ground: one JSON object per line, flushed as written (a preempted
or crashed run keeps everything up to its last step), with size-bounded
rotation so million-step runs don't grow an unbounded artifact.

    log = RunLog("/runs/exp1/run.jsonl", rotate_records=100_000)
    log.write({"step": 10, "wall_s": 0.012, "loss": 3.2})
    log.close()

    for rec in read_records("/runs/exp1/run.jsonl"):  # rotated-aware
        ...

tools/run_report.py renders a RunLog (optionally joined with an XPlane
trace dir) into the human-readable run report.
"""

import glob
import json
import os
import threading


class RunLog:
    """Thread-safe JSONL writer with optional record-count rotation.

    rotate_records=N (0 = never rotate): after N records the live file is
    rolled to ``<path>.1`` (existing rolls shift up, the oldest beyond
    ``keep_rotated`` is dropped) and a fresh file starts. ``read_records``
    reassembles the full stream oldest-first.
    """

    def __init__(self, path, rotate_records=0, keep_rotated=3):
        self.path = str(path)
        self.rotate_records = int(rotate_records or 0)
        self.keep_rotated = max(1, int(keep_rotated))
        self._lock = threading.Lock()
        self._fh = None
        self._count = 0
        d = os.path.dirname(os.path.abspath(self.path))
        os.makedirs(d, exist_ok=True)

    def _open(self):
        if self._fh is None:
            self._fh = open(self.path, "a")
        return self._fh

    def _rotate(self):
        if self._fh is not None:
            self._fh.close()
            self._fh = None
        oldest = f"{self.path}.{self.keep_rotated}"
        if os.path.exists(oldest):
            os.remove(oldest)
        for i in range(self.keep_rotated - 1, 0, -1):
            src = f"{self.path}.{i}"
            if os.path.exists(src):
                os.replace(src, f"{self.path}.{i + 1}")
        if os.path.exists(self.path):
            os.replace(self.path, f"{self.path}.1")
        self._count = 0

    def write(self, record):
        """Append one record (a JSON-serializable dict) and flush."""
        line = json.dumps(record)
        with self._lock:
            if self.rotate_records and self._count >= self.rotate_records:
                self._rotate()
            fh = self._open()
            fh.write(line + "\n")
            fh.flush()
            self._count += 1

    def close(self):
        with self._lock:
            if self._fh is not None:
                self._fh.close()
                self._fh = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False


def _rotated_siblings(path):
    """``path.N`` rotation siblings, oldest (highest N) first. Only
    fully numeric suffixes qualify: the ``.[0-9]*`` glob alone also
    matches e.g. ``run.jsonl.2bak``, whose suffix would crash the sort
    key and take every report down with it."""
    sibs = []
    for p in glob.glob(glob.escape(str(path)) + ".[0-9]*"):
        suffix = p.rsplit(".", 1)[1]
        if suffix.isdigit():
            sibs.append((int(suffix), p))
    return [p for _, p in sorted(sibs, reverse=True)]


def read_records(path):
    """Every record of a (possibly rotated) RunLog, oldest first.

    Tolerates a torn final line (a run killed mid-write leaves at most
    one truncated record; it is skipped, everything durable is kept)."""
    files = _rotated_siblings(path)
    if os.path.exists(path):
        files.append(str(path))
    out = []
    for f in files:
        with open(f) as fh:
            for line in fh:
                line = line.strip()
                if not line:
                    continue
                try:
                    out.append(json.loads(line))
                except ValueError:
                    continue    # torn tail of a killed writer
    return out


def tail_records(path, limit=200):
    """The last ``limit`` records of a (possibly rotated) RunLog, in
    order — the flight recorder's RunLog-tail bundle section. Reads the
    full stream (RunLogs are size-bounded by rotation) and slices."""
    recs = read_records(path)
    return recs[-int(limit):] if limit else recs
