"""Step telemetry — per-step run records for the Trainer, with no device
sync on the hot path.

Ref: the reference trainer printed loss from each DeviceWorker thread
(device_worker.cc VLOGs) and had no notion of achieved utilization; its
profiler had to be switched on globally. Here telemetry is a run-scoped,
opt-in sidecar: the Trainer hands it (step, batch, loss) after every
step and it emits JSONL records to a RunLog every N steps — wall time,
tokens/s, achieved MFU (XLA cost analysis over perf.peak_flops),
host-visible loss/grad-norm, and device memory peaks.

Hot-path discipline: the loss scalar is NOT fetched for the step that
just dispatched — that would serialize host and device exactly like the
`float(loss)` logging path. Instead the device array is parked and
fetched via `jax.device_get` at the NEXT emission point, by which time
its step has long completed — the fetch returns without waiting on the
in-flight step (tests assert no `block_until_ready` appears on the
path). Records therefore trail by one interval; `finish()` flushes the
last one plus a final metrics-registry snapshot.
"""

import dataclasses
import time

import jax

from paddle_tpu.core import flags as F
from paddle_tpu.observability import metrics as _metrics
from paddle_tpu.observability import perf as _perf
from paddle_tpu.observability.runlog import RunLog
from paddle_tpu.observability.spans import span_summary


@dataclasses.dataclass
class TelemetryConfig:
    """Knobs for Trainer step telemetry. ``None`` fields resolve from the
    ``telemetry*`` flags (env: PT_FLAGS_telemetry,
    PT_FLAGS_telemetry_run_log, PT_FLAGS_telemetry_every_n) so a run can
    be instrumented without code changes."""

    enabled: bool = None          # None -> flag "telemetry"
    run_log: str = None           # JSONL path; None -> flag ('' = memory)
    every_n_steps: int = None     # None -> flag "telemetry_every_n"
    rotate_records: int = 0       # RunLog rotation (0 = never)
    flops_per_step: float = None  # known FLOPs; skips the estimate
    estimate_flops: bool = True   # cost-analysis estimate when unknown
    tokens_fn: object = None      # batch -> tokens/step (None = infer)
    grad_norm_fn: object = None   # state -> device scalar (optional)
    metrics_port: int = None      # /metrics exporter; None -> flag
    #                               "metrics_port", 0 = off

    def resolve(self):
        """A copy with every None filled from the current flags."""
        c = dataclasses.replace(self)
        if c.enabled is None:
            c.enabled = bool(F.get_flag("telemetry"))
        if c.run_log is None:
            c.run_log = F.get_flag("telemetry_run_log") or None
        if c.every_n_steps is None:
            c.every_n_steps = int(F.get_flag("telemetry_every_n"))
        c.every_n_steps = max(1, int(c.every_n_steps))
        if c.metrics_port is None:
            c.metrics_port = int(F.get_flag("metrics_port"))
        return c


def default_tokens(batch):
    """Tokens per step inferred from the batch: the first >=2-D array
    contributes batch*seq; else the first array's leading dim (examples
    stand in for tokens); else 0."""
    arrays = [a for a in batch if getattr(a, "shape", None)]
    for a in arrays:
        if len(a.shape) >= 2:
            return int(a.shape[0]) * int(a.shape[1])
    for a in arrays:
        if len(a.shape) >= 1:
            return int(a.shape[0])
    return 0


class StepTelemetry:
    """Accumulates per-step records and writes them to a RunLog.

    Usage (what static/trainer.py does):

        tele = StepTelemetry(TelemetryConfig(enabled=True, run_log=p))
        tele.maybe_estimate_flops(jitted_step, state, *batch)   # once
        for ...:
            loss, state = jitted_step(state, *batch)
            tele.on_step(step, batch, loss, state, wall_s)
        tele.finish({"steps": step})
    """

    def __init__(self, config=None):
        self.cfg = (config or TelemetryConfig()).resolve()
        self.enabled = bool(self.cfg.enabled)
        self.records = []          # in-memory mirror (tests, no-sink runs)
        self._log = None
        if self.enabled and self.cfg.run_log:
            self._log = RunLog(self.cfg.run_log,
                               rotate_records=self.cfg.rotate_records)
        self._flops = self.cfg.flops_per_step
        self._pending = None       # (step, wall_s, tokens, loss, gnorm)
        self._hist = _metrics.histogram(
            "trainer.step_s", "Per-step wall time seen by the Trainer.")
        # the final snapshot's `step_time` must cover THIS run only; the
        # registry histogram above accumulates process-wide (exporter
        # continuity), so the per-run figures come from a private copy
        self._run_hist = _metrics.Histogram("trainer.step_s")
        self._finished = False
        self._metrics_server = None
        if self.enabled and self.cfg.metrics_port:
            from paddle_tpu.observability.exporter import \
                start_metrics_server
            self._metrics_server = start_metrics_server(
                self.cfg.metrics_port)

    # -- setup ------------------------------------------------------------
    def maybe_estimate_flops(self, step_fn, *args):
        """One-time FLOPs-per-step estimate via XLA cost analysis (only
        when the config didn't supply flops_per_step). Runs BEFORE the
        first step so donated buffers are still live; the lower+compile
        hits the in-process executable cache for jitted fns. Failure
        degrades to mfu=None records, never into the train loop."""
        if not self.enabled or self._flops is not None:
            return
        if not self.cfg.estimate_flops or not hasattr(step_fn, "lower"):
            self._flops = 0.0
            return
        self._flops = _perf.cost_flops(step_fn, *args)

    # -- per-step ---------------------------------------------------------
    def on_step(self, step, batch, loss, state=None, wall_s=None):
        """Record one completed step. `loss` stays a device array — it is
        parked and host-fetched at the next emission (trailing), so this
        call never blocks on the device."""
        if not self.enabled:
            return
        if wall_s is not None:
            self._hist.observe(wall_s)
            self._run_hist.observe(wall_s)
        if step % self.cfg.every_n_steps != 0:
            return
        self._flush_pending(at_step=step)
        tokens = (self.cfg.tokens_fn(batch) if self.cfg.tokens_fn
                  else default_tokens(batch))
        gnorm = (self.cfg.grad_norm_fn(state)
                 if self.cfg.grad_norm_fn is not None else None)
        self._pending = (int(step), wall_s, tokens, loss, gnorm)

    def _flush_pending(self, at_step=None):
        """Emit the parked record. When called from on_step(at_step), the
        parked step is strictly older than `at_step` — its loss has been
        computed for >= one full interval, so device_get returns without
        stalling the in-flight step."""
        if self._pending is None:
            return
        step, wall_s, tokens, loss, gnorm = self._pending
        self._pending = None
        rec = {"step": step, "time": time.time(), "wall_s": wall_s}
        rec["tokens_per_s"] = (
            tokens / wall_s if tokens and wall_s else None)
        rec["mfu"] = _perf.mfu(self._flops, wall_s)
        try:
            rec["loss"] = float(jax.device_get(loss)) if loss is not None else None  # graft-lint: disable=hot-path-sync (trailing fetch: this loss is >= one full step old, so device_get returns without stalling the in-flight step)
        except Exception:
            rec["loss"] = None
        try:
            rec["grad_norm"] = float(jax.device_get(gnorm)) if gnorm is not None else None  # graft-lint: disable=hot-path-sync (same parked-step fetch as loss above — never blocks on in-flight work)
        except Exception:
            rec["grad_norm"] = None
        rec["memory"] = _perf.device_memory_stats()
        self._write(rec)

    def _write(self, rec):
        self.records.append(rec)
        if self._log is not None:
            self._log.write(rec)

    # -- teardown ---------------------------------------------------------
    def finish(self, extra=None):
        """Flush the trailing record and write the final snapshot record:
        the full metrics-registry state (retry / pallas-fallback /
        checkpoint / heartbeat / trainer counters) + step-time stats +
        the span table — the run's whole degraded-path story in one
        JSON object."""
        if not self.enabled or self._finished:
            return
        self._finished = True
        self._flush_pending()
        snap = _metrics.snapshot()
        rec = {"final": True, "time": time.time(),
               "counters": snap.get("counters", {}),
               "gauges": snap.get("gauges", {}),
               "histograms": snap.get("histograms", {}),
               "step_time": self._run_hist.stats(),
               "spans": span_summary()}
        if extra:
            rec.update(extra)
        self._write(rec)
        if self._log is not None:
            self._log.close()
        if self._metrics_server is not None:
            self._metrics_server.stop()
            self._metrics_server = None

    def close(self):
        self.finish()
