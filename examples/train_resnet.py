"""Train ResNet on synthetic images — the image_classification book recipe.

Run (CPU or TPU):  python examples/train_resnet.py --steps 20 --batch 32
"""

import argparse

import numpy as np

import jax
import jax.numpy as jnp

import paddle_tpu as pt
from paddle_tpu.models import ResNet
from paddle_tpu.ops import loss as L


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=20)
    ap.add_argument("--batch", type=int, default=32)
    ap.add_argument("--depth", type=int, default=18)
    ap.add_argument("--ckpt", default=None, help="checkpoint dir")
    args = ap.parse_args()

    model = ResNet(args.depth, num_classes=10, small_input=True)
    variables = model.init(jax.random.key(0))
    params, state = variables["params"], variables["state"]
    opt = pt.amp.decorate(pt.optimizer.Momentum(0.05, 0.9),
                          pt.amp.bf16_policy())
    opt_state = opt.init(params)

    def loss_fn(p, images, labels, state):
        out, new_state = model.apply({"params": p, "state": state}, images,
                                     training=True)
        return jnp.mean(L.softmax_with_cross_entropy(out, labels)), new_state

    @jax.jit
    def step(params, opt_state, state, images, labels):
        loss, params, opt_state, state = opt.minimize(
            loss_fn, params, opt_state, images, labels, state)
        return loss, params, opt_state, state

    loader = pt.data.DataLoader.from_generator(
        generator=lambda: pt.data.synthetic_images(
            args.steps * args.batch, num_classes=10),
        batch_size=args.batch)
    for i, (images, labels) in enumerate(loader):
        loss, params, opt_state, state = step(params, opt_state, state,
                                              images, labels)
        if i % 5 == 0:
            print(f"step {i} loss {float(loss):.4f}")

    if args.ckpt:
        mgr = pt.io.CheckpointManager(args.ckpt)
        mgr.save(args.steps, {"params": params, "opt": opt_state,
                              "state": state})
        mgr.close()
        print(f"checkpoint saved to {args.ckpt}")


if __name__ == "__main__":
    main()
