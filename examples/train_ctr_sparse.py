"""DeepFM CTR with a beyond-HBM host-resident embedding table.

The PSLib-successor flow: the Trainer pulls each batch's unique rows from
a HostTable, trains through them, and pushes row gradients back
(ref: DownpourWorker / fleet_wrapper.h pull-push cycle).

Run: python examples/train_ctr_sparse.py --steps 10
"""

import argparse

import numpy as np

import jax
import jax.numpy as jnp

import paddle_tpu as pt
from paddle_tpu.models.ctr import CTRConfig, DeepFM, ctr_loss
from paddle_tpu.parallel import HostTable
from paddle_tpu.static import Trainer, TrainerConfig


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=10)
    ap.add_argument("--batch", type=int, default=64)
    args = ap.parse_args()

    cfg = CTRConfig(num_sparse_fields=8, num_dense_fields=4,
                    vocab_size=100000, embed_dim=16, hidden=(64, 32))
    model = DeepFM(cfg, sparse_tables=True)
    params = model.init(jax.random.key(0))["params"]
    opt = pt.optimizer.Adam(1e-3)
    opt_state = opt.init(params)
    vtot = cfg.vocab_size * cfg.num_sparse_fields
    table = HostTable(vtot, cfg.embed_dim, pt.optimizer.Adagrad(0.05))
    lin = HostTable(vtot, 1, pt.optimizer.Adagrad(0.05))
    print(f"host table: {table.nbytes() / 1e6:.1f} MB in host RAM")

    offsets = np.arange(cfg.num_sparse_fields) * cfg.vocab_size
    B = args.batch
    F = cfg.num_sparse_fields

    @jax.jit
    def grad_step(st, dense, labels, erows, einv, lrows, linv):
        # erows/lrows are padded to a FIXED row count so this never retraces
        params, opt_state = st

        def loss_fn(p, er, lr):
            emb = jnp.take(er, einv, axis=0).reshape(B, F, cfg.embed_dim)
            first = jnp.take(lr, linv, axis=0).reshape(B, F, 1)
            logits = model.apply({"params": p, "state": {}}, dense, emb,
                                 first, method="forward_from_emb")
            return ctr_loss(logits, labels)

        loss, (gp, ge, gl) = jax.value_and_grad(loss_fn, argnums=(0, 1, 2))(
            params, erows, lrows)
        params, opt_state = opt.apply_gradients(params, gp, opt_state)
        return loss, (params, opt_state), ge, gl

    rng = np.random.RandomState(0)
    st = (params, opt_state)
    K = B * F  # fixed pull size: pad uniques so grad_step never retraces
    for i in range(args.steps):
        dense = jnp.asarray(rng.rand(B, cfg.num_dense_fields), jnp.float32)
        sparse = rng.randint(0, cfg.vocab_size, (B, F)).astype(np.int32)
        labels = jnp.asarray(rng.randint(0, 2, (B, 1)), jnp.float32)
        ids = sparse + offsets[None, :]
        # both tables share the id space: one unique/inverse serves both
        uniq, inv = np.unique(ids.reshape(-1), return_inverse=True)
        n_real = len(uniq)
        uniq_padded = np.pad(uniq, (0, K - n_real), mode="edge")
        erows = jnp.asarray(table.table[uniq_padded])
        lrows = jnp.asarray(lin.table[uniq_padded])
        inv_j = jnp.asarray(inv)
        loss, st, ge, gl = grad_step(st, dense, labels, erows, inv_j,
                                     lrows, inv_j)
        # padded tail rows duplicate uniq[-1]; drop their (zero-grad is not
        # guaranteed after dedup) contribution by truncating to real rows
        table.push(uniq, np.asarray(ge)[:n_real])
        lin.push(uniq, np.asarray(gl)[:n_real])
        print(f"step {i} loss {float(loss):.4f} "
              f"(pulled {n_real} rows, padded to {K})")


if __name__ == "__main__":
    main()
