"""SSD detection training on synthetic data — the detection family
end-to-end (MultiBoxHead priors + loc/conf convs -> ssd_loss matching ->
detection_output NMS inference).

CPU smoke:  python examples/train_ssd.py --steps 4 --tiny
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=20)
    ap.add_argument("--batch", type=int, default=4)
    ap.add_argument("--tiny", action="store_true")
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp

    import paddle_tpu as pt
    from paddle_tpu import nn
    from paddle_tpu.ops import detection as D

    num_classes = 4       # background + 3
    base = 64 if args.tiny else 300

    class TinySSD(nn.Module):
        def __init__(self):
            super().__init__()
            self.backbone = nn.Sequential([
                nn.Conv2D(3, 16, 3, stride=2, padding=1, act="relu"),
                nn.Conv2D(16, 32, 3, stride=2, padding=1, act="relu"),
            ])
            self.extra = nn.Conv2D(32, 64, 3, stride=2, padding=1,
                                   act="relu")
            self.head = nn.MultiBoxHead(
                [32, 64], num_classes,
                per_map_cfg=[
                    {"min_sizes": [base * 0.2], "max_sizes": [base * 0.4],
                     "aspect_ratios": [2.0]},
                    {"min_sizes": [base * 0.4], "max_sizes": [base * 0.8],
                     "aspect_ratios": [2.0]},
                ],
                base_size=base)

        def forward(self, images):
            f1 = self.backbone(images)
            f2 = self.extra(f1)
            return self.head([f1, f2])

    model = TinySSD()
    variables = model.init(jax.random.key(0))
    params = variables["params"]
    opt = pt.optimizer.Momentum(0.01, 0.9)
    opt_state = opt.init(params)

    rng = np.random.RandomState(0)
    B, G = args.batch, 3

    def batch_data():
        images = rng.rand(B, 3, base, base).astype(np.float32)
        # G normalized gt boxes per image + labels (0 rows = padding)
        x1 = rng.uniform(0, 0.6, (B, G, 1))
        y1 = rng.uniform(0, 0.6, (B, G, 1))
        gt = np.concatenate([x1, y1, x1 + 0.3, y1 + 0.3], -1)
        labels = rng.randint(1, num_classes, (B, G))
        return (jnp.asarray(images), jnp.asarray(gt.astype(np.float32)),
                jnp.asarray(labels))

    def loss_fn(p, images, gt, labels):
        locs, confs, boxes, vars_ = model.apply(
            {"params": p, "state": {}}, images)
        norm_boxes = boxes / base                    # normalized priors
        per_img = jax.vmap(
            lambda l, c, g, gl: D.ssd_loss(l, c, g, gl, norm_boxes))
        return jnp.mean(per_img(locs, confs, gt, labels)), 0.0

    @jax.jit
    def step(p, s, *batch):
        loss, p, s, _ = opt.minimize(loss_fn, p, s, *batch)
        return loss, p, s

    data = batch_data()
    first = None
    for i in range(args.steps):
        loss, params, opt_state = step(params, opt_state, *data)
        if first is None:
            first = float(loss)
        if (i + 1) % 5 == 0 or i == 0:
            print(f"step {i + 1} loss {float(loss):.4f}")
    print(f"loss {first:.4f} -> {float(loss):.4f}")
    assert float(loss) < first, "loss did not decrease"

    # inference: decode + NMS through detection_output
    locs, confs, boxes, vars_ = model.apply(
        {"params": params, "state": {}}, data[0])
    out, count = D.detection_output(
        locs[0], jax.nn.softmax(confs[0], -1), boxes / base, vars_,
        score_threshold=0.01, nms_threshold=0.45, keep_top_k=10)
    print(f"detection_output: {int(count)} kept, shape {out.shape}")


if __name__ == "__main__":
    main()
