"""Continuous-batching serving over the paged KV cache.

CPU smoke:  python examples/serve_gpt.py --requests 12 --slots 4
(untrained tiny model — demonstrates the serving engine: mixed-length
prompts stream through a fixed set of decode slots; admissions land in
freed slots between decode steps, the jitted serve step compiles once,
and the paged cache grows/frees page-by-page.)
"""

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--requests", type=int, default=12)
    ap.add_argument("--slots", type=int, default=4)
    ap.add_argument("--page-size", type=int, default=8)
    ap.add_argument("--max-new", type=int, default=16)
    ap.add_argument("--temperature", type=float, default=0.0)
    args = ap.parse_args()

    import jax

    from paddle_tpu.models.gpt import GPTConfig, GPTDecoder
    from paddle_tpu.serving import ServeConfig, ServingEngine

    cfg = GPTConfig.tiny()
    cfg.dropout = 0.0
    cfg.use_flash = False
    model = GPTDecoder(cfg)
    v = model.init(jax.random.key(0))

    engine = ServingEngine(model, v, ServeConfig(
        num_slots=args.slots, page_size=args.page_size,
        max_len=32 + args.max_new, prefill_len=32,
        temperature=args.temperature))

    rng = np.random.RandomState(0)
    t0 = time.time()
    for _ in range(args.requests):
        plen = int(rng.randint(2, 32))
        engine.submit(rng.randint(0, cfg.vocab_size, (plen,),
                                  dtype=np.int32),
                      max_new=int(rng.randint(4, args.max_new + 1)))
    done = engine.drain()
    dt = time.time() - t0
    for r in sorted(done, key=lambda r: r.id):
        print(f"req {r.id}: prompt {len(r.prompt):2d} tok -> "
              f"+{len(r.tokens):2d} generated  {r.output.tolist()}")
    total = sum(len(r.tokens) for r in done)
    print(f"{len(done)} requests, {total} tokens in {dt:.2f}s "
          f"({total / max(dt, 1e-9):.1f} tok/s incl. compile); "
          f"serve step traced {engine.decode_traces}x")
    print("latency:", engine.latency_stats())


if __name__ == "__main__":
    main()
