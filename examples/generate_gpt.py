"""Text generation with the KV-cache decoder.

CPU smoke:  python examples/generate_gpt.py --max-new 16
(untrained tiny model — demonstrates the serving path: prefill scan +
O(1)-projection incremental steps + greedy/temperature sampling)
"""

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--max-new", type=int, default=16)
    ap.add_argument("--prompt-len", type=int, default=8)
    ap.add_argument("--temperature", type=float, default=0.0)
    ap.add_argument("--int8", action="store_true",
                    help="serve with weight-only int8 weights "
                         "(quant.quantize_weights_int8 — the weights stay "
                         "int8 in HBM, halving per-token weight reads)")
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp

    from paddle_tpu.models.gpt import GPTConfig, GPTDecoder

    cfg = GPTConfig.tiny()
    cfg.dropout = 0.0
    cfg.use_flash = False
    model = GPTDecoder(cfg)
    v = model.init(jax.random.key(0))
    if args.int8:
        from paddle_tpu.quant import quantize_weights_int8
        v = {"params": quantize_weights_int8(model, v["params"],
                                             min_size=16),
             "state": v.get("state", {})}

    prompt = jnp.asarray(np.random.RandomState(0).randint(
        0, cfg.vocab_size, (1, args.prompt_len), dtype=np.int32))

    key = jax.random.key(1) if args.temperature > 0 else None
    gen = jax.jit(lambda p_: model.apply(
        v, p_, method=lambda pr: model.generate(
            pr, max_new=args.max_new, temperature=args.temperature,
            key=key)))
    t0 = time.time()
    out = gen(prompt)
    out.block_until_ready()
    compile_s = time.time() - t0
    t0 = time.time()
    out = gen(prompt)
    out.block_until_ready()
    run_s = time.time() - t0
    print("prompt:", np.asarray(prompt)[0].tolist())
    print("output:", np.asarray(out)[0].tolist())
    print(f"compile {compile_s:.2f}s; generate {run_s * 1e3:.1f} ms "
          f"({args.max_new / max(run_s, 1e-9):.1f} tok/s)")


if __name__ == "__main__":
    main()
