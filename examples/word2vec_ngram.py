"""Word2vec-style n-gram language model over the corpus parsers.

Ref: the reference book's word2vec recipe
(/root/reference/python/paddle/fluid/tests/book/test_word2vec.py:
imikolov n-grams -> shared embedding -> concat -> fc -> softmax) and
the imikolov loader conventions (dataset/imikolov.py:54 build_dict with
<s>/<e>/<unk>, :92 n-gram windows) — here fed by the offline parsers in
pt.data.formats on a local corpus file.

CPU smoke:  python examples/word2vec_ngram.py
"""

import argparse
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

CORPUS = """the quick brown fox jumps over the lazy dog
the lazy dog sleeps while the quick fox runs
a quick brown fox is quicker than a lazy dog
the dog and the fox are friends in the field
"""


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=60)
    ap.add_argument("--embed-dim", type=int, default=16)
    ap.add_argument("--n", type=int, default=4, help="n-gram width")
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp

    import paddle_tpu as pt

    with tempfile.NamedTemporaryFile("w", suffix=".txt",
                                     delete=False) as f:
        f.write(CORPUS)
        corpus = f.name
    try:
        word_idx = pt.data.build_dict([corpus], cutoff=0, markers=True)
        grams = np.asarray(list(pt.data.ngram_reader([corpus], word_idx,
                                                     args.n)()), np.int32)
    finally:
        os.unlink(corpus)  # last read above; never leak on failure
    vocab = len(word_idx)
    if len(grams) == 0:
        sys.exit(f"--n {args.n} is wider than every corpus line; "
                 "no n-grams to train on")
    print(f"vocab {vocab}, {len(grams)} {args.n}-grams")

    # the book model: shared embedding over the n-1 context words,
    # concatenated, one hidden fc, softmax over the vocab
    emb = pt.nn.Embedding(vocab, args.embed_dim)
    fc = pt.nn.Linear((args.n - 1) * args.embed_dim, vocab)
    key = jax.random.key(0)
    params = {"emb": emb.init(key)["params"],
              "fc": fc.init(jax.random.key(1))["params"]}
    opt = pt.optimizer.Adam(5e-2)
    state = opt.init(params)

    ctx = jnp.asarray(grams[:, :-1])
    tgt = jnp.asarray(grams[:, -1:])

    def loss_fn(p):
        e = emb.apply({"params": p["emb"], "state": {}}, ctx)
        h = e.reshape(e.shape[0], -1)
        logits = fc.apply({"params": p["fc"], "state": {}}, h)
        return jnp.mean(pt.ops.loss.softmax_with_cross_entropy(logits, tgt))

    @jax.jit
    def step(p, s):
        l, g = jax.value_and_grad(loss_fn)(p)
        p, s = opt.apply_gradients(p, g, s)
        return l, p, s

    first = float(loss_fn(params))
    for i in range(max(args.steps, 1)):
        _, params, state = step(params, state)
    final = float(loss_fn(params))
    print(f"loss {first:.4f} -> {final:.4f}")
    assert final < first

    # nearest neighbors in the learned embedding (the book's payoff demo)
    table = np.asarray(params["emb"]["weight"])
    inv = {v: k for k, v in word_idx.items()}
    w = word_idx["dog"]
    sims = table @ table[w] / (
        np.linalg.norm(table, axis=1) * np.linalg.norm(table[w]) + 1e-9)
    sims[w] = -np.inf  # never list the query as its own neighbor
    top = np.argsort(-sims)[:3]
    print("nearest to 'dog':", [inv[i] for i in top])


if __name__ == "__main__":
    main()
