"""BERT-base MLM pretraining with the full round-3 feature set:

  * masked Pallas flash attention (default-on, handles the padded batch)
  * bf16 mixed precision (amp policy + master weights)
  * Trainer runtime: threaded ingestion, periodic checkpoint + auto-resume,
    cross-process heartbeat when launched multi-host
  * synthetic token stream (zero egress)

Single chip:
    python examples/pretrain_bert_flash.py --steps 50

Multi-host (each worker):
    python -m paddle_tpu.parallel.launch --nproc 2 \
        examples/pretrain_bert_flash.py -- --steps 50 --heartbeat-dir /tmp/hb
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=50)
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--seq", type=int, default=128)
    ap.add_argument("--ckpt-dir", default="/tmp/bert_flash_ckpt")
    ap.add_argument("--ckpt-every", type=int, default=20)
    ap.add_argument("--heartbeat-dir", default=None)
    ap.add_argument("--tiny", action="store_true",
                    help="tiny config (CPU-friendly smoke run)")
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp

    import paddle_tpu as pt
    from paddle_tpu.models.bert import (BertConfig, BertForPretraining,
                                        pretrain_loss)
    from paddle_tpu.static.trainer import Trainer, TrainerConfig

    cfg = BertConfig.tiny() if args.tiny else BertConfig.base()
    cfg.dropout = 0.0
    cfg.max_position = max(cfg.max_position, args.seq)
    model = BertForPretraining(cfg)
    variables = model.init(jax.random.key(0))
    params = variables["params"]

    opt = pt.amp.decorate(pt.optimizer.Adam(1e-4), pt.amp.bf16_policy())
    opt_state = opt.init(params)

    def loss_fn(p, ids, mlm_l, nsp_l, mmask, amask):
        mlm, nsp = model.apply({"params": p, "state": {}}, ids,
                               attention_mask=amask)
        return pretrain_loss(mlm, nsp, mlm_l, nsp_l, mmask), 0.0

    @jax.jit
    def train_step(state, ids, mlm_l, nsp_l, mmask, amask):
        loss, params, opt_state, _ = opt.minimize(
            loss_fn, state["params"], state["opt"], ids, mlm_l, nsp_l,
            mmask, amask)
        return loss, {"params": params, "opt": opt_state}

    def reader():
        rng = np.random.RandomState(jax.process_index())
        B, T = args.batch, args.seq
        while True:
            ids = rng.randint(0, cfg.vocab_size, (B, T)).astype(np.int32)
            mlm_l = rng.randint(0, cfg.vocab_size, (B, T)).astype(np.int32)
            nsp_l = rng.randint(0, 2, (B,)).astype(np.int32)
            mmask = (rng.rand(B, T) < 0.15).astype(np.float32)
            # ragged padded batch — the masked flash path handles it
            lens = rng.randint(T // 2, T + 1, (B,))
            amask = (np.arange(T)[None, :] < lens[:, None]).astype(
                np.float32)
            yield ids, mlm_l, nsp_l, mmask, amask

    tcfg = TrainerConfig(
        max_steps=args.steps, log_every=10, num_ingest_threads=1,
        checkpoint_dir=args.ckpt_dir, checkpoint_every=args.ckpt_every,
        heartbeat=args.heartbeat_dir is not None,
        heartbeat_dir=args.heartbeat_dir)
    trainer = Trainer(train_step, tcfg)
    state, stats = trainer.train({"params": params, "opt": opt_state},
                                 lambda: reader())
    print(f"done: {stats['run_steps']} steps this run "
          f"(total {stats['steps']}), {stats['steps_per_s']:.2f} steps/s, "
          f"final loss {stats['final_loss']:.4f}")


if __name__ == "__main__":
    main()
