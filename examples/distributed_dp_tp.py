"""Plan + run a dp x tp distributed BERT step on an 8-device mesh.

On CPU:  XLA_FLAGS=--xla_force_host_platform_device_count=8 \
         JAX_PLATFORMS=cpu python examples/distributed_dp_tp.py
On a TPU pod slice the same code runs over the real mesh.
"""

import numpy as np

import jax
import jax.numpy as jnp

import paddle_tpu as pt
from paddle_tpu.models.bert import BertConfig, BertForPretraining, \
    pretrain_loss
from paddle_tpu.parallel import DistributionPlanner


def main():
    n = len(jax.devices())
    tp = 2 if n % 2 == 0 else 1
    mesh = pt.parallel.make_mesh({"dp": n // tp, "tp": tp})
    print(f"mesh: {dict(mesh.shape)}")

    cfg = BertConfig(vocab_size=512, hidden_size=64, num_layers=2,
                     num_heads=4, intermediate_size=128, max_position=64,
                     dropout=0.0)
    model = BertForPretraining(cfg)
    params = model.init(jax.random.key(0))["params"]
    opt = pt.optimizer.Adam(1e-3)

    rng = np.random.RandomState(0)
    ids = jnp.asarray(rng.randint(0, 512, (16, 32), dtype=np.int32))
    labels = jnp.asarray(rng.randint(0, 512, (16, 32), dtype=np.int32))

    def step(params, opt_state, ids, labels):
        def loss_fn(p):
            mlm, nsp = model.apply({"params": p, "state": {}}, ids)
            return pretrain_loss(mlm, nsp, labels,
                                 jnp.zeros((ids.shape[0],), jnp.int32),
                                 jnp.ones(ids.shape, jnp.float32))
        loss, grads = jax.value_and_grad(loss_fn)(params)
        params, opt_state = opt.apply_gradients(params, grads, opt_state)
        return loss, params, opt_state

    planner = DistributionPlanner(mesh, tp_auto=True)
    jitted, p, o, plan = planner.compile_step(step, params, opt.init(params),
                                              (ids, labels), donate=False)
    print("plan (first entries):")
    for line in plan.describe().splitlines()[:12]:
        print(" ", line)
    with mesh:
        for i in range(3):
            loss, p, o = jitted(p, o, ids, labels)
            print(f"step {i} loss {float(loss):.4f}")


if __name__ == "__main__":
    main()
