"""Seq2seq NMT through the composable cell/decoder protocol.

Ref: the reference's machine-translation recipe built on layers/rnn.py
(RNNCell -> BeamSearchDecoder -> dynamic_decode, rnn.py:440/791) and the
seq2seq book example. Here: GRU encoder (nn.RNN) -> custom attention cell
(the protocol's whole point: the decoder has never seen this cell) ->
beam-search decode.

Task: translate "copy-reverse" sequences (target = reversed source) —
learnable in seconds on CPU, and decode quality is exactly measurable.

Run: python examples/nmt_seq2seq.py [--steps 300]
"""

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import jax
import jax.numpy as jnp

import paddle_tpu as pt
from paddle_tpu import nn

VOCAB = 20
BOS, EOS = 1, 2
SEQ = 6


class AttentionGRUCell(nn.RNNCell):
    """GRU cell + dot-product attention over the encoder outputs — a
    CUSTOM cell (not part of the framework) driving the stock
    BeamSearchDecoder, which is the protocol contract under test.
    State = (h, encoder_outputs): the memory rides in the state pytree so
    the decoder's beam-tiling handles it automatically."""

    def __init__(self, emb_dim, hidden):
        super().__init__()
        self.hidden = hidden
        self.gru = nn.GRUCell(emb_dim + hidden, hidden)
        self.attn_q = nn.Linear(hidden, hidden, bias=False)

    @property
    def state_shape(self):
        return ((self.hidden,), (SEQ, self.hidden))

    def forward(self, inputs, states):
        h, enc = states                                  # [N,H], [N,T,H]
        q = self.attn_q(h)                               # [N, H]
        w = jax.nn.softmax(jnp.einsum("nh,nth->nt", q, enc), -1)
        ctx = jnp.einsum("nt,nth->nh", w, enc)
        out, h = self.gru(jnp.concatenate([inputs, ctx], -1), h)
        return out, (h, enc)


class Seq2Seq(nn.Module):
    def __init__(self, emb_dim=32, hidden=64):
        super().__init__()
        self.src_emb = nn.Embedding(VOCAB, emb_dim)
        self.tgt_emb = nn.Embedding(VOCAB, emb_dim)
        self.encoder = nn.RNN(nn.GRUCell(emb_dim, hidden))
        self.cell = AttentionGRUCell(emb_dim, hidden)
        self.proj = nn.Linear(hidden, VOCAB)

    def encode(self, src):
        enc, h = self.encoder(self.src_emb(src))
        return enc, h

    def forward(self, src, tgt_in):
        """Teacher-forced training logits [B, T, V]."""
        enc, h = self.encode(src)
        xs = jnp.moveaxis(self.tgt_emb(tgt_in), 1, 0)

        def step(carry, x_t):
            out, carry = self.cell(x_t, carry)
            return carry, out

        _, outs = jax.lax.scan(step, (h, enc), xs)
        return self.proj(jnp.moveaxis(outs, 0, 1))


def make_batch(rng, n):
    body = rng.randint(3, VOCAB, (n, SEQ - 1))
    src = np.concatenate([body, np.full((n, 1), EOS)], 1)
    tgt = np.concatenate([body[:, ::-1], np.full((n, 1), EOS)], 1)
    tgt_in = np.concatenate([np.full((n, 1), BOS), tgt[:, :-1]], 1)
    return jnp.asarray(src), jnp.asarray(tgt_in), jnp.asarray(tgt)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=300)
    ap.add_argument("--beam", type=int, default=4)
    args = ap.parse_args()

    model = Seq2Seq()
    variables = model.init(jax.random.key(0))
    opt = pt.optimizer.Adam(2e-3)
    ostate = opt.init(variables["params"])
    rng = np.random.RandomState(0)

    def loss_fn(p, src, tgt_in, tgt):
        logits = model.apply({"params": p, "state": {}}, src, tgt_in)
        return jnp.mean(pt.ops.loss.softmax_with_cross_entropy(
            logits, tgt[..., None]))

    @jax.jit
    def train_step(p, o, src, tgt_in, tgt):
        l, g = jax.value_and_grad(loss_fn)(p, src, tgt_in, tgt)
        p, o = opt.apply_gradients(p, g, o)
        return l, p, o

    t0 = time.time()
    params = variables["params"]
    for i in range(args.steps):
        src, tgt_in, tgt = make_batch(rng, 64)
        l, params, ostate = train_step(params, ostate, src, tgt_in, tgt)
        if i % 100 == 0 or i == args.steps - 1:
            print(f"step {i} loss {float(l):.4f}")
    print(f"trained in {time.time() - t0:.1f}s")

    # --- beam-search decode through the protocol -----------------------
    src, _, tgt = make_batch(rng, 32)
    full = {"params": params, "state": {}}
    enc, h = model.apply(full, src, method="encode")
    cell_vars = {"params": params["cell"], "state": {}}
    dec = nn.BeamSearchDecoder(
        model.cell, start_token=BOS, end_token=EOS, beam_size=args.beam,
        embedding_fn=lambda tok: model.apply(
            full, tok, method=lambda t: model.tgt_emb(t)),
        output_fn=lambda out: model.apply(
            full, out, method=lambda o: model.proj(o)),
        vocab_size=VOCAB, cell_variables=cell_vars)
    seqs, scores = nn.dynamic_decode(dec, (h, enc), max_step_num=SEQ + 2)
    best = np.asarray(seqs)[:, 0, :SEQ]
    acc = float((best == np.asarray(tgt)).mean())
    print(f"beam={args.beam} token accuracy vs reference reversal: "
          f"{acc:.3f}")
    assert acc > 0.9, acc
    print("OK")


if __name__ == "__main__":
    main()
