"""Pipeline-parallel training schedules on an 8-device mesh.

Ref: the reference's PipelineTrainer/SectionWorker
(paddle/fluid/framework/pipeline_trainer.cc, section_worker.cc:141) —
here as three scan-native schedules over a `pp` mesh axis plus the
dp x pp hybrid, all loss-equivalent:

  gpipe        forward wave + autodiff-transposed backward wave
  1f1b         one fwd + one bwd microstep per tick; O(stages)
               activation residency instead of O(microbatches)
  interleaved  1f1b over V virtual chunks per device (chunk-granular
               pipeline ramp)

On CPU:  XLA_FLAGS=--xla_force_host_platform_device_count=8 \
         JAX_PLATFORMS=cpu python examples/pipeline_schedules.py
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def main():
    import jax
    import jax.numpy as jnp

    import paddle_tpu as pt
    from paddle_tpu.parallel.pipeline import (
        interleave_stage_params, make_pipeline_train_step,
        split_microbatches, stack_stage_params)

    n = len(jax.devices())
    S, V, dim, M, mb = n, 2, 32, 2 * n, 4
    keys = jax.random.split(jax.random.key(0), S * V)
    stages16 = stack_stage_params(
        [{"w": jax.random.normal(k, (dim, dim)) * 0.25} for k in keys])
    stages8 = jax.tree_util.tree_map(lambda a: a[:S], stages16)

    def stage_fn(p, h):
        return jnp.tanh(h @ p["w"])

    def loss_fn(outs, labels):
        return jnp.mean((outs - labels) ** 2)

    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.rand(M * mb, dim).astype(np.float32) - 0.5)
    y = jnp.asarray(rng.rand(M * mb, dim).astype(np.float32) - 0.5)
    xm, ym = split_microbatches(x, M), split_microbatches(y, M)

    mesh = pt.parallel.make_mesh({"pp": S})
    opt = pt.optimizer.Adam(1e-2)

    def run(label, step, params):
        step = jax.jit(step)
        p, st = params, opt.init(params)
        losses = []
        for _ in range(10):
            l, p, st = step(p, st, xm, ym)
            losses.append(float(l))
        print(f"{label}:  loss {losses[0]:.4f} -> {losses[-1]:.4f}")

    for schedule in ("gpipe", "1f1b"):
        run(f"{schedule:12s} S={S} M={M}",
            make_pipeline_train_step(mesh, stage_fn, loss_fn, opt, "pp",
                                     remat=True, schedule=schedule),
            stages8)

    run(f"interleaved  S={S} V={V} ({S * V} stages)",
        make_pipeline_train_step(mesh, stage_fn, loss_fn, opt, "pp",
                                 schedule="interleaved", num_chunks=V),
        interleave_stage_params(stages16, S, V))

    # the dp x pp hybrid from a strategy object (explicit dp)
    if n % 2 == 0:
        s = pt.parallel.DistributedStrategy(dp=2, pp=n // 2,
                                            pp_schedule="1f1b")
        hmesh = pt.parallel.fleet.build_mesh(s)
        run(f"dp(2) x pp({n // 2}) 1f1b",
            make_pipeline_train_step(hmesh, stage_fn, loss_fn, opt, "pp",
                                     **s.pipeline_kwargs()),
            jax.tree_util.tree_map(lambda a: a[:n // 2], stages8))


if __name__ == "__main__":
    main()
